"""Guarded elastic-fleet actuator: advice-driven pod/worker scaling.

PR 16 gave `/debug/rebalance` advice a deadline (`lead_s`, the forecast
time-to-saturation); this module is the actuator that consumes it —
ROADMAP item 2(a), grounded in P/D-Serve (arXiv:2408.08147): at fleet
scale the fleet SIZE must track traffic, not just the P:D ratio. An
actuator is first and foremost a robustness problem — a scaling action
that fires on a bad signal, wedges mid-drain, or flaps is worse than no
autoscaler at all — so every action flows through one guarded pipeline:

- **preflight** — advice direction sustained >= ``sustainTicks`` AND
  (for scale-up) the forecast lead still positive; capacity bounds
  (``minPodsPerRole``/``maxPodsPerRole``, never a role's last pod);
  per-target backoff circuit closed; actuator not frozen.
- **bounded budgets** — at most ``maxActionsPerWindow`` actions per
  ``windowS``, plus ``dwellS`` minimum between OPPOSING actions on the
  same target dimension, so advice flapping at the
  ``router_pool_advice_changes_total`` rate can't saw the fleet.
- **safe execution** — retire reuses the PR 15 drain-cycle discipline
  (draining mark -> scrape-confirmed empty -> teardown, bounded by
  ``drainTimeoutS``); spawn registers the pod DRAINING (not
  pick-eligible) and only clears the mark after health + first scrape.
- **watchdogs** — a stuck spawn/drain times out, is force-finalized,
  and opens a per-target backoff circuit (resilience.py breaker).
- **rollback-on-incident** — a burn-rate trip (PR 12 monitor) or
  attainment collapse inside the post-action ``observationWindowS``
  reverses the last action and FREEZES the actuator with the reason on
  record (``router_autoscale_frozen``).

Every action — including refusals, timeouts, and rollbacks — is a
DecisionRecord-style ledger entry on ``GET /debug/autoscale`` (inputs:
advice, lead_s, headroom, budgets; outcome judged post-hoc against the
realized headroom — the predict->observe discipline every prior loop
follows), with fleet fan-in via ``merge_autoscale``.

Kill-switch: ``autoscale: {enabled: false}`` (the default) is
bit-identical — no task, zero ticks, zero actions.
"""

from __future__ import annotations

import asyncio
import contextlib
import dataclasses
import logging
import time
from collections import deque
from typing import Any, Callable

from .metrics import (
    AUTOSCALE_ACTIONS,
    AUTOSCALE_FROZEN,
    FLEET_SIZE,
)
from .resilience import CircuitBreaker

log = logging.getLogger(__name__)

PREFILL, DECODE = "prefill", "decode"
ROLES = (PREFILL, DECODE)

# Ledger action kinds.
SPAWN_POD = "spawn_pod"
RETIRE_POD = "retire_pod"
SPAWN_WORKER = "spawn_worker"
RETIRE_WORKER = "retire_worker"

_OPPOSITE = {SPAWN_POD: RETIRE_POD, RETIRE_POD: SPAWN_POD,
             SPAWN_WORKER: RETIRE_WORKER, RETIRE_WORKER: SPAWN_WORKER}

# Terminal record states (the AUTOSCALE_ACTIONS outcome label).
COMPLETED, ABORTED, REFUSED, ROLLED_BACK = ("completed", "aborted",
                                            "refused", "rolled_back")


@dataclasses.dataclass
class AutoscaleConfig:
    """The YAML ``autoscale:`` section (camelCase keys, like every other
    EndpointPickerConfig surface). Defaults are deliberately cautious —
    an actuator ships OFF and slow."""

    enabled: bool = False
    tick_s: float = 1.0
    # Preflight: advice direction must hold for this many consecutive
    # actuator ticks before it is actionable.
    sustain_ticks: int = 3
    # Scale-up additionally requires a positive forecast lead
    # (advice.lead_s) when the forecaster is wired; reactive deployments
    # (no forecast) set requireLead: false and act on sustain alone.
    require_lead: bool = True
    # Budgets: max actions per sliding window, and the minimum dwell
    # between OPPOSING actions on the same target (role, or the worker
    # dimension) — the anti-flap hysteresis.
    max_actions_per_window: int = 4
    window_s: float = 300.0
    dwell_s: float = 60.0
    # Post-action observation: burn-rate trip or attainment collapse in
    # this window rolls the action back and freezes the actuator; after
    # it closes the action's outcome is judged against realized headroom.
    observation_window_s: float = 30.0
    rollback_attainment: float = 0.5
    # Safe-execution watchdogs.
    spawn_timeout_s: float = 30.0
    drain_timeout_s: float = 20.0
    # Capacity bounds per role.
    min_pods_per_role: int = 1
    max_pods_per_role: int = 8
    # Worker dimension: target worker count tracks ceil(pods /
    # podsPerWorker) within [minWorkers, provisioned]. 0 disables worker
    # scaling (the default — pods only).
    pods_per_worker: int = 0
    min_workers: int = 1
    # Per-target backoff circuit opened by watchdog force-finalization.
    breaker_failure_threshold: int = 2
    breaker_open_s: float = 60.0
    ledger_n: int = 256

    @classmethod
    def from_spec(cls, spec: dict[str, Any] | None) -> "AutoscaleConfig":
        spec = spec or {}
        cfg = cls(
            enabled=bool(spec.get("enabled", False)),
            tick_s=float(spec.get("tickS", 1.0)),
            sustain_ticks=max(1, int(spec.get("sustainTicks", 3))),
            require_lead=bool(spec.get("requireLead", True)),
            max_actions_per_window=max(
                1, int(spec.get("maxActionsPerWindow", 4))),
            window_s=float(spec.get("windowS", 300.0)),
            dwell_s=float(spec.get("dwellS", 60.0)),
            observation_window_s=float(
                spec.get("observationWindowS", 30.0)),
            rollback_attainment=float(spec.get("rollbackAttainment", 0.5)),
            spawn_timeout_s=float(spec.get("spawnTimeoutS", 30.0)),
            drain_timeout_s=float(spec.get("drainTimeoutS", 20.0)),
            min_pods_per_role=max(1, int(spec.get("minPodsPerRole", 1))),
            max_pods_per_role=int(spec.get("maxPodsPerRole", 8)),
            pods_per_worker=max(0, int(spec.get("podsPerWorker", 0))),
            min_workers=max(1, int(spec.get("minWorkers", 1))),
            breaker_failure_threshold=max(
                1, int(spec.get("breakerFailureThreshold", 2))),
            breaker_open_s=float(spec.get("breakerOpenS", 60.0)),
            ledger_n=max(16, int(spec.get("ledgerN", 256))),
        )
        if cfg.tick_s <= 0:
            raise ValueError("autoscale.tickS must be > 0")
        if cfg.window_s <= 0:
            raise ValueError("autoscale.windowS must be > 0")
        if cfg.max_pods_per_role < cfg.min_pods_per_role:
            raise ValueError("autoscale.maxPodsPerRole must be >= "
                             "minPodsPerRole")
        if not 0.0 <= cfg.rollback_attainment <= 1.0:
            raise ValueError(
                "autoscale.rollbackAttainment must be in [0, 1]")
        return cfg


class SpawnHandle:
    """What a launcher returns from ``spawn``: the launcher (or the chaos
    shim standing in for it) flips ``state`` to "ok" once the pod's
    process is up and its endpoint is registered (DRAINING — the
    controller clears the mark after the first scrape), or to "failed"
    with ``error`` set."""

    def __init__(self) -> None:
        self.state = "pending"       # pending | ok | failed
        self.address_port: str | None = None
        self.error: str | None = None


class _Action:
    """One in-flight guarded action (the controller runs at most one at a
    time — serialized actions are the cheapest mid-action invariant)."""

    def __init__(self, kind: str, role: str, *, inputs: dict[str, Any],
                 wall: float, mono: float, rollback_of: int | None = None):
        self.kind = kind
        self.role = role             # pod role, or "worker"
        self.inputs = inputs
        self.started_unix = wall
        self.start_mono = mono
        self.rollback_of = rollback_of
        self.target: str | None = None
        self.handle: Any = None      # SpawnHandle for spawns
        self.record: dict[str, Any] = {}
        self.watchdog = False


class ActuatorController:
    """Grid-tick guarded actuator. ``tick()`` is synchronous and
    injectable-clock so the full guard pipeline is testable without
    asyncio (RebalanceController precedent); ``start()`` runs it on the
    wall-clock grid.

    Collaborators are injected:

    - ``advice_fn`` -> the rebalancer's live per-role advice dict
      ({role: {direction, why, headroom, lead_s?, forecast?}}).
    - ``datastore`` -> endpoint census + the draining lifecycle the
      drain cycle rides (set_endpoint_draining / endpoint_get).
    - ``launcher`` -> object with ``spawn(role) -> SpawnHandle`` and
      ``retire(address_port)`` (teardown + endpoint_delete). None means
      the pod dimension is observed but never acted on (advice-driven
      refusals still ledger — the dry-run view).
    - ``worker_scaler`` -> object with ``counts() -> (active,
      provisioned)``, ``retire() -> str|None`` and ``restore() ->
      str|None`` (shard id, or None = refused). Fleet mode wires this to
      the supervisor's ``POST /fleet/scale``.
    - ``burn_fn`` -> True when the PR 12 burn-rate monitor is tripped;
      ``attainment_fn`` -> the last tick's attainment (None = no
      arrivals): the rollback triggers.
    """

    def __init__(self, cfg: AutoscaleConfig, *,
                 datastore: Any = None,
                 advice_fn: Callable[[], dict[str, Any]] | None = None,
                 launcher: Any = None,
                 worker_scaler: Any = None,
                 burn_fn: Callable[[], bool] | None = None,
                 attainment_fn: Callable[[], float | None] | None = None,
                 acting: bool = True,
                 clock: Callable[[], float] = time.monotonic,
                 wall: Callable[[], float] = time.time):
        self.cfg = cfg
        self.datastore = datastore
        self.advice_fn = advice_fn
        self.launcher = launcher
        self.worker_scaler = worker_scaler
        self.burn_fn = burn_fn
        self.attainment_fn = attainment_fn
        self.acting = acting
        self._clock = clock
        self._wall = wall
        self._task: asyncio.Task | None = None

        self.ticks_total = 0
        self.actions_total = 0
        self.refusals_total = 0
        self.rollbacks_total = 0
        self.watchdog_total = 0
        self.frozen = False
        self.frozen_reason: str | None = None
        self.frozen_unix: float | None = None

        self._records: deque[dict[str, Any]] = deque(maxlen=cfg.ledger_n)
        self._next_id = 1
        self._pending: _Action | None = None
        # Sustain streaks per pod role: (direction, consecutive ticks).
        self._streak: dict[str, tuple[str, int]] = {}
        # Budget window: wall times of STARTED actions (refusals are
        # free — a refusal that consumed budget would starve recovery).
        self._window: deque[float] = deque()
        # Dwell anchors per dimension key: (kind, wall time).
        self._last_kind: dict[str, tuple[str, float]] = {}
        # Refusal dedup per dimension: last refusal reason -> its record,
        # so a sustained refusal bumps a count instead of flooding the
        # ledger every tick.
        self._last_refusal: dict[str, dict[str, Any]] = {}
        # Post-action observation: records completed but not yet judged.
        self._observing: list[dict[str, Any]] = []
        self._breakers: dict[str, CircuitBreaker] = {}
        self._g_size = {r: FLEET_SIZE.labels(r) for r in ROLES}
        self._g_size_worker = FLEET_SIZE.labels("worker")
        AUTOSCALE_FROZEN.set(0)

    # ---- lifecycle ------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self.cfg.enabled

    def start(self) -> None:
        if not self.cfg.enabled or self._task is not None:
            return
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._task
            self._task = None
        stop = getattr(self.worker_scaler, "stop", None)
        if stop is not None:
            await stop()

    def promote(self) -> None:
        """This worker just became the acting datalayer leader: arm the
        actuator. A half-done action belongs to the dead ex-leader's
        ledger, not ours — the new leader starts with a clean slate and
        re-anchored dwell (no inherited momentum)."""
        if not self.cfg.enabled:
            return
        self.acting = True
        now = self._wall()
        for key in list(self._last_kind):
            kind, _ = self._last_kind[key]
            self._last_kind[key] = (kind, now)
        if self._task is None:
            with contextlib.suppress(RuntimeError):
                self.start()

    async def _run(self) -> None:
        tick = self.cfg.tick_s
        try:
            while True:
                now = self._wall()
                next_t = (int(now / tick) + 1) * tick
                await asyncio.sleep(max(next_t - now, 0.0))
                with contextlib.suppress(Exception):
                    self.tick()
        except asyncio.CancelledError:
            pass

    # ---- census ---------------------------------------------------------

    def _census(self) -> dict[str, dict[str, Any]]:
        out: dict[str, dict[str, Any]] = {}
        if self.datastore is not None:
            out = self.datastore.role_census()
        for role in ROLES:
            row = out.get(role) or {"total": 0, "ready": 0, "pods": []}
            out[role] = row
            self._g_size[role].set(row["total"])
        if self.worker_scaler is not None:
            active, provisioned = self.worker_scaler.counts()
            out["worker"] = {"total": active, "provisioned": provisioned}
            self._g_size_worker.set(active)
        return out

    # ---- ledger ---------------------------------------------------------

    def _record(self, kind: str, role: str, state: str, *,
                why: str, inputs: dict[str, Any] | None = None,
                target: str | None = None,
                watchdog: bool = False,
                rollback_of: int | None = None) -> dict[str, Any]:
        rec: dict[str, Any] = {
            "id": self._next_id,
            "t_unix": round(self._wall(), 3),
            "kind": kind,
            "role": role,
            "state": state,
            "why": why,
            "inputs": inputs or {},
        }
        self._next_id += 1
        if target is not None:
            rec["target"] = target
        if watchdog:
            rec["watchdog"] = True
        if rollback_of is not None:
            rec["rollback_of"] = rollback_of
        self._records.append(rec)
        if state in (COMPLETED, ABORTED, REFUSED, ROLLED_BACK):
            AUTOSCALE_ACTIONS.labels(kind, state).inc()
        return rec

    def _finalize(self, rec: dict[str, Any], state: str) -> None:
        rec["state"] = state
        rec["finished_unix"] = round(self._wall(), 3)
        AUTOSCALE_ACTIONS.labels(rec["kind"], state).inc()

    def _refuse(self, dim: str, kind: str, role: str, why: str,
                inputs: dict[str, Any]) -> None:
        """Ledger a refusal, deduped per dimension: the same reason on
        consecutive ticks bumps a count on the existing record."""
        self.refusals_total += 1
        last = self._last_refusal.get(dim)
        if (last is not None and last["why"] == why
                and last["kind"] == kind):
            last["count"] = last.get("count", 1) + 1
            last["t_unix"] = round(self._wall(), 3)
            last["inputs"] = inputs
            return
        rec = self._record(kind, role, REFUSED, why=why, inputs=inputs)
        rec["count"] = 1
        self._last_refusal[dim] = rec

    # ---- breakers -------------------------------------------------------

    def _breaker(self, key: str) -> CircuitBreaker:
        b = self._breakers.get(key)
        if b is None:
            b = CircuitBreaker(
                failure_threshold=self.cfg.breaker_failure_threshold,
                open_s=self.cfg.breaker_open_s, clock=self._clock)
            self._breakers[key] = b
        return b

    # ---- freeze ---------------------------------------------------------

    def freeze(self, reason: str) -> None:
        if self.frozen:
            return
        self.frozen = True
        self.frozen_reason = reason
        self.frozen_unix = round(self._wall(), 3)
        AUTOSCALE_FROZEN.set(1)
        log.warning("autoscale frozen: %s", reason)

    def unfreeze(self) -> None:
        """Operator reset (tests, or a config reload): clear the freeze
        and start from a clean dwell slate."""
        self.frozen = False
        self.frozen_reason = None
        self.frozen_unix = None
        AUTOSCALE_FROZEN.set(0)

    # ---- one tick -------------------------------------------------------

    def tick(self, wall: float | None = None) -> None:
        """One guarded-actuator cycle: advance the in-flight action,
        check the rollback trigger, judge closed observation windows,
        then run the preflight pipeline on fresh advice. Kill-switch:
        one attribute check."""
        cfg = self.cfg
        if not cfg.enabled or not self.acting:
            return
        self.ticks_total += 1
        now = wall if wall is not None else self._wall()
        mono = self._clock()

        # Census AFTER advancing the in-flight action: completing a
        # retire deletes its endpoint (and completing a spawn clears a
        # DRAINING mark), and every preflight below must see that —
        # a stale pre-advance census once let a same-tick follow-up
        # retire the pool's genuinely last pod.
        self._advance_pending(now, mono)
        census = self._census()
        self._check_rollback(now, census)
        self._judge_observed(now)

        advice = self.advice_fn() if self.advice_fn is not None else {}
        self._update_streaks(advice)
        if self._pending is not None:
            return      # serialized: one action in flight at a time
        self._consider_pods(advice, census, now, mono)
        if self._pending is None:
            self._consider_workers(census, now, mono)

    # ---- in-flight state machine ---------------------------------------

    def _advance_pending(self, now: float, mono: float) -> None:
        act = self._pending
        if act is None:
            return
        if act.kind in (SPAWN_POD, SPAWN_WORKER):
            self._advance_spawn(act, now, mono)
        else:
            self._advance_retire(act, now, mono)

    def _advance_spawn(self, act: _Action, now: float, mono: float) -> None:
        cfg = self.cfg
        h = act.handle
        rec = act.record
        if act.kind == SPAWN_WORKER:
            # The scaler resolved synchronously (restore() returned the
            # shard); the spawn completes once the worker is back alive.
            active, _ = (self.worker_scaler.counts()
                         if self.worker_scaler is not None else (0, 0))
            if active >= act.inputs.get("target_workers", 0):
                self._complete(act)
            elif mono - act.start_mono > cfg.spawn_timeout_s:
                self._abort(act, "worker restore did not come up within "
                            f"spawnTimeoutS={cfg.spawn_timeout_s}")
            return
        if h is not None and h.state == "failed":
            self._abort(act, f"launcher spawn failed: {h.error}")
            return
        if h is not None and h.state == "ok" and h.address_port:
            act.target = h.address_port
            rec["target"] = h.address_port
            ep = (self.datastore.endpoint_get(h.address_port)
                  if self.datastore is not None else None)
            # Pick-eligibility gate: health (launcher says ok) + first
            # scrape (the datalayer observed the pod) before the
            # draining mark is cleared.
            if ep is not None and ep.metrics.update_time > act.start_mono:
                self.datastore.set_endpoint_draining(h.address_port, False)
                self._complete(act)
                return
        if mono - act.start_mono > cfg.spawn_timeout_s:
            self._abort(act, "spawn stuck (no healthy scrape within "
                        f"spawnTimeoutS={cfg.spawn_timeout_s})",
                        cleanup=True)

    def _advance_retire(self, act: _Action, now: float, mono: float) -> None:
        cfg = self.cfg
        if act.kind == RETIRE_WORKER:
            active, _ = (self.worker_scaler.counts()
                         if self.worker_scaler is not None else (0, 0))
            if active <= act.inputs.get("target_workers", 1 << 30):
                self._complete(act)
            elif mono - act.start_mono > cfg.drain_timeout_s:
                act.watchdog = True
                self._complete(act, why_suffix="; drain timed out, "
                               "force-finalized by watchdog")
            return
        ep = (self.datastore.endpoint_get(act.target)
              if self.datastore is not None else None)
        if ep is None:
            # Pod vanished under the drain (crash, operator delete):
            # nothing left to tear down.
            self._complete(act, why_suffix="; pod vanished mid-drain")
            return
        m = ep.metrics
        drained = (m.update_time > act.start_mono
                   and m.running_requests_size == 0
                   and m.waiting_queue_size == 0)
        if drained:
            if self.launcher is not None:
                self.launcher.retire(act.target)
            self._complete(act)
            return
        if mono - act.start_mono > cfg.drain_timeout_s:
            # Watchdog: force-finalize — tear the pod down anyway (its
            # residual work is lost, which is exactly what the record
            # says) and open the backoff circuit for this dimension.
            act.watchdog = True
            if self.launcher is not None:
                self.launcher.retire(act.target)
            self._breaker(f"pod:{act.role}").record_failure()
            self._complete(act, why_suffix="; drain timed out, "
                           "force-finalized by watchdog")

    def _complete(self, act: _Action, *, why_suffix: str = "") -> None:
        rec = act.record
        if why_suffix:
            rec["why"] += why_suffix
        if act.watchdog:
            rec["watchdog"] = True
            rec["drain_timed_out"] = True
            self.watchdog_total += 1
        self._finalize(rec, COMPLETED)
        rec["observe_until"] = round(
            self._wall() + self.cfg.observation_window_s, 3)
        # Incident baseline at completion: rollback is attribution, not
        # alarm-forwarding — an incident already burning when the action
        # landed (e.g. the very overload a scale-up answers) must not
        # reverse it; only an incident that APPEARS inside the window is
        # chargeable to the action.
        rec["baseline"] = {
            "burn": bool(self.burn_fn()) if self.burn_fn is not None
            else False,
            "attainment": (self.attainment_fn()
                           if self.attainment_fn is not None else None),
        }
        self._observing.append(rec)
        self._pending = None

    def _abort(self, act: _Action, why: str, *, cleanup: bool = False) -> None:
        rec = act.record
        rec["why"] += f"; {why}"
        rec["watchdog"] = True
        self.watchdog_total += 1
        if cleanup and act.handle is not None and act.handle.address_port \
                and self.launcher is not None:
            # Undo the half-made pod: the launcher tears down whatever
            # came up (the endpoint was registered draining, so no pick
            # ever reached it).
            with contextlib.suppress(Exception):
                self.launcher.retire(act.handle.address_port)
        key = ("worker" if act.kind in (SPAWN_WORKER, RETIRE_WORKER)
               else f"pod:{act.role}")
        self._breaker(key).record_failure()
        self._finalize(rec, ABORTED)
        self._pending = None

    # ---- rollback + judging ---------------------------------------------

    def _check_rollback(self, now: float, census: dict[str, Any]) -> None:
        """Burn-rate trip or attainment collapse inside the post-action
        observation window: reverse the last completed action and freeze."""
        if self.frozen or self._pending is not None or not self._observing:
            return
        rec = self._observing[-1]
        if now > rec["observe_until"] or rec.get("rollback_of") is not None:
            return
        base = rec.get("baseline") or {}
        tripped = None
        if (self.burn_fn is not None and self.burn_fn()
                and not base.get("burn")):
            tripped = "burn-rate monitor tripped"
        elif self.attainment_fn is not None:
            att = self.attainment_fn()
            base_att = base.get("attainment")
            was_healthy = (base_att is None
                           or base_att >= self.cfg.rollback_attainment)
            if (att is not None and att < self.cfg.rollback_attainment
                    and was_healthy):
                tripped = (f"attainment {att:.3f} < rollbackAttainment "
                           f"{self.cfg.rollback_attainment}")
        if tripped is None:
            return
        reason = (f"{tripped} within {self.cfg.observation_window_s}s of "
                  f"action #{rec['id']} ({rec['kind']} {rec['role']})")
        rec["state"] = ROLLED_BACK
        rec["outcome"] = "regressed"
        rec["rollback_reason"] = tripped
        AUTOSCALE_ACTIONS.labels(rec["kind"], ROLLED_BACK).inc()
        self._observing.remove(rec)
        self.rollbacks_total += 1
        self._start_reverse(rec, reason)
        self.freeze(reason)

    def _start_reverse(self, rec: dict[str, Any], reason: str) -> None:
        kind = _OPPOSITE[rec["kind"]]
        inputs = {"reverses": rec["id"], "reason": reason}
        act = _Action(kind, rec["role"], inputs=inputs, wall=self._wall(),
                      mono=self._clock(), rollback_of=rec["id"])
        if kind == RETIRE_POD:
            target = rec.get("target")
            if target is None or self.datastore is None \
                    or self.datastore.endpoint_get(target) is None:
                return      # nothing concrete to reverse
            act.target = target
            self.datastore.set_endpoint_draining(target, True)
        elif kind == SPAWN_POD:
            if self.launcher is None:
                return
            act.handle = SpawnHandle()
            try:
                act.handle = self.launcher.spawn(rec["role"])
            except Exception as e:
                self._record(kind, rec["role"], ABORTED,
                             why=f"rollback spawn failed: {e}",
                             inputs=inputs, rollback_of=rec["id"])
                return
        elif self.worker_scaler is not None:
            target = (self.worker_scaler.retire()
                      if kind == RETIRE_WORKER
                      else self.worker_scaler.restore())
            if target is None:
                self._record(kind, "worker", ABORTED,
                             why="rollback refused by the worker scaler",
                             inputs=inputs, rollback_of=rec["id"])
                return
            act.target = target
            active, _ = self.worker_scaler.counts()
            act.inputs["target_workers"] = (
                active - 1 if kind == RETIRE_WORKER else active + 1)
        else:
            return
        act.record = self._record(
            act.kind, act.role, "pending",
            why=f"rollback of action #{rec['id']}: {reason}",
            inputs=inputs, target=act.target, rollback_of=rec["id"])
        self._pending = act
        self.actions_total += 1

    def _judge_observed(self, now: float) -> None:
        """Close observation windows: judge each completed action's
        outcome against the realized headroom (predict->observe)."""
        still: list[dict[str, Any]] = []
        advice = self.advice_fn() if self.advice_fn is not None else {}
        for rec in self._observing:
            if now <= rec["observe_until"]:
                still.append(rec)
                continue
            before = rec["inputs"].get("headroom")
            after = None
            row = advice.get(rec["role"]) if rec["role"] in ROLES else None
            if row is not None:
                after = row.get("headroom")
            rec["realized_headroom"] = after
            if before is None or after is None:
                rec["outcome"] = "no_change"
            elif rec["kind"] in (SPAWN_POD, SPAWN_WORKER):
                rec["outcome"] = ("improved" if after > before + 0.01
                                  else "no_change")
            else:
                # A retire that kept headroom at/above target realized
                # its bet (capacity was surplus); one that cratered it
                # regressed — the rollback window usually catches that
                # first, this is the slow-path verdict.
                rec["outcome"] = ("regressed" if after < before - 0.25
                                  else "improved")
        self._observing = still

    # ---- preflight + dispatch -------------------------------------------

    def _update_streaks(self, advice: dict[str, Any]) -> None:
        for role in ROLES:
            row = advice.get(role) or {}
            direction = row.get("direction", "hold")
            prev_dir, n = self._streak.get(role, ("hold", 0))
            self._streak[role] = ((direction, n + 1)
                                  if direction == prev_dir
                                  else (direction, 1))

    def _budget_ok(self, now: float) -> tuple[bool, str]:
        cfg = self.cfg
        while self._window and now - self._window[0] > cfg.window_s:
            self._window.popleft()
        if len(self._window) >= cfg.max_actions_per_window:
            return False, (f"budget exhausted: {len(self._window)} actions "
                           f"in the last {cfg.window_s:.0f}s "
                           f"(max {cfg.max_actions_per_window})")
        return True, ""

    def _dwell_ok(self, dim: str, kind: str, now: float) -> tuple[bool, str]:
        last = self._last_kind.get(dim)
        if last is None:
            return True, ""
        last_kind, t = last
        if last_kind != kind and now - t < self.cfg.dwell_s:
            return False, (f"dwell: opposing action {last_kind} ran "
                           f"{now - t:.0f}s ago (< dwellS="
                           f"{self.cfg.dwell_s:.0f})")
        return True, ""

    def _consider_pods(self, advice: dict[str, Any],
                       census: dict[str, Any], now: float,
                       mono: float) -> None:
        cfg = self.cfg
        for role in ROLES:
            row = advice.get(role) or {}
            direction = row.get("direction", "hold")
            if direction not in ("up", "down"):
                self._last_refusal.pop(f"pod:{role}", None)
                continue
            kind = SPAWN_POD if direction == "up" else RETIRE_POD
            streak_dir, streak_n = self._streak.get(role, ("hold", 0))
            inputs = {
                "advice": direction, "why_advice": row.get("why"),
                "headroom": row.get("headroom"),
                "lead_s": row.get("lead_s"),
                "sustained_ticks": streak_n,
                "budget_used": len(self._window),
                "pods": census.get(role, {}).get("total", 0),
            }
            dim = f"pod:{role}"
            ok, why = self._preflight_pod(kind, role, row, streak_n,
                                          census, now)
            if not ok:
                self._refuse(dim, kind, role, why, inputs)
                continue
            self._last_refusal.pop(dim, None)
            if kind == SPAWN_POD:
                self._start_spawn_pod(role, inputs, now, mono)
            else:
                self._start_retire_pod(role, inputs, census, now, mono)
            return      # one action per tick fleet-wide

    def _preflight_pod(self, kind: str, role: str, row: dict[str, Any],
                       streak_n: int, census: dict[str, Any],
                       now: float) -> tuple[bool, str]:
        cfg = self.cfg
        if self.frozen:
            return False, f"actuator frozen: {self.frozen_reason}"
        if self.launcher is None:
            return False, "no pod launcher wired (dry-run)"
        if streak_n < cfg.sustain_ticks:
            # Streak progress lives in inputs.sustained_ticks; keeping it
            # out of the reason text lets the ledger dedup consecutive
            # not-yet-sustained refusals into one counted record.
            return False, (f"advice not sustained for sustainTicks="
                           f"{cfg.sustain_ticks} yet")
        if kind == SPAWN_POD and cfg.require_lead:
            # lead_s is the forecaster's time-to-saturation: None means
            # no saturation is projected (trend flat/rising) — refuse.
            # 0.0 means saturated NOW, the most actionable lead of all.
            lead = row.get("lead_s")
            if lead is None or lead < 0:
                return False, ("scale-up requires a projected saturation "
                               f"(forecast lead_s={lead!r})")
        n = census.get(role, {}).get("total", 0)
        if kind == SPAWN_POD and n >= cfg.max_pods_per_role:
            return False, (f"{role} already at maxPodsPerRole="
                           f"{cfg.max_pods_per_role}")
        if kind == RETIRE_POD and n <= cfg.min_pods_per_role:
            return False, (f"never retire {role}'s last pod(s): "
                           f"{n} <= minPodsPerRole={cfg.min_pods_per_role}")
        if not self._breaker(f"pod:{role}").would_allow():
            return False, (f"backoff circuit open for pod:{role} "
                           "(a previous action wedged)")
        ok, why = self._budget_ok(now)
        if not ok:
            return False, why
        return self._dwell_ok(f"pod:{role}", kind, now)

    def _start_spawn_pod(self, role: str, inputs: dict[str, Any],
                         now: float, mono: float) -> None:
        act = _Action(SPAWN_POD, role, inputs=inputs, wall=now, mono=mono)
        try:
            act.handle = self.launcher.spawn(role)
        except Exception as e:
            self._record(SPAWN_POD, role, ABORTED,
                         why=f"launcher spawn raised: {e}", inputs=inputs)
            self._breaker(f"pod:{role}").record_failure()
            self.watchdog_total += 1
            return
        act.record = self._record(
            SPAWN_POD, role, "pending",
            why=f"sustained up-advice with lead "
                f"{inputs.get('lead_s')!r}s", inputs=inputs)
        self._commit(act, f"pod:{role}", now)

    def _start_retire_pod(self, role: str, inputs: dict[str, Any],
                          census: dict[str, Any], now: float,
                          mono: float) -> None:
        pods = census.get(role, {}).get("pods") or []
        # Victim: the least-loaded pick-eligible pod of the role.
        eligible = [p for p in pods if not p.get("draining")]
        if not eligible:
            self._refuse(f"pod:{role}", RETIRE_POD, role,
                         "no pick-eligible pod to retire", inputs)
            return
        victim = min(eligible, key=lambda p: (p.get("load", 0),
                                              p["address_port"]))
        addr = victim["address_port"]
        act = _Action(RETIRE_POD, role, inputs=inputs, wall=now, mono=mono)
        act.target = addr
        self.datastore.set_endpoint_draining(addr, True)
        act.record = self._record(
            RETIRE_POD, role, "pending",
            why="sustained down-advice; draining least-loaded pod",
            inputs=inputs, target=addr)
        self._commit(act, f"pod:{role}", now)

    def _consider_workers(self, census: dict[str, Any], now: float,
                          mono: float) -> None:
        cfg = self.cfg
        if cfg.pods_per_worker <= 0 or self.worker_scaler is None:
            return
        active, provisioned = self.worker_scaler.counts()
        if provisioned <= 0:
            return      # scaler view not populated yet (HTTP refresh)
        total_pods = sum(census.get(r, {}).get("total", 0) for r in ROLES)
        want = -(-total_pods // cfg.pods_per_worker)  # ceil
        want = max(cfg.min_workers, min(want, provisioned))
        if want == active:
            self._last_refusal.pop("worker", None)
            return
        kind = SPAWN_WORKER if want > active else RETIRE_WORKER
        inputs = {"active_workers": active, "provisioned": provisioned,
                  "target_workers": want, "pods": total_pods,
                  "pods_per_worker": cfg.pods_per_worker,
                  "budget_used": len(self._window)}
        if self.frozen:
            self._refuse("worker", kind, "worker",
                         f"actuator frozen: {self.frozen_reason}", inputs)
            return
        if not self._breaker("worker").would_allow():
            self._refuse("worker", kind, "worker",
                         "backoff circuit open for the worker dimension",
                         inputs)
            return
        ok, why = self._budget_ok(now)
        if ok:
            ok, why = self._dwell_ok("worker", kind, now)
        if not ok:
            self._refuse("worker", kind, "worker", why, inputs)
            return
        self._last_refusal.pop("worker", None)
        target = (self.worker_scaler.restore() if kind == SPAWN_WORKER
                  else self.worker_scaler.retire())
        if target is None:
            self._refuse("worker", kind, "worker",
                         "worker scaler refused (leader or last worker)",
                         inputs)
            return
        act = _Action(kind, "worker", inputs=inputs, wall=now, mono=mono)
        act.target = str(target)
        act.record = self._record(
            kind, "worker", "pending",
            why=f"worker count {active} -> {want} tracks "
                f"{total_pods} pods / podsPerWorker={cfg.pods_per_worker}",
            inputs=inputs, target=str(target))
        self._commit(act, "worker", now)

    def _commit(self, act: _Action, dim: str, now: float) -> None:
        self._pending = act
        self.actions_total += 1
        self._window.append(now)
        self._last_kind[dim] = (act.kind, now)

    # ---- render ---------------------------------------------------------

    def snapshot(self, *, records_n: int | None = 64) -> dict[str, Any]:
        cfg = self.cfg
        doc: dict[str, Any] = {
            "enabled": cfg.enabled,
            "acting": self.acting,
            "config": {
                "tick_s": cfg.tick_s,
                "sustain_ticks": cfg.sustain_ticks,
                "require_lead": cfg.require_lead,
                "max_actions_per_window": cfg.max_actions_per_window,
                "window_s": cfg.window_s,
                "dwell_s": cfg.dwell_s,
                "observation_window_s": cfg.observation_window_s,
                "rollback_attainment": cfg.rollback_attainment,
                "spawn_timeout_s": cfg.spawn_timeout_s,
                "drain_timeout_s": cfg.drain_timeout_s,
                "min_pods_per_role": cfg.min_pods_per_role,
                "max_pods_per_role": cfg.max_pods_per_role,
                "pods_per_worker": cfg.pods_per_worker,
            },
            "ticks": self.ticks_total,
            "actions_total": self.actions_total,
            "refusals_total": self.refusals_total,
            "rollbacks_total": self.rollbacks_total,
            "watchdog_total": self.watchdog_total,
            "frozen": self.frozen,
            "budget": {
                "window_used": len(self._window),
                "window_max": cfg.max_actions_per_window,
            },
        }
        if self.frozen:
            doc["frozen_reason"] = self.frozen_reason
            doc["frozen_unix"] = self.frozen_unix
        if self.datastore is not None:
            doc["fleet_size"] = {
                role: row.get("total", 0)
                for role, row in self.datastore.role_census().items()}
        if self.worker_scaler is not None:
            active, provisioned = self.worker_scaler.counts()
            doc["workers"] = {"active": active, "provisioned": provisioned}
        if self._pending is not None:
            doc["pending"] = self._pending.record
        breakers = {k: b.state for k, b in self._breakers.items()
                    if b.state != "closed"}
        if breakers:
            doc["breakers"] = breakers
        records = list(self._records)
        if records_n is not None:
            records = records[-records_n:]
        doc["records"] = list(reversed(records))
        return doc


# ---------------------------------------------------------------------------
# Fleet-mode worker scaler: the acting worker's view of the supervisor's
# POST /fleet/scale surface.
# ---------------------------------------------------------------------------


class HttpWorkerScaler:
    """Worker-dimension scaler over the supervisor's admin plane. The
    actuator tick is synchronous, so this adapter is deliberately
    eventually-consistent: ``counts()`` serves a cached view refreshed in
    the background from ``/debug/fleet`` (worker states), and
    ``retire()``/``restore()`` fire the ``POST /fleet/scale`` without
    awaiting it — the action's completion (or a supervisor-side refusal)
    is observed the same way every worker action is judged: the counts
    converge to the target, or the spawn/drain watchdog times the action
    out and opens the breaker."""

    def __init__(self, host: str, port: int, token: str | None = None, *,
                 refresh_s: float = 2.0,
                 clock: Callable[[], float] = time.monotonic):
        self._base = f"http://{host}:{port}"
        self._token = token
        self._refresh_s = refresh_s
        self._clock = clock
        self._last_refresh = float("-inf")
        self._counts = (0, 0)     # (active, provisioned); 0 = unknown
        self._session: Any = None

    def counts(self) -> tuple[int, int]:
        now = self._clock()
        if now - self._last_refresh >= self._refresh_s:
            self._last_refresh = now
            self._kick(self._refresh())
        return self._counts

    def retire(self) -> str | None:
        self._kick(self._post("retire"))
        return "supervisor"   # provisional: convergence judged via counts

    def restore(self) -> str | None:
        self._kick(self._post("restore"))
        return "supervisor"

    def _kick(self, coro: Any) -> None:
        try:
            asyncio.get_running_loop().create_task(coro)
        except RuntimeError:     # no loop (sync tests): stay on cache
            coro.close()

    async def _ensure_session(self) -> Any:
        if self._session is None:
            import aiohttp

            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=5.0))
        return self._session

    async def _refresh(self) -> None:
        with contextlib.suppress(Exception):
            session = await self._ensure_session()
            async with session.get(f"{self._base}/debug/fleet") as resp:
                doc = await resp.json()
            rows = doc.get("admin") or []
            active = sum(1 for r in rows if r.get("state") == "up")
            self._counts = (active, int(doc.get("workers", len(rows))))

    async def _post(self, action: str) -> None:
        with contextlib.suppress(Exception):
            session = await self._ensure_session()
            headers = ({"x-fleet-token": self._token}
                       if self._token else {})
            async with session.post(f"{self._base}/fleet/scale",
                                    json={"action": action},
                                    headers=headers):
                pass
            self._last_refresh = float("-inf")  # re-census promptly

    async def stop(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None


# ---------------------------------------------------------------------------
# Fleet fan-in.
# ---------------------------------------------------------------------------

MERGE_RECORDS_TOTAL = 64


def merge_autoscale(docs: list[tuple[int, dict[str, Any]]]) -> dict[str, Any]:
    """Fleet /debug/autoscale: only the datalayer-owning worker acts (its
    doc carries the ledger and the live budget); the merged view tags
    every record with its shard, sums the counters, and keeps each
    shard's compact row so a non-acting follower is visibly a follower
    rather than silently empty."""
    out: dict[str, Any] = {
        "workers": len(docs),
        "enabled": any(d.get("enabled") for _, d in docs),
        "acting_shards": [s for s, d in docs if d.get("acting")],
        "frozen": any(d.get("frozen") for _, d in docs),
        "actions_total": sum(d.get("actions_total", 0) for _, d in docs),
        "refusals_total": sum(d.get("refusals_total", 0) for _, d in docs),
        "rollbacks_total": sum(d.get("rollbacks_total", 0)
                               for _, d in docs),
        "shards": {},
        "records": [],
    }
    for shard, doc in docs:
        row: dict[str, Any] = {
            "enabled": doc.get("enabled"),
            "acting": doc.get("acting"),
            "actions_total": doc.get("actions_total", 0),
            "frozen": doc.get("frozen", False),
        }
        if doc.get("frozen_reason"):
            row["frozen_reason"] = doc["frozen_reason"]
            out["frozen_reason"] = doc["frozen_reason"]
        if doc.get("fleet_size"):
            row["fleet_size"] = doc["fleet_size"]
            out["fleet_size"] = doc["fleet_size"]
        out["shards"][str(shard)] = row
        for rec in doc.get("records") or []:
            out["records"].append({**rec, "shard": shard})
    out["records"] = sorted(out["records"],
                            key=lambda r: r.get("t_unix", 0.0),
                            reverse=True)[:MERGE_RECORDS_TOTAL]
    return out
