"""Traffic forecaster & capacity observatory: judged multi-horizon
prediction over the flight recorder.

Everything the router's closed loops consume is *reactive*: the burn-rate
monitor (PR 12) and the rebalancer's scaling advice (PR 15) fire after
demand has already moved. P/D-Serve (arXiv:2408.08147) shows that at
fleet scale both the P:D ratio and the fleet size must track traffic
*before* the ramp lands — which needs a forecast, and a forecast nobody
judges is a guess. Following the repo's predict→observe sequence (PR 6
SLO predictor → judged calibration; PR 14 shadow ledger → PR 15 live
scorer), ``ForecastEngine`` rides the timeline sampler's wall-clock grid
and, every tick:

1. **joins** the forecasts whose horizon elapsed THIS bucket against the
   actual sample — signed error, |error|, the persistence-baseline error
   and the interval hit land in a bounded per-series × per-horizon error
   ledger;
2. **updates** one damped Holt-Winters model per series (level + damped
   trend + seasonal EWMA, additive): arrival rate, drain rate,
   prefill:decode token mix, per-band queue depth, gateway in-flight,
   and — when the rebalancer runs — per-role headroom. A never-seen
   seasonal slot seeds from its first residual (``y − (level+trend)``)
   so the cycle lands in the seasonal term instead of being chased by
   the level;
3. **stamps** a new forecast per horizon (default 30s / 120s / 600s)
   with a prediction interval calibrated from the measured per-horizon
   error itself (EWMA of judged |error|; until the first join, the
   one-step MAD random-walk-scaled by sqrt(steps)). Long horizons stamp
   on a decimated grid (every ``steps // 8`` ticks): a 600s-out
   forecast re-stamped every second is 600× redundant, and the stamp +
   join cost is the tick budget.

Gap discipline is the merge_timeline rule: a bucket the sampler never
produced (stalled loop, restart) or a series absent from its sample is a
GAP — forecasts that targeted it are dropped and counted
(``gap_skips``), never judged against a neighbour's value. Nothing is
interpolated.

**Skill, not vibes**: every (series, horizon) cell keeps the judged MAE
next to the MAE of the naive last-value persistence baseline stamped at
the same instant, and ``skill = 1 − MAE/MAE_persistence``. A forecaster
that cannot beat persistence shows skill ≤ 0 at ``GET /debug/forecast``
and in ``router_forecast_skill`` — visibly worthless, by design.

On top rides the **capacity observatory**: the headroom series' level +
trend project when each role crosses zero headroom
(``router_time_to_saturation_seconds{role}``), and the rebalancer's
advice rows gain ``lead_s`` + the forecast basis (/debug/rebalance) —
the input the ROADMAP item 2 autoscaler will actuate.

``forecast: {enabled: false}`` is the kill-switch (default-on, the
timeline precedent): the sampler never calls the engine, zero stamps,
``/debug/forecast`` still answers JSON. The engine has no task of its
own — it ticks inside ``TimelineSampler.tick()``, so it inherits the
grid alignment that makes fleet shards' buckets comparable, and
``merge_forecast`` fans per-shard ledgers in n-weighted.
"""

from __future__ import annotations

import dataclasses
import math
import time
from collections import deque
from statistics import NormalDist
from typing import Any, Callable

from .metrics import (
    FORECAST_COVERAGE,
    FORECAST_GAP_SKIPS,
    FORECAST_JOINS,
    FORECAST_MAE,
    FORECAST_SKILL,
    FORECAST_STAMPS,
    TIME_TO_SATURATION,
)

PREFILL, DECODE = "prefill", "decode"
CAPACITY_ROLES = (PREFILL, DECODE)

# |residual| EWMAs estimate the mean absolute deviation; for a normal
# error the central-interval z-score applies to sigma ≈ 1.2533 · MAD.
MAD_TO_SIGMA = math.sqrt(math.pi / 2.0)
# Gauge-refresh cadence in ticks: gauges × series × horizons is real
# prometheus_client work, and skill/coverage drift on a joins scale, not
# per tick — the hot path only touches flat counters and EWMAs, and the
# Prom counters flush as deltas on the same cadence (plus at render, so
# /debug and /metrics stay coherent).
METRICS_EVERY = 100
# A horizon of k grid steps stamps every max(1, k // STAMP_DECIMATE)
# ticks — i.e. ~STAMP_DECIMATE forecasts in flight per horizon at any
# instant: forecast information changes on the scale of its horizon, and
# every stamp buys a later join — both are tick-budget spend.
STAMP_DECIMATE = 4
# EWMA weights: adaptive interval width (per-horizon judged |error|) and
# the gauge-feeding error/coverage trackers.
MAD_H_ALPHA = 0.1
GAUGE_ALPHA = 0.05
# Hard ceiling on tracked series (bands and roles mint names at runtime;
# a runaway label source must not grow models unbounded). Drops count.
MAX_SERIES = 24
# Time-to-saturation values at/above this read "no saturation projected"
# (the gauge carries +Inf; JSON carries null).
TTS_CAP_S = 86400.0


@dataclasses.dataclass
class ForecastConfig:
    """The YAML ``forecast:`` section. Default-on (the timeline
    precedent); ``enabled: false`` is the kill-switch — the sampler never
    calls the engine, zero stamps, zero model state."""

    enabled: bool = True
    # Forecast horizons in seconds (each becomes a judged ledger column).
    horizons_s: tuple = (30.0, 120.0, 600.0)
    # Seasonal cycle length; 0 disables the seasonal component. The
    # default expects minutes-scale periodicity (compressed diurnal in
    # benches, thermostat-style batch cycles in production); the slot
    # count is period/tick, so a day-scale period wants a coarser tick.
    seasonal_period_s: float = 3600.0
    # Central prediction-interval coverage target in (0, 1): 0.9 means
    # the [lo, hi] band should contain ~90% of outcomes — the judged
    # coverage rate is held against exactly this number.
    intervals: float = 0.9
    # Damped-Holt-Winters smoothing weights: level, trend, seasonal, and
    # the trend damping factor (k-step trend extrapolation sums phi^i —
    # an undamped trend overshoots every ramp inflection).
    alpha: float = 0.3
    beta: float = 0.05
    gamma: float = 0.3
    damping: float = 0.9
    # Ticks of observation per series before the first stamp (a model
    # one sample old forecasts garbage; judging garbage pollutes skill).
    warmup_ticks: int = 5
    # Joined-row retention per (series, horizon) cell — the window the
    # /debug MAE / MAPE / coverage / skill stats are computed over.
    error_window: int = 240

    @classmethod
    def from_spec(cls, spec: dict[str, Any] | None) -> "ForecastConfig":
        spec = spec or {}
        horizons = spec.get("horizons")
        if horizons is None:
            horizons = [30.0, 120.0, 600.0]
        cfg = cls(
            enabled=bool(spec.get("enabled", True)),
            horizons_s=tuple(sorted(float(h) for h in horizons)),
            seasonal_period_s=float(spec.get("seasonalPeriodS", 3600.0)),
            intervals=float(spec.get("intervals", 0.9)),
            alpha=float(spec.get("alpha", 0.3)),
            beta=float(spec.get("beta", 0.05)),
            gamma=float(spec.get("gamma", 0.3)),
            damping=float(spec.get("damping", 0.9)),
            warmup_ticks=max(2, int(spec.get("warmupTicks", 5))),
            error_window=max(8, int(spec.get("errorWindow", 240))),
        )
        if not cfg.horizons_s:
            raise ValueError("forecast.horizons must name >= 1 horizon")
        if any(h <= 0 for h in cfg.horizons_s):
            raise ValueError("forecast.horizons must all be > 0 seconds")
        if cfg.seasonal_period_s < 0:
            raise ValueError("forecast.seasonalPeriodS must be >= 0")
        if not 0.0 < cfg.intervals < 1.0:
            raise ValueError("forecast.intervals must be in (0, 1)")
        for knob in ("alpha", "beta", "gamma"):
            if not 0.0 < getattr(cfg, knob) <= 1.0:
                raise ValueError(f"forecast.{knob} must be in (0, 1]")
        if not 0.0 < cfg.damping <= 1.0:
            raise ValueError("forecast.damping must be in (0, 1]")
        return cfg


class _Series:
    """One forecasted series: damped-Holt-Winters state, the latest
    stamp per horizon, and the per-horizon judged rings (the stamped
    not-yet-elapsed forecasts live in the engine's single bucket-keyed
    pending dict — one pop per tick, not one per series). Hot-path state
    lives in __slots__ and the engine loads it into locals once per tick
    — the whole engine is budgeted at <1% of the scheduler cycle
    floor."""

    __slots__ = ("level", "trend", "season", "resid_mad", "n_obs",
                 "missing", "last_y", "latest", "rings",
                 "mad_h", "mae_e", "naive_e", "cov_e")

    def __init__(self, n_horizons: int, season_slots: int, window: int):
        self.level = 0.0
        self.trend = 0.0
        # Seasonal offsets by bucket % slots; None until the slot is
        # seeded (an unseeded slot must not drag forecasts toward 0).
        self.season: list | None = ([None] * season_slots
                                    if season_slots else None)
        self.resid_mad = 0.0
        self.n_obs = 0
        self.missing = 0
        self.last_y = 0.0
        # Latest stamp per horizon: (target_bucket, yhat, half_width).
        self.latest: list = [None] * n_horizons
        # Judged rows per horizon:
        # (t_unix, actual, predicted, abs_err, naive_abs_err, covered).
        self.rings: list[deque] = [deque(maxlen=window)
                                   for _ in range(n_horizons)]
        # EWMAs: adaptive interval width + the gauge feeds (exact window
        # stats are computed from the rings at render time only).
        self.mad_h: list = [None] * n_horizons
        self.mae_e: list = [None] * n_horizons
        self.naive_e: list = [None] * n_horizons
        self.cov_e: list = [None] * n_horizons


class ForecastEngine:
    """Multi-horizon judged forecaster over the timeline grid (module
    docstring). All state mutates on the gateway's event loop inside
    ``TimelineSampler.tick()`` — single-writer, no locks, no task of its
    own. ``observe()`` is synchronous and injectable-clock testable
    through the sampler's ``tick(wall=...)``."""

    def __init__(self, cfg: ForecastConfig, *, tick_s: float = 1.0,
                 wall: Callable[[], float] = time.time):
        self.cfg = cfg
        self.tick_s = tick_s
        self._wall = wall
        # Horizons → whole grid steps (a horizon under one tick rounds up
        # to the next bucket: the soonest observable join).
        self._steps = tuple(max(1, int(round(h / tick_s)))
                            for h in cfg.horizons_s)
        self._sqrt_steps = tuple(math.sqrt(k) for k in self._steps)
        # Damped k-step trend multiplier: sum(phi^i, i=1..k).
        phi = cfg.damping
        self._trend_k = tuple(
            (phi * (1.0 - phi ** k) / (1.0 - phi)) if phi < 1.0 else float(k)
            for k in self._steps)
        self._cadence = tuple(max(1, k // STAMP_DECIMATE)
                              for k in self._steps)
        self._h_labels = tuple(
            str(int(h)) if float(h).is_integer() else str(h)
            for h in cfg.horizons_s)
        self._n_h = len(self._steps)
        self._z = NormalDist().inv_cdf(0.5 + cfg.intervals / 2.0)
        self._season_slots = (int(round(cfg.seasonal_period_s / tick_s))
                              if cfg.seasonal_period_s > 0 else 0)
        self._series: dict[str, _Series] = {}
        # All stamped, not-yet-elapsed forecasts, engine-wide: target
        # bucket -> list of (series_name, hidx, y_at_stamp, yhat, half).
        # One pop per tick judges everything that elapses here, and a
        # series absent from the sample is discovered AT the pop — the
        # gap-skip falls out of the same join attempt.
        self._pending: dict[int, list] = {}
        self._band_names: dict[Any, str] = {}
        self._role_names: dict[str, str] = {}
        self._last_bucket: int | None = None
        self._dropped_series = 0
        # Flat counters (the timeline _Baseline convention: cheap loads
        # for per-tick deltas and the /debug join-coverage math). The
        # Prometheus counters trail them by <= METRICS_EVERY ticks.
        self.ticks = 0
        self.stamps_total = 0
        self.joins_total = 0
        self.gap_skips_total = 0
        self._prom_flushed = [0, 0, 0]  # stamps, joins, gap_skips
        # role -> (tts_s | None, headroom_now, level, trend_per_s); the
        # explain dict renders lazily (role_projection / snapshot).
        self._capacity_raw: dict[str, tuple] = {}
        # Label children resolved once / on first use (metric refresh is
        # amortized over METRICS_EVERY ticks, but .labels() is a lock).
        self._g_tts = {r: TIME_TO_SATURATION.labels(r)
                       for r in CAPACITY_ROLES}
        self._g_cells: dict[tuple, tuple] = {}

    @property
    def enabled(self) -> bool:
        return self.cfg.enabled

    # ---- series extraction ----------------------------------------------

    def _extract(self, sample: dict[str, Any]) -> dict[str, float]:
        """Pull the forecastable series out of one timeline sample.
        Absent keys are absent series (a gap for that series this tick);
        a band missing from a present queued_by_band map is a real 0.
        The prefill:decode mix is the two token rates — the fraction is
        their ratio, and a forecast of a ratio is derivable from the
        forecasts of its parts."""
        tick_s = self.tick_s
        get = sample.get
        vals: dict[str, float] = {}
        v = get("requests")
        if v is not None:
            vals["arrival_rate"] = v / tick_s
        v = get("drain_rate_rps")
        if v is not None:
            vals["drain_rate_rps"] = v
        v = get("inflight")
        if v is not None:
            vals["inflight"] = v
        v = get("queued")
        if v is not None:
            vals["queued"] = v
            qb = get("queued_by_band")
            if qb is not None:
                names = self._band_names
                for b in qb:
                    if b not in names:
                        names[b] = f"queued_band_{b}"
                for b, name in names.items():
                    vals[name] = qb.get(b, 0)
        mix = get("token_mix")
        if mix is not None:
            vals["prefill_tok_rate"] = mix.get("prefill_tokens", 0) / tick_s
            vals["decode_tok_rate"] = mix.get("decode_tokens", 0) / tick_s
        rb = get("rebalance")
        if rb is not None:
            hr = rb.get("headroom")
            if hr:
                names = self._role_names
                for role, h in hr.items():
                    name = names.get(role)
                    if name is None:
                        name = names[role] = f"headroom_{role}"
                    vals[name] = h
        return vals

    # ---- one tick (called from TimelineSampler.tick) --------------------

    def observe(self, sample: dict[str, Any]) -> dict[str, Any] | None:
        """Judge elapsed forecasts against this sample, update every
        present series' model, stamp fresh forecasts, and return the
        compact per-tick row the sample embeds. Kill-switch: one
        attribute check (the sampler also holds None when disabled)."""
        cfg = self.cfg
        if not cfg.enabled:
            return None
        tick_s = self.tick_s
        t_now = sample["t_unix"]
        bucket = int(round(t_now / tick_s))
        vals = self._extract(sample)
        gap_skips = 0
        series_map = self._series
        pending = self._pending
        # Skipped buckets (a stalled loop jumping the grid) are gaps for
        # EVERY series: forecasts that targeted them can never be judged
        # — drop and count, never join against a neighbour bucket.
        if (self._last_bucket is not None
                and bucket - self._last_bucket > 1 and pending):
            stale = [b for b in pending if b < bucket]
            for b in stale:
                gap_skips += len(pending.pop(b))
        self._last_bucket = bucket
        # Missing-series bookkeeping (render-only; a series absent from
        # this sample is ALSO a gap for any forecast targeting this
        # bucket — the judge below discovers that at the join attempt).
        if len(vals) != len(series_map):
            for name, st in series_map.items():
                if name not in vals:
                    st.missing += 1
        stamps = joins = 0
        # 1) judge: one pop fetches every forecast elapsing exactly here.
        rows = pending.pop(bucket, None)
        if rows is not None:
            for name, hidx, y_stamp, yhat, half in rows:
                y = vals.get(name)
                st = series_map.get(name)
                if y is None or st is None:
                    gap_skips += 1
                    continue
                abs_err = yhat - y
                if abs_err < 0.0:
                    abs_err = -abs_err
                naive_abs = y - y_stamp
                if naive_abs < 0.0:
                    naive_abs = -naive_abs
                covered = 1 if abs_err <= half else 0
                st.rings[hidx].append(
                    (t_now, y, yhat, abs_err, naive_abs, covered))
                mad_h = st.mad_h
                m = mad_h[hidx]
                mad_h[hidx] = (abs_err if m is None
                               else m + MAD_H_ALPHA * (abs_err - m))
                mae_e = st.mae_e
                m = mae_e[hidx]
                mae_e[hidx] = (abs_err if m is None
                               else m + GAUGE_ALPHA * (abs_err - m))
                naive_e = st.naive_e
                m = naive_e[hidx]
                naive_e[hidx] = (naive_abs if m is None
                                 else m + GAUGE_ALPHA * (naive_abs - m))
                cov_e = st.cov_e
                m = cov_e[hidx]
                cov_e[hidx] = (float(covered) if m is None
                               else m + GAUGE_ALPHA * (covered - m))
                joins += 1
        n_h = self._n_h
        slots = self._season_slots
        alpha, beta, gamma = cfg.alpha, cfg.beta, cfg.gamma
        phi = cfg.damping
        warmup = cfg.warmup_ticks
        # Which horizons stamp THIS tick is a property of the bucket, not
        # the series — resolve the decimation grid once.
        cadence = self._cadence
        stamp_h = [hidx for hidx in range(n_h)
                   if not bucket % cadence[hidx]]
        if stamp_h:
            steps = self._steps
            sqrt_steps = self._sqrt_steps
            trend_k = self._trend_k
            z_mad = self._z * MAD_TO_SIGMA
        for name, y in vals.items():
            st = series_map.get(name)
            if st is None:
                if len(series_map) >= MAX_SERIES:
                    self._dropped_series += 1
                    continue
                st = series_map[name] = _Series(
                    n_h, slots, cfg.error_window)
            # 2) update the damped-HW state (hot locals, one writeback).
            level, trend = st.level, st.trend
            season = st.season
            if st.n_obs == 0:
                level, trend = y, 0.0
                if season is not None:
                    season[bucket % slots] = 0.0
            else:
                damped = trend * phi
                drift = level + damped
                if season is not None:
                    sidx = bucket % slots
                    seas = season[sidx]
                    if seas is None:
                        # First visit: the whole residual is the slot's
                        # seed, so the cycle lands in the seasonal term
                        # instead of being chased by the level.
                        seas = y - drift
                        season[sidx] = seas
                        new_level = drift
                    else:
                        new_level = alpha * (y - seas) \
                            + (1.0 - alpha) * drift
                        season[sidx] = gamma * (y - new_level) \
                            + (1.0 - gamma) * seas
                    resid = y - (drift + seas)
                else:
                    new_level = alpha * y + (1.0 - alpha) * drift
                    resid = y - drift
                trend = beta * (new_level - level) + (1.0 - beta) * damped
                level = new_level
                if resid < 0.0:
                    resid = -resid
                st.resid_mad += MAD_H_ALPHA * (resid - st.resid_mad)
            st.level, st.trend = level, trend
            st.n_obs += 1
            st.last_y = y
            # 3) stamp, on each horizon's decimated grid.
            if stamp_h and st.n_obs >= warmup:
                mad_h = st.mad_h
                latest = st.latest
                for hidx in stamp_h:
                    k = steps[hidx]
                    yhat = level + trend * trend_k[hidx]
                    if season is not None:
                        seas = season[(bucket + k) % slots]
                        if seas is not None:
                            yhat += seas
                    m = mad_h[hidx]
                    # Interval width: calibrated from this horizon's own
                    # judged errors once any exist; random-walk-scaled
                    # one-step MAD until then.
                    half = (z_mad * m if m is not None
                            else z_mad * st.resid_mad * sqrt_steps[hidx])
                    tb = bucket + k
                    row = (name, hidx, y, yhat, half)
                    entry = pending.get(tb)
                    if entry is None:
                        pending[tb] = [row]
                    else:
                        entry.append(row)
                    latest[hidx] = (tb, yhat, half)
                    stamps += 1
        self.ticks += 1
        self.stamps_total += stamps
        self.joins_total += joins
        if gap_skips:
            self.gap_skips_total += gap_skips
        if self._role_names:
            self._project_capacity()
        if self.ticks % METRICS_EVERY == 0:
            self._refresh_metrics()
        row: dict[str, Any] = {"stamps": stamps, "joins": joins}
        if gap_skips:
            row["gap_skips"] = gap_skips
        return row

    def prime(self, samples: list[dict[str, Any]]) -> int:
        """Restart resume: replay an existing timeline ring through the
        model updates WITHOUT stamping or judging (those forecasts were
        the dead process's; judging them here would double-count), so a
        rebuilt engine forecasts from live state instead of cold.
        Returns the number of samples consumed."""
        if not self.cfg.enabled:
            return 0
        n = 0
        saved = self.cfg.warmup_ticks
        try:
            # Warmup ∞: observe() with an unreachable warmup stamps
            # nothing but updates every model — exactly a replay.
            self.cfg.warmup_ticks = (1 << 62)
            for s in samples:
                if isinstance(s, dict) and "t_unix" in s:
                    self.observe(s)
                    n += 1
        finally:
            self.cfg.warmup_ticks = saved
        # The replay consumed ticks as if live; only the model state and
        # gap bookkeeping should survive it.
        self.ticks = 0
        return n

    # ---- capacity observatory -------------------------------------------

    def _project_capacity(self) -> None:
        """Per-role time-to-saturation from the headroom series' level +
        damped trend: the forecasted instant headroom crosses zero.
        Trend flat or rising → no saturation projected (gauge +Inf,
        JSON null). Hot path stores raw floats; the gauge sets ride the
        METRICS_EVERY refresh and the explain dict renders lazily."""
        for role, sname in self._role_names.items():
            st = self._series.get(sname)
            if st is None or st.n_obs < 2:
                continue
            trend_per_s = st.trend / self.tick_s
            level = st.level
            if level <= 0.0:
                tts: float | None = 0.0
            elif trend_per_s < -1e-6:
                tts = level / -trend_per_s
                if tts >= TTS_CAP_S:
                    tts = None
            else:
                tts = None
            self._capacity_raw[role] = (tts, st.last_y, level, trend_per_s)

    def _capacity_doc(self) -> dict[str, dict[str, Any]]:
        return {
            role: {
                "time_to_saturation_s": (round(tts, 1)
                                         if tts is not None else None),
                "headroom_now": round(last_y, 4),
                "headroom_level": round(level, 4),
                "trend_per_s": round(trend_per_s, 6),
                "basis": "headroom level+trend zero-crossing",
            }
            for role, (tts, last_y, level, trend_per_s)
            in self._capacity_raw.items()
        }

    def role_projection(self, role: str) -> dict[str, Any] | None:
        """The rebalancer's advice-qualification hook: the role's current
        saturation projection, or None before the headroom series has a
        model."""
        if not self.cfg.enabled:
            return None
        raw = self._capacity_raw.get(role)
        if raw is None:
            return None
        tts, last_y, level, trend_per_s = raw
        return {
            "time_to_saturation_s": (round(tts, 1)
                                     if tts is not None else None),
            "headroom_now": round(last_y, 4),
            "headroom_level": round(level, 4),
            "trend_per_s": round(trend_per_s, 6),
            "basis": "headroom level+trend zero-crossing",
        }

    # ---- metrics refresh (amortized off the hot path) -------------------

    def _refresh_metrics(self) -> None:
        # Flush the flat counters into the Prometheus families as deltas
        # (inc() takes a lock; once per METRICS_EVERY ticks, not per
        # tick). snapshot() also refreshes, so a stopped sampler still
        # converges before anyone reads.
        flushed = self._prom_flushed
        d = self.stamps_total - flushed[0]
        if d:
            FORECAST_STAMPS.inc(d)
            flushed[0] = self.stamps_total
        d = self.joins_total - flushed[1]
        if d:
            FORECAST_JOINS.inc(d)
            flushed[1] = self.joins_total
        d = self.gap_skips_total - flushed[2]
        if d:
            FORECAST_GAP_SKIPS.inc(d)
            flushed[2] = self.gap_skips_total
        for role, raw in self._capacity_raw.items():
            gauge = self._g_tts.get(role)
            if gauge is not None:
                tts = raw[0]
                gauge.set(tts if tts is not None else math.inf)
        cells = self._g_cells
        labels = self._h_labels
        for name, st in self._series.items():
            mae_e, naive_e, cov_e = st.mae_e, st.naive_e, st.cov_e
            for hidx in range(self._n_h):
                mae = mae_e[hidx]
                if mae is None:
                    continue
                key = (name, hidx)
                gauges = cells.get(key)
                if gauges is None:
                    gauges = cells[key] = (
                        FORECAST_MAE.labels(name, labels[hidx]),
                        FORECAST_SKILL.labels(name, labels[hidx]),
                        FORECAST_COVERAGE.labels(name, labels[hidx]))
                gauges[0].set(mae)
                naive = naive_e[hidx]
                if naive and naive > 1e-9:
                    gauges[1].set(1.0 - mae / naive)
                cov = cov_e[hidx]
                if cov is not None:
                    gauges[2].set(cov)

    # ---- render ---------------------------------------------------------

    @staticmethod
    def _ring_stats(ring: deque) -> dict[str, Any] | None:
        """Exact window statistics from one judged ring (render-time
        only — the hot path keeps EWMAs)."""
        n = len(ring)
        if n == 0:
            return None
        abs_sum = naive_sum = signed_sum = 0.0
        cover = 0
        pct_sum = 0.0
        pct_n = 0
        for _, y, yhat, abs_err, naive_abs, covered in ring:
            abs_sum += abs_err
            naive_sum += naive_abs
            signed_sum += yhat - y
            cover += covered
            ay = y if y >= 0.0 else -y
            if ay > 1e-9:
                pct_sum += abs_err / ay
                pct_n += 1
        mae = abs_sum / n
        naive = naive_sum / n
        return {
            "n": n,
            "mae": round(mae, 4),
            "bias": round(signed_sum / n, 4),
            "mape": round(pct_sum / pct_n, 4) if pct_n else None,
            "coverage": round(cover / n, 4),
            "naive_mae": round(naive, 4),
            "skill": (round(1.0 - mae / naive, 4) if naive > 1e-9
                      else None),
        }

    def snapshot(self, *, joins_n: int | None = None) -> dict[str, Any]:
        """The /debug/forecast payload: per-series model state, the
        latest stamped forecast per horizon, and the judged error ledger
        (MAE / MAPE / bias / interval coverage / skill vs persistence).
        ``joins_n`` additionally inlines the most recent joined rows per
        cell (the bench reads windowed skill around ramp inflections
        from them)."""
        cfg = self.cfg
        if cfg.enabled:
            self._refresh_metrics()
        pend_counts: dict[str, int] = {}
        for rows in self._pending.values():
            for row in rows:
                pend_counts[row[0]] = pend_counts.get(row[0], 0) + 1
        elapsed = self.joins_total + self.gap_skips_total
        doc: dict[str, Any] = {
            "enabled": cfg.enabled,
            "tick_s": self.tick_s,
            "horizons_s": list(cfg.horizons_s),
            "stamp_every_ticks": list(self._cadence),
            "seasonal_period_s": cfg.seasonal_period_s,
            "interval": cfg.intervals,
            "ticks": self.ticks,
            "stamps_total": self.stamps_total,
            "joins_total": self.joins_total,
            "gap_skips_total": self.gap_skips_total,
            "join_coverage": (round(self.joins_total / elapsed, 4)
                              if elapsed else None),
            "series": {},
        }
        if self._dropped_series:
            doc["dropped_series"] = self._dropped_series
        tick_s = self.tick_s
        for name, st in sorted(self._series.items()):
            row: dict[str, Any] = {
                "n_obs": st.n_obs,
                "missing_ticks": st.missing,
                "last": round(st.last_y, 4),
                "level": round(st.level, 4),
                "trend_per_s": round(st.trend / tick_s, 6),
                "resid_mad": round(st.resid_mad, 4),
                "pending": pend_counts.get(name, 0),
            }
            forecasts: dict[str, Any] = {}
            errors: dict[str, Any] = {}
            joins: dict[str, Any] = {}
            for hidx, label in enumerate(self._h_labels):
                latest = st.latest[hidx]
                if latest is not None:
                    tb, yhat, half = latest
                    forecasts[label] = {
                        "t_unix": round(tb * tick_s, 3),
                        "yhat": round(yhat, 4),
                        "lo": round(yhat - half, 4),
                        "hi": round(yhat + half, 4),
                    }
                stats = self._ring_stats(st.rings[hidx])
                if stats is not None:
                    errors[label] = stats
                if joins_n:
                    joins[label] = [
                        [round(t, 3), round(y, 4), round(yhat, 4),
                         round(abs_e, 4), round(naive, 4), cov]
                        for t, y, yhat, abs_e, naive, cov
                        in list(st.rings[hidx])[-joins_n:]]
            if forecasts:
                row["forecast"] = forecasts
            if errors:
                row["errors"] = errors
            if joins_n:
                row["joins"] = joins
            doc["series"][name] = row
        if self._capacity_raw:
            doc["capacity"] = self._capacity_doc()
        return doc

    def incident_context(self) -> dict[str, Any]:
        """The compact was-this-predicted block /debug/incidents embeds
        at trigger time: every series' active forecasts + its error
        rollup — enough to answer whether the forecaster saw the
        excursion coming, without the full ledger."""
        out: dict[str, Any] = {"enabled": self.cfg.enabled, "series": {}}
        if not self.cfg.enabled:
            return out
        tick_s = self.tick_s
        for name, st in self._series.items():
            active: dict[str, Any] = {}
            errors: dict[str, Any] = {}
            for hidx, label in enumerate(self._h_labels):
                latest = st.latest[hidx]
                if latest is not None:
                    tb, yhat, half = latest
                    active[label] = {"t_unix": round(tb * tick_s, 3),
                                     "yhat": round(yhat, 4),
                                     "lo": round(yhat - half, 4),
                                     "hi": round(yhat + half, 4)}
                mae = st.mae_e[hidx]
                if mae is not None:
                    naive = st.naive_e[hidx]
                    errors[label] = {
                        "mae": round(mae, 4),
                        "skill": (round(1.0 - mae / naive, 4)
                                  if naive and naive > 1e-9 else None),
                        "n": len(st.rings[hidx]),
                    }
            if active or errors:
                out["series"][name] = {"last": round(st.last_y, 4),
                                       "forecast": active,
                                       "errors": errors}
        if self._capacity_raw:
            out["capacity"] = self._capacity_doc()
        return out


# ---------------------------------------------------------------------------
# Fleet fan-in.
# ---------------------------------------------------------------------------

def merge_forecast(docs: list[tuple[int, dict[str, Any]]]) -> dict[str, Any]:
    """Merge N workers' /debug/forecast payloads. Each shard forecasts
    its OWN traffic slice (arrival splits across workers), so the merged
    error ledger weights every (series, horizon) cell by its join count
    — a shard with 400 judged joins moves the fleet MAE 10× more than
    one with 40 — and skill is recomputed from the merged MAE against
    the merged persistence MAE (a mean of per-shard skills would let an
    empty shard's noise vote). Capacity comes from the lowest responding
    shard that projects one (the datalayer leader's rebalancer feeds
    it)."""
    out: dict[str, Any] = {
        "workers": len(docs),
        "responding": sorted(s for s, _ in docs),
        "enabled": any(d.get("enabled") for _, d in docs),
        "shards": {},
        "series": {},
    }
    first = next((d for _, d in docs if d.get("enabled")), None)
    if first is not None:
        out["horizons_s"] = first.get("horizons_s")
        out["tick_s"] = first.get("tick_s")
    acc: dict[str, dict[str, dict[str, float]]] = {}
    joins_total = gaps_total = 0
    for shard, doc in docs:
        joins_total += doc.get("joins_total", 0)
        gaps_total += doc.get("gap_skips_total", 0)
        out["shards"][str(shard)] = {
            "enabled": doc.get("enabled"),
            "ticks": doc.get("ticks", 0),
            "stamps_total": doc.get("stamps_total", 0),
            "joins_total": doc.get("joins_total", 0),
            "gap_skips_total": doc.get("gap_skips_total", 0),
            "join_coverage": doc.get("join_coverage"),
        }
        for name, row in (doc.get("series") or {}).items():
            for label, stats in (row.get("errors") or {}).items():
                n = stats.get("n") or 0
                if n <= 0:
                    continue
                cell = acc.setdefault(name, {}).setdefault(
                    label, {"n": 0.0, "abs": 0.0, "naive": 0.0,
                            "cover": 0.0})
                cell["n"] += n
                cell["abs"] += n * (stats.get("mae") or 0.0)
                cell["naive"] += n * (stats.get("naive_mae") or 0.0)
                cell["cover"] += n * (stats.get("coverage") or 0.0)
        if "capacity" not in out and doc.get("capacity"):
            out["capacity"] = doc["capacity"]
            out["capacity_shard"] = shard
    for name, by_h in acc.items():
        merged: dict[str, Any] = {}
        for label, cell in by_h.items():
            n = cell["n"]
            mae = cell["abs"] / n
            naive = cell["naive"] / n
            merged[label] = {
                "n": int(n),
                "mae": round(mae, 4),
                "naive_mae": round(naive, 4),
                "coverage": round(cell["cover"] / n, 4),
                "skill": (round(1.0 - mae / naive, 4) if naive > 1e-9
                          else None),
            }
        out["series"][name] = merged
    elapsed = joins_total + gaps_total
    out["joins_total"] = joins_total
    out["gap_skips_total"] = gaps_total
    out["join_coverage"] = (round(joins_total / elapsed, 4)
                            if elapsed else None)
    return out
