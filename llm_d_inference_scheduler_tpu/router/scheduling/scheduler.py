"""Scheduler core: iterative profile loop → filters → weighted scorers → picker.

Mirrors /root/reference/pkg/epp/scheduling/{scheduler.go:54-102,
scheduler_profile.go:117-202}: the profile handler picks which profiles to run
until none remain, each profile runs its filter chain (short-circuit on
empty), weighted-sums scorer outputs (clamped to [0,1]), and delegates the
final choice to its picker; the handler then folds per-profile results into a
SchedulingResult.

Decision flight recorder (router/decisions.py): when the request carries a
DecisionRecord, each profile run logs per-filter drops, per-scorer
per-endpoint raw+weighted scores (top-K at render), and the picker's choice
with its win margin; the aggregate metric shadows (router_scorer_score —
sampled, router_filter_dropped_endpoints_total, router_picker_win_margin)
ride the same gate so the decisions kill-switch restores the pre-recorder
baseline. The record travels via CycleState (DECISION_STATE_KEY) so plugins
can annotate the cycle too.
"""

from __future__ import annotations

import collections.abc
import dataclasses
import itertools
import logging
import time
from typing import Any

import numpy as np

from ..decisions import DECISION_STATE_KEY
from ..framework.datalayer import Endpoint
from ..snapshot import EndpointBatch
from ..framework.scheduling import (
    CycleState,
    InferenceRequest,
    ProfileRunResult,
    ScoredEndpoint,
    SchedulingResult,
)
from ..metrics import (
    FILTER_DROPPED_TOTAL,
    PICKER_WIN_MARGIN,
    PLUGIN_DURATION_SECONDS,
    SCHEDULER_E2E_SECONDS,
    SCORER_SCORE,
)

log = logging.getLogger("router.scheduler")


class LazyScoreTable(collections.abc.Mapping):
    """A {address_port: score} view over a score vector that materializes
    its dict on first key access. Vectorized cycles hand these to
    ProfileRunResult so a recorder-off cycle builds ZERO per-key dicts; the
    gated consumers (shadow policies, cache ledger, no-hit-lru's
    pre_request probe) trigger materialization only when they actually
    read. Never flows into a DecisionRecord — the scheduler materializes
    eagerly when the recorder is on, so /debug/decisions always serializes
    plain dicts."""

    __slots__ = ("_batch", "_rows", "_vec", "_d")

    def __init__(self, batch: "EndpointBatch", rows: np.ndarray,
                 vec: np.ndarray):
        self._batch = batch
        self._rows = rows
        self._vec = vec
        self._d: dict[str, float] | None = None

    def _mat(self) -> dict[str, float]:
        d = self._d
        if d is None:
            d = self._d = dict(zip(self._batch.keys_at(self._rows),
                                   self._vec.tolist()))
        return d

    def __getitem__(self, k):
        return self._mat()[k]

    def __iter__(self):
        return iter(self._mat())

    def __len__(self):
        return len(self._vec)

    def __contains__(self, k):
        return k in self._mat()

    def get(self, k, default=None):
        return self._mat().get(k, default)

    def __eq__(self, other):
        if isinstance(other, LazyScoreTable):
            other = other._mat()
        return self._mat() == other

    __hash__ = None

    def __repr__(self):
        return repr(self._mat())


@dataclasses.dataclass
class WeightedScorer:
    scorer: Any
    weight: float = 1.0


class SchedulerProfile:
    def __init__(self, name: str, filters: list[Any], scorers: list[WeightedScorer],
                 picker: Any):
        self.name = name
        self.filters = filters
        self.scorers = scorers
        self.picker = picker
        # Metric label children resolved once (labels() hashes + locks per
        # call — measurable when the recorder observes per endpoint).
        self._filter_meta = [(f, str(f.typed_name()),
                              FILTER_DROPPED_TOTAL.labels(str(f.typed_name())))
                             for f in filters]
        self._scorer_meta = [(ws, str(ws.scorer.typed_name()),
                              SCORER_SCORE.labels(str(ws.scorer.typed_name())))
                             for ws in scorers]
        self._picker_name = str(picker.typed_name())
        self._picker_margin = PICKER_WIN_MARGIN.labels(self._picker_name)
        # Per-endpoint score observations are sampled 1-in-N: the decision
        # record keeps every score (zero-copy), but feeding each of
        # |scorers| × |candidates| values through a prometheus histogram
        # every cycle is the recorder's single biggest CPU cost, and the
        # distribution converges just as well sampled. itertools.count:
        # its __next__ is C-level GIL-atomic, so concurrent cycles on
        # scheduler-pool workers (router/schedpool.py) never lose ticks the
        # way a Python read-modify-write would — the profile itself must
        # honor the THREAD_SAFE contract it imposes on its plugins. Counts
        # from 0 so the very first recorded cycle observes (test
        # determinism).
        self._obs_counter = itertools.count()
        # Per-plugin duration observations ride the same scheme: a cycle
        # with 1 filter + 2 scorers + picker used to pay 18 monotonic reads
        # and 9 histogram observes per request; sampled 1-in-N the latency
        # distributions converge identically while the hot path keeps only
        # the e2e pair.
        self._dur_counter = itertools.count()

    # Sampling period for router_scorer_score observations (see __init__).
    SCORE_OBS_SAMPLE = 8
    # Sampling period for router_plugin_duration_seconds observations.
    DURATION_OBS_SAMPLE = 8

    def run(self, ctx: Any, request: InferenceRequest, state: CycleState,
            endpoints: list[Endpoint]) -> ProfileRunResult | None:
        if isinstance(endpoints, EndpointBatch):
            return self._run_batch(ctx, request, state, endpoints)
        # Plugins shared across profiles (one instance per pluginRef) can
        # read which profile pass they are scoring (e.g. no-hit-lru records
        # its cold decision per profile).
        state.write("current_profile", self.name)
        rec = state.read(DECISION_STATE_KEY)
        rec_sec = (rec.begin_profile(self.name, len(endpoints))
                   if rec is not None else None)
        # Per-plugin duration observes are sampled (see __init__); a skipped
        # cycle does zero monotonic reads for them.
        observe_dur = next(self._dur_counter) % self.DURATION_OBS_SAMPLE == 0
        candidates = endpoints
        # address_port keys re-snapshotted after every filter (cheap now
        # that the property is cached on the metadata): filters may drop,
        # reorder, or mutate the list in place — only the length is trusted
        # to detect drops (a filter returns a permutation of a subset of
        # its input, so equal length ⇒ nothing dropped).
        keys = [ep.metadata.address_port for ep in candidates]
        for f, fname, drop_counter in self._filter_meta:
            prev_keys = keys
            t0 = time.monotonic() if observe_dur else 0.0
            candidates = f.filter(ctx, state, request, candidates)
            if observe_dur:
                PLUGIN_DURATION_SECONDS.labels("filter", fname).observe(
                    time.monotonic() - t0)
            keys = [ep.metadata.address_port for ep in candidates]
            # Drop bookkeeping + aggregate shadow metrics ride the recorder
            # gate: the decisions kill-switch must restore the pre-recorder
            # baseline, so nothing here runs when it is off — and the
            # kept/dropped set rebuild is skipped when nothing was dropped.
            if rec_sec is not None:
                if len(keys) == len(prev_keys):
                    rec.profile_filter(rec_sec, fname, len(prev_keys),
                                       keys, [])
                else:
                    kept = set(keys)
                    dropped = [k for k in prev_keys if k not in kept]
                    if dropped:
                        drop_counter.inc(len(dropped))
                    rec.profile_filter(rec_sec, fname, len(prev_keys),
                                       keys, dropped)
            if not candidates:
                log.debug("profile %s: filter %s emptied the candidate set",
                          self.name, f.typed_name())
                if rec_sec is not None:
                    rec_sec["outcome"] = "filtered_empty"
                return None

        observe_scores = False
        if rec_sec is not None:
            observe_scores = (
                next(self._obs_counter) % self.SCORE_OBS_SAMPLE == 0)
        totals: dict[str, float] = dict.fromkeys(keys, 0.0)
        raw_scores: dict[str, dict[str, float]] = {}
        for ws, sname, score_hist in self._scorer_meta:
            t0 = time.monotonic() if observe_dur else 0.0
            scores = ws.scorer.score(ctx, state, request, candidates)
            if observe_dur:
                PLUGIN_DURATION_SECONDS.labels("scorer", sname).observe(
                    time.monotonic() - t0)
            raw_scores[sname] = scores
            if rec_sec is not None:
                # The record keeps every score (zero-copy: the scorer result
                # dict is referenced); the histogram shadow is sampled.
                if observe_scores:
                    for key in totals:
                        s = min(max(scores.get(key, 0.0), 0.0), 1.0)
                        totals[key] += ws.weight * s
                        score_hist.observe(s)
                else:
                    for key in totals:
                        s = min(max(scores.get(key, 0.0), 0.0), 1.0)
                        totals[key] += ws.weight * s
                rec.profile_scorer(rec_sec, sname, ws.weight, scores)
            else:
                for key in totals:
                    s = min(max(scores.get(key, 0.0), 0.0), 1.0)  # clamp [0,1]
                    totals[key] += ws.weight * s

        scored = [ScoredEndpoint(ep, totals[k])
                  for ep, k in zip(candidates, keys)]
        pname = self._picker_name
        t0 = time.monotonic() if observe_dur else 0.0
        picked = self.picker.pick(ctx, state, request, scored)
        if observe_dur:
            PLUGIN_DURATION_SECONDS.labels("picker", pname).observe(
                time.monotonic() - t0)
        if rec_sec is not None:
            picked_keys = [ep.metadata.address_port for ep in picked]
            if picked and len(totals) > 1:
                winner = totals[picked_keys[0]]
                runner_up = max(v for k, v in totals.items()
                                if k != picked_keys[0])
                self._picker_margin.observe(max(winner - runner_up, 0.0))
            rec.profile_picker(rec_sec, pname, picked_keys, totals)
        if not picked:
            return None
        return ProfileRunResult(target_endpoints=picked,
                                raw_scores=raw_scores, totals=totals)

    # ---- vectorized (columnar) cycle -----------------------------------
    #
    # One row per endpoint over the snapshot's PoolColumns: filters reduce a
    # row-index array with boolean masks, scorers contribute whole-pool
    # score vectors, the weighted sum is one fused multiply-add pass, and
    # the picker argmax/top-Ks the total vector. Every in-tree plugin may
    # expose a batch kernel (filter_batch / score_batch / pick_batch); a
    # plugin without one — or one that DECLINES by returning None (e.g. a
    # NaN pool where Python's order-dependent min/max semantics can't be
    # reproduced in array form) — falls back to its scalar method through
    # the auto-adapter below, bit-identically. The scalar path above and
    # this path produce identical picks, identical DecisionRecord tables,
    # and identical sampled metric observations: the float ops are the same
    # IEEE ops in the same order, the RNG draw sequences are identical, and
    # the shared sampling counters advance identically.

    def _run_batch(self, ctx: Any, request: InferenceRequest,
                   state: CycleState, batch: EndpointBatch
                   ) -> ProfileRunResult | None:
        state.write("current_profile", self.name)
        cols = batch.columns
        rows = batch.all_rows()
        rec = state.read(DECISION_STATE_KEY)
        rec_sec = (rec.begin_profile(self.name, len(rows))
                   if rec is not None else None)
        observe_dur = next(self._dur_counter) % self.DURATION_OBS_SAMPLE == 0
        # Key list maintained only when the recorder needs per-filter
        # kept/dropped bookkeeping (matches the scalar path's rec gate).
        keys = batch.keys_at(rows) if rec_sec is not None else None
        row_of = None
        for f, fname, drop_counter in self._filter_meta:
            prev_keys = keys
            t0 = time.monotonic() if observe_dur else 0.0
            kern = getattr(f, "filter_batch", None)
            mask = kern(ctx, state, request, batch, rows) \
                if kern is not None else None
            if mask is not None:
                new_rows = rows[mask]
            else:
                # Auto-adapter: scalar filter over materialized views; its
                # output order is preserved by mapping back to rows.
                kept = f.filter(ctx, state, request,
                                batch.endpoints_at(rows))
                if row_of is None:
                    row_of = cols.row_of()
                new_rows = np.fromiter(
                    (row_of[ep.metadata.address_port] for ep in kept),
                    dtype=np.int64, count=len(kept))
            if observe_dur:
                PLUGIN_DURATION_SECONDS.labels("filter", fname).observe(
                    time.monotonic() - t0)
            rows = new_rows
            if rec_sec is not None:
                keys = batch.keys_at(rows)
                if len(keys) == len(prev_keys):
                    rec.profile_filter(rec_sec, fname, len(prev_keys),
                                       keys, [])
                else:
                    kept_set = set(keys)
                    dropped = [k for k in prev_keys if k not in kept_set]
                    if dropped:
                        drop_counter.inc(len(dropped))
                    rec.profile_filter(rec_sec, fname, len(prev_keys),
                                       keys, dropped)
            if len(rows) == 0:
                log.debug("profile %s: filter %s emptied the candidate set",
                          self.name, f.typed_name())
                if rec_sec is not None:
                    rec_sec["outcome"] = "filtered_empty"
                return None

        observe_scores = False
        if rec_sec is not None:
            observe_scores = (
                next(self._obs_counter) % self.SCORE_OBS_SAMPLE == 0)
        n = len(rows)
        acc = np.zeros(n, dtype=np.float64)
        raw_scores: dict[str, dict[str, float]] = {}
        for ws, sname, score_hist in self._scorer_meta:
            t0 = time.monotonic() if observe_dur else 0.0
            kern = getattr(ws.scorer, "score_batch", None)
            vec = kern(ctx, state, request, batch, rows) \
                if kern is not None else None
            if vec is None:
                if keys is None:
                    keys = batch.keys_at(rows)
                scores = ws.scorer.score(ctx, state, request,
                                         batch.endpoints_at(rows))
                vec = np.fromiter((scores.get(k, 0.0) for k in keys),
                                  dtype=np.float64, count=n)
            elif rec_sec is not None:
                # Recorder on: the decision record serializes the table, so
                # materialize the plain dict now (one zip at C speed).
                if keys is None:
                    keys = batch.keys_at(rows)
                scores = dict(zip(keys, vec.tolist()))
            else:
                # Kernel result, recorder off: the per-key view (shadow
                # policies / cache ledger / pre_request probes) stays a
                # lazy table — nothing is built unless a consumer reads.
                scores = LazyScoreTable(batch, rows, vec)
            if observe_dur:
                PLUGIN_DURATION_SECONDS.labels("scorer", sname).observe(
                    time.monotonic() - t0)
            raw_scores[sname] = scores
            # min(max(s, 0.0), 1.0) ≡ np.clip elementwise, NaN included
            # (both propagate a NaN score unchanged).
            clamped = np.clip(vec, 0.0, 1.0)
            acc = acc + ws.weight * clamped
            if rec_sec is not None:
                if observe_scores:
                    for s in clamped.tolist():
                        score_hist.observe(s)
                rec.profile_scorer(rec_sec, sname, ws.weight, scores)

        pname = self._picker_name
        t0 = time.monotonic() if observe_dur else 0.0
        kern = getattr(self.picker, "pick_batch", None)
        picked_pos = kern(ctx, state, request, acc) \
            if kern is not None else None
        if picked_pos is not None:
            picked = batch.endpoints_at([int(rows[p]) for p in picked_pos])
        else:
            totals_list = acc.tolist()
            scored = [ScoredEndpoint(ep, s) for ep, s in
                      zip(batch.endpoints_at(rows), totals_list)]
            picked = self.picker.pick(ctx, state, request, scored)
        if observe_dur:
            PLUGIN_DURATION_SECONDS.labels("picker", pname).observe(
                time.monotonic() - t0)
        totals = (dict(zip(keys, acc.tolist())) if rec_sec is not None
                  else LazyScoreTable(batch, rows, acc))
        if rec_sec is not None:
            picked_keys = [ep.metadata.address_port for ep in picked]
            if picked and len(totals) > 1:
                winner = totals[picked_keys[0]]
                runner_up = max(v for k, v in totals.items()
                                if k != picked_keys[0])
                self._picker_margin.observe(max(winner - runner_up, 0.0))
            rec.profile_picker(rec_sec, pname, picked_keys, totals)
        if not picked:
            return None
        return ProfileRunResult(target_endpoints=picked,
                                raw_scores=raw_scores, totals=totals)


class Scheduler:
    def __init__(self, profiles: dict[str, SchedulerProfile], profile_handler: Any):
        self.profiles = profiles
        self.profile_handler = profile_handler

    def schedule(self, ctx: Any, request: InferenceRequest,
                 candidates: list[Endpoint]) -> SchedulingResult:
        t_start = time.monotonic()
        state = CycleState()
        rec = getattr(request, "decision", None)
        if rec is not None:
            state.write(DECISION_STATE_KEY, rec)
            rec.begin_round("reschedule" if rec.rounds else "schedule",
                            len(candidates))
        results: dict[str, ProfileRunResult] = {}
        while True:
            to_run = self.profile_handler.pick_profiles(
                ctx, request, {n: p for n, p in self.profiles.items() if n not in results},
                results)
            if not to_run:
                break
            for name, profile in to_run.items():
                res = profile.run(ctx, request, state, candidates)
                results[name] = res  # None marks a failed/empty profile
        result = self.profile_handler.process_results(ctx, request, results)
        SCHEDULER_E2E_SECONDS.observe(time.monotonic() - t_start)
        return result
