"""Scheduler core: iterative profile loop → filters → weighted scorers → picker.

Mirrors /root/reference/pkg/epp/scheduling/{scheduler.go:54-102,
scheduler_profile.go:117-202}: the profile handler picks which profiles to run
until none remain, each profile runs its filter chain (short-circuit on
empty), weighted-sums scorer outputs (clamped to [0,1]), and delegates the
final choice to its picker; the handler then folds per-profile results into a
SchedulingResult.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any

from ..framework.datalayer import Endpoint
from ..framework.scheduling import (
    CycleState,
    InferenceRequest,
    ProfileRunResult,
    ScoredEndpoint,
    SchedulingResult,
)
from ..metrics import SCHEDULER_E2E_SECONDS, PLUGIN_DURATION_SECONDS

log = logging.getLogger("router.scheduler")


@dataclasses.dataclass
class WeightedScorer:
    scorer: Any
    weight: float = 1.0


class SchedulerProfile:
    def __init__(self, name: str, filters: list[Any], scorers: list[WeightedScorer],
                 picker: Any):
        self.name = name
        self.filters = filters
        self.scorers = scorers
        self.picker = picker

    def run(self, ctx: Any, request: InferenceRequest, state: CycleState,
            endpoints: list[Endpoint]) -> ProfileRunResult | None:
        # Plugins shared across profiles (one instance per pluginRef) can
        # read which profile pass they are scoring (e.g. no-hit-lru records
        # its cold decision per profile).
        state.write("current_profile", self.name)
        candidates = endpoints
        for f in self.filters:
            t0 = time.monotonic()
            candidates = f.filter(ctx, state, request, candidates)
            PLUGIN_DURATION_SECONDS.labels("filter", str(f.typed_name())).observe(
                time.monotonic() - t0)
            if not candidates:
                log.debug("profile %s: filter %s emptied the candidate set",
                          self.name, f.typed_name())
                return None

        totals: dict[str, float] = {ep.metadata.address_port: 0.0 for ep in candidates}
        raw_scores: dict[str, dict[str, float]] = {}
        for ws in self.scorers:
            t0 = time.monotonic()
            scores = ws.scorer.score(ctx, state, request, candidates)
            PLUGIN_DURATION_SECONDS.labels("scorer", str(ws.scorer.typed_name())).observe(
                time.monotonic() - t0)
            raw_scores[str(ws.scorer.typed_name())] = scores
            for key in totals:
                s = min(max(scores.get(key, 0.0), 0.0), 1.0)  # clamp to [0,1]
                totals[key] += ws.weight * s

        scored = [ScoredEndpoint(ep, totals[ep.metadata.address_port])
                  for ep in candidates]
        t0 = time.monotonic()
        picked = self.picker.pick(ctx, state, request, scored)
        PLUGIN_DURATION_SECONDS.labels("picker", str(self.picker.typed_name())).observe(
            time.monotonic() - t0)
        if not picked:
            return None
        return ProfileRunResult(target_endpoints=picked, raw_scores=raw_scores)


class Scheduler:
    def __init__(self, profiles: dict[str, SchedulerProfile], profile_handler: Any):
        self.profiles = profiles
        self.profile_handler = profile_handler

    def schedule(self, ctx: Any, request: InferenceRequest,
                 candidates: list[Endpoint]) -> SchedulingResult:
        t_start = time.monotonic()
        state = CycleState()
        results: dict[str, ProfileRunResult] = {}
        while True:
            to_run = self.profile_handler.pick_profiles(
                ctx, request, {n: p for n, p in self.profiles.items() if n not in results},
                results)
            if not to_run:
                break
            for name, profile in to_run.items():
                res = profile.run(ctx, request, state, candidates)
                results[name] = res  # None marks a failed/empty profile
        result = self.profile_handler.process_results(ctx, request, results)
        SCHEDULER_E2E_SECONDS.observe(time.monotonic() - t_start)
        return result
