from .scheduler import Scheduler, SchedulerProfile, WeightedScorer

__all__ = ["Scheduler", "SchedulerProfile", "WeightedScorer"]
