"""Well-known endpoint attribute keys shared between producers and scorers
(reference: framework/plugins/datalayer/attribute/*)."""

from __future__ import annotations

import dataclasses

PREFIX_ATTRIBUTE_KEY = "attribute/prefix"
INFLIGHT_ATTRIBUTE_KEY = "attribute/concurrency"
LATENCY_ATTRIBUTE_KEY = "attribute/latency"

AVG_CHARS_PER_TOKEN = 4  # reference prefix_based_pd_decider.go:23


def estimate_input_tokens(request) -> int:
    """Shared token estimate: exact when a tokenized prompt is present,
    chars/4 heuristic otherwise (never below 1)."""
    if request.body.tokenized_prompt is not None:
        return max(len(request.body.tokenized_prompt), 1)
    return max(len(request.body.prompt_text()) // AVG_CHARS_PER_TOKEN, 1)


@dataclasses.dataclass
class PrefixCacheMatchInfo:
    match_blocks: int
    total_blocks: int
    block_size_tokens: int

    def clone(self) -> "PrefixCacheMatchInfo":
        return dataclasses.replace(self)

    @property
    def hit_ratio(self) -> float:
        return self.match_blocks / self.total_blocks if self.total_blocks else 0.0


@dataclasses.dataclass
class InFlightLoad:
    requests: int = 0
    tokens: int = 0

    def clone(self) -> "InFlightLoad":
        return dataclasses.replace(self)


@dataclasses.dataclass
class LatencyPredictionInfo:
    """Per-endpoint TTFT/TPOT prediction vs the request's SLO (reference:
    framework/plugins/datalayer/attribute/latency — LatencyPredictionInfo).

    Headroom = SLO − predicted, in ms: positive meets the SLO, negative
    violates it. With no SLO header set, headroom = −predicted (always
    negative), which makes downstream plugins rank by raw predicted latency.
    """

    ttft_ms: float = 0.0
    tpot_ms: float = 0.0
    ttft_headroom_ms: float = 0.0
    tpot_headroom_ms: float = 0.0
    ttft_valid: bool = False          # TTFT within SLO?
    tpot_valid: bool = False          # TPOT within SLO (or neutralized)?
    # Requests dispatched by THIS router instance (more current than the
    # scraped running_requests_size).
    dispatched: int = 0

    @property
    def is_valid(self) -> bool:
        return self.ttft_valid and self.tpot_valid

    def clone(self) -> "LatencyPredictionInfo":
        return dataclasses.replace(self)
