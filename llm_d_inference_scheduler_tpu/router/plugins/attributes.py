"""Well-known endpoint attribute keys shared between producers and scorers
(reference: framework/plugins/datalayer/attribute/*)."""

from __future__ import annotations

import dataclasses

PREFIX_ATTRIBUTE_KEY = "attribute/prefix"
INFLIGHT_ATTRIBUTE_KEY = "attribute/concurrency"

AVG_CHARS_PER_TOKEN = 4  # reference prefix_based_pd_decider.go:23


def estimate_input_tokens(request) -> int:
    """Shared token estimate: exact when a tokenized prompt is present,
    chars/4 heuristic otherwise (never below 1)."""
    if request.body.tokenized_prompt is not None:
        return max(len(request.body.tokenized_prompt), 1)
    return max(len(request.body.prompt_text()) // AVG_CHARS_PER_TOKEN, 1)


@dataclasses.dataclass
class PrefixCacheMatchInfo:
    match_blocks: int
    total_blocks: int
    block_size_tokens: int

    def clone(self) -> "PrefixCacheMatchInfo":
        return dataclasses.replace(self)

    @property
    def hit_ratio(self) -> float:
        return self.match_blocks / self.total_blocks if self.total_blocks else 0.0


@dataclasses.dataclass
class InFlightLoad:
    requests: int = 0
    tokens: int = 0

    def clone(self) -> "InFlightLoad":
        return dataclasses.replace(self)
