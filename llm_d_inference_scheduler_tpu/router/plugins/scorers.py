"""Scheduling scorers — all return {address_port: score in [0,1]}
(reference: framework/plugins/scheduling/scorer/*, SURVEY §2.7)."""

from __future__ import annotations

from typing import Any

import numpy as np

from ..framework.datalayer import Endpoint
from ..framework.plugin import PluginBase, register_plugin
from ..framework.scheduling import CycleState, InferenceRequest
from ..shadow import transfer_pair_scores
from .attributes import (
    INFLIGHT_ATTRIBUTE_KEY,
    PREFIX_ATTRIBUTE_KEY,
    InFlightLoad,
    PrefixCacheMatchInfo,
    estimate_input_tokens,
)


def _normalized_inverse(values: dict[str, float]) -> dict[str, float]:
    """Lower raw value → higher score; equal values → all 1.0."""
    if not values:
        return {}
    lo, hi = min(values.values()), max(values.values())
    if hi == lo:
        return {k: 1.0 for k in values}
    return {k: (hi - v) / (hi - lo) for k, v in values.items()}


def _normalized_inverse_vec(vals: np.ndarray) -> np.ndarray | None:
    """Vector twin of _normalized_inverse — same IEEE ops, so scores are
    bit-identical. Declines (None) on NaN input: Python's min/max over a
    dict is order-dependent with NaN, so only the scalar path is
    authoritative there."""
    if vals.size == 0:
        return vals
    if np.isnan(vals).any():
        return None
    lo = vals.min()
    hi = vals.max()
    if hi == lo:
        return np.ones(vals.size, dtype=np.float64)
    return (hi - vals) / (hi - lo)


@register_plugin("transfer-aware-pair-scorer")
class TransferAwarePairScorer(PluginBase):
    """Transfer-cost-aware joint P/D pair scoring (NetKV, arXiv:2606.03910
    — ROADMAP item 2): scores PREFILL candidates by the measured KV-pull
    cost of the (candidate, chosen-decode) pair, read from the Datastore's
    per-pair TransferTable EWMAs. The decode pick the disagg handler
    stamped (``request.decode_pick``) fixes the other half of the pair, so
    adding this scorer to the prefill profile makes the pick jointly
    pair-aware.

    The scoring function is shared with the ``transfer-pair`` shadow
    policy (router/shadow.py ``transfer_pair_scores``) — the shadow ledger
    proves this scorer's regret curve BEFORE a config activates it live
    (docs/shadow.md). No signal (no decode pick / no measured pairs yet)
    scores nothing: the base scorers keep ranking alone."""

    # Audited: score() reads one request attribute and the TransferTable's
    # plain dict + stat fields — each access is a GIL-atomic load, and the
    # gateway's loop-bound writer never tears a row mid-read.
    THREAD_SAFE = True

    def __init__(self, name: str | None = None):
        super().__init__(name)
        self._datastore: Any = None

    def configure(self, params: dict[str, Any], handle: Any) -> None:
        self._datastore = getattr(handle, "datastore", None)

    def score(self, ctx, state, request, endpoints):
        decode = getattr(request, "decode_pick", None)
        if self._datastore is None or not decode:
            return {}
        scores = transfer_pair_scores(
            self._datastore.transfers, decode,
            [ep.metadata.address_port for ep in endpoints])
        return scores or {}


@register_plugin("queue-scorer", "queue")
class QueueScorer(PluginBase):
    """Inverse waiting-queue depth (reference scorer/queuedepth)."""

    # Thread-safety audit (scheduler-pool offload, router/schedpool.py):
    # metrics/attribute reads only — declared on each stateless scorer.
    THREAD_SAFE = True

    def score(self, ctx, state, request, endpoints):
        return _normalized_inverse(
            {ep.metadata.address_port: float(ep.metrics.waiting_queue_size)
             for ep in endpoints})

    def score_batch(self, ctx, state, request, batch, rows):
        return _normalized_inverse_vec(
            batch.columns.num["waiting_queue_size"][rows])


@register_plugin("kv-cache-utilization-scorer", "kv-cache-scorer")
class KvCacheUtilizationScorer(PluginBase):
    """1 − KV cache usage (reference scorer/kvcacheutilization)."""

    THREAD_SAFE = True

    def score(self, ctx, state, request, endpoints):
        return {ep.metadata.address_port:
                min(max(1.0 - ep.metrics.kv_cache_usage_percent, 0.0), 1.0)
                for ep in endpoints}

    def score_batch(self, ctx, state, request, batch, rows):
        # np.clip matches min(max(x, 0), 1) bit-for-bit, NaN included
        # (both propagate NaN through the comparisons).
        usage = batch.columns.num["kv_cache_usage_percent"][rows]
        return np.clip(1.0 - usage, 0.0, 1.0)


@register_plugin("running-requests-size-scorer")
class RunningRequestsScorer(PluginBase):
    THREAD_SAFE = True

    def score(self, ctx, state, request, endpoints):
        return _normalized_inverse(
            {ep.metadata.address_port: float(ep.metrics.running_requests_size)
             for ep in endpoints})

    def score_batch(self, ctx, state, request, batch, rows):
        return _normalized_inverse_vec(
            batch.columns.num["running_requests_size"][rows])


@register_plugin("load-aware-scorer")
class LoadAwareScorer(PluginBase):
    """Queue depth against a saturation threshold (reference scorer/loadaware):
    score = max(0, 1 - queue/threshold)."""

    THREAD_SAFE = True

    def __init__(self, name: str | None = None):
        super().__init__(name)
        self.queue_threshold = 128

    def configure(self, params: dict[str, Any], handle: Any) -> None:
        self.queue_threshold = int(params.get("queueDepthThreshold", self.queue_threshold))

    def score(self, ctx, state, request, endpoints):
        t = max(self.queue_threshold, 1)
        return {ep.metadata.address_port:
                max(0.0, 1.0 - ep.metrics.waiting_queue_size / t)
                for ep in endpoints}

    def score_batch(self, ctx, state, request, batch, rows):
        q = batch.columns.num["waiting_queue_size"][rows]
        if np.isnan(q).any():
            # Scalar max(0.0, nan) yields 0.0 (Python returns the first
            # operand on an unordered compare) while np.maximum propagates
            # NaN — decline so the authoritative scalar path decides.
            return None
        t = max(self.queue_threshold, 1)
        return np.maximum(1.0 - q / t, 0.0)


@register_plugin("prefix-cache-scorer", "prefix-cache")
class PrefixCacheScorer(PluginBase):
    """Approximate prefix-match ratio from the approx-prefix-cache-producer's
    PrefixCacheMatchInfo attribute (reference scorer/prefix)."""

    THREAD_SAFE = True

    def consumes(self) -> list[str]:
        return [PREFIX_ATTRIBUTE_KEY]

    def score(self, ctx, state, request, endpoints):
        out = {}
        for ep in endpoints:
            info: PrefixCacheMatchInfo | None = ep.attributes.get(PREFIX_ATTRIBUTE_KEY)
            out[ep.metadata.address_port] = info.hit_ratio if info else 0.0
        return out

    def score_batch(self, ctx, state, request, batch, rows):
        # Attribute-backed: still one Python pass over the per-request
        # views (producer writes land on their overlays, so the base
        # columns alone are blind to them), but peek() borrows the stored
        # value instead of clone-per-read and no dict is built.
        view_row = batch.view_row
        out = np.empty(len(rows), dtype=np.float64)
        for i, r in enumerate(rows.tolist()):
            info = view_row(r).attributes.peek(PREFIX_ATTRIBUTE_KEY)
            out[i] = info.hit_ratio if info else 0.0
        return out


@register_plugin("active-request-scorer")
class ActiveRequestScorer(PluginBase):
    """EPP-side in-flight request count from inflight-load-producer
    (reference scorer/activerequest)."""

    THREAD_SAFE = True

    def consumes(self) -> list[str]:
        return [INFLIGHT_ATTRIBUTE_KEY]

    def score(self, ctx, state, request, endpoints):
        vals = {}
        for ep in endpoints:
            load: InFlightLoad | None = ep.attributes.get(INFLIGHT_ATTRIBUTE_KEY)
            vals[ep.metadata.address_port] = float(load.requests if load else 0)
        return _normalized_inverse(vals)

    def score_batch(self, ctx, state, request, batch, rows):
        view_row = batch.view_row
        vals = np.empty(len(rows), dtype=np.float64)
        for i, r in enumerate(rows.tolist()):
            load = view_row(r).attributes.peek(INFLIGHT_ATTRIBUTE_KEY)
            vals[i] = float(load.requests if load else 0)
        return _normalized_inverse_vec(vals)


@register_plugin("token-load-scorer")
class TokenLoadScorer(PluginBase):
    """Token-weighted in-flight load (reference scorer/tokenload)."""

    THREAD_SAFE = True

    def consumes(self) -> list[str]:
        return [INFLIGHT_ATTRIBUTE_KEY]

    def score(self, ctx, state, request, endpoints):
        vals = {}
        for ep in endpoints:
            load: InFlightLoad | None = ep.attributes.get(INFLIGHT_ATTRIBUTE_KEY)
            vals[ep.metadata.address_port] = float(load.tokens if load else 0)
        return _normalized_inverse(vals)

    def score_batch(self, ctx, state, request, batch, rows):
        view_row = batch.view_row
        vals = np.empty(len(rows), dtype=np.float64)
        for i, r in enumerate(rows.tolist()):
            load = view_row(r).attributes.peek(INFLIGHT_ATTRIBUTE_KEY)
            vals[i] = float(load.tokens if load else 0)
        return _normalized_inverse_vec(vals)


@register_plugin("lora-affinity-scorer")
class LoraAffinityScorer(PluginBase):
    """Prefer pods with the requested LoRA active (1.0) or waiting (0.75),
    else pods with a free adapter slot (0.5) (reference scorer/loraaffinity)."""

    THREAD_SAFE = True

    def score(self, ctx, state, request, endpoints):
        model = request.target_model
        out = {}
        for ep in endpoints:
            m = ep.metrics
            if model in m.active_models:
                s = 1.0
            elif model in m.waiting_models:
                s = 0.75
            elif m.max_active_models and (
                    len(m.active_models) + len(m.waiting_models) < m.max_active_models):
                s = 0.5
            else:
                s = 0.0
            out[ep.metadata.address_port] = s
        return out


@register_plugin("session-affinity-scorer")
class SessionAffinityScorer(PluginBase):
    """Sticky routing via an encoded session token (reference
    scorer/sessionaffinity: base64 pod identity, session_affinity.go).
    The token is stamped after scheduling and returned to the client on the
    response (x-session-token); a client presenting it on a later request
    scores its previous endpoint 1.0. Tokens that don't decode or don't name
    a live endpoint simply score nothing (fresh placement)."""

    SESSION_HEADER = "x-session-token"
    # Audit: stateless (header decode + metadata compare).
    THREAD_SAFE = True

    @staticmethod
    def _encode(address_port: str) -> str:
        import base64

        return base64.standard_b64encode(address_port.encode()).decode()

    @staticmethod
    def _decode(token: str) -> str:
        import base64
        import binascii

        try:
            return base64.standard_b64decode(token.encode()).decode()
        except (binascii.Error, UnicodeDecodeError, ValueError):
            return ""

    def score(self, ctx, state, request, endpoints):
        target = self._decode(request.headers.get(self.SESSION_HEADER, ""))
        return {ep.metadata.address_port:
                (1.0 if target and target == ep.metadata.address_port else 0.0)
                for ep in endpoints}

    def score_batch(self, ctx, state, request, batch, rows):
        out = np.zeros(len(rows), dtype=np.float64)
        target = self._decode(request.headers.get(self.SESSION_HEADER, ""))
        if target:
            r = batch.columns.row_of().get(target)
            if r is not None:
                out[rows == r] = 1.0
        return out

    def pre_request(self, ctx, request, result) -> None:
        primary = result.primary().target_endpoints
        if primary:
            request.headers[self.SESSION_HEADER] = self._encode(
                primary[0].metadata.address_port)


@register_plugin("no-hit-lru-scorer")
class NoHitLruScorer(PluginBase):
    """For cold requests (no prefix hit on any endpoint), rank endpoints by
    how recently they last received a cold request, spreading cache growth
    across the pool (reference scorer/nohitlru/no_hit_lru.go:180-321):

    - cache hit anywhere → flat neutral 0.5;
    - cold → never-cold-routed endpoints outrank all others (1 - i/(N-1) in
      candidate order), then LRU-ordered ones (rank = neverUsed + lruPos,
      pos 0 = oldest), clamped ≥ 0; single candidate scores 1.0;
    - the cold decision is recorded at score time and consumed in
      pre_request, which moves the PRIMARY profile's pick AND the "prefill"
      profile's pick to the LRU front (both grow cache on a P/D split).
    """

    # Audit: the LRU/cold-tracking dicts are mutated with individually
    # GIL-atomic operations (get / setdefault / pop-with-default /
    # move-semantics via pop+store); concurrent cycles at worst reorder LRU
    # positions, never corrupt state. Eviction pops pass a default so two
    # threads draining the same oldest key cannot raise.
    THREAD_SAFE = True

    def __init__(self, name: str | None = None, lru_size: int = 1024):
        super().__init__(name)
        self._lru: dict[str, None] = {}   # insertion-ordered; front = oldest
        self._lru_size = lru_size
        # request id -> profile names whose score-pass was cold. Tracked per
        # profile (not a single flag) so one scorer instance shared across
        # profiles can't have a warm profile pass erase another profile's
        # cold decision (last-writer-wins would be run-order dependent).
        self._cold: dict[str, set[str]] = {}

    def consumes(self) -> list[str]:
        return [PREFIX_ATTRIBUTE_KEY]

    def _any_hit(self, endpoints) -> bool:
        for ep in endpoints:
            info: PrefixCacheMatchInfo | None = ep.attributes.get(PREFIX_ATTRIBUTE_KEY)
            if info and info.match_blocks > 0:
                return True
        return False

    def score(self, ctx, state, request, endpoints):
        profile = state.read("current_profile", "") if state else ""
        cold = not self._any_hit(endpoints)
        if not cold:
            profiles = self._cold.get(request.request_id)
            if profiles is not None:
                profiles.discard(profile)
            return {ep.metadata.address_port: 0.5 for ep in endpoints}
        while len(self._cold) > 4096:
            # Cold requests that never reached pre_request (rejected
            # post-schedule) would otherwise accumulate; evict the OLDEST
            # entries (insertion order) so in-flight requests keep theirs.
            # Default-None pop: two off-loop cycles may race to drain the
            # same oldest key.
            try:
                self._cold.pop(next(iter(self._cold)), None)
            except (StopIteration, RuntimeError):
                break
        self._cold.setdefault(request.request_id, set()).add(profile)
        n = len(endpoints)
        if n == 1:
            return {endpoints[0].metadata.address_port: 1.0}
        # LRU positions RESTRICTED to the candidate set: entries for
        # endpoints no longer in the pool must not inflate ranks.
        addrs = {ep.metadata.address_port for ep in endpoints}
        pos = {addr: i for i, addr in
               enumerate(a for a in self._lru if a in addrs)}  # 0 = oldest
        never = [ep for ep in endpoints
                 if ep.metadata.address_port not in pos]
        out: dict[str, float] = {}
        for i, ep in enumerate(never):
            out[ep.metadata.address_port] = 1.0 - i / (n - 1)
        for ep in endpoints:
            addr = ep.metadata.address_port
            if addr in pos:
                rank = len(never) + pos[addr]
                out[addr] = max(0.0, 1.0 - rank / (n - 1))
        return out

    def _touch(self, addr: str) -> None:
        self._lru.pop(addr, None)
        self._lru[addr] = None           # most-recent at the back
        while len(self._lru) > self._lru_size:
            try:
                self._lru.pop(next(iter(self._lru)), None)
            except (StopIteration, RuntimeError):
                break

    def pre_request(self, ctx, request, result) -> None:
        profiles_cold = self._cold.pop(request.request_id, None)
        if not profiles_cold:
            return
        # Reference semantics: the primary (decode) profile's decision wins
        # when that profile was scored by this plugin; otherwise any cold
        # pass counts. A cold route touches BOTH the primary and prefill
        # picks (both grow cache on a P/D split, no_hit_lru.go:180-321).
        primary = result.primary_profile_name
        pr_primary = result.profile_results.get(primary)
        scored_primary = (pr_primary is not None
                          and str(self.typed_name()) in pr_primary.raw_scores)
        if scored_primary and primary not in profiles_cold:
            return
        for profile in (primary, "prefill"):
            pr = result.profile_results.get(profile)
            if pr is not None and pr.target_endpoints:
                self._touch(pr.target_endpoints[0].metadata.address_port)


@register_plugin("context-length-aware-scorer", "context-length-aware")
class ContextLengthAwareScorer(PluginBase):
    """Route long-context requests to endpoints with token budget for them
    (reference scorer/contextlengthaware): estimated tokens vs remaining KV
    token capacity; falls back to chars/4 when no tokenization is present."""

    THREAD_SAFE = True

    def score(self, ctx, state, request, endpoints):
        need = estimate_input_tokens(request)
        out = {}
        for ep in endpoints:
            cap = ep.metrics.kv_cache_max_token_capacity
            if cap <= 0:
                out[ep.metadata.address_port] = 0.5  # unknown capacity: neutral
                continue
            free_tokens = cap * (1.0 - ep.metrics.kv_cache_usage_percent)
            out[ep.metadata.address_port] = 1.0 if need <= free_tokens else 0.0
        return out

    def score_batch(self, ctx, state, request, batch, rows):
        need = estimate_input_tokens(request)
        cols = batch.columns
        cap = cols.num["kv_cache_max_token_capacity"][rows]
        usage = cols.num["kv_cache_usage_percent"][rows]
        out = np.where(need <= cap * (1.0 - usage), 1.0, 0.0)
        out[cap <= 0] = 0.5
        return out
