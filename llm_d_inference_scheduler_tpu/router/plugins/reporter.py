"""request-attribute-reporter: per-request attribute emission.

Reference: framework/plugins/requestcontrol/requestattributereporter — emits
per-request attributes (usage, timings, decision context) to logs/metrics so
operators can trace scheduling decisions per request.
"""

from __future__ import annotations

import logging
import time
from typing import Any

from ..framework.plugin import PluginBase, register_plugin
from ..framework.scheduling import InferenceRequest, SchedulingResult

log = logging.getLogger("router.request_report")


@register_plugin("request-attribute-reporter")
class RequestAttributeReporter(PluginBase):
    def __init__(self, name: str | None = None):
        super().__init__(name)
        self.log_level = logging.INFO
        self._start_times: dict[str, float] = {}
        self._decisions: dict[str, dict[str, Any]] = {}

    def configure(self, params: dict[str, Any], handle: Any) -> None:
        if params.get("verbose"):
            self.log_level = logging.DEBUG

    def pre_request(self, ctx: Any, request: InferenceRequest,
                    result: SchedulingResult) -> None:
        self._start_times[request.request_id] = time.monotonic()
        self._decisions[request.request_id] = {
            "profiles": {name: [ep.metadata.address_port
                                for ep in r.target_endpoints]
                         for name, r in result.profile_results.items()},
            "model": request.target_model,
            "priority": request.objectives.priority,
        }

    def response_complete(self, ctx: Any, request: InferenceRequest,
                          endpoint: Any, usage: dict[str, int]) -> None:
        start = self._start_times.pop(request.request_id, None)
        decision = self._decisions.pop(request.request_id, {})
        log.log(self.log_level,
                "request=%s model=%s priority=%s endpoint=%s duration_ms=%s "
                "prompt_tokens=%s completion_tokens=%s profiles=%s",
                request.request_id, decision.get("model"),
                decision.get("priority"),
                endpoint.metadata.address_port if endpoint else None,
                round((time.monotonic() - start) * 1e3, 1) if start else None,
                usage.get("prompt_tokens"), usage.get("completion_tokens"),
                decision.get("profiles"))
