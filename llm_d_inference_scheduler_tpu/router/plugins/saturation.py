"""Saturation detectors (reference: framework/plugins/flowcontrol/
saturationdetector/{utilization,concurrency} — SURVEY §2.6).

Each detector doubles as a scheduling Filter with fail-open fallback and
exposes saturation() in [0, 1+] for the admission layer.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from ..framework.datalayer import Endpoint
from ..framework.plugin import PluginBase, register_plugin
from ..plugins.attributes import INFLIGHT_ATTRIBUTE_KEY, InFlightLoad


@register_plugin("utilization-detector", "saturation-detector")
class UtilizationDetector(PluginBase):
    """EndpointScore = max(queue/queueThresh, kv/kvThresh); pool = mean."""

    # Thread-safety audit (scheduler-pool offload, doubles as a filter):
    # metrics reads; thresholds written once at configure().
    THREAD_SAFE = True

    def __init__(self, name: str | None = None):
        super().__init__(name)
        self.queue_threshold = 5
        self.kv_threshold = 0.8

    def configure(self, params: dict[str, Any], handle: Any) -> None:
        self.queue_threshold = int(params.get("queueDepthThreshold", self.queue_threshold))
        self.kv_threshold = float(params.get("kvCacheUtilThreshold", self.kv_threshold))

    def endpoint_score(self, ep: Endpoint) -> float:
        q = ep.metrics.waiting_queue_size / max(self.queue_threshold, 1)
        kv = ep.metrics.kv_cache_usage_percent / max(self.kv_threshold, 1e-9)
        return max(q, kv)

    def saturation(self, endpoints: list[Endpoint]) -> float:
        if not endpoints:
            return 1.0  # empty pool is saturated by definition
        return sum(self.endpoint_score(ep) for ep in endpoints) / len(endpoints)

    def filter(self, ctx, state, request, endpoints):
        ok = [ep for ep in endpoints if self.endpoint_score(ep) < 1.0]
        return ok or endpoints  # fail open

    def filter_batch(self, ctx, state, request, batch, rows):
        cols = batch.columns
        q = cols.num["waiting_queue_size"][rows] / max(self.queue_threshold, 1)
        kv = (cols.num["kv_cache_usage_percent"][rows]
              / max(self.kv_threshold, 1e-9))
        # Scalar parity incl. NaN: max(q, kv) keeps q when kv is NaN (q is
        # the running max and NaN comparisons are False), but yields NaN —
        # dropped by `< 1.0` — when q itself is NaN.
        keep = (q < 1.0) & ((kv < 1.0) | np.isnan(kv))
        return keep if keep.any() else np.ones(len(rows), dtype=bool)


@register_plugin("concurrency-detector")
class ConcurrencyDetector(PluginBase):
    """In-flight load against capacity×(1+headroom), requests or tokens mode."""

    # Audit: clone-on-read attribute lookups only.
    THREAD_SAFE = True

    def __init__(self, name: str | None = None):
        super().__init__(name)
        self.capacity = 64
        self.headroom = 0.25
        self.mode = "requests"

    def configure(self, params: dict[str, Any], handle: Any) -> None:
        self.capacity = int(params.get("capacity", self.capacity))
        self.headroom = float(params.get("headroom", self.headroom))
        self.mode = params.get("mode", self.mode)

    def consumes(self) -> list[str]:
        return [INFLIGHT_ATTRIBUTE_KEY]

    def endpoint_score(self, ep: Endpoint) -> float:
        load: InFlightLoad | None = ep.attributes.get(INFLIGHT_ATTRIBUTE_KEY)
        if load is None:
            return 0.0
        used = load.tokens if self.mode == "tokens" else load.requests
        limit = self.capacity * (1 + self.headroom)
        return used / max(limit, 1e-9)

    def saturation(self, endpoints: list[Endpoint]) -> float:
        if not endpoints:
            return 1.0
        return sum(self.endpoint_score(ep) for ep in endpoints) / len(endpoints)

    def filter(self, ctx, state, request, endpoints):
        ok = [ep for ep in endpoints if self.endpoint_score(ep) < 1.0]
        return ok or endpoints

    def filter_batch(self, ctx, state, request, batch, rows):
        view_row = batch.view_row  # overlay reads: producers may stage loads
        n = len(rows)
        keep = np.empty(n, dtype=bool)
        limit = max(self.capacity * (1 + self.headroom), 1e-9)
        tokens = self.mode == "tokens"
        for i, r in enumerate(rows.tolist()):
            load = view_row(r).attributes.peek(INFLIGHT_ATTRIBUTE_KEY)
            used = (0 if load is None
                    else load.tokens if tokens else load.requests)
            keep[i] = (used / limit) < 1.0
        return keep if keep.any() else np.ones(n, dtype=bool)
