"""latency-scorer + slo-headroom-tier-filter: SLO-aware routing plugins.

Reference: framework/plugins/scheduling/scorer/latency (plugin.go — headroom
normalization/blending, idle preference, deficit bucketing, least/most
strategies, composite fallback) and …/filter/sloheadroomtier (plugin.go —
positive/negative tier split with epsilon exploration). Both consume the
LatencyPredictionInfo attribute written by predicted-latency-producer.
"""

from __future__ import annotations

import random
from typing import Any

from ..framework.datalayer import Endpoint
from ..framework.plugin import PluginBase, register_plugin
from ..framework.scheduling import CycleState, InferenceRequest
from .attributes import (
    LATENCY_ATTRIBUTE_KEY,
    PREFIX_ATTRIBUTE_KEY,
    LatencyPredictionInfo,
)


def _info(ep: Endpoint) -> LatencyPredictionInfo | None:
    return ep.attributes.get(LATENCY_ATTRIBUTE_KEY)


@register_plugin("latency-scorer")
class LatencyScorer(PluginBase):
    """Scores endpoints by predicted-latency SLO headroom.

    Semantics (reference scorer/latency README):
    - positive-headroom endpoints outrank negative ones (negatives get 0 when
      both kinds are present);
    - all-negative: idle endpoints (dispatched == 0) are preferred; otherwise
      deficit buckets rank only-TPOT-negative > only-TTFT-negative > both;
    - within a group, headrooms are range-normalized and blended with
      ttftWeight/tpotWeight (a zero-range dimension's weight renormalizes to
      the other);
    - strategy "least" favors the endpoint closest to the SLO boundary
      (bin-packing); "most" favors maximum margin (positives only — for
      negatives "most" would prefer the most overloaded endpoint);
    - no predictions anywhere → composite fallback on KV utilization, queue
      depth, and prefix-cache score.
    """

    # Thread-safety audit (scheduler-pool offload): attribute/metrics reads
    # only; weights written once at configure().
    THREAD_SAFE = True

    def __init__(self, name: str | None = None):
        super().__init__(name)
        self.ttft_weight = 0.5
        self.tpot_weight = 0.5
        self.strategy = "least"  # "least" | "most"

    def configure(self, params: dict[str, Any], handle: Any) -> None:
        self.ttft_weight = float(params.get("ttftWeight", self.ttft_weight))
        self.tpot_weight = float(params.get("tpotWeight", self.tpot_weight))
        self.strategy = params.get("headroomStrategy", self.strategy)
        if self.strategy not in ("least", "most"):
            raise ValueError(f"headroomStrategy must be least|most, "
                             f"got {self.strategy!r}")

    def consumes(self) -> list[str]:
        return [LATENCY_ATTRIBUTE_KEY]

    def score(self, ctx: Any, state: CycleState, request: InferenceRequest,
              endpoints: list[Endpoint]) -> dict[str, float]:
        infos = {ep.metadata.address_port: _info(ep) for ep in endpoints}
        if not any(infos.values()):
            return self._composite_fallback(endpoints)

        pos = [ep for ep in endpoints
               if (i := infos[ep.metadata.address_port]) and i.is_valid]
        if pos:
            scores = self._headroom_scores(pos, infos, self.strategy)
            return {ap: scores.get(ap, 0.0) for ap in infos}

        # All negative (or prediction-less, which counts as negative).
        neg = [ep for ep in endpoints if infos[ep.metadata.address_port]]
        if not neg:
            return self._composite_fallback(endpoints)
        idle = [ep for ep in neg
                if infos[ep.metadata.address_port].dispatched == 0]
        if idle:
            neg = idle
        else:
            neg = self._best_deficit_bucket(neg, infos)
        # Negative headroom ranks by "closest to the SLO boundary" — the
        # LEAST-negative value, i.e. the highest headroom, must win (the
        # reference's always-least rule for negatives). In normalized terms
        # that is the NON-inverted blend ("most"); inverting here would steer
        # traffic onto the deepest violator.
        scores = self._headroom_scores(neg, infos, "most")
        return {ap: scores.get(ap, 0.0) for ap in infos}

    def _best_deficit_bucket(self, endpoints, infos):
        only_tpot, only_ttft, both = [], [], []
        for ep in endpoints:
            i = infos[ep.metadata.address_port]
            if i.ttft_valid and not i.tpot_valid:
                only_tpot.append(ep)
            elif i.tpot_valid and not i.ttft_valid:
                only_ttft.append(ep)
            else:
                both.append(ep)
        return only_tpot or only_ttft or both

    def _headroom_scores(self, endpoints, infos, strategy):
        ttfts = [infos[ep.metadata.address_port].ttft_headroom_ms
                 for ep in endpoints]
        tpots = [infos[ep.metadata.address_port].tpot_headroom_ms
                 for ep in endpoints]

        def norm(vals):
            lo, hi = min(vals), max(vals)
            rng = hi - lo
            if rng <= 0:
                return None  # zero-range: dimension carries no signal
            return [(v - lo) / rng for v in vals]

        n_ttft, n_tpot = norm(ttfts), norm(tpots)
        w_ttft, w_tpot = self.ttft_weight, self.tpot_weight
        if n_ttft is None and n_tpot is None:
            return {ep.metadata.address_port: 1.0 for ep in endpoints}
        if n_ttft is None:
            w_ttft, w_tpot = 0.0, 1.0
            n_ttft = [0.0] * len(endpoints)
        elif n_tpot is None:
            w_ttft, w_tpot = 1.0, 0.0
            n_tpot = [0.0] * len(endpoints)
        total = (w_ttft + w_tpot) or 1.0
        out = {}
        for ep, a, b in zip(endpoints, n_ttft, n_tpot):
            blended = (w_ttft * a + w_tpot * b) / total
            # "least": closest to the SLO boundary wins → invert.
            out[ep.metadata.address_port] = (1.0 - blended
                                             if strategy == "least" else blended)
        return out

    def _composite_fallback(self, endpoints):
        # Sidecar-down analogue: weighted KV-util + queue + prefix blend.
        out = {}
        for ep in endpoints:
            m = ep.metrics
            queue = 1.0 / (1.0 + m.waiting_queue_size)
            kv = 1.0 - min(max(m.kv_cache_usage_percent, 0.0), 1.0)
            prefix = ep.attributes.get(PREFIX_ATTRIBUTE_KEY)
            hit = prefix.hit_ratio if prefix is not None else 0.0
            out[ep.metadata.address_port] = 0.4 * kv + 0.3 * queue + 0.3 * hit
        return out


@register_plugin("slo-headroom-tier-filter")
class SloHeadroomTierFilter(PluginBase):
    """Probabilistic tier filter on SLO headroom (reference sloheadroomtier).

    Positive tier: both headrooms ≥ 0. Endpoints without predictions fall in
    the negative tier. When both tiers exist the negative tier is explored
    with probability epsilonExploreNeg (default 1%) so recovering endpoints
    still see traffic; no predictions at all → pass-through.
    """

    # Audit: attribute reads + GIL-atomic rng draw.
    THREAD_SAFE = True

    def __init__(self, name: str | None = None):
        super().__init__(name)
        self.epsilon = 0.01
        self._rng = random.Random()

    def configure(self, params: dict[str, Any], handle: Any) -> None:
        self.epsilon = float(params.get("epsilonExploreNeg", self.epsilon))

    def consumes(self) -> list[str]:
        return [LATENCY_ATTRIBUTE_KEY]

    def filter(self, ctx: Any, state: CycleState, request: InferenceRequest,
               endpoints: list[Endpoint]) -> list[Endpoint]:
        infos = {ep.metadata.address_port: _info(ep) for ep in endpoints}
        if not any(infos.values()):
            return endpoints
        pos = [ep for ep in endpoints
               if (i := infos[ep.metadata.address_port]) and i.is_valid]
        neg = [ep for ep in endpoints
               if not ((i := infos[ep.metadata.address_port]) and i.is_valid)]
        if not pos:
            return neg
        if not neg:
            return pos
        return neg if self._rng.random() < self.epsilon else pos
