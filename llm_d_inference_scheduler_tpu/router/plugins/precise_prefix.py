"""precise-prefix-cache-scorer: token-exact KV block index fed by engine
cache events.

Mirrors the reference's preciseprefixcache scorer
(/root/reference/pkg/epp/framework/plugins/scheduling/scorer/
preciseprefixcache/precise_prefix_cache.go:34-853): an exact KV-block index
built from engine KV events over ZMQ; block keys derive from the tokenized
prompt; speculative entries with TTL cover the routing→event blind spot; the
EndpointLifecycle hooks tear per-pod subscribers up and down.

Engine side: engine/kv_events.py publishes stored/removed block-hash events
on tcp://<pod>:<port+1000> using the shared hash chain (utils/hashing.py).

Transports: the default "http" (SSE /kv_events) works both against direct
engine endpoints and sidecar-fronted ones (the sidecar stream-proxies the
route). The "zmq" transport requires DIRECT engine endpoints: the engine
binds its serving-port+offset, which a sidecar-fronted endpoint's port does
not resolve to (an HTTP sidecar cannot proxy ZMQ).
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Any

import zmq

from ..framework.datalayer import Endpoint
from ..framework.plugin import PluginBase, register_plugin
from ..framework.scheduling import InferenceRequest, SchedulingResult
from ..hashmemo import request_prefix_hashes

log = logging.getLogger("router.precise_prefix")

TOPIC = b"kv-events"
SPECULATIVE_TTL_S = 10.0


def drain_sse_frames(buf: str) -> tuple[list[str], str]:
    """Split complete ``\\n\\n``-terminated SSE frames off ``buf``, returning
    (frames, remainder). Find-offset parsing: one advancing scan position
    instead of re-splitting (and so rescanning/copying) the whole buffer per
    frame — the same fix the gateway SSE leg got (``buf += chunk`` + repeated
    ``split`` is O(n²) across a long stream)."""
    pos = 0
    frames = []
    while True:
        end = buf.find("\n\n", pos)
        if end < 0:
            break
        frames.append(buf[pos:end])
        pos = end + 2
    return frames, (buf[pos:] if pos else buf)


class KvBlockIndex:
    """(pod, hash) → expiry index with TTL'd speculative entries.

    Confirmed entries also carry a TTL (renewed by the engines' 1s snapshot
    re-publication): a lost 'removed' event — dropped SSE frame, subscriber
    reconnect, HWM drop — then self-heals within CONFIRMED_TTL_S instead of
    poisoning routing forever. Thread-safe: written by subscriber threads,
    read by the scheduler on the event loop.
    """

    CONFIRMED_TTL_S = 10.0  # several snapshot periods
    SWEEP_INTERVAL_S = 1.0  # batched expiry cadence (replaces per-lookup TTL pops)

    def __init__(self):
        self._by_pod: dict[str, dict[int, float]] = {}  # hash -> expiry
        self._speculative: dict[tuple[str, int], float] = {}  # -> expiry
        self._lock = threading.Lock()
        self._next_pod_sweep: dict[str, float] = {}  # per-pod cadence
        self._next_spec_sweep = 0.0
        # Fleet confirmed-index replication tap (router/fleet.py
        # KvReplicationSource): fired OUTSIDE the lock with
        # (op, pod, hashes) on confirmed-state CHANGES only — the engines'
        # 1 s idempotent snapshot re-publication produces no deltas, so the
        # replica stream carries churn, not steady-state re-sends.
        self._on_delta = None

    def set_delta_listener(self, fn) -> None:
        """fn(op, pod, hashes) with op in {'add', 'remove', 'drop'};
        called from whichever thread mutated the index (listener must be
        thread-safe)."""
        self._on_delta = fn

    def add(self, pod: str, hashes: list[int]) -> None:
        expiry = time.monotonic() + self.CONFIRMED_TTL_S
        # Capture the listener once: a concurrent set_delta_listener(None)
        # (publisher teardown while subscriber threads still deliver)
        # must not turn the post-lock call into None(...).
        listener = self._on_delta
        fresh: list[int] | None = [] if listener is not None else None
        with self._lock:
            entries = self._by_pod.setdefault(pod, {})
            now = expiry - self.CONFIRMED_TTL_S
            for h in hashes:
                if fresh is not None:
                    prev = entries.get(h)
                    if prev is None or prev <= now:
                        fresh.append(h)  # new OR expired-dead: a change
                entries[h] = expiry
                self._speculative.pop((pod, h), None)  # confirmed
            # The speculative sweep rides the subscriber threads' writes,
            # never the scheduler's scoring path.
            if now >= self._next_spec_sweep:
                self._next_spec_sweep = now + self.SWEEP_INTERVAL_S
                dead = [k for k, exp in self._speculative.items()
                        if exp <= now]
                for k in dead:
                    del self._speculative[k]
        if fresh:
            listener("add", pod, fresh)

    def remove(self, pod: str, hashes: list[int]) -> None:
        listener = self._on_delta
        gone: list[int] = []
        with self._lock:
            entries = self._by_pod.get(pod, {})
            for h in hashes:
                if entries.pop(h, None) is not None and listener is not None:
                    gone.append(h)
        if gone and listener is not None:
            listener("remove", pod, gone)

    def add_speculative(self, pod: str, hashes: list[int]) -> None:
        expiry = time.monotonic() + SPECULATIVE_TTL_S
        with self._lock:
            for h in hashes:
                self._speculative[(pod, h)] = expiry

    def _sweep_pod(self, pod: str, entries: dict[int, float],
                   now: float) -> None:
        """Batched per-pod expiry (caller holds the lock): drop the queried
        pod's dead entries at most once per SWEEP_INTERVAL_S instead of
        popping per lookup. Per-pod — never a full-index scan under the
        lock — so the hold is O(one pod's cache), not O(pods × hashes);
        reads between sweeps are plain dict gets guarded by `exp > now`."""
        if now < self._next_pod_sweep.get(pod, 0.0):
            return
        self._next_pod_sweep[pod] = now + self.SWEEP_INTERVAL_S
        dead = [h for h, exp in entries.items() if exp <= now]
        for h in dead:
            del entries[h]

    def match_prefix(self, pod: str, hashes: list[int]) -> int:
        """Length of the consecutive-from-start prefix of ``hashes`` held by
        ``pod`` — ONE lock acquisition for the whole walk (the per-hash
        ``holds`` loop used to take the lock once per block per endpoint)."""
        now = time.monotonic()
        with self._lock:
            entries = self._by_pod.get(pod)
            if entries is not None:
                self._sweep_pod(pod, entries, now)
            spec = self._speculative
            match = 0
            for h in hashes:
                if entries is not None:
                    exp = entries.get(h)
                    if exp is not None and exp > now:
                        match += 1
                        continue
                exp = spec.get((pod, h))
                if exp is not None and exp > now:
                    match += 1
                    continue
                break
            return match

    def holds(self, pod: str, h: int) -> bool:
        return self.match_prefix(pod, [h]) == 1

    def drop_pod(self, pod: str) -> None:
        listener = self._on_delta  # captured: see add()
        dropped = False
        with self._lock:
            dropped = self._by_pod.pop(pod, None) is not None
            self._next_pod_sweep.pop(pod, None)
            self._speculative = {k: v for k, v in self._speculative.items()
                                 if k[0] != pod}
        if dropped and listener is not None:
            listener("drop", pod, [])

    # ---- fleet confirmed-index replication (router/fleet.py) -----------

    def dump_confirmed(self) -> dict[str, list[int]]:
        """Live confirmed entries per pod — the periodic full-index
        checkpoint frame a mid-stream joiner (or a gap-detected follower)
        resyncs from."""
        now = time.monotonic()
        with self._lock:
            return {pod: [h for h, exp in entries.items() if exp > now]
                    for pod, entries in self._by_pod.items()}

    def apply_checkpoint(self, dump: dict[str, list[int]]) -> None:
        """Install a leader-published full-index checkpoint: the replica's
        confirmed view is REPLACED wholesale (pods absent from the dump are
        dropped). Speculative stamps are process-local and untouched.
        Replica entries carry the normal CONFIRMED_TTL_S — the checkpoint
        cadence (< TTL) is the renewal, so a dead leader's replica decays
        instead of poisoning routing forever."""
        expiry = time.monotonic() + self.CONFIRMED_TTL_S
        replaced = {pod: {h: expiry for h in hashes}
                    for pod, hashes in dump.items()}
        with self._lock:
            self._by_pod = replaced
            for pod, entries in replaced.items():
                for h in entries:
                    self._speculative.pop((pod, h), None)

    def pod_block_count(self, pod: str) -> int:
        now = time.monotonic()
        with self._lock:
            entries = self._by_pod.get(pod, {})
            return sum(1 for exp in entries.values() if exp > now)

    def counts(self) -> dict[str, dict[str, int]]:
        """Per-pod live confirmed/speculative stamp counts — the precise
        half of /debug/kv's index-occupancy view, and the quantity the
        fleet supervisor's divergence gauge compares across shards (with
        fleet.replication a follower's confirmed entries are replicas of
        the leader's, so the gauge reads ~0; without it the follower
        holds only speculative stamps)."""
        now = time.monotonic()
        with self._lock:
            out = {pod: {"confirmed": sum(1 for exp in entries.values()
                                          if exp > now),
                         "speculative": 0}
                   for pod, entries in self._by_pod.items()}
            for (pod, _h), exp in self._speculative.items():
                if exp > now:
                    row = out.setdefault(pod,
                                         {"confirmed": 0, "speculative": 0})
                    row["speculative"] += 1
            return out


@register_plugin("precise-prefix-cache-scorer")
class PrecisePrefixCacheScorer(PluginBase):
    # Thread-safety audit (scheduler-pool offload): the KvBlockIndex is
    # already lock-protected (written by subscriber threads, read by
    # scheduling wherever it runs); the prefix-hash memo rides the request
    # (one cycle = one thread) with its global LRU behind its own lock.
    THREAD_SAFE = True

    def __init__(self, name: str | None = None):
        super().__init__(name)
        self.index = KvBlockIndex()
        self.block_size_tokens = 16
        self.events_port_offset = 1000
        self.transport = "http"  # "http" (SSE, default) | "zmq"
        # TLS verification for https kv-event streams: skip-verify default
        # (pod-local certs), CA bundle opts into real verification.
        self.insecure_skip_verify = True
        self.ca_cert_path: str | None = None
        # One sync SUB per pod, each on its own thread. Deliberately NOT
        # zmq.asyncio: asyncio SUB sockets in this stack intermittently never
        # woke for delivered messages (the same wire traffic was visible to a
        # sync socket); a blocking recv loop with RCVTIMEO is boring and
        # reliable, and the index is lock-protected for cross-thread reads.
        self._subs: dict[str, tuple[threading.Thread, threading.Event]] = {}

    def index_counts(self) -> dict[str, dict[str, int]]:
        """Per-pod confirmed/speculative stamp counts for the CacheLedger's
        /debug/kv view (router/kvobs.py)."""
        return self.index.counts()

    def configure(self, params: dict[str, Any], handle: Any) -> None:
        self.block_size_tokens = int(params.get("blockSizeTokens",
                                                self.block_size_tokens))
        self.events_port_offset = int(params.get("eventsPortOffset",
                                                 self.events_port_offset))
        self.transport = params.get("transport", self.transport)
        self.insecure_skip_verify = bool(
            params.get("insecureSkipVerify", self.insecure_skip_verify))
        self.ca_cert_path = params.get("caCertPath") or None

    # ---- scoring -------------------------------------------------------

    def consumes(self) -> list[str]:
        return ["request/tokenized"]

    def _hashes(self, request: InferenceRequest, block_size: int) -> list[int]:
        return request_prefix_hashes(request, block_size)

    def score(self, ctx, state, request, endpoints):
        out: dict[str, float] = {}
        for ep in endpoints:
            bs = ep.metrics.cache_block_size or self.block_size_tokens
            hashes = self._hashes(request, bs)  # memoized per (request, bs)
            # One lock acquisition per endpoint for the whole
            # consecutive-prefix walk (the per-hash holds() loop was one per
            # block per endpoint).
            match = self.index.match_prefix(ep.metadata.address_port, hashes)
            out[ep.metadata.address_port] = (match / len(hashes)
                                             if hashes else 0.0)
        return out

    def pre_request(self, ctx, request: InferenceRequest,
                    result: SchedulingResult) -> None:
        # Speculative indexing: the chosen pod will hold these blocks once the
        # engine commits them; cover the blind spot with a TTL'd entry.
        for ep in result.primary().target_endpoints[:1]:
            bs = ep.metrics.cache_block_size or self.block_size_tokens
            self.index.add_speculative(ep.metadata.address_port,
                                       self._hashes(request, bs))

    # ---- endpoint lifecycle: ZMQ subscriber per pod --------------------

    def endpoint_added(self, ep: Endpoint) -> None:
        pod = ep.metadata.address_port
        if pod in self._subs:
            return
        if self.transport == "zmq":
            # Engines bind serving-port + offset (config.resolved_kv_events_port)
            # — NOT the metrics port.
            port = ep.metadata.port + self.events_port_offset
            url = f"tcp://{ep.metadata.address}:{port}"
        else:
            url = ep.metadata.url + "/kv_events"
        stop = threading.Event()
        target = self._subscribe if self.transport == "zmq" else self._subscribe_http
        thread = threading.Thread(target=target, args=(pod, url, stop),
                                  name=f"kv-sub-{pod}", daemon=True)
        self._subs[pod] = (thread, stop)
        thread.start()

    def endpoint_removed(self, ep: Endpoint) -> None:
        pod = ep.metadata.address_port
        sub = self._subs.pop(pod, None)
        if sub:
            sub[1].set()
        self.index.drop_pod(pod)

    def shutdown(self) -> None:
        for _, stop in self._subs.values():
            stop.set()
        self._subs.clear()

    def _handle_event(self, pod: str, msg: dict) -> None:
        hashes = [int(h) for h in msg.get("hashes", [])]
        if msg.get("event") == "stored":
            self.index.add(pod, hashes)
        elif msg.get("event") == "removed":
            self.index.remove(pod, hashes)

    def _subscribe_http(self, pod: str, url: str, stop: threading.Event) -> None:
        """SSE subscriber (default transport) with reconnect."""
        import httpx

        log.info("kv-event SSE subscriber for %s at %s", pod, url)
        from ..tlsutil import client_verify

        verify = client_verify(self.insecure_skip_verify, self.ca_cert_path)
        while not stop.is_set():
            try:
                with httpx.Client(timeout=httpx.Timeout(5.0, read=5.0),
                                  verify=verify) as client:
                    with client.stream("GET", url) as r:
                        if r.status_code != 200:
                            raise ConnectionError(f"status {r.status_code}")
                        buf = ""
                        for chunk in r.iter_text():
                            if stop.is_set():
                                return
                            buf += chunk
                            frames, buf = drain_sse_frames(buf)
                            for frame in frames:
                                for line in frame.splitlines():
                                    if line.startswith("data: "):
                                        try:
                                            self._handle_event(pod,
                                                               json.loads(line[6:]))
                                        except Exception:
                                            log.debug("bad kv event from %s", pod)
            except Exception:
                # read timeouts double as stop-flag checks; reconnect otherwise
                if stop.is_set():
                    return
                stop.wait(1.0)

    def _subscribe(self, pod: str, url: str, stop: threading.Event) -> None:
        ctx = zmq.Context.instance()
        sock = ctx.socket(zmq.SUB)
        sock.setsockopt(zmq.SUBSCRIBE, TOPIC)
        sock.setsockopt(zmq.RCVHWM, 10_000)
        sock.setsockopt(zmq.RCVTIMEO, 500)  # wake to check the stop flag
        sock.connect(url)
        log.info("kv-event subscriber for %s at %s", pod, url)
        try:
            while not stop.is_set():
                try:
                    _, payload = sock.recv_multipart()
                    msg = json.loads(payload)
                except zmq.Again:
                    continue
                except Exception:
                    log.debug("bad kv event from %s", pod)
                    continue
                self._handle_event(pod, msg)
        finally:
            sock.close(linger=0)
