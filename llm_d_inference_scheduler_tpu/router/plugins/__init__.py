"""In-tree router plugins (reference: cmd/epp/main.go RegisterAllPlugins).

Importing this package registers every in-tree plugin type with the global
registry; the config loader instantiates them by type name.
"""

from . import (  # noqa: F401
    disagg,
    filters,
    latency,
    pickers,
    precise_prefix,
    profile_handlers,
    reporter,
    saturation,
    scorers,
    testing,
)

from .attributes import PrefixCacheMatchInfo, PREFIX_ATTRIBUTE_KEY, INFLIGHT_ATTRIBUTE_KEY

__all__ = ["filters", "scorers", "pickers", "profile_handlers",
           "PrefixCacheMatchInfo", "PREFIX_ATTRIBUTE_KEY", "INFLIGHT_ATTRIBUTE_KEY"]
