"""Conformance/test-only plugins.

Reference parity: `header-based-testing-filter` (scheduling/test/filter) and
`destination-endpoint-served-verifier` (test/responsereceived) exist solely
for conformance suites — they let CI steer scheduling decisions via a header
and assert that the served endpoint matches the scheduled one
(registered at runner.go:496-499).
"""

from __future__ import annotations

import logging

from ..framework.plugin import PluginBase, register_plugin
from ..requestcontrol.director import H_DESTINATION, H_DESTINATION_SERVED

log = logging.getLogger("router.testing")

TEST_HEADER = "test-epp-endpoint-selection"


@register_plugin("header-based-testing-filter")
class HeaderBasedTestingFilter(PluginBase):
    """Keep only the endpoint named by the test header (conformance steering)."""

    # Audit: stateless header/metadata comparison.
    THREAD_SAFE = True

    def filter(self, ctx, state, request, endpoints):
        want = request.headers.get(TEST_HEADER)
        if not want:
            return endpoints
        chosen = [ep for ep in endpoints if ep.metadata.address_port == want]
        return chosen or endpoints  # fail open if the named endpoint is absent


@register_plugin("destination-endpoint-served-verifier")
class DestinationEndpointServedVerifier(PluginBase):
    """ResponseReceived verifier: the endpoint that served must be the one
    scheduling picked; mismatches are counted and logged."""

    def __init__(self, name: str | None = None):
        super().__init__(name)
        self.mismatches = 0
        self.checked = 0

    def response_received(self, ctx, request, endpoint, status: int) -> None:
        scheduled = request.headers.get(H_DESTINATION, "")
        served = (endpoint.metadata.address_port if endpoint is not None
                  else request.headers.get(H_DESTINATION_SERVED, ""))
        self.checked += 1
        if scheduled and served and served not in scheduled.split(","):
            self.mismatches += 1
            log.error("served endpoint %s not among scheduled %s (request %s)",
                      served, scheduled, request.request_id)
