"""Pickers (reference: framework/plugins/scheduling/picker/*): all share
maxNumOfEndpoints (default 1); picking N>1 yields multi-endpoint routing."""

from __future__ import annotations

import random
from typing import Any

import numpy as np

from ..framework.plugin import PluginBase, register_plugin
from ..framework.scheduling import ScoredEndpoint


class _PickerBase(PluginBase):
    # Thread-safety audit (scheduler-pool offload, router/schedpool.py):
    # config fields written once at configure(); the shared random.Random's
    # C-level draws are GIL-atomic (interleaved draws change tie-break
    # outcomes, never corrupt state). Seeded mode derives a private
    # per-request Random, so it is trivially safe.
    THREAD_SAFE = True

    # Seeded tie-break mode: when set (per-picker `pickSeed` parameter, or
    # the `scheduling.pickSeed` config knob applied to every picker by the
    # loader), every draw comes from a Random seeded by (pickSeed,
    # request_id) — a pure function of the request, independent of draw
    # order, process, and interleaving. That is what makes picks
    # bit-identical between a single-process run and a sharded fleet run
    # over the same request stream (router/fleet.py, SCHED_SCALEOUT.json):
    # a shared sequential RNG would entangle every pick with global request
    # order, which sharding necessarily changes. None (the default) keeps
    # the historical shared-RNG behavior bit-identical.
    pick_seed: int | None = None

    def __init__(self, name: str | None = None):
        super().__init__(name)
        self.max_endpoints = 1
        self._rng = random.Random()

    def configure(self, params: dict[str, Any], handle: Any) -> None:
        self.max_endpoints = int(params.get("maxNumOfEndpoints", 1))
        if params.get("pickSeed") is not None:
            self.pick_seed = int(params["pickSeed"])

    def _rng_for(self, request: Any) -> random.Random:
        if self.pick_seed is None:
            return self._rng
        # str seeding hashes via SHA-512: deterministic across processes
        # (unlike hash(), which is salted per interpreter).
        rid = getattr(request, "request_id", "") or ""
        return random.Random(f"{self.pick_seed}:{rid}")


@register_plugin("max-score-picker")
class MaxScorePicker(_PickerBase):
    """Highest total score; ties broken randomly."""

    def pick(self, ctx, state, request, scored: list[ScoredEndpoint]):
        if not scored:
            return []
        pool = list(scored)
        self._rng_for(request).shuffle(pool)  # randomize tie order
        pool.sort(key=lambda s: s.score, reverse=True)
        return [s.endpoint for s in pool[: self.max_endpoints]]

    def pick_batch(self, ctx, state, request, totals):
        n = len(totals)
        if n == 0:
            return []
        if np.isnan(totals).any():
            # NaN makes comparison sorts order-dependent; only the scalar
            # path's exact sequence of comparisons is authoritative.
            return None
        if self.max_endpoints == 1:
            hi = totals.max()
            if (totals == hi).sum() == 1:
                # Unique max: the shuffle only permutes TIE order, so the
                # winner is the argmax no matter what the RNG draws — skip
                # the O(n)-draw Fisher-Yates entirely (the dominant cost of
                # a large-pool cycle). In seeded mode the per-request RNG is
                # private and discarded, so skipping draws is unobservable;
                # in shared-RNG mode the pick is still exactly what the
                # scalar path would have returned, only the (already
                # nondeterministic) global draw stream advances differently.
                return [int(np.argmax(totals))]
        # Ties: shuffling an index list consumes the identical Fisher-Yates
        # draw sequence as shuffling the ScoredEndpoint list, and a stable
        # descending sort of the shuffled scores reproduces the scalar
        # shuffle-then-stable-sort tie-break exactly.
        order = list(range(n))
        self._rng_for(request).shuffle(order)
        shuffled = totals[order]
        if self.max_endpoints == 1:
            # argmax = first max in shuffled order = stable-sort winner.
            return [order[int(np.argmax(shuffled))]]
        top = np.argsort(-shuffled, kind="stable")[: self.max_endpoints]
        return [order[int(j)] for j in top]


@register_plugin("random-picker")
class RandomPicker(_PickerBase):
    def pick(self, ctx, state, request, scored: list[ScoredEndpoint]):
        if not scored:
            return []
        picked = self._rng_for(request).sample(
            scored, k=min(self.max_endpoints, len(scored)))
        return [s.endpoint for s in picked]

    def pick_batch(self, ctx, state, request, totals):
        n = len(totals)
        if n == 0:
            return []
        # sample() draws depend only on (len(population), k), so sampling
        # positions consumes the same RNG sequence as sampling the list.
        return list(self._rng_for(request).sample(range(n),
                                                  k=min(self.max_endpoints, n)))


@register_plugin("weighted-random-picker")
class WeightedRandomPicker(_PickerBase):
    """Score-proportional sampling without replacement."""

    def pick(self, ctx, state, request, scored: list[ScoredEndpoint]):
        pool = list(scored)
        out = []
        rng = self._rng_for(request)
        while pool and len(out) < self.max_endpoints:
            total = sum(max(s.score, 0.0) for s in pool)
            if total <= 0:
                out.extend(s.endpoint for s in
                           rng.sample(pool, k=min(self.max_endpoints - len(out),
                                                  len(pool))))
                break
            r = rng.uniform(0, total)
            acc = 0.0
            for i, s in enumerate(pool):
                acc += max(s.score, 0.0)
                if r <= acc:
                    out.append(s.endpoint)
                    pool.pop(i)
                    break
        return out
