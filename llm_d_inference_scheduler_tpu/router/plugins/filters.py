"""Scheduling filters (reference: framework/plugins/scheduling/filter/*)."""

from __future__ import annotations

from typing import Any

from ..framework.datalayer import ROLE_LABEL, Endpoint
from ..framework.plugin import PluginBase, register_plugin
from ..framework.scheduling import CycleState, InferenceRequest


class _RoleFilter(PluginBase):
    """Match the llm-d.ai/role label against a role set
    (reference filter/bylabel/roles.go:10-69)."""

    ROLES: tuple[str, ...] = ()
    MATCH_UNLABELED = False

    def filter(self, ctx: Any, state: CycleState, request: InferenceRequest,
               endpoints: list[Endpoint]) -> list[Endpoint]:
        out = []
        for ep in endpoints:
            role = ep.metadata.labels.get(ROLE_LABEL)
            if role in self.ROLES or (role in (None, "") and self.MATCH_UNLABELED):
                out.append(ep)
        return out


@register_plugin("decode-filter")
class DecodeFilter(_RoleFilter):
    ROLES = ("decode", "both")
    MATCH_UNLABELED = True  # unlabeled pods count as decode-capable


@register_plugin("prefill-filter")
class PrefillFilter(_RoleFilter):
    ROLES = ("prefill", "both")


@register_plugin("encode-filter")
class EncodeFilter(_RoleFilter):
    ROLES = ("encode",)


@register_plugin("label-selector-filter", "by-label-selector", "by-label")
class LabelSelectorFilter(PluginBase):
    """Generic label matcher: matchLabels equality + matchExpressions
    (In/NotIn/Exists/DoesNotExist)."""

    def __init__(self, name: str | None = None):
        super().__init__(name)
        self.match_labels: dict[str, str] = {}
        self.match_expressions: list[dict[str, Any]] = []

    def configure(self, params: dict[str, Any], handle: Any) -> None:
        self.match_labels = params.get("matchLabels") or {}
        self.match_expressions = params.get("matchExpressions") or []

    def _matches(self, labels: dict[str, str]) -> bool:
        for k, v in self.match_labels.items():
            if labels.get(k) != v:
                return False
        for expr in self.match_expressions:
            key, op = expr.get("key"), expr.get("operator", "In")
            values = expr.get("values") or []
            if op == "In" and labels.get(key) not in values:
                return False
            if op == "NotIn" and labels.get(key) in values:
                return False
            if op == "Exists" and key not in labels:
                return False
            if op == "DoesNotExist" and key in labels:
                return False
        return True

    def filter(self, ctx, state, request, endpoints):
        return [ep for ep in endpoints if self._matches(ep.metadata.labels)]


@register_plugin("fresh-metrics-filter")
class FreshMetricsFilter(PluginBase):
    """Drop endpoints with stale telemetry unless that would empty the set
    (fail-open, like the reference's PodsWithFreshMetrics + utilization
    detector fallback)."""

    def filter(self, ctx, state, request, endpoints):
        fresh = [ep for ep in endpoints if ep.metrics.fresh]
        return fresh or endpoints
