"""Scheduling filters (reference: framework/plugins/scheduling/filter/*)."""

from __future__ import annotations

import time
from typing import Any

import numpy as np

from ..framework.datalayer import DRAINING_LABEL, ROLE_LABEL, Endpoint
from ..framework.plugin import PluginBase, register_plugin
from ..framework.scheduling import CycleState, InferenceRequest
from ..snapshot import role_mask_table


class _RoleFilter(PluginBase):
    """Match the llm-d.ai/role label against a role set
    (reference filter/bylabel/roles.go:10-69)."""

    ROLES: tuple[str, ...] = ()
    MATCH_UNLABELED = False
    # Thread-safety audit (scheduler-pool offload, router/schedpool.py):
    # pure read of immutable metadata labels.
    THREAD_SAFE = True
    # Role-code lookup table for the vectorized kernel, built once per
    # class on first batch cycle (immutable afterwards).
    _ROLE_TABLE: np.ndarray | None = None

    def filter(self, ctx: Any, state: CycleState, request: InferenceRequest,
               endpoints: list[Endpoint]) -> list[Endpoint]:
        out = []
        for ep in endpoints:
            labels = ep.metadata.labels
            if labels.get(DRAINING_LABEL):
                # Mid-role-flip drain cycle (router/rebalance.py): the pod
                # is between roles — no new picks of either role until the
                # flip republishes its metadata. Hard exclusion, not
                # fail-open: the rebalancer never drains a role's last pod.
                continue
            role = labels.get(ROLE_LABEL)
            if role in self.ROLES or (role in (None, "") and self.MATCH_UNLABELED):
                out.append(ep)
        return out

    def filter_batch(self, ctx, state, request, batch, rows):
        cls = type(self)
        table = cls._ROLE_TABLE
        if table is None:
            table = cls._ROLE_TABLE = role_mask_table(cls.ROLES,
                                                      cls.MATCH_UNLABELED)
        cols = batch.columns
        return table[cols.role_code[rows]] & ~cols.draining[rows]


@register_plugin("decode-filter")
class DecodeFilter(_RoleFilter):
    ROLES = ("decode", "both")
    MATCH_UNLABELED = True  # unlabeled pods count as decode-capable


@register_plugin("prefill-filter")
class PrefillFilter(_RoleFilter):
    ROLES = ("prefill", "both")


@register_plugin("encode-filter")
class EncodeFilter(_RoleFilter):
    ROLES = ("encode",)


@register_plugin("label-selector-filter", "by-label-selector", "by-label")
class LabelSelectorFilter(PluginBase):
    """Generic label matcher: matchLabels equality + matchExpressions
    (In/NotIn/Exists/DoesNotExist)."""

    # Audit: match rules are written once at configure(); reads only.
    THREAD_SAFE = True

    def __init__(self, name: str | None = None):
        super().__init__(name)
        self.match_labels: dict[str, str] = {}
        self.match_expressions: list[dict[str, Any]] = []

    def configure(self, params: dict[str, Any], handle: Any) -> None:
        self.match_labels = params.get("matchLabels") or {}
        self.match_expressions = params.get("matchExpressions") or []

    def _matches(self, labels: dict[str, str]) -> bool:
        for k, v in self.match_labels.items():
            if labels.get(k) != v:
                return False
        for expr in self.match_expressions:
            key, op = expr.get("key"), expr.get("operator", "In")
            values = expr.get("values") or []
            if op == "In" and labels.get(key) not in values:
                return False
            if op == "NotIn" and labels.get(key) in values:
                return False
            if op == "Exists" and key not in labels:
                return False
            if op == "DoesNotExist" and key in labels:
                return False
        return True

    def filter(self, ctx, state, request, endpoints):
        return [ep for ep in endpoints if self._matches(ep.metadata.labels)]


@register_plugin("fresh-metrics-filter")
class FreshMetricsFilter(PluginBase):
    """Drop endpoints with stale telemetry unless that would empty the set
    (fail-open, like the reference's PodsWithFreshMetrics + utilization
    detector fallback)."""

    # Audit: reads the (snapshot-copied) metrics view only.
    THREAD_SAFE = True

    def filter(self, ctx, state, request, endpoints):
        fresh = [ep for ep in endpoints if ep.metrics.fresh]
        return fresh or endpoints

    def filter_batch(self, ctx, state, request, batch, rows):
        # Metrics.fresh: update_time truthy AND (monotonic - update_time) < 5.
        ut = batch.columns.num["update_time"][rows]
        now = time.monotonic()
        mask = (ut != 0) & ((now - ut) < 5.0)
        if not mask.any():  # fail-open parity with `fresh or endpoints`
            return np.ones(len(rows), dtype=bool)
        return mask


@register_plugin("prefix-cache-affinity-filter")
class PrefixCacheAffinityFilter(PluginBase):
    """Keep only endpoints whose prefix-cache score clears a stickiness
    threshold (reference filter/prefixcacheaffinity/plugin.go):

    - exploration: with probability explorationProbability the gate is
      skipped entirely so cold endpoints still see traffic;
    - no sticky endpoint → keep all;
    - TTFT load gate: if the best sticky endpoint's predicted TTFT exceeds
      the best non-sticky one's by more than maxTTFTPenaltyMs, stickiness is
      broken (an overloaded cache holder shouldn't trap traffic).
    """

    # Audit: attribute reads (clone-on-read) + a shared random.Random whose
    # C-level draws are GIL-atomic.
    THREAD_SAFE = True

    def __init__(self, name=None):
        super().__init__(name)
        import random

        self.affinity_threshold = 0.80
        self.exploration_probability = 0.01
        self.max_ttft_penalty_ms = 5000.0
        self._rng = random.Random()

    def configure(self, params, handle):
        self.affinity_threshold = float(
            params.get("affinityThreshold", self.affinity_threshold))
        self.exploration_probability = float(
            params.get("explorationProbability", self.exploration_probability))
        self.max_ttft_penalty_ms = float(
            params.get("maxTTFTPenaltyMs", self.max_ttft_penalty_ms))
        if self.affinity_threshold > 1.0:
            raise ValueError("affinityThreshold must be <= 1.0")
        if not 0.0 <= self.exploration_probability <= 1.0:
            raise ValueError("explorationProbability must be in [0, 1]")
        if self.max_ttft_penalty_ms < 0:
            raise ValueError("maxTTFTPenaltyMs must be >= 0")

    def consumes(self):
        from .attributes import LATENCY_ATTRIBUTE_KEY, PREFIX_ATTRIBUTE_KEY

        return [PREFIX_ATTRIBUTE_KEY, LATENCY_ATTRIBUTE_KEY]

    @staticmethod
    def _prefix_score(ep) -> float:
        from .attributes import PREFIX_ATTRIBUTE_KEY

        info = ep.attributes.get(PREFIX_ATTRIBUTE_KEY)
        return info.hit_ratio if info is not None else 0.0

    @staticmethod
    def _best_ttft(endpoints) -> float:
        from .attributes import LATENCY_ATTRIBUTE_KEY

        best = float("inf")
        for ep in endpoints:
            info = ep.attributes.get(LATENCY_ATTRIBUTE_KEY)
            if info is not None and info.ttft_ms < best:
                best = info.ttft_ms
        return best

    def filter(self, ctx, state, request, endpoints):
        if len(endpoints) <= 1 or self.affinity_threshold <= 0:
            return endpoints
        if self._rng.random() < self.exploration_probability:
            return endpoints
        sticky = [ep for ep in endpoints
                  if self._prefix_score(ep) >= self.affinity_threshold]
        if not sticky:
            return endpoints
        non_sticky = [ep for ep in endpoints if ep not in sticky]
        if self.max_ttft_penalty_ms > 0 and non_sticky:
            best_sticky = self._best_ttft(sticky)
            best_non_sticky = self._best_ttft(non_sticky)
            # Fail open (keep stickiness) when either group lacks predictions:
            # an untrained endpoint is not known-overloaded, and breaking
            # affinity during predictor warm-up scatters the cache build.
            if (best_sticky != float("inf") and best_non_sticky != float("inf")
                    and best_sticky - best_non_sticky > self.max_ttft_penalty_ms):
                return endpoints
        return sticky


@register_plugin("circuit-breaker-filter")
class CircuitBreakerFilter(PluginBase):
    """Exclude endpoints whose passive circuit breaker is hard-open — the
    fleet-wide half of the resilience layer (router/resilience.py): the
    gateway's retry path records failures into the datastore's breaker
    registry, and this filter keeps every subsequent scheduling cycle off
    the ejected pods until their half-open window. Half-open endpoints stay
    schedulable (probes must flow), and the filter fails open when every
    candidate is broken (scheduling must not brick on a fully-ejected
    pool)."""

    # Audit: BreakerRegistry.would_allow mutates only single scalar state
    # fields (GIL-atomic); a racing open→half-open flip at worst double
    # counts one transition metric.
    THREAD_SAFE = True

    def __init__(self, name: str | None = None):
        super().__init__(name)
        self._datastore = None

    def configure(self, params, handle) -> None:
        self._datastore = getattr(handle, "datastore", None)

    def filter(self, ctx, state, request, endpoints):
        reg = getattr(self._datastore, "breakers", None)
        if reg is None:
            return endpoints
        kept = [ep for ep in endpoints
                if reg.would_allow(ep.metadata.address_port)]
        return kept or endpoints


@register_plugin("model-serving-filter")
class ModelServingFilter(PluginBase):
    """Keep endpoints whose polled /v1/models list contains the requested
    model — the model-aware consumer of models-data-source (reference
    source/models/README.md:11: routing on served-model data; the reference
    ships the data plumbing, this filter closes the loop for heterogeneous
    pools). Fail-open per endpoint until its first poll lands, and for the
    whole set when no endpoint matches (scheduling must not brick on stale
    model lists)."""

    # Audit: clone-on-read attribute lookups only.
    THREAD_SAFE = True

    def filter(self, ctx, state, request, endpoints):
        from ..datalayer.models_source import endpoint_models

        model = request.target_model
        if not model:
            return endpoints
        kept = []
        for ep in endpoints:
            models = endpoint_models(ep)
            if models is None:  # not polled yet: don't exclude
                kept.append(ep)
            elif any(m.get("id") == model or m.get("parent") == model
                     for m in models):
                kept.append(ep)
        return kept or endpoints
