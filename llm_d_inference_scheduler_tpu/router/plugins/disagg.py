"""P/D(/E) disaggregation: profile handler + deciders.

Mirrors the reference's disagg-profile-handler
(/root/reference/pkg/epp/framework/plugins/scheduling/profilehandler/disagg/
disagg_profile_handler.go:246-444) and its decider sub-plugins
(decider_plugin.go, prefix_based_pd_decider.go:99-149):

- the decode profile always runs first;
- the prefill stage is gated by a PD decider evaluated against the *chosen
  decode pod's* prefix-cache state (only non-cached prefix tokens justify a
  remote prefill);
- PreRequest writes the x-prefiller-host-port (and x-encoder-hosts-ports)
  routing headers consumed by the decode pod's sidecar.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any

from ..framework.datalayer import Endpoint
from ..framework.plugin import PluginBase, global_registry, register_plugin
from ..framework.scheduling import (
    InferenceRequest,
    ProfileRunResult,
    SchedulingResult,
)
from ..metrics import (
    DISAGG_DECISION_TOTAL,
    PD_CLASSIFIER_DECISIONS_TOTAL,
    PD_HOP_SKIPPED_TOTAL,
)
from ..requestcontrol.director import H_DATA_PARALLEL, H_ENCODERS, H_PREFILLER
from .attributes import PREFIX_ATTRIBUTE_KEY, PrefixCacheMatchInfo, estimate_input_tokens
from .profile_handlers import SchedulingError

log = logging.getLogger("router.disagg")


@dataclasses.dataclass
class PdClassifierConfig:
    """The YAML ``disagg: {classifier: ...}`` section (config/loader.py
    applies it to every handler exposing ``set_classifier``, the
    ``scheduling.pickSeed`` precedent).

    The classifier is the session-aware prefill stage PPD
    (arXiv:2603.13358) motivates: multi-turn traffic splits into cache-hit
    prefills (cheap, decode-adjacent) and cold prefills (expensive,
    prefill-pool work). When the *confidence-adjusted* cold-token estimate
    for the chosen decode pod falls under ``cold_token_threshold``, the
    P/D hop is skipped entirely — no prefill leg, no KV pull for blocks
    the decode pod already holds. ``enabled: false`` (the default) is the
    kill-switch: the handler behaves bit-identically to the pre-classifier
    always-run-the-decider path."""

    enabled: bool = False
    # Confidence-adjusted cold tokens below this → skip the hop. Same
    # units as PrefixBasedPdDecider.thresholdTokens: the router-side
    # estimate (exact when a token producer tokenized the prompt, chars/4
    # otherwise).
    cold_token_threshold: int = 256
    # Minimum trust in the hit prediction before the classifier may act.
    # Confidence saturates with joined predicted→confirmed observations
    # (CacheLedger → Datastore.kv_obs): n / (n + PRIOR_N), so the default
    # 0.5 requires PRIOR_N measured joins before the first skip.
    min_confidence: float = 0.5
    # Measured-pair-cost coupling (ROADMAP item 1's noted extension): the
    # cheapest measured KV-pull EWMA into the chosen decode pod scales the
    # skip threshold by clamp(pull_ms / pairCostRefMs, MARGIN band) — a
    # cheap measured pull weakens the case for skipping the hop (the hop
    # costs little), an expensive one strengthens it. 0 (the default)
    # disables the coupling; with no measured pair into the pod the margin
    # is neutral either way (bit-identical on a cold TransferTable).
    pair_cost_ref_ms: float = 0.0

    @classmethod
    def from_spec(cls, spec: dict[str, Any] | None) -> "PdClassifierConfig":
        spec = spec or {}
        return cls(
            enabled=bool(spec.get("enabled", False)),
            cold_token_threshold=max(
                0, int(spec.get("coldTokenThreshold", 256))),
            min_confidence=min(max(
                float(spec.get("minConfidence", 0.5)), 0.0), 1.0),
            pair_cost_ref_ms=max(
                0.0, float(spec.get("pairCostRefMs", 0.0))))


@register_plugin("prefix-based-pd-decider")
class PrefixBasedPdDecider(PluginBase):
    """Disaggregate iff non-cached input tokens ≥ threshold
    (prefix_based_pd_decider.go:99-149)."""

    # Audited: disaggregate (called off-loop from the disagg handler's
    # pick_profiles) only reads the request and endpoint attributes;
    # threshold_tokens is configure-time constant.
    THREAD_SAFE = True

    def __init__(self, name: str | None = None):
        super().__init__(name)
        self.threshold_tokens = 256

    def configure(self, params: dict[str, Any], handle: Any) -> None:
        self.threshold_tokens = int(params.get("thresholdTokens", self.threshold_tokens))

    def disaggregate(self, ctx: Any, request: InferenceRequest,
                     decode_endpoint: Endpoint) -> bool:
        input_tokens = estimate_input_tokens(request)
        info: PrefixCacheMatchInfo | None = decode_endpoint.attributes.get(
            PREFIX_ATTRIBUTE_KEY)
        cached = info.match_blocks * info.block_size_tokens if info else 0
        return (input_tokens - cached) >= self.threshold_tokens


@register_plugin("always-disagg-pd-decider")
class AlwaysDisaggPdDecider(PluginBase):
    """Always split (benchmarking — always_disagg_pd_decider.go)."""

    THREAD_SAFE = True  # audited: stateless

    def disaggregate(self, ctx, request, decode_endpoint) -> bool:
        return True


@register_plugin("always-disagg-multimodal-decider")
class AlwaysDisaggMultimodalDecider(PluginBase):
    """Split iff the request carries image/video/audio blocks
    (always_disagg_mm_decider.go)."""

    MM_TYPES = ("image_url", "video_url", "input_audio")

    THREAD_SAFE = True  # audited: pure read of the request body

    def disaggregate(self, ctx, request, decode_endpoint) -> bool:
        chat = request.body.chat_completions
        if not chat:
            return False
        for m in chat.get("messages", []):
            content = m.get("content")
            if isinstance(content, list):
                for block in content:
                    if isinstance(block, dict) and block.get("type") in self.MM_TYPES:
                        return True
        return False


@register_plugin("data-parallel-profile-handler")
class DataParallelProfileHandler(PluginBase):
    """DP-rank routing (reference profilehandler/dataparallel/
    dp_profile_handler.go:21-40, deprecated there in favor of Istio ≥1.28.1
    but kept for inventory parity): a single profile picks the pod; this
    handler then selects a DP rank and writes x-data-parallel-host-port so
    the sidecar's per-rank listener (port+rank) dispatches to that rank's
    engine. Rank count comes from the pod label llm-d.ai/dp-size."""

    DP_SIZE_LABEL = "llm-d.ai/dp-size"

    # Audited: pick_profiles/process_results (the off-loop methods) are
    # stateless; the _rr rotation is only mutated in pre_request, which the
    # director runs on the event loop.
    THREAD_SAFE = True

    def __init__(self, name: str | None = None):
        super().__init__(name)
        self._rr = 0

    def pick_profiles(self, ctx, request, profiles, results):
        return {} if results else profiles

    def process_results(self, ctx, request, results):
        ok = {n: r for n, r in results.items() if r is not None}
        if not ok:
            raise SchedulingError("no profile produced a target endpoint")
        return SchedulingResult(profile_results=ok,
                                primary_profile_name=next(iter(ok)))

    def pre_request(self, ctx, request: InferenceRequest,
                    result: SchedulingResult) -> None:
        targets = result.primary().target_endpoints
        if not targets:
            return
        ep = targets[0]
        try:
            dp_size = int(ep.metadata.labels.get(self.DP_SIZE_LABEL, "1"))
        except ValueError:
            dp_size = 1
        if dp_size <= 1:
            return
        rank = self._rr % dp_size
        self._rr += 1
        request.headers[H_DATA_PARALLEL] = (
            f"{ep.metadata.address}:{ep.metadata.port + rank}")


@register_plugin("disagg-headers-handler", "prefill-header-handler")
class DisaggHeadersHandler(PluginBase):
    """Header-only PreRequest wiring for externally-orchestrated disagg
    profiles (reference disagg_headers_handler.go — deprecated there in
    favor of disagg-profile-handler's native PreRequest, kept for config
    compatibility): reads the named prefill/encode profile results off the
    SchedulingResult and writes x-prefiller-host-port /
    x-encoder-hosts-ports, clearing any stale values first."""

    def __init__(self, name: str | None = None):
        super().__init__(name)
        self.prefill_profile = "prefill"
        self.encode_profile = "encode"

    def configure(self, params: dict[str, Any], handle: Any) -> None:
        self.prefill_profile = params.get("prefillProfile", self.prefill_profile)
        self.encode_profile = params.get("encodeProfile", self.encode_profile)

    def pre_request(self, ctx, request: InferenceRequest,
                    result: SchedulingResult) -> None:
        if result is None:
            return
        request.headers.pop(H_PREFILLER, None)
        prefill = result.profile_results.get(self.prefill_profile)
        if prefill and prefill.target_endpoints:
            request.headers[H_PREFILLER] = ",".join(
                ep.metadata.address_port for ep in prefill.target_endpoints)
        request.headers.pop(H_ENCODERS, None)
        encode = result.profile_results.get(self.encode_profile)
        if encode and encode.target_endpoints:
            request.headers[H_ENCODERS] = ",".join(
                ep.metadata.address_port for ep in encode.target_endpoints)


@register_plugin("disagg-profile-handler", "pd-profile-handler")
class DisaggProfileHandler(PluginBase):
    """Unified D / P-D (E-stages reserved) profile orchestration."""

    DECODE, PREFILL, ENCODE = "decode", "prefill", "encode"

    # Audited: pick_profiles/process_results read configure-time decider
    # refs and per-cycle arguments only; the deciders they delegate to
    # declare their own THREAD_SAFE audits. A decider declaring False makes
    # this handler unsafe too — the scheduler pool enforces that at bind
    # time (schedpool._handler_threadsafe trampolines the whole handler).
    # The classifier stage keeps the audit: KvHitTable.pod()/overall() are
    # single GIL-atomic dict reads, the verdict stamp is one attribute
    # store on the request, the DecisionRecord write is one slot set, and
    # prometheus counters are thread-safe.
    THREAD_SAFE = True

    # Confidence prior for the trust gate: confidence = n / (n + PRIOR_N)
    # over the pod's (or, before the pod has its own record, the pool-wide)
    # joined predicted→confirmed observation count. With the default
    # minConfidence 0.5 the classifier will not skip until PRIOR_N joins
    # have been measured.
    CONFIDENCE_PRIOR_N = 4
    # Pair-cost margin clamp band: the measured-pull/reference ratio can at
    # most halve or double the skip threshold — a single extreme EWMA must
    # not swing the classifier to always/never skipping.
    PAIR_MARGIN_MIN = 0.5
    PAIR_MARGIN_MAX = 2.0

    def __init__(self, name: str | None = None):
        super().__init__(name)
        self.pd_decider: Any = None
        self.encode_decider: Any = None
        # Session-aware prefill classifier (PdClassifierConfig): None or
        # enabled: false keeps the handler bit-identical to the
        # pre-classifier router. The loader injects the `disagg:
        # {classifier: ...}` config post-instantiation (set_classifier).
        self.classifier_cfg: PdClassifierConfig | None = None
        self._datastore: Any = None
        # Flat skip counter beside the Prometheus family: the rebalance
        # controller (router/rebalance.py) reads it per tick — a sustained
        # skip rate is evidence the prefill pool is over-provisioned for
        # the live mix (the degraded_total precedent). += under the GIL;
        # a racing off-loop cycle at worst defers one count a tick.
        self.hop_skips = 0

    def configure(self, params: dict[str, Any], handle: Any) -> None:
        # The KvHitTable trust signal lives on the datastore
        # (Datastore.kv_obs, PR 10 — built explicitly as this classifier's
        # input); tests constructing the handler directly may leave it None
        # (the classifier then runs with zero measured trust).
        self._datastore = getattr(handle, "datastore", None)
        spec = params.get("pdDecider") or {"type": "prefix-based-pd-decider"}
        if isinstance(spec, str):
            spec = {"type": spec}
        self.pd_decider = global_registry.instantiate(
            spec["type"], spec.get("name") or spec["type"],
            spec.get("parameters") or params.get("pdDeciderParameters") or {}, handle)
        enc = params.get("encodeDecider")
        if enc:
            if isinstance(enc, str):
                enc = {"type": enc}
            self.encode_decider = global_registry.instantiate(
                enc["type"], enc.get("name") or enc["type"],
                enc.get("parameters") or {}, handle)

    def set_classifier(self, cfg: PdClassifierConfig,
                       datastore: Any = None) -> None:
        """Loader hook (config/loader.py): apply the top-level ``disagg:
        {classifier: ...}`` section. ``datastore`` override is for tests."""
        self.classifier_cfg = cfg
        if datastore is not None:
            self._datastore = datastore

    # ---- prefill classifier (PPD, arXiv:2603.13358) ---------------------

    def _classify(self, request: InferenceRequest, decode_ep: Endpoint,
                  decode_res: ProfileRunResult | None) -> dict[str, Any] | None:
        """Classify the chosen decode pod's prefill: estimate its expected
        prefix-hit depth from the same per-candidate signals the CacheLedger
        stamps (PrefixCacheMatchInfo attribute, precise-prefix raw scores),
        discount it by the pod's measured KvHitTable signed-error EWMA, and
        verdict ``skip`` when the confidence-adjusted cold-token estimate
        falls under the threshold. Returns the explainable verdict block
        (recorded on the DecisionRecord, judged post-hoc by the
        CacheLedger), or None when the stage is disabled."""
        cfg = self.classifier_cfg
        if cfg is None or not cfg.enabled:
            return None
        addr = decode_ep.metadata.address_port
        input_tokens = estimate_input_tokens(request)

        # Predicted hit ratio: the approx producer's per-request attribute
        # and/or the precise scorer's event-fed raw score — take the more
        # optimistic signal (both under-predict in distinct blind spots:
        # approx is LRU-bounded, precise sees only event-fed pods).
        info: PrefixCacheMatchInfo | None = decode_ep.attributes.get(
            PREFIX_ATTRIBUTE_KEY)
        predicted_ratio = info.hit_ratio if info is not None else 0.0
        source = "approx" if info is not None else "none"
        if decode_res is not None:
            for name, scores in decode_res.raw_scores.items():
                if "precise-prefix" in name:
                    pr = scores.get(addr)
                    if pr is not None and pr > predicted_ratio:
                        predicted_ratio = min(max(pr, 0.0), 1.0)
                        source = "precise"

        # Trust, two-scope: the signed-error DISCOUNT is pod-scoped when
        # the pod has its own predicted-vs-confirmed record (pool-wide
        # otherwise — a decode pod that always rides the P/D hop never
        # lands its own joins, the actual is confirmed on the prefill pod,
        # so without the fallback the classifier could never bootstrap out
        # of always-disagg). CONFIDENCE is pool-scoped deliberately: it
        # gates on how much the predict→confirm loop has measured AT ALL,
        # and a pod's first own join must not reset an established pool
        # record back below the gate (n flipping 6 → 1 would re-close a
        # classifier that just started skipping).
        table = getattr(self._datastore, "kv_obs", None)
        pod_stats = table.pod(addr) if table is not None else None
        pool_stats = table.overall() if table is not None else None
        pod_n = pod_stats.n if pod_stats is not None else 0
        pool_n = pool_stats.n if pool_stats is not None else 0
        if pod_n > 0 and pod_stats.ewma_signed_error is not None:
            signed, scope = pod_stats.ewma_signed_error, "pod"
        elif pool_n > 0 and pool_stats.ewma_signed_error is not None:
            signed, scope = pool_stats.ewma_signed_error, "pool"
        else:
            signed, scope = 0.0, "none"
        confidence = pool_n / (pool_n + self.CONFIDENCE_PRIOR_N)

        # Trust discount: signed error is predicted − actual in hit-ratio
        # units; positive = the scorers promise more reuse than the engine
        # finds, so subtract it. A pod that under-promises (negative) is
        # NOT inflated — the discount only ever makes the estimate more
        # conservative.
        adjusted_ratio = min(max(predicted_ratio - max(signed, 0.0), 0.0), 1.0)
        expected_cold = input_tokens * (1.0 - adjusted_ratio)

        # Measured-pair-cost margin (ROADMAP item 1's noted extension):
        # skipping the hop avoids the KV pull, so the skip/keep bar should
        # track what that pull actually costs TO THIS decode pod. The
        # cheapest measured pair EWMA scales the threshold — cheap pull →
        # lower threshold (keep the hop more often), expensive pull →
        # higher (skip more eagerly). No measured pair → neutral margin,
        # bit-identical to the uncoupled classifier.
        threshold = float(cfg.cold_token_threshold)
        pair_block: dict[str, Any] | None = None
        if cfg.pair_cost_ref_ms > 0:
            table_t = getattr(self._datastore, "transfers", None)
            min_pull = (table_t.cheapest_pull_ms(addr)
                        if table_t is not None else None)
            if min_pull is not None:
                margin = min(max(min_pull / cfg.pair_cost_ref_ms,
                                 self.PAIR_MARGIN_MIN),
                             self.PAIR_MARGIN_MAX)
                threshold = cfg.cold_token_threshold * margin
                pair_block = {
                    "min_ewma_pull_ms": round(min_pull, 3),
                    "ref_ms": cfg.pair_cost_ref_ms,
                    "margin": round(margin, 4),
                    "effective_threshold": round(threshold, 1),
                }

        if predicted_ratio <= 0.0:
            verdict = "keep"      # no reuse signal — nothing to act on
        elif confidence < cfg.min_confidence:
            verdict = "low_confidence"
        elif expected_cold < threshold:
            verdict = "skip"
        else:
            verdict = "keep"
        block: dict[str, Any] = {
            "verdict": verdict,
            "pod": addr,
            "input_tokens": input_tokens,
            "predicted_ratio": round(predicted_ratio, 4),
            "predicted_source": source,
            "trust": {"scope": scope, "pod_n": pod_n, "pool_n": pool_n,
                      "ewma_signed_error": round(signed, 4),
                      "confidence": round(confidence, 4)},
            "adjusted_ratio": round(adjusted_ratio, 4),
            "expected_cold_tokens": round(expected_cold, 1),
            "threshold": cfg.cold_token_threshold,
            "min_confidence": cfg.min_confidence,
        }
        if pair_block is not None:
            block["pair_cost"] = pair_block
        return block

    def _stamp_classifier(self, request: InferenceRequest,
                          block: dict[str, Any]) -> None:
        """Stamp the verdict where the observability stack reads it: the
        request (the CacheLedger's post-hoc judge), the DecisionRecord
        (/debug/decisions/<id>), and the aggregate counters. A failover
        reschedule re-classifies against the fresh decode pick; the stamped
        dict is updated IN PLACE so the record and the judge follow the
        verdict that actually served (unless the response already landed
        and judged it — then the verdict is history)."""
        PD_CLASSIFIER_DECISIONS_TOTAL.labels(block["verdict"]).inc()
        prev = getattr(request, "classifier", None)
        if prev is None:
            request.classifier = block
            rec = getattr(request, "decision", None)
            if rec is not None and hasattr(rec, "record_classifier"):
                rec.record_classifier(block)
        elif "judged" not in prev:
            prev.clear()
            prev.update(block)

    # ---- ProfileHandler ------------------------------------------------

    def pick_profiles(self, ctx, request: InferenceRequest, profiles: dict[str, Any],
                      results: dict[str, ProfileRunResult]) -> dict[str, Any]:
        # Decode first, always (disagg_profile_handler.go:246-319).
        if self.DECODE not in results:
            if self.DECODE not in profiles:
                raise SchedulingError("disagg-profile-handler requires a 'decode' profile")
            return {self.DECODE: profiles[self.DECODE]}
        decode_res = results.get(self.DECODE)
        if decode_res is None:
            return {}  # decode failed; nothing else to do

        to_run: dict[str, Any] = {}
        decode_ep = decode_res.target_endpoints[0]
        # Pair-scoring hook: the chosen decode pod, stamped BEFORE the
        # prefill profile runs, is what lets prefill-profile scorers
        # (transfer-aware-pair-scorer) and shadow policies
        # (router/shadow.py) score the (prefill, decode) PAIR instead of
        # the legs independently — NetKV (arXiv:2606.03910), ROADMAP item
        # 2. One attribute store; thread-safe for off-loop cycles.
        request.decode_pick = decode_ep.metadata.address_port
        if (self.ENCODE in profiles and self.ENCODE not in results
                and self.encode_decider is not None
                and self.encode_decider.disaggregate(ctx, request, decode_ep)):
            to_run[self.ENCODE] = profiles[self.ENCODE]
        if (self.PREFILL in profiles and self.PREFILL not in results
                and self.pd_decider is not None):
            # Prefill-classifier stage (PPD): a confident cache-hit prefill
            # routes straight to the decode pod — the prefill profile never
            # runs, so pre_request writes no x-prefiller header and the
            # sidecar decodes locally (no prefill leg, no KV pull). Any
            # other verdict (keep / low_confidence / classifier disabled)
            # falls through to the configured PD decider unchanged.
            block = self._classify(request, decode_ep, decode_res)
            if block is not None:
                self._stamp_classifier(request, block)
            if block is not None and block["verdict"] == "skip":
                PD_HOP_SKIPPED_TOTAL.inc()
                self.hop_skips += 1
            elif self.pd_decider.disaggregate(ctx, request, decode_ep):
                to_run[self.PREFILL] = profiles[self.PREFILL]
        return to_run

    def process_results(self, ctx, request, results) -> SchedulingResult:
        ok = {n: r for n, r in results.items() if r is not None}
        if self.DECODE not in ok:
            raise SchedulingError("no decode endpoint available")
        stages = []
        if self.ENCODE in ok:
            stages.append("encode")
        if self.PREFILL in ok:
            stages.append("prefill")
        stages.append("decode")
        DISAGG_DECISION_TOTAL.labels(decision_type="-".join(stages)).inc()
        return SchedulingResult(profile_results=ok, primary_profile_name=self.DECODE)

    # ---- PreRequest: routing headers (disagg_profile_handler.go:360-444) --

    def pre_request(self, ctx, request: InferenceRequest,
                    result: SchedulingResult) -> None:
        # Delete-then-set (reference disagg_profile_handler.go PreRequest):
        # ingress already strips client-supplied routing headers, but an
        # earlier plugin in the PreRequest chain may have written them.
        # The FULL ranked candidate list rides the header (comma-separated):
        # the sidecar's P/D protocols fail over across candidates before
        # falling back to local decode. Pickers default to one endpoint;
        # set maxNumOfEndpoints > 1 on the prefill profile's picker to give
        # the sidecar failover room.
        request.headers.pop(H_PREFILLER, None)
        prefill = result.profile_results.get(self.PREFILL)
        if prefill and prefill.target_endpoints:
            request.headers[H_PREFILLER] = ",".join(
                ep.metadata.address_port for ep in prefill.target_endpoints)
        request.headers.pop(H_ENCODERS, None)
        encode = result.profile_results.get(self.ENCODE)
        if encode and encode.target_endpoints:
            request.headers[H_ENCODERS] = ",".join(
                ep.metadata.address_port for ep in encode.target_endpoints)
