"""P/D(/E) disaggregation: profile handler + deciders.

Mirrors the reference's disagg-profile-handler
(/root/reference/pkg/epp/framework/plugins/scheduling/profilehandler/disagg/
disagg_profile_handler.go:246-444) and its decider sub-plugins
(decider_plugin.go, prefix_based_pd_decider.go:99-149):

- the decode profile always runs first;
- the prefill stage is gated by a PD decider evaluated against the *chosen
  decode pod's* prefix-cache state (only non-cached prefix tokens justify a
  remote prefill);
- PreRequest writes the x-prefiller-host-port (and x-encoder-hosts-ports)
  routing headers consumed by the decode pod's sidecar.
"""

from __future__ import annotations

import logging
from typing import Any

from ..framework.datalayer import Endpoint
from ..framework.plugin import PluginBase, global_registry, register_plugin
from ..framework.scheduling import (
    InferenceRequest,
    ProfileRunResult,
    SchedulingResult,
)
from ..metrics import DISAGG_DECISION_TOTAL
from ..requestcontrol.director import H_DATA_PARALLEL, H_ENCODERS, H_PREFILLER
from .attributes import PREFIX_ATTRIBUTE_KEY, PrefixCacheMatchInfo, estimate_input_tokens
from .profile_handlers import SchedulingError

log = logging.getLogger("router.disagg")


@register_plugin("prefix-based-pd-decider")
class PrefixBasedPdDecider(PluginBase):
    """Disaggregate iff non-cached input tokens ≥ threshold
    (prefix_based_pd_decider.go:99-149)."""

    # Audited: disaggregate (called off-loop from the disagg handler's
    # pick_profiles) only reads the request and endpoint attributes;
    # threshold_tokens is configure-time constant.
    THREAD_SAFE = True

    def __init__(self, name: str | None = None):
        super().__init__(name)
        self.threshold_tokens = 256

    def configure(self, params: dict[str, Any], handle: Any) -> None:
        self.threshold_tokens = int(params.get("thresholdTokens", self.threshold_tokens))

    def disaggregate(self, ctx: Any, request: InferenceRequest,
                     decode_endpoint: Endpoint) -> bool:
        input_tokens = estimate_input_tokens(request)
        info: PrefixCacheMatchInfo | None = decode_endpoint.attributes.get(
            PREFIX_ATTRIBUTE_KEY)
        cached = info.match_blocks * info.block_size_tokens if info else 0
        return (input_tokens - cached) >= self.threshold_tokens


@register_plugin("always-disagg-pd-decider")
class AlwaysDisaggPdDecider(PluginBase):
    """Always split (benchmarking — always_disagg_pd_decider.go)."""

    THREAD_SAFE = True  # audited: stateless

    def disaggregate(self, ctx, request, decode_endpoint) -> bool:
        return True


@register_plugin("always-disagg-multimodal-decider")
class AlwaysDisaggMultimodalDecider(PluginBase):
    """Split iff the request carries image/video/audio blocks
    (always_disagg_mm_decider.go)."""

    MM_TYPES = ("image_url", "video_url", "input_audio")

    THREAD_SAFE = True  # audited: pure read of the request body

    def disaggregate(self, ctx, request, decode_endpoint) -> bool:
        chat = request.body.chat_completions
        if not chat:
            return False
        for m in chat.get("messages", []):
            content = m.get("content")
            if isinstance(content, list):
                for block in content:
                    if isinstance(block, dict) and block.get("type") in self.MM_TYPES:
                        return True
        return False


@register_plugin("data-parallel-profile-handler")
class DataParallelProfileHandler(PluginBase):
    """DP-rank routing (reference profilehandler/dataparallel/
    dp_profile_handler.go:21-40, deprecated there in favor of Istio ≥1.28.1
    but kept for inventory parity): a single profile picks the pod; this
    handler then selects a DP rank and writes x-data-parallel-host-port so
    the sidecar's per-rank listener (port+rank) dispatches to that rank's
    engine. Rank count comes from the pod label llm-d.ai/dp-size."""

    DP_SIZE_LABEL = "llm-d.ai/dp-size"

    # Audited: pick_profiles/process_results (the off-loop methods) are
    # stateless; the _rr rotation is only mutated in pre_request, which the
    # director runs on the event loop.
    THREAD_SAFE = True

    def __init__(self, name: str | None = None):
        super().__init__(name)
        self._rr = 0

    def pick_profiles(self, ctx, request, profiles, results):
        return {} if results else profiles

    def process_results(self, ctx, request, results):
        ok = {n: r for n, r in results.items() if r is not None}
        if not ok:
            raise SchedulingError("no profile produced a target endpoint")
        return SchedulingResult(profile_results=ok,
                                primary_profile_name=next(iter(ok)))

    def pre_request(self, ctx, request: InferenceRequest,
                    result: SchedulingResult) -> None:
        targets = result.primary().target_endpoints
        if not targets:
            return
        ep = targets[0]
        try:
            dp_size = int(ep.metadata.labels.get(self.DP_SIZE_LABEL, "1"))
        except ValueError:
            dp_size = 1
        if dp_size <= 1:
            return
        rank = self._rr % dp_size
        self._rr += 1
        request.headers[H_DATA_PARALLEL] = (
            f"{ep.metadata.address}:{ep.metadata.port + rank}")


@register_plugin("disagg-headers-handler", "prefill-header-handler")
class DisaggHeadersHandler(PluginBase):
    """Header-only PreRequest wiring for externally-orchestrated disagg
    profiles (reference disagg_headers_handler.go — deprecated there in
    favor of disagg-profile-handler's native PreRequest, kept for config
    compatibility): reads the named prefill/encode profile results off the
    SchedulingResult and writes x-prefiller-host-port /
    x-encoder-hosts-ports, clearing any stale values first."""

    def __init__(self, name: str | None = None):
        super().__init__(name)
        self.prefill_profile = "prefill"
        self.encode_profile = "encode"

    def configure(self, params: dict[str, Any], handle: Any) -> None:
        self.prefill_profile = params.get("prefillProfile", self.prefill_profile)
        self.encode_profile = params.get("encodeProfile", self.encode_profile)

    def pre_request(self, ctx, request: InferenceRequest,
                    result: SchedulingResult) -> None:
        if result is None:
            return
        request.headers.pop(H_PREFILLER, None)
        prefill = result.profile_results.get(self.prefill_profile)
        if prefill and prefill.target_endpoints:
            request.headers[H_PREFILLER] = ",".join(
                ep.metadata.address_port for ep in prefill.target_endpoints)
        request.headers.pop(H_ENCODERS, None)
        encode = result.profile_results.get(self.encode_profile)
        if encode and encode.target_endpoints:
            request.headers[H_ENCODERS] = ",".join(
                ep.metadata.address_port for ep in encode.target_endpoints)


@register_plugin("disagg-profile-handler", "pd-profile-handler")
class DisaggProfileHandler(PluginBase):
    """Unified D / P-D (E-stages reserved) profile orchestration."""

    DECODE, PREFILL, ENCODE = "decode", "prefill", "encode"

    # Audited: pick_profiles/process_results read configure-time decider
    # refs and per-cycle arguments only; the deciders they delegate to
    # declare their own THREAD_SAFE audits. A decider declaring False makes
    # this handler unsafe too — the scheduler pool enforces that at bind
    # time (schedpool._handler_threadsafe trampolines the whole handler).
    THREAD_SAFE = True

    def __init__(self, name: str | None = None):
        super().__init__(name)
        self.pd_decider: Any = None
        self.encode_decider: Any = None

    def configure(self, params: dict[str, Any], handle: Any) -> None:
        spec = params.get("pdDecider") or {"type": "prefix-based-pd-decider"}
        if isinstance(spec, str):
            spec = {"type": spec}
        self.pd_decider = global_registry.instantiate(
            spec["type"], spec.get("name") or spec["type"],
            spec.get("parameters") or params.get("pdDeciderParameters") or {}, handle)
        enc = params.get("encodeDecider")
        if enc:
            if isinstance(enc, str):
                enc = {"type": enc}
            self.encode_decider = global_registry.instantiate(
                enc["type"], enc.get("name") or enc["type"],
                enc.get("parameters") or {}, handle)

    # ---- ProfileHandler ------------------------------------------------

    def pick_profiles(self, ctx, request: InferenceRequest, profiles: dict[str, Any],
                      results: dict[str, ProfileRunResult]) -> dict[str, Any]:
        # Decode first, always (disagg_profile_handler.go:246-319).
        if self.DECODE not in results:
            if self.DECODE not in profiles:
                raise SchedulingError("disagg-profile-handler requires a 'decode' profile")
            return {self.DECODE: profiles[self.DECODE]}
        decode_res = results.get(self.DECODE)
        if decode_res is None:
            return {}  # decode failed; nothing else to do

        to_run: dict[str, Any] = {}
        decode_ep = decode_res.target_endpoints[0]
        if (self.ENCODE in profiles and self.ENCODE not in results
                and self.encode_decider is not None
                and self.encode_decider.disaggregate(ctx, request, decode_ep)):
            to_run[self.ENCODE] = profiles[self.ENCODE]
        if (self.PREFILL in profiles and self.PREFILL not in results
                and self.pd_decider is not None
                and self.pd_decider.disaggregate(ctx, request, decode_ep)):
            to_run[self.PREFILL] = profiles[self.PREFILL]
        return to_run

    def process_results(self, ctx, request, results) -> SchedulingResult:
        ok = {n: r for n, r in results.items() if r is not None}
        if self.DECODE not in ok:
            raise SchedulingError("no decode endpoint available")
        stages = []
        if self.ENCODE in ok:
            stages.append("encode")
        if self.PREFILL in ok:
            stages.append("prefill")
        stages.append("decode")
        DISAGG_DECISION_TOTAL.labels(decision_type="-".join(stages)).inc()
        return SchedulingResult(profile_results=ok, primary_profile_name=self.DECODE)

    # ---- PreRequest: routing headers (disagg_profile_handler.go:360-444) --

    def pre_request(self, ctx, request: InferenceRequest,
                    result: SchedulingResult) -> None:
        # Delete-then-set (reference disagg_profile_handler.go PreRequest):
        # ingress already strips client-supplied routing headers, but an
        # earlier plugin in the PreRequest chain may have written them.
        # The FULL ranked candidate list rides the header (comma-separated):
        # the sidecar's P/D protocols fail over across candidates before
        # falling back to local decode. Pickers default to one endpoint;
        # set maxNumOfEndpoints > 1 on the prefill profile's picker to give
        # the sidecar failover room.
        request.headers.pop(H_PREFILLER, None)
        prefill = result.profile_results.get(self.PREFILL)
        if prefill and prefill.target_endpoints:
            request.headers[H_PREFILLER] = ",".join(
                ep.metadata.address_port for ep in prefill.target_endpoints)
        request.headers.pop(H_ENCODERS, None)
        encode = result.profile_results.get(self.ENCODE)
        if encode and encode.target_endpoints:
            request.headers[H_ENCODERS] = ",".join(
                ep.metadata.address_port for ep in encode.target_endpoints)
