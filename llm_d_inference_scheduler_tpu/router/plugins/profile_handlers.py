"""Profile handlers (reference: framework/plugins/scheduling/profilehandler/*)."""

from __future__ import annotations

from typing import Any

from ..framework.plugin import PluginBase, register_plugin
from ..framework.scheduling import InferenceRequest, ProfileRunResult, SchedulingResult


class SchedulingError(Exception):
    pass


@register_plugin("single-profile-handler")
class SingleProfileHandler(PluginBase):
    """One profile, one pass (reference profilehandler/single)."""

    # Audited: pick_profiles/process_results (the methods that run inside
    # Scheduler.schedule, off-loop under the scheduler pool) are stateless.
    THREAD_SAFE = True

    def pick_profiles(self, ctx, request: InferenceRequest, profiles: dict[str, Any],
                      results: dict[str, ProfileRunResult]) -> dict[str, Any]:
        if results:
            return {}
        return profiles

    def process_results(self, ctx, request, results) -> SchedulingResult:
        ok = {n: r for n, r in results.items() if r is not None}
        if not ok:
            raise SchedulingError("no profile produced a target endpoint")
        primary = next(iter(ok))
        return SchedulingResult(profile_results=ok, primary_profile_name=primary)
