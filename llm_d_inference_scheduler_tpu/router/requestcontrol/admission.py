"""Admission controllers (reference: pkg/epp/requestcontrol/admission.go).

LegacyAdmissionController: sheddable requests (priority < 0) are rejected
while the pool saturation is >= 1.0 (admission.go:64-128). The
flow-control-backed controller lives in router.flowcontrol and blocks in
EnqueueAndWait instead.
"""

from __future__ import annotations

from typing import Any

from ..framework.datalayer import Endpoint
from ..framework.scheduling import InferenceRequest

X_REMOVAL_REASON = "x-removal-reason"


class AdmissionError(Exception):
    def __init__(self, code: int, reason: str, *,
                 retry_after_s: float | None = None, shed: bool = False):
        super().__init__(reason)
        self.code = code
        self.reason = reason
        # Overload-control extras (router/overload.py): a finite computed
        # Retry-After for 429s, and the shed marker that makes the SLO
        # ledger stamp the distinct "shed" verdict instead of "error".
        self.retry_after_s = retry_after_s
        self.shed = shed


class LegacyAdmissionController:
    def __init__(self, detector: Any):
        self.detector = detector

    async def admit(self, ctx: Any, request: InferenceRequest,
                    endpoints: list[Endpoint]) -> None:
        if request.objectives.priority >= 0:
            return  # non-sheddable: always admitted here
        if self.detector is not None and self.detector.saturation(endpoints) >= 1.0:
            raise AdmissionError(429, "saturated: sheddable request rejected")


class AlwaysAdmitController:
    async def admit(self, ctx, request, endpoints) -> None:
        return
