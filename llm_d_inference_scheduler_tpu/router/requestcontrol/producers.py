"""DataProducer plugins: approximate prefix cache + in-flight load.

- approx-prefix-cache-producer (reference:
  framework/plugins/requestcontrol/dataproducer/approximateprefix — xxhash
  chains of prompt blocks, per-pod LRU of served block hashes; Produce writes
  PrefixCacheMatchInfo per endpoint, PreRequest records the chosen pod's
  blocks; block size auto-tunes from the endpoint's cache_config metrics).
- inflight-load-producer (reference: .../dataproducer/inflightload — atomic
  per-endpoint in-flight request/token counters via PreRequest /
  ResponseComplete; writes InFlightLoad).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any

from ...utils.hashing import text_fingerprint
from ..framework.datalayer import Endpoint
from ..hashmemo import request_prefix_hashes
from ..framework.plugin import PluginBase, register_plugin
from ..framework.scheduling import InferenceRequest, SchedulingResult
from ..metrics import PREFIX_HIT_RATIO
from ..plugins.attributes import (
    INFLIGHT_ATTRIBUTE_KEY,
    PREFIX_ATTRIBUTE_KEY,
    InFlightLoad,
    PrefixCacheMatchInfo,
    estimate_input_tokens,
)

DEFAULT_BLOCK_SIZE_TOKENS = 16
DEFAULT_LRU_CAPACITY = 4096


class _PodLru:
    """LRU set of block hashes served by one pod."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._od: OrderedDict[int, None] = OrderedDict()

    def add(self, h: int) -> None:
        self._od[h] = None
        self._od.move_to_end(h)
        while len(self._od) > self.capacity:
            self._od.popitem(last=False)

    def resize(self, capacity: int) -> None:
        self.capacity = capacity
        while len(self._od) > capacity:
            self._od.popitem(last=False)

    def contains(self, h: int) -> bool:
        if h in self._od:
            self._od.move_to_end(h)
            return True
        return False

    def __len__(self):
        return len(self._od)


@register_plugin("approx-prefix-cache-producer", "prefix-cache-producer")
class ApproxPrefixCacheProducer(PluginBase):
    def __init__(self, name: str | None = None):
        super().__init__(name)
        self.block_size_tokens = DEFAULT_BLOCK_SIZE_TOKENS
        self.lru_capacity = DEFAULT_LRU_CAPACITY
        self._indexes: dict[str, _PodLru] = {}

    def configure(self, params: dict[str, Any], handle: Any) -> None:
        self.block_size_tokens = int(params.get("blockSizeTokens", self.block_size_tokens))
        self.lru_capacity = int(params.get("lruCapacity", self.lru_capacity))

    def produces(self) -> list[str]:
        return [PREFIX_ATTRIBUTE_KEY]

    def consumes(self) -> list[str]:
        return []

    def _block_size_for(self, ep: Endpoint) -> int:
        # autoTune from scraped cache geometry (reference plugin.go:135-248)
        return ep.metrics.cache_block_size or self.block_size_tokens

    def _lru_for(self, ep: Endpoint) -> _PodLru:
        key = ep.metadata.address_port
        # Capacity follows the scraped cache geometry: before the first
        # scrape lands, cache_num_blocks is 0 and the default applies, but
        # the LRU re-sizes as soon as (or whenever) real geometry appears —
        # it is never pinned at first sight. A scrape flapping back to 0
        # (family missing one poll) keeps the last known capacity rather
        # than shrinking to the default and evicting warm entries.
        scraped = ep.metrics.cache_num_blocks
        lru = self._indexes.get(key)
        if lru is None:
            lru = self._indexes[key] = _PodLru(scraped or self.lru_capacity)
        elif scraped and lru.capacity != scraped:
            lru.resize(scraped)
        return lru

    def _hashes(self, request: InferenceRequest, block_size: int) -> list[int]:
        return request_prefix_hashes(request, block_size)

    async def produce(self, ctx: Any, request: InferenceRequest,
                      endpoints: list[Endpoint]) -> None:
        for ep in endpoints:
            bs = self._block_size_for(ep)
            hashes = self._hashes(request, bs)
            lru = self._lru_for(ep)
            match = 0
            for h in hashes:
                if lru.contains(h):
                    match += 1
                else:
                    break  # prefix match must be consecutive from the start
            ep.attributes.put(PREFIX_ATTRIBUTE_KEY,
                              PrefixCacheMatchInfo(match, len(hashes), bs))
            if hashes:
                PREFIX_HIT_RATIO.observe(match / len(hashes))

    def pre_request(self, ctx: Any, request: InferenceRequest,
                    result: SchedulingResult) -> None:
        # The chosen pod will now hold these blocks: record them.
        for ep in result.primary().target_endpoints[:1]:
            bs = self._block_size_for(ep)
            lru = self._lru_for(ep)
            for h in self._hashes(request, bs):
                lru.add(h)

    def index_sizes(self) -> dict[str, int]:
        """Per-pod speculative index occupancy (block hashes this router
        believes each pod holds) — the approx half of /debug/kv's
        index-occupancy view (router/kvobs.py CacheLedger)."""
        return {pod: len(lru) for pod, lru in self._indexes.items()}

    def endpoint_removed(self, endpoint: Endpoint) -> None:
        self._indexes.pop(endpoint.metadata.address_port, None)

    def endpoint_added(self, endpoint: Endpoint) -> None:
        pass


@register_plugin("token-producer", "tokenizer")
class TokenProducer(PluginBase):
    """Tokenizes the prompt via an engine's render endpoints and publishes
    TokenizedPrompt on the request body.

    Reference: dataproducer/tokenizer — calls vLLM's /v1/completions/render +
    /v1/chat/completions/render over HTTP (tokenizer/vllm_http.go); here the
    TPU engines expose the same endpoints. An LRU keyed by
    (model, prompt-fingerprint) keeps repeat tokenizations off the producer
    budget.

    With ``udsPath`` set, the render calls go to a node-local tokenizer
    service over a unix-domain socket instead of the scheduled endpoint —
    the reference's UdsTokenizer transport (dataproducer/tokenizer/uds.go),
    which avoids a network hop for every admission-path tokenization.
    """

    TOKENIZED_KEY = "request/tokenized"

    def __init__(self, name: str | None = None):
        super().__init__(name)
        self.timeout_s = 0.35  # must fit the director's 400ms producer budget
        self.cache_capacity = 2048
        self.uds_path: str | None = None
        # Keyed by (model, xxh64(prompt-text)) — a fingerprint, not the
        # prompt itself: 2048 long prompts held verbatim pin megabytes.
        self._cache: OrderedDict[tuple[str, int], list[int]] = OrderedDict()
        self._client = None

    def configure(self, params: dict[str, Any], handle: Any) -> None:
        self.timeout_s = float(params.get("timeoutSeconds", self.timeout_s))
        self.cache_capacity = int(params.get("cacheCapacity", self.cache_capacity))
        self.uds_path = params.get("udsPath", self.uds_path) or None

    def produces(self) -> list[str]:
        return [self.TOKENIZED_KEY]

    def consumes(self) -> list[str]:
        return []

    async def produce(self, ctx: Any, request: InferenceRequest,
                      endpoints: list[Endpoint]) -> None:
        if request.body.tokenized_prompt is not None or not endpoints:
            return
        chat = request.body.chat_completions is not None
        key = (request.target_model, text_fingerprint(request.body.prompt_text()))
        cached = self._cache.get(key)
        if cached is not None:
            self._cache.move_to_end(key)
            request.body.tokenized_prompt = cached
            return
        import httpx

        if self._client is None:
            if self.uds_path:
                self._client = httpx.AsyncClient(
                    timeout=self.timeout_s,
                    transport=httpx.AsyncHTTPTransport(uds=self.uds_path))
            else:
                self._client = httpx.AsyncClient(timeout=self.timeout_s)
        path = "/v1/chat/completions/render" if chat else "/v1/completions/render"
        # UDS: the authority part is ignored by the socket transport but
        # required by the URL grammar (uds.go targets a fixed local service).
        base = ("http://tokenizer" if self.uds_path
                else endpoints[0].metadata.url)
        payload = (request.body.chat_completions if chat
                   else request.body.completions) or {}
        from ..tracing import tracer

        trace_headers: dict[str, str] = {}
        tracer.inject_headers(trace_headers)
        try:
            r = await self._client.post(base + path, json=payload,
                                        headers=trace_headers)
            r.raise_for_status()
            ids = r.json().get("token_ids")
        except Exception:
            return  # tokenization is best-effort; char estimates take over
        if isinstance(ids, list):
            request.body.tokenized_prompt = ids
            self._cache[key] = ids
            while len(self._cache) > self.cache_capacity:
                self._cache.popitem(last=False)


@register_plugin("inflight-load-producer")
class InflightLoadProducer(PluginBase):
    def __init__(self, name: str | None = None):
        super().__init__(name)
        self._loads: dict[str, InFlightLoad] = {}

    def produces(self) -> list[str]:
        return [INFLIGHT_ATTRIBUTE_KEY]

    def consumes(self) -> list[str]:
        return []

    async def produce(self, ctx, request, endpoints):
        for ep in endpoints:
            load = self._loads.get(ep.metadata.address_port, InFlightLoad())
            ep.attributes.put(INFLIGHT_ATTRIBUTE_KEY, load.clone())

    def _estimate_tokens(self, request: InferenceRequest) -> int:
        return estimate_input_tokens(request)

    def _release(self, key: str, request: InferenceRequest) -> None:
        load = self._loads.get(key)
        if load:
            load.requests = max(load.requests - 1, 0)
            load.tokens = max(load.tokens - self._estimate_tokens(request), 0)

    def pre_request(self, ctx, request, result: SchedulingResult) -> None:
        # The incremented endpoint is remembered ON the request: failover
        # can re-run pre_request (reschedule) or complete on a different
        # endpoint than was scheduled, and decrementing by the completion
        # endpoint would leak a permanent phantom +1 on the failed one.
        prev = getattr(request, "_inflight_load_key", None)
        if prev is not None:
            self._release(prev, request)
        for ep in result.primary().target_endpoints[:1]:
            key = ep.metadata.address_port
            load = self._loads.setdefault(key, InFlightLoad())
            load.requests += 1
            load.tokens += self._estimate_tokens(request)
            setattr(request, "_inflight_load_key", key)

    def response_complete(self, ctx, request, endpoint, usage) -> None:
        key = getattr(request, "_inflight_load_key", None)
        if key is None:
            key = endpoint.metadata.address_port if endpoint is not None else None
        if key is not None:
            self._release(key, request)
            setattr(request, "_inflight_load_key", None)
