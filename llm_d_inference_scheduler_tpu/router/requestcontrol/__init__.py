from .director import Director, RequestError
from .admission import LegacyAdmissionController
from . import producers  # noqa: F401 (registers plugins)
from . import predicted_latency  # noqa: F401 (registers plugins)
from . import admitters  # noqa: F401 (registers plugins)

__all__ = ["Director", "RequestError", "LegacyAdmissionController"]
