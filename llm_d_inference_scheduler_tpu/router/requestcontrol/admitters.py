"""AdmitRequest plugins: latency-slo-admitter + probabilistic-admitter.

Reference: framework/plugins/requestcontrol/admitter/{latencyslo,
probabilisticadmitter}/plugin.go. Both act only on sheddable requests
(priority < 0) and fail open on missing signals.
"""

from __future__ import annotations

import os
import random
from typing import Any

from ..framework.datalayer import Endpoint
from ..framework.plugin import PluginBase, register_plugin
from ..framework.scheduling import InferenceRequest
from ..plugins.attributes import LATENCY_ATTRIBUTE_KEY
from ..slo import H_SLO_TPOT, H_SLO_TTFT, parse_slo_header_ms


@register_plugin("latency-slo-admitter")
class LatencySloAdmitter(PluginBase):
    """Rejects sheddable requests when no endpoint can meet the SLO.

    Reject only when ALL hold (reference latencyslo/plugin.go:99-157):
    an SLO header is set, predictions exist, no endpoint has a valid
    (both-headrooms-positive) prediction, no endpoint is idle, and no
    endpoint is cold (KV < 2%, predictions unreliable).
    """

    COLD_KV_THRESHOLD = 0.02

    def consumes(self) -> list[str]:
        return [LATENCY_ATTRIBUTE_KEY]

    async def admit(self, ctx: Any, request: InferenceRequest,
                    endpoints: list[Endpoint]) -> tuple[bool, str]:
        if request.objectives.priority >= 0:
            return True, ""
        has_slo = (parse_slo_header_ms(request.headers, H_SLO_TTFT) > 0
                   or parse_slo_header_ms(request.headers, H_SLO_TPOT) > 0)
        if not has_slo:
            return True, ""

        has_valid = has_cold = has_idle = has_predictions = False
        for ep in endpoints:
            m = ep.metrics
            if m.kv_cache_usage_percent < self.COLD_KV_THRESHOLD:
                has_cold = True
            if m.running_requests_size == 0:
                has_idle = True
            info = ep.attributes.get(LATENCY_ATTRIBUTE_KEY)
            if info is not None:
                has_predictions = True
                if info.is_valid:
                    has_valid = True
        if not has_predictions:
            return True, ""  # fail open
        if not has_valid and not has_idle and not has_cold:
            return False, "no endpoint can serve the request within SLO"
        return True, ""


@register_plugin("probabilistic-admitter")
class ProbabilisticAdmitter(PluginBase):
    """Probabilistically sheds sheddable requests as pool saturation rises.

    saturation = mean over endpoints of max(queue/queueThresh, kv/kvThresh);
    P(reject) = min(saturation^power · k, 1) (reference
    probabilisticadmitter/plugin.go).
    """

    def __init__(self, name: str | None = None):
        super().__init__(name)
        self.queue_depth_threshold = 5.0
        self.kv_cache_util_threshold = 0.8
        self.power = 5.0
        self.k = 300.0
        # Deterministic shed decisions under the chaos harness and in unit
        # tests: an explicit `seed` param wins, else CHAOS_SEED (the same
        # env `make test-chaos` pins), else an unseeded RNG as before.
        try:
            chaos_seed = int(os.environ.get("CHAOS_SEED", ""))
        except ValueError:
            chaos_seed = None  # absent or non-numeric: unseeded as before
        self._rng = random.Random(chaos_seed)

    def configure(self, params: dict[str, Any], handle: Any) -> None:
        self.queue_depth_threshold = float(
            params.get("queueDepthThreshold", self.queue_depth_threshold))
        self.kv_cache_util_threshold = float(
            params.get("kvCacheUtilThreshold", self.kv_cache_util_threshold))
        self.power = float(params.get("power", self.power))
        self.k = float(params.get("k", self.k))
        if "seed" in params:
            self._rng = random.Random(int(params["seed"]))
        for field, v in (("queueDepthThreshold", self.queue_depth_threshold),
                         ("kvCacheUtilThreshold", self.kv_cache_util_threshold),
                         ("power", self.power), ("k", self.k)):
            if v <= 0:
                raise ValueError(f"probabilistic-admitter: {field} must be > 0")

    async def admit(self, ctx: Any, request: InferenceRequest,
                    endpoints: list[Endpoint]) -> tuple[bool, str]:
        if request.objectives.priority >= 0 or not endpoints:
            return True, ""
        sat = self._saturation(endpoints)
        prob = min(sat ** self.power * self.k, 1.0)
        if self._rng.random() < prob:
            return False, (f"probabilistic-admitter: rejected, "
                           f"saturation={sat:.3f} prob={prob:.2f}")
        return True, ""

    def _saturation(self, endpoints: list[Endpoint]) -> float:
        total = 0.0
        for ep in endpoints:
            m = ep.metrics
            total += max(m.waiting_queue_size / self.queue_depth_threshold,
                         m.kv_cache_usage_percent / self.kv_cache_util_threshold)
        return total / len(endpoints)
