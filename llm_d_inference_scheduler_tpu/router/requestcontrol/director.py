"""Director: per-request orchestration.

Mirrors /root/reference/pkg/epp/requestcontrol/director.go:182-306 —
model rewrite → objective lookup → admission → candidate endpoints →
DataProducer plugins (bounded budget, director.go:55: 400ms) → AdmitRequest
plugins → scheduler → prepareRequest (target header + PreRequest plugins).
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from typing import Any

from ..datalayer.datastore import Datastore
from ..framework.datalayer import Endpoint
from ..framework.scheduling import InferenceRequest, SchedulingResult
from ..snapshot import EndpointBatch
from ..metrics import (
    REQUEST_ERROR_TOTAL,
    REQUEST_TOTAL,
    RUNNING_REQUESTS,
)
from .admission import AdmissionError

log = logging.getLogger("router.director")

PRODUCER_BUDGET_S = 0.4  # reference director.go:55
_COMPLETE = object()  # stream-worker sentinel carrying the final usage

# Wire contract headers (reference pkg/epp/metadata/metadata.go:38-61,
# pkg/common/routing/common.go:11-17).
H_REQUEST_ID = "x-request-id"
H_OBJECTIVE = "x-gateway-inference-objective"
H_FAIRNESS_ID = "x-gateway-inference-fairness-id"
H_MODEL_REWRITE = "x-gateway-model-name-rewrite"
H_DESTINATION = "x-gateway-destination-endpoint"
H_DESTINATION_SERVED = "x-gateway-destination-endpoint-served"
H_SUBSET_HINT = "x-gateway-destination-endpoint-subset"
H_PREFILLER = "x-prefiller-host-port"
H_ENCODERS = "x-encoder-hosts-ports"
H_DATA_PARALLEL = "x-data-parallel-host-port"


class RequestError(Exception):
    def __init__(self, code: int, reason: str, *,
                 retry_after_s: float | None = None, shed: bool = False):
        super().__init__(reason)
        self.code = code
        self.reason = reason
        # Overload-control extras (router/overload.py): the gateway turns
        # retry_after_s into a Retry-After header and `shed` into the SLO
        # ledger's distinct shed verdict.
        self.retry_after_s = retry_after_s
        self.shed = shed


class Director:
    def __init__(self, datastore: Datastore, scheduler: Any, *,
                 admission: Any,
                 producers: list[Any] | None = None,
                 admit_plugins: list[Any] | None = None,
                 pre_request_plugins: list[Any] | None = None,
                 response_received: list[Any] | None = None,
                 response_streaming: list[Any] | None = None,
                 response_complete: list[Any] | None = None,
                 recorder: Any = None,
                 sched_pool: Any = None,
                 overload: Any = None,
                 shadow: Any = None):
        self.datastore = datastore
        self.scheduler = scheduler
        self.admission = admission
        # Goodput-max overload controller (router/overload.py): predictive
        # SLO admission + degrade ladder, run BEFORE the flow-control
        # enqueue. None (or disabled) = pre-overload behavior bit-identical.
        self.overload = overload
        # Scheduler pool (router/schedpool.py): when offloaded
        # (scheduling.workers > 0), cycles run on worker threads over
        # copy-on-write pool snapshots; None or workers: 0 = inline.
        self.sched_pool = sched_pool
        # Decision flight recorder (router/decisions.py DecisionRecorder);
        # None or disabled → request.decision stays None and every layer
        # hook costs one `is None` check.
        self.recorder = recorder
        # Shadow policy evaluator (router/shadow.py): every live scheduling
        # result is handed to the counterfactual ledger AFTER the cycle —
        # the hot path pays only an enqueue; None or inert (no policies)
        # costs one attribute check.
        self.shadow = shadow
        self.producers = producers or []
        self.admit_plugins = admit_plugins or []
        self.pre_request_plugins = pre_request_plugins or []
        self.response_received = response_received or []
        self.response_streaming = response_streaming or []
        self.response_complete = response_complete or []
        self._rng = random.Random()

    # ---- request path ---------------------------------------------------

    async def handle_request(self, ctx: Any, request: InferenceRequest) -> SchedulingResult:
        from ..tracing import tracer

        if self.recorder is not None:
            request.decision = self.recorder.start(request.request_id,
                                                   request.target_model)
        with tracer.span("gateway.request_orchestration",
                         request_id=request.request_id,
                         model=request.target_model) as span:
            try:
                result = await self._handle_request(ctx, request)
            finally:
                # Attach the decision phase summaries as span events so
                # /debug/traces?merge=1 correlates decision and latency in
                # one tree (rejections included). The events-attr probe
                # skips the summary building entirely on no-op spans
                # (tracing off / sampled out).
                rec = request.decision
                if rec is not None and hasattr(span, "events"):
                    for name, attrs in rec.span_events():
                        span.add_event(name, **attrs)
            span.set_attribute(
                "target", request.headers.get(H_DESTINATION, ""))
            span.set_attribute("profiles", list(result.profile_results))
            return result

    async def _handle_request(self, ctx: Any,
                              request: InferenceRequest) -> SchedulingResult:
        original_model = request.target_model
        rec = request.decision

        # 1. weighted model rewrite (director.go:263-343)
        rewrite_hdr = request.headers.get(H_MODEL_REWRITE)
        if rewrite_hdr:
            request.target_model = rewrite_hdr
        else:
            rw = self.datastore.rewrite_for(request.target_model)
            if rw is not None:
                request.target_model = rw.pick_target(self._rng)
        if rec is not None and request.target_model != original_model:
            rec.record_rewrite(request.target_model)

        # 2. objective → priority (director.go:164-178)
        obj_name = request.headers.get(H_OBJECTIVE, "")
        if obj_name:
            obj = self.datastore.objective_get(obj_name)
            if obj is not None:
                request.objectives.priority = obj.priority
        if rec is not None:
            rec.priority = request.objectives.priority

        # 3. candidates (+ Envoy subset hint restriction, metadata.go:40-50)
        candidates = self._candidates(request)
        if not candidates:
            REQUEST_ERROR_TOTAL.labels(original_model, "no_endpoints").inc()
            if rec is not None:
                rec.finalize(503, reason="no ready endpoints in pool")
            raise RequestError(503, "no ready endpoints in pool")

        # 3b. overload control (router/overload.py): BEFORE enqueueing,
        # estimate time-to-first-token if admitted now (queue wait from the
        # measured drain rate + the best per-endpoint ridge prediction) and
        # on a predicted SLO miss walk the degrade ladder — degrade-and-
        # admit, or fast-fail 429 with a computed Retry-After before any
        # capacity is spent. assess() is None when the kill-switch is off,
        # the band is exempt, or the request carries no SLO.
        if self.overload is not None:
            verdict = self.overload.assess(request, candidates)
            if verdict is not None:
                if verdict.action == "shed":
                    REQUEST_ERROR_TOTAL.labels(original_model,
                                               "overload_shed").inc()
                    if rec is not None:
                        rec.record_shed(verdict.block())
                        rec.record_admission("overload-controller", "shed",
                                             reason=verdict.detail)
                        rec.finalize(429, reason=verdict.detail)
                    raise RequestError(429, verdict.detail,
                                       retry_after_s=verdict.retry_after_s,
                                       shed=True)
                if verdict.action == "degrade":
                    applied = self.overload.apply_degrade(request, verdict)
                    if rec is not None:
                        rec.record_shed(verdict.block())
                        if "model_rewrite" in applied:
                            rec.record_rewrite(request.target_model)
                # Feasibility stamp for the flow-control queue: predicted
                # service time + SLO budget drive unmeetable eviction.
                self.overload.stamp_hint(request, verdict)

        # 4. admission (may block in flow control / shed sheddable load).
        # The flow-control controller writes the detailed section (queue
        # time, band, flow id); this fallback covers the legacy/always paths.
        try:
            await self.admission.admit(ctx, request, candidates)
            if rec is not None and not rec.admission:
                rec.record_admission(type(self.admission).__name__, "admitted")
        except AdmissionError as e:
            REQUEST_ERROR_TOTAL.labels(original_model, "admission").inc()
            if rec is not None:
                if not rec.admission:
                    rec.record_admission(type(self.admission).__name__,
                                         "rejected", reason=e.reason)
                rec.finalize(e.code, reason=e.reason)
            raise RequestError(e.code, e.reason,
                               retry_after_s=getattr(e, "retry_after_s", None),
                               shed=getattr(e, "shed", False)) from None

        # 4b. scheduling candidates: with the scheduler pool offloaded,
        # re-resolve against the epoch-versioned pool snapshot AFTER the
        # (possibly long) admission wait — producer attribute writes then
        # land on this request's private overlay views and the off-loop
        # cycle never races a scrape landing. Co-dispatched flow-control
        # batch members resolve the same epoch (the snapshot rebuilds at
        # most once per dirty event). An emptied pool keeps the
        # pre-admission candidates: scheduling proceeds against the old
        # epoch (endpoint deletion mid-flight is a proxy-time failure, not
        # a scheduling KeyError).
        # The vectorized path (SchedulingConfig.vectorized) rides the same
        # re-resolve: an EndpointBatch over the snapshot's columns is what
        # lets plugin batch kernels index whole-pool arrays.
        if self.sched_pool is not None and (
                self.sched_pool.offloaded or self.sched_pool.vectorized):
            snap_candidates = self._candidates(request, snapshot=True)
            if len(snap_candidates):
                candidates = snap_candidates
            # Remembered for failover reschedules: the producer attribute
            # overlays live on these per-request views, not on the shared
            # endpoints, so a reschedule must score the same views.
            request._sched_candidates = candidates

        # 5. data producers under a global budget (director.go:232, 400ms)
        t_prod = time.monotonic()
        await self._run_producers(ctx, request, candidates)
        if rec is not None and self.producers:
            rec.record_producers(
                (time.monotonic() - t_prod) * 1e3, PRODUCER_BUDGET_S * 1e3,
                [str(p.typed_name()) for p in self.producers])

        # 6. admit plugins (latency SLO admitters etc.)
        for p in self.admit_plugins:
            ok, reason = await p.admit(ctx, request, candidates)
            if not ok:
                REQUEST_ERROR_TOTAL.labels(original_model, "admit_plugin").inc()
                if rec is not None:
                    # The flow-control section (if any) stays; the plugin
                    # verdict lands beside it rather than clobbering it.
                    rec.record_admit_plugin_reject(str(p.typed_name()), reason)
                    rec.finalize(429, reason=reason)
                raise RequestError(429, reason)

        # 7. schedule (off-loop via the scheduler pool when configured).
        # The waterfall's sched stage (router/tails.py) wraps the await:
        # cycle compute PLUS the offload queue/dispatch wait — the
        # request-visible scheduling cost, which the inline path and the
        # pool path must account identically.
        wf = getattr(request, "waterfall", None)
        t_sched = time.monotonic() if wf is not None else 0.0
        try:
            result = await self._schedule(ctx, request, candidates)
        except Exception as e:
            REQUEST_ERROR_TOTAL.labels(original_model, "scheduling").inc()
            if rec is not None:
                rec.finalize(503, reason=f"scheduling failed: {e}")
            raise RequestError(503, f"scheduling failed: {e}") from None
        if wf is not None:
            wf.sched_ms = (time.monotonic() - t_sched) * 1e3
        request.scheduling_result = result

        # 7b. shadow policy evaluation (router/shadow.py): submit the live
        # cycle's frozen result to the counterfactual ledger. Enqueue-only
        # on this path; evaluation runs on the shadow worker.
        if self.shadow is not None:
            self.shadow.submit(request, result)

        # 8. prepare: destination header + PreRequest plugins (director.go:347-372)
        primary = result.primary().target_endpoints
        request.headers[H_DESTINATION] = ",".join(
            ep.metadata.address_port for ep in primary)
        for p in self.pre_request_plugins:
            p.pre_request(ctx, request, result)

        REQUEST_TOTAL.labels(original_model, request.target_model).inc()
        RUNNING_REQUESTS.labels(request.target_model).inc()
        return result

    async def _schedule(self, ctx: Any, request: InferenceRequest,
                        candidates: list[Endpoint]):
        if self.sched_pool is not None:
            return await self.sched_pool.schedule(ctx, request, candidates)
        return self.scheduler.schedule(ctx, request, candidates)

    def _candidates(self, request: InferenceRequest,
                    *, snapshot: bool = False) -> list[Endpoint]:
        if snapshot:
            snap = self.datastore.snapshot()
            if self.sched_pool is not None and self.sched_pool.vectorized:
                # Columnar candidate set: vectorized kernels index the
                # snapshot's arrays; list-duck iteration still hands
                # producers and scalar fallbacks per-request overlay views.
                batch = EndpointBatch(snap)
                subset = request.headers.get(H_SUBSET_HINT)
                if subset:
                    allowed = {s.strip() for s in subset.split(",")
                               if s.strip()}
                    batch = batch.subset(allowed)
                return batch
            # Per-request overlay views over the current snapshot epoch
            # (router/snapshot.py) — safe to score off-loop.
            eps: list = snap.view()
        else:
            eps = self.datastore.endpoint_list()
        subset = request.headers.get(H_SUBSET_HINT)
        if subset:
            allowed = {s.strip() for s in subset.split(",") if s.strip()}
            eps = [ep for ep in eps if ep.metadata.address_port in allowed]
        return eps

    async def _run_producers(self, ctx, request, candidates):
        if not self.producers:
            return
        async def run_all():
            for p in self.producers:  # DAG order (validated at startup)
                await p.produce(ctx, request, candidates)
        try:
            await asyncio.wait_for(run_all(), timeout=PRODUCER_BUDGET_S)
        except asyncio.TimeoutError:
            log.warning("data producers exceeded %.0fms budget for %s",
                        PRODUCER_BUDGET_S * 1e3, request.request_id)

    def reschedule(self, ctx: Any, request: InferenceRequest,
                   exclude: set[str]) -> SchedulingResult | None:
        """Failover re-schedule (gateway retry path): re-run the scheduler
        over the surviving candidates with the ``exclude``d address_ports
        removed. Admission and data producers are NOT re-run — the request
        was already admitted and its producer attributes are still fresh —
        and the request counters are not re-incremented (the original
        handle_request/handle_response_complete pair still brackets the
        request exactly once). Runs INLINE even when the scheduler pool is
        offloaded: failovers are rare, the caller is synchronous, and the
        surviving candidates carry the original cycle's producer overlays.
        Returns None when no viable result exists."""
        base = None
        if self.sched_pool is not None and (
                self.sched_pool.offloaded or self.sched_pool.vectorized):
            # Offloaded/vectorized cycles scored per-request snapshot views;
            # the producer overlays (prefix match info, in-flight load)
            # exist only there, so the reschedule reuses them. Iterating an
            # EndpointBatch base materializes those same views.
            base = getattr(request, "_sched_candidates", None)
        if base is None:
            base = self._candidates(request)
        candidates = [ep for ep in base
                      if ep.metadata.address_port not in exclude]
        rec = request.decision
        if not candidates:
            if rec is not None:
                rec.record_event("reschedule_exhausted",
                                 excluded=sorted(exclude))
            return None
        if rec is not None:
            rec.record_event("reschedule", excluded=sorted(exclude))
        try:
            result = self.scheduler.schedule(ctx, request, candidates)
        except Exception as e:
            log.warning("failover reschedule failed for %s: %s",
                        request.request_id, e)
            if rec is not None:
                rec.record_event("reschedule_failed", error=str(e))
            return None
        request.scheduling_result = result
        primary = result.primary().target_endpoints
        request.headers[H_DESTINATION] = ",".join(
            ep.metadata.address_port for ep in primary)
        # Re-run PreRequest so the new target's routing headers (prefiller
        # candidates, DP rank) match the re-scheduled result.
        for p in self.pre_request_plugins:
            p.pre_request(ctx, request, result)
        # Shadow re-evaluation against the pick that will actually serve
        # (router/shadow.py; the PR 11 classifier re-classification
        # precedent) — judging the measured outcome against the
        # pre-failover pick would bias the regret curve.
        if self.shadow is not None:
            self.shadow.submit(request, result, resubmit=True)
        return result

    # ---- fallback & response path ----------------------------------------

    def get_random_endpoint(self) -> Endpoint | None:
        """Fallback for unparseable bodies (director.go:466)."""
        eps = self.datastore.endpoint_list()
        return self._rng.choice(eps) if eps else None

    def handle_response_received(self, ctx, request, endpoint, status: int) -> None:
        for p in self.response_received:
            try:
                p.response_received(ctx, request, endpoint, status)
            except Exception:
                log.exception("response_received plugin failure")

    def handle_response_streaming(self, ctx, request, endpoint, chunk: bytes) -> None:
        """Streaming chunks run plugins on a per-request async worker
        (reference director.go:92-134): a slow plugin must not add per-chunk
        latency to the hot proxy path. The queue rides the request object —
        torn down by handle_response_complete."""
        if not self.response_streaming:
            return
        state = getattr(request, "_stream_plugin_state", None)
        if state is None:
            queue: asyncio.Queue = asyncio.Queue(maxsize=256)

            async def worker():
                while True:
                    item = await queue.get()
                    if item is None:
                        return
                    if isinstance(item, tuple) and item[0] is _COMPLETE:
                        # Ordered completion: all queued chunks were processed
                        # first (the reference's final-chunk-sync semantics).
                        self._run_complete_plugins(ctx, request, endpoint, item[1])
                        return
                    for p in self.response_streaming:
                        try:
                            p.response_streaming(ctx, request, endpoint, item)
                        except Exception:
                            log.exception("response_streaming plugin failure")

            task = asyncio.get_running_loop().create_task(worker())
            state = (queue, task)
            setattr(request, "_stream_plugin_state", state)
        try:
            state[0].put_nowait(chunk)
        except asyncio.QueueFull:
            log.warning("response-streaming plugin queue full; dropping chunk "
                        "for %s", request.request_id)

    def handle_response_complete(self, ctx, request, endpoint,
                                 usage: dict[str, int]) -> None:
        RUNNING_REQUESTS.labels(request.target_model).dec()
        state = getattr(request, "_stream_plugin_state", None)
        if state is not None:
            # Route completion through the worker so it runs AFTER every
            # queued chunk (chunk → complete ordering must hold for plugins
            # like the latency producer's first-token timestamping).
            try:
                state[0].put_nowait((_COMPLETE, usage))
                return
            except asyncio.QueueFull:
                state[1].cancel()  # fall through to inline completion
        self._run_complete_plugins(ctx, request, endpoint, usage)

    def _run_complete_plugins(self, ctx, request, endpoint,
                              usage: dict[str, int]) -> None:
        for p in self.response_complete:
            try:
                p.response_complete(ctx, request, endpoint, usage)
            except Exception:
                log.exception("response_complete plugin failure")
