"""predicted-latency-producer: online TTFT/TPOT prediction + SLO headroom.

Reference: framework/plugins/requestcontrol/dataproducer/predictedlatency
(plugin.go / training.go / prediction.go — bulk predictions in Produce,
TTFT training on first token, TPOT training at EOS, per-request context with
TTL, TPOT neutralization for prefill endpoints) plus latencypredictorclient.

TPU-native redesign: the reference trains XGBoost/Bayesian-ridge models in an
external Python sidecar reached over HTTP (latencypredictorclient, ~4k LoC of
client plumbing). Here the predictor IS the in-process model: an
exponentially-decayed online ridge regression (closed-form normal equations,
d≈6 features, numpy solve) — no sidecar hop inside the 400ms producer budget,
no model snapshot syncing, same signal set (queue depth, KV utilisation,
running/dispatched requests, input/uncached token counts).

SLO headers (reference latencyslo/plugin.go:38-40): ``x-slo-ttft-ms`` and
``x-slo-tpot-ms``; headroom = SLO − predicted.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any

import numpy as np

from ..framework.datalayer import ROLE_LABEL, Endpoint
from ..framework.plugin import PluginBase, register_plugin
from ..framework.scheduling import InferenceRequest, SchedulingResult
from ..metrics import (
    LATENCY_TRAINING_SAMPLES,
    PREDICTED_TPOT_MS,
    PREDICTED_TTFT_MS,
    SLO_VIOLATION_TOTAL,
)
from ..plugins.attributes import (
    LATENCY_ATTRIBUTE_KEY,
    PREFIX_ATTRIBUTE_KEY,
    LatencyPredictionInfo,
    estimate_input_tokens,
)

# SLO header contract shared with the outcome side (router/slo.py is the
# single source; the ledger judges the same targets this producer predicts
# against).
from ..slo import (  # noqa: F401 (re-export)
    H_SLO_TPOT,
    H_SLO_TTFT,
    parse_slo_header_ms,
)

log = logging.getLogger("router.predicted_latency")


class OnlineRidge:
    """Exponentially-decayed online ridge regression.

    Keeps A = Σ λ^age · x xᵀ and b = Σ λ^age · x y; predict solves
    (A + αI) w = b. With d ≈ 6 the solve is microseconds — cheap enough to
    run per request without caching a fitted model.
    """

    def __init__(self, dim: int, alpha: float = 1.0, decay: float = 0.999):
        self.dim = dim
        self.alpha = alpha
        self.decay = decay
        self.n_samples = 0
        self._A = np.zeros((dim, dim))
        self._b = np.zeros(dim)
        self._w: np.ndarray | None = None  # cache invalidated on update

    def update(self, x: list[float], y: float) -> None:
        xv = np.asarray(x, dtype=float)
        self._A = self.decay * self._A + np.outer(xv, xv)
        self._b = self.decay * self._b + xv * y
        self.n_samples += 1
        self._w = None

    def predict(self, x: list[float]) -> float:
        if self._w is None:
            self._w = np.linalg.solve(
                self._A + self.alpha * np.eye(self.dim), self._b)
        return float(np.asarray(x, dtype=float) @ self._w)


@dataclasses.dataclass
class _RequestContext:
    endpoint: str                 # address_port the request was dispatched to
    start: float                  # dispatch time
    ttft_features: list[float]
    tpot_features: list[float]
    streaming: bool
    slo_ttft_ms: float = 0.0
    slo_tpot_ms: float = 0.0
    first_token_at: float | None = None
    status: int | None = None     # upstream status from ResponseReceived
    done: bool = False            # guards double-complete accounting


# Contexts ride ON the request object (attribute below) rather than in an
# id-keyed cache: client-supplied x-request-id values can collide (the same
# bug class fixed in RequestEvictor), and the object's lifetime IS the
# request's lifetime — no TTL sweep, no collision space. The reference needs
# its TTL'd context cache only because Go hook signatures can't carry state.
_CTX_ATTR = "_predicted_latency_ctx"


@register_plugin("predicted-latency-producer")
class PredictedLatencyProducer(PluginBase):
    """DataProducer + PreRequest + ResponseStreaming + ResponseComplete."""

    TTFT_DIM = 6
    TPOT_DIM = 4
    MIN_SAMPLES = 5  # fewer → no prediction attribute (fail-open downstream)

    def __init__(self, name: str | None = None):
        super().__init__(name)
        self.slo_buffer_factor = 1.0
        self.streaming_mode = True  # record TTFT on first chunk when streaming
        self.predict_in_produce = True
        self.role_label = ROLE_LABEL  # prefill pods get TPOT neutralized
        # One model pair per endpoint: the per-endpoint intercept captures
        # systematic slowness (hardware/config skew) that load features can't
        # explain — the signal that lets routing steer AROUND a slow pod.
        self._ttft_models: dict[str, OnlineRidge] = {}
        self._tpot_models: dict[str, OnlineRidge] = {}
        self._dispatched: dict[str, int] = {}  # address_port -> in-flight

    def configure(self, params: dict[str, Any], handle: Any) -> None:
        self.slo_buffer_factor = float(params.get("sloBufferFactor",
                                                  self.slo_buffer_factor))
        self.streaming_mode = bool(params.get("streamingMode",
                                              self.streaming_mode))
        self.predict_in_produce = bool(params.get("predictInProduce",
                                                  self.predict_in_produce))
        self.role_label = params.get("endpointRoleLabel", self.role_label)

    def produces(self) -> list[str]:
        return [LATENCY_ATTRIBUTE_KEY]

    def consumes(self) -> list[str]:
        return [PREFIX_ATTRIBUTE_KEY]

    # ---- feature engineering -------------------------------------------

    def _ttft_features(self, request: InferenceRequest, ep: Endpoint) -> list[float]:
        tokens = estimate_input_tokens(request)
        prefix = ep.attributes.get(PREFIX_ATTRIBUTE_KEY)
        hit = prefix.hit_ratio if prefix is not None else 0.0
        m = ep.metrics
        return [1.0,
                tokens / 1000.0,
                tokens * (1.0 - hit) / 1000.0,   # uncached prefill work
                float(m.waiting_queue_size),
                float(m.kv_cache_usage_percent),
                float(self._dispatched.get(ep.metadata.address_port, 0))]

    def _tpot_features(self, ep: Endpoint) -> list[float]:
        m = ep.metrics
        return [1.0,
                float(m.running_requests_size),
                float(m.kv_cache_usage_percent),
                float(self._dispatched.get(ep.metadata.address_port, 0))]

    @staticmethod
    def _slo(request: InferenceRequest, header: str) -> float:
        return parse_slo_header_ms(request.headers, header)

    # ---- admission-time feasibility probe (router/overload.py) ----------

    def admission_estimate(self, request: InferenceRequest,
                           endpoints: list[Endpoint]
                           ) -> tuple[float, float | None] | None:
        """Best-endpoint service estimate for the overload controller,
        BEFORE this request's Produce/admission ran: (min predicted TTFT
        ms over endpoints, min predicted TPOT ms over endpoints or None).
        The two minima are taken INDEPENDENTLY — feasibility asks whether
        any endpoint can meet each axis, and coupling TPOT to the
        TTFT-winning endpoint would shed requests another endpoint could
        serve inside both SLOs. An endpoint without a trained TPOT model
        (or with the prefill role) is neutral on that axis, same rule as
        produce(). Returns None when no endpoint has a trained TTFT model
        (fail open — a cold router must not shed)."""
        best_ttft: float | None = None
        best_tpot: float | None = None
        tpot_neutral = False  # any endpoint with no TPOT constraint at all
        for ep in endpoints:
            ap = ep.metadata.address_port
            model = self._ttft_models.get(ap)
            if model is None or model.n_samples < self.MIN_SAMPLES:
                continue
            ttft = max(model.predict(self._ttft_features(request, ep)), 0.0)
            if best_ttft is None or ttft < best_ttft:
                best_ttft = ttft
            tpot_model = self._tpot_models.get(ap)
            if (tpot_model is not None
                    and tpot_model.n_samples >= self.MIN_SAMPLES
                    and ep.metadata.labels.get(self.role_label) != "prefill"):
                tpot = max(tpot_model.predict(self._tpot_features(ep)), 0.0)
                if best_tpot is None or tpot < best_tpot:
                    best_tpot = tpot
            else:
                tpot_neutral = True
        if best_ttft is None:
            return None
        return best_ttft, None if tpot_neutral else best_tpot

    # ---- Produce: bulk predictions --------------------------------------

    async def produce(self, ctx: Any, request: InferenceRequest,
                      endpoints: list[Endpoint]) -> None:
        if not self.predict_in_produce:
            return
        ttft_slo = self._slo(request, H_SLO_TTFT) * self.slo_buffer_factor
        tpot_slo = self._slo(request, H_SLO_TPOT) * self.slo_buffer_factor
        for ep in endpoints:
            ap = ep.metadata.address_port
            ttft_model = self._ttft_models.get(ap)
            if ttft_model is None or ttft_model.n_samples < self.MIN_SAMPLES:
                continue  # no attribute → downstream plugins fail open
            tpot_model = self._tpot_models.get(ap)
            tpot_trained = (tpot_model is not None
                            and tpot_model.n_samples >= self.MIN_SAMPLES)
            ttft = max(ttft_model.predict(self._ttft_features(request, ep)), 0.0)
            tpot = (max(tpot_model.predict(self._tpot_features(ep)), 0.0)
                    if tpot_trained else 0.0)
            info = LatencyPredictionInfo(
                ttft_ms=ttft, tpot_ms=tpot,
                ttft_headroom_ms=ttft_slo - ttft,
                tpot_headroom_ms=tpot_slo - tpot,
                ttft_valid=ttft_slo - ttft >= 0,
                tpot_valid=tpot_slo - tpot >= 0,
                dispatched=self._dispatched.get(ep.metadata.address_port, 0))
            if not tpot_trained or ep.metadata.labels.get(self.role_label) == "prefill":
                # TPOT neutralization (reference prediction.go): prefill pods
                # never decode; untrained TPOT must not poison tiering.
                info.tpot_valid = True
                info.tpot_headroom_ms = 0.0
            ep.attributes.put(LATENCY_ATTRIBUTE_KEY, info)

    # ---- training-sample hooks ------------------------------------------

    def pre_request(self, ctx: Any, request: InferenceRequest,
                    result: SchedulingResult) -> None:
        targets = result.primary().target_endpoints
        if not targets:
            return
        ep = targets[0]
        key = ep.metadata.address_port
        self._dispatched[key] = self._dispatched.get(key, 0) + 1
        info = ep.attributes.get(LATENCY_ATTRIBUTE_KEY)
        if info is not None:
            PREDICTED_TTFT_MS.observe(info.ttft_ms)
            PREDICTED_TPOT_MS.observe(info.tpot_ms)
        # SLO-ledger outcome hook: stamp THIS request's prediction (for the
        # endpoint actually picked) so the ledger can compute calibration
        # error at completion. Re-runs on failover reschedules, so the
        # prediction always targets the endpoint that serves.
        obs = getattr(request, "outcome", None)
        if obs is not None:
            obs.endpoint = key
            role = ep.metadata.labels.get(self.role_label)
            if role:
                obs.role = role
            if info is not None:
                obs.predicted_ttft_ms = info.ttft_ms
                obs.predicted_tpot_ms = info.tpot_ms if info.tpot_ms else None
        setattr(request, _CTX_ATTR, _RequestContext(
            endpoint=key, start=time.monotonic(),
            ttft_features=self._ttft_features(request, ep),
            tpot_features=self._tpot_features(ep),
            streaming=request.body.stream(),
            slo_ttft_ms=self._slo(request, H_SLO_TTFT),
            slo_tpot_ms=self._slo(request, H_SLO_TPOT)))

    def response_received(self, ctx: Any, request: InferenceRequest,
                          endpoint: Endpoint | None, status: int) -> None:
        rc = getattr(request, _CTX_ATTR, None)
        if rc is not None:
            rc.status = status

    def response_streaming(self, ctx: Any, request: InferenceRequest,
                           endpoint: Endpoint | None, chunk: bytes) -> None:
        rc = getattr(request, _CTX_ATTR, None)
        if rc is None or rc.first_token_at is not None:
            return
        rc.first_token_at = time.monotonic()
        if self.streaming_mode and self._succeeded(rc):
            self._ttft_model_for(rc.endpoint).update(
                rc.ttft_features, (rc.first_token_at - rc.start) * 1e3)
            LATENCY_TRAINING_SAMPLES.labels("ttft").inc()

    @staticmethod
    def _succeeded(rc: _RequestContext) -> bool:
        """Train only on successful upstream responses: failed/cancelled
        requests return in milliseconds and would teach the model that a
        DEAD endpoint is the fastest one."""
        return rc.status is not None and rc.status < 300

    def response_complete(self, ctx: Any, request: InferenceRequest,
                          endpoint: Endpoint | None,
                          usage: dict[str, int]) -> None:
        rc = getattr(request, _CTX_ATTR, None)
        if rc is None or rc.done:
            return
        rc.done = True
        n = self._dispatched.get(rc.endpoint, 0)
        if n > 1:
            self._dispatched[rc.endpoint] = n - 1
        else:
            self._dispatched.pop(rc.endpoint, None)
        if not self._succeeded(rc):
            return
        now = time.monotonic()
        observed_ttft_ms = ((rc.first_token_at or now) - rc.start) * 1e3
        if rc.first_token_at is None or not self.streaming_mode:
            # Non-streaming (or no chunk seen): TTFT sample is the e2e
            # latency (reference default streamingMode=false behavior).
            observed_ttft_ms = (now - rc.start) * 1e3
            self._ttft_model_for(rc.endpoint).update(
                rc.ttft_features, observed_ttft_ms)
            LATENCY_TRAINING_SAMPLES.labels("ttft").inc()
        if rc.slo_ttft_ms > 0 and observed_ttft_ms > rc.slo_ttft_ms:
            SLO_VIOLATION_TOTAL.labels("ttft").inc()
        completion = int(usage.get("completion_tokens") or 0)
        if rc.first_token_at is not None and completion > 1:
            per_tok = (now - rc.first_token_at) * 1e3 / (completion - 1)
            self._tpot_model_for(rc.endpoint).update(rc.tpot_features, per_tok)
            LATENCY_TRAINING_SAMPLES.labels("tpot").inc()
            if rc.slo_tpot_ms > 0 and per_tok > rc.slo_tpot_ms:
                SLO_VIOLATION_TOTAL.labels("tpot").inc()

    def _ttft_model_for(self, endpoint: str) -> OnlineRidge:
        model = self._ttft_models.get(endpoint)
        if model is None:
            model = self._ttft_models[endpoint] = OnlineRidge(self.TTFT_DIM)
        return model

    def _tpot_model_for(self, endpoint: str) -> OnlineRidge:
        model = self._tpot_models.get(endpoint)
        if model is None:
            model = self._tpot_models[endpoint] = OnlineRidge(self.TPOT_DIM)
        return model

    def endpoint_added(self, endpoint: Endpoint) -> None:
        pass

    def endpoint_removed(self, endpoint: Endpoint) -> None:
        ap = endpoint.metadata.address_port
        self._ttft_models.pop(ap, None)
        self._tpot_models.pop(ap, None)
        self._dispatched.pop(ap, None)
