"""Goodput-max overload control: predictive SLO admission, a degrade
ladder, and Retry-After shedding.

PR 6's ledger measured the failure this module fixes: under a 2x/4x
overload ramp raw throughput holds while goodput collapses toward zero and
the wasted-token fraction hits 1.0 (benchmarks/SLO_OBS.json) — the router
admits work that will blow its deadline, burns tokens on it, then kills it
mid-stream. P/D-Serve's gateway (arXiv:2408.08147) closes this loop at
*admission*: every token generated should be a token delivered inside SLO.

``OverloadController.assess`` runs in the director BEFORE the flow-control
enqueue and estimates time-to-first-token *if admitted now*:

    predicted TTFT = queue wait (queued / measured drain rate)
                   + best per-endpoint ridge prediction
                     (requestcontrol/predicted_latency.py, calibrated by
                      the PR 6 ledger)

On a predicted SLO miss it walks a configurable degrade ladder:

1. **degrade** — clamp ``max_tokens`` and/or rewrite to a configured
   cheaper model variant (the director's rewrite hook), then admit;
2. **shed** — fast-fail with 429 and a computed ``Retry-After`` derived
   from the queue drain rate, before any capacity is spent.

The flow-control queues get two overload-aware behaviors (gated on the
same kill-switch): **predicted-unmeetable eviction** (a queued item whose
remaining SLO budget is smaller than its predicted service time is evicted
before its TTL fires, freeing capacity for meetable work) and
**priority decay** (a long-waiting sheddable item's effective priority
decays with queue age, so it loses its victim-selection slot to fresh
feasible work).

Every shed/degrade decision is explainable: the DecisionRecord gains a
``shed`` block (predicted TTFT vs SLO vs drain estimate —
``/debug/decisions/<id>``), the SLO ledger stamps the distinct ``shed``
verdict (router/slo.py — a shed is not an SLO miss), and the new metric
families (``router_admission_shed_total{reason}``,
``router_degraded_requests_total{action}``, ``router_retry_after_seconds``,
``router_queue_drain_rate``) make the control loop graphable.

``overload: {enabled: false}`` (the default) is the kill-switch: every
hook degrades to one attribute check and behavior is bit-identical to the
pre-overload router.
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Any, Callable

from .metrics import (
    ADMISSION_SHED_TOTAL,
    DEGRADED_REQUESTS_TOTAL,
    QUEUE_DRAIN_RATE,
    RETRY_AFTER_SECONDS,
)

# Machine-readable shed reasons (the {reason} label on
# router_admission_shed_total — bounded cardinality).
REASON_TTFT = "predicted_ttft_miss"
REASON_TPOT = "predicted_tpot_miss"
REASON_QUEUE = "queue_unmeetable"

SHED_REASON = "overload-shed"  # x-removal-reason for admission-time sheds


@dataclasses.dataclass
class OverloadConfig:
    """The YAML ``overload:`` section. ``enabled: false`` (default) is the
    kill-switch: assess() returns None, the flow-control queues keep their
    pre-overload semantics, and the ledger never sees a shed verdict."""

    enabled: bool = False
    # Priority bands STRICTLY ABOVE this are exempt from overload control
    # (premium tiers are never predictively shed; the existing sheddable
    # semantics — priority < 0 — stay untouched below it).
    max_priority: int = 0
    # Feasibility slack: predicted <= SLO * headroom_factor admits. < 1
    # sheds early (reserve headroom), > 1 tolerates predicted overshoot.
    headroom_factor: float = 1.0
    # Degrade ladder step 1: 0 / "" disables each action.
    degrade_max_tokens: int = 0
    degrade_model: str = ""
    # Degrade-and-admit while predicted TTFT <= SLO * degrade_admit_ratio;
    # beyond that the request sheds even when degrade actions exist (a
    # degraded request that still misses its SLO is pure wasted work).
    degrade_admit_ratio: float = 1.5
    # Retry-After bounds (seconds; the header must be finite).
    retry_after_min_s: float = 1.0
    retry_after_max_s: float = 30.0
    # Flow-control queue behaviors.
    queue_eviction: bool = True
    # Effective-priority decay for shed victim selection, in priority bands
    # per second of queue age.
    priority_decay_per_s: float = 0.1

    @classmethod
    def from_spec(cls, spec: dict[str, Any] | None) -> "OverloadConfig":
        spec = spec or {}
        degrade = spec.get("degrade") or {}
        cfg = cls(
            enabled=bool(spec.get("enabled", False)),
            max_priority=int(spec.get("maxPriority", 0)),
            headroom_factor=float(spec.get("headroomFactor", 1.0)),
            degrade_max_tokens=int(degrade.get("maxTokensClamp", 0)),
            degrade_model=str(degrade.get("modelRewrite", "") or ""),
            degrade_admit_ratio=float(degrade.get("admitRatio", 1.5)),
            retry_after_min_s=float(spec.get("retryAfterMinS", 1.0)),
            retry_after_max_s=float(spec.get("retryAfterMaxS", 30.0)),
            queue_eviction=bool(spec.get("queueEviction", True)),
            priority_decay_per_s=float(spec.get("priorityDecayPerS", 0.1)),
        )
        if cfg.headroom_factor <= 0:
            raise ValueError("overload.headroomFactor must be > 0")
        if cfg.degrade_admit_ratio < 1.0:
            raise ValueError("overload.degrade.admitRatio must be >= 1")
        if not (0 < cfg.retry_after_min_s <= cfg.retry_after_max_s):
            raise ValueError("overload: retryAfterMinS/MaxS must satisfy "
                             "0 < min <= max")
        return cfg


class DrainRateEstimator:
    """Measured queue drain rate (dispatches/second), EWMA over 1 s windows.

    ``note(n)`` is called from the flow-control dispatch loop (one call per
    shard wake, not per item); ``rate()`` folds in the decay of elapsed
    empty windows, so a stalled queue's estimate falls toward zero instead
    of reporting the last busy second forever."""

    WINDOW_S = 1.0

    def __init__(self, halflife_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        # Per-window EWMA coefficient from the half-life.
        self._alpha = 1.0 - 0.5 ** (self.WINDOW_S / max(halflife_s, 1e-3))
        self._window_start = clock()
        self._window_count = 0
        self._rate = 0.0
        self.total = 0  # lifetime dispatches (cold-start detection)

    def _roll(self, now: float) -> None:
        elapsed = now - self._window_start
        if elapsed < self.WINDOW_S:
            return
        windows = int(elapsed / self.WINDOW_S)
        # First elapsed window carries the accumulated count …
        self._rate += self._alpha * (self._window_count / self.WINDOW_S
                                     - self._rate)
        self._window_count = 0
        # … the rest were empty. Cap the loop: past ~20 half-lives the
        # EWMA is zero to double precision anyway.
        for _ in range(min(windows - 1, 128)):
            self._rate -= self._alpha * self._rate
        self._window_start += windows * self.WINDOW_S

    def note(self, n: int = 1) -> None:
        self._roll(self._clock())
        self._window_count += n
        self.total += n

    def rate(self) -> float:
        """Dispatches/second; blends the EWMA with the live window so a
        fresh burst registers before its window closes."""
        now = self._clock()
        self._roll(now)
        if not self._window_count:
            return self._rate
        open_s = max(now - self._window_start, 1e-6)
        live = self._window_count / max(open_s, 0.25)
        return max(self._rate, live)


class QueueOverloadPolicy:
    """The slice of overload state the flow-control shards read: whether
    predicted-unmeetable eviction runs in the TTL sweep, and the
    priority-decay rate for shed victim selection. A disabled singleton is
    the default so the shard hot path stays one attribute check."""

    __slots__ = ("eviction_enabled", "decay_per_s")

    def __init__(self, eviction_enabled: bool = False,
                 decay_per_s: float = 0.0):
        self.eviction_enabled = eviction_enabled
        self.decay_per_s = decay_per_s

    def note_unmeetable(self, n: int = 1) -> None:
        ADMISSION_SHED_TOTAL.labels(REASON_QUEUE).inc(n)


DISABLED_QUEUE_POLICY = QueueOverloadPolicy()


@dataclasses.dataclass
class OverloadAssessment:
    """One admission-time feasibility verdict. ``action`` is the rung of
    the degrade ladder taken: "admit" (feasible), "degrade" (ladder step
    1, then admit), or "shed" (ladder step 2: 429 + Retry-After)."""

    action: str
    reason: str = ""                 # machine reason (metric label)
    detail: str = ""                 # human reason (error body / record)
    predicted_ttft_ms: float = 0.0   # queue wait + service estimate + bias
    service_ttft_ms: float = 0.0     # best per-endpoint ridge prediction
    queue_wait_ms: float = 0.0
    bias_ms: float = 0.0             # observed-vs-predicted corrector
    drain_rate_rps: float = 0.0
    slo_ttft_ms: float = 0.0
    predicted_tpot_ms: float | None = None
    slo_tpot_ms: float = 0.0
    retry_after_s: float | None = None
    degrade_actions: tuple[str, ...] = ()

    def block(self) -> dict[str, Any]:
        """The DecisionRecord ``shed`` block: predicted TTFT vs SLO vs the
        drain estimate — every shed/degrade explainable at
        /debug/decisions."""
        b: dict[str, Any] = {
            "action": self.action,
            "predicted_ttft_ms": round(self.predicted_ttft_ms, 3),
            "service_ttft_ms": round(self.service_ttft_ms, 3),
            "queue_wait_ms": round(self.queue_wait_ms, 3),
            "drain_rate_rps": round(self.drain_rate_rps, 3),
            "slo_ttft_ms": self.slo_ttft_ms,
        }
        if self.bias_ms:
            b["bias_ms"] = round(self.bias_ms, 3)
        if self.slo_tpot_ms > 0:
            b["slo_tpot_ms"] = self.slo_tpot_ms
        if self.predicted_tpot_ms is not None:
            b["predicted_tpot_ms"] = round(self.predicted_tpot_ms, 3)
        if self.reason:
            b["reason"] = self.reason
        if self.retry_after_s is not None:
            b["retry_after_s"] = self.retry_after_s
        if self.degrade_actions:
            b["degrade_actions"] = list(self.degrade_actions)
        return b


# Stamped onto the InferenceRequest so the flow-control admission can carry
# the feasibility estimate into the queued item (unmeetable eviction needs
# predicted service time + SLO budget per item).
HINT_ATTR = "_overload_hint"


@dataclasses.dataclass
class OverloadHint:
    service_ttft_ms: float
    slo_ttft_ms: float
    # Total admission-time prediction (queue wait + service + bias): the
    # served outcome is compared against THIS to train the bias corrector.
    predicted_ttft_ms: float = 0.0


class OverloadController:
    """Admission-time feasibility check + degrade ladder + Retry-After.

    Lives on the gateway; the director calls ``assess`` before the
    flow-control enqueue, the flow controller feeds ``note_dispatch`` and
    reads ``queue_policy``, and the flow-control admission asks
    ``retry_after_s`` when a queued item is evicted as unmeetable."""

    # Healthy-e2e EWMA coefficient (note_completion).
    E2E_ALPHA = 0.1
    # Observed-vs-predicted TTFT bias EWMA coefficients (note_served).
    # Asymmetric by design: under-prediction (the overload tax) folds in
    # fast — every completion that ran slower than predicted means the
    # admissions made in the pipeline's blind window are already too
    # optimistic — while relief decays slowly, so one lucky completion
    # can't reopen the gate mid-overload. Shedding a feasible request
    # costs one 429 + Retry-After; admitting an infeasible one costs its
    # whole token budget.
    BIAS_ALPHA_UP = 0.4
    BIAS_ALPHA_DOWN = 0.05
    # Wall-clock bias half-life: completion-driven decay alone can latch
    # the controller shut — shed everything and no completion ever arrives
    # to relax the bias that is causing the shedding. Time decay is the
    # probe valve: after a few seconds of silence admissions trickle again
    # and re-measure reality.
    BIAS_HALFLIFE_S = 3.0

    def __init__(self, cfg: OverloadConfig, *, ledger: Any = None,
                 predictor: Any = None,
                 clock: Callable[[], float] = time.monotonic):
        self.cfg = cfg
        self.ledger = ledger          # SloLedger (resolve_targets)
        self.predictor = predictor    # PredictedLatencyProducer (or None)
        self.drain = DrainRateEstimator(clock=clock)
        self.flow = None              # FlowController (queue depth), optional
        # Gateway in-flight counter (requests between arrival and terminal
        # response — queued, scheduled, and streaming alike). With it the
        # wait estimate sees the backlog that lives INSIDE the gateway and
        # engines before flow-control saturation ever gates: Little's law
        # says a healthy pipeline holds ~drain_rate x healthy_e2e requests,
        # and everything beyond that is queueing ahead of a new arrival.
        self.inflight_fn: Callable[[], int] | None = None
        self._e2e_ewma_ms: float | None = None
        # Signed EWMA of (actual - predicted) TTFT over served requests:
        # the overload tax the ridge never saw (loop contention, connection
        # handling under flood) shows up here and folds back into the next
        # admission decision — the same predict→observe loop the PR 6
        # ledger closed for calibration, closed for CONTROL.
        self._bias_ms: float | None = None
        self._bias_at: float = 0.0  # last update (wall-clock decay anchor)
        self._clock = clock
        # Flat counter for the timeline sampler (requests that took a
        # degrade rung — the Prometheus family is per-action, this is the
        # per-request total the per-tick delta wants).
        self.degraded_total = 0
        self.queue_policy = (QueueOverloadPolicy(
            eviction_enabled=cfg.queue_eviction,
            decay_per_s=max(cfg.priority_decay_per_s, 0.0))
            if cfg.enabled else DISABLED_QUEUE_POLICY)

    @property
    def enabled(self) -> bool:
        return self.cfg.enabled

    # ---- flow-control coupling -----------------------------------------

    def attach_flow(self, flow: Any) -> None:
        """Wire the flow controller: its queue depth feeds the wait
        estimate, its dispatch loop feeds the drain estimator, and its
        shards read the queue policy (unmeetable eviction, priority
        decay)."""
        self.flow = flow
        flow.dispatch_observer = self.note_dispatch
        flow.queue_policy = self.queue_policy

    def note_dispatch(self, n: int = 1) -> None:
        self.drain.note(n)

    def note_completion(self, e2e_ms: float) -> None:
        """Healthy-pipeline e2e EWMA, fed by the gateway on every served
        (sub-400) response — the Little's-law anchor for how many in-flight
        requests the stack holds when it is meeting its latency."""
        prev = self._e2e_ewma_ms
        self._e2e_ewma_ms = (e2e_ms if prev is None
                             else prev + self.E2E_ALPHA * (e2e_ms - prev))

    def note_served(self, request: Any, e2e_ms: float) -> None:
        """Terminal feedback for a served response: feeds the healthy-e2e
        anchor always, and — when the request carried an admission-time
        assessment — the observed-vs-predicted TTFT bias corrector."""
        self.note_completion(e2e_ms)
        if request is None:
            return
        hint = getattr(request, HINT_ATTR, None)
        if hint is None or hint.predicted_ttft_ms <= 0:
            return
        obs = getattr(request, "outcome", None)
        if obs is not None and obs.first_token_at is not None:
            actual = (obs.first_token_at - obs.t_start) * 1e3
        else:
            # Non-streamed (or ledger off): e2e is the first byte.
            actual = e2e_ms
        err = actual - hint.predicted_ttft_ms
        prev = self._decayed_bias()
        if prev is None:
            self._bias_ms = err
        else:
            alpha = (self.BIAS_ALPHA_UP if err > prev
                     else self.BIAS_ALPHA_DOWN)
            self._bias_ms = prev + alpha * (err - prev)
        self._bias_at = self._clock()

    def _decayed_bias(self) -> float | None:
        """The bias corrector with its wall-clock half-life applied."""
        if self._bias_ms is None:
            return None
        dt = self._clock() - self._bias_at
        if dt <= 0:
            return self._bias_ms
        return self._bias_ms * 0.5 ** (dt / self.BIAS_HALFLIFE_S)

    # ---- feasibility ----------------------------------------------------

    # Below this drain rate (req/s) the estimator carries no usable signal
    # — dividing a backlog by a decayed-to-nothing EWMA would report hours
    # of wait on an idle router.
    DRAIN_RATE_FLOOR = 0.05

    def _queue_wait_ms(self, slo_ttft_ms: float) -> tuple[float, float]:
        """(estimated wait for a new arrival, drain rate).

        Backlog = the gateway's in-flight count when wired (it includes the
        flow-control queue, scheduled work, and live streams — the queue a
        new arrival actually stands behind), else the flow queue alone.
        The request being assessed is itself already counted in-flight, so
        one is subtracted. The healthy pipeline population
        drain_rate x e2e_ewma rides for free (Little's law); only the
        EXCESS above it is queueing delay. The e2e anchor is clamped to 2x
        the SLO so a degraded pipeline (long e2e BECAUSE it is overloaded)
        can't talk the estimate into admitting more.

        Fail-open: before the estimator has ever seen a dispatch, with no
        backlog, or once the drain EWMA has decayed below the signal floor
        (an idle router), the wait is 0 — unless explicit flow-queue items
        are waiting with no drain at all, which is a stalled pipeline and
        reports one full Retry-After window."""
        rate = self.drain.rate()
        QUEUE_DRAIN_RATE.set(rate)
        queued = self.flow.queued_requests if self.flow is not None else 0
        if self.inflight_fn is not None:
            # Queued requests are in-flight too; -1 excludes the request
            # being assessed (the gateway counted it on arrival).
            backlog = max(self.inflight_fn() - 1, 0)
        else:
            backlog = queued
        if backlog <= 0 or self.drain.total == 0:
            return 0.0, rate
        if rate <= self.DRAIN_RATE_FLOOR:
            # No usable drain signal. Explicitly queued work with no drain
            # is a stalled pipeline — effectively unbounded wait; a backlog
            # of live streams on an idle-decayed estimator is not evidence
            # of queueing, so fail open (the ridge + bias still protect).
            return (self.cfg.retry_after_max_s * 1e3 if queued > 0 else 0.0,
                    rate)
        e2e = self._e2e_ewma_ms
        if e2e is None:
            # No completion observed yet: assume the in-flight population
            # is the healthy one (fail open), count only the explicit queue.
            excess = float(queued)
        else:
            cap = 2.0 * slo_ttft_ms if slo_ttft_ms > 0 else e2e
            steady = rate * min(e2e, cap) / 1e3
            excess = max(backlog - steady, 0.0)
        return excess / rate * 1e3, rate

    def retry_after_s(self, overshoot_ms: float = 0.0) -> float:
        """Finite Retry-After from the drain estimate: how long until the
        backlog has drained enough that the same request would fit its SLO
        (the predicted overshoot), bounded to [min, max]. Every computed
        value feeds router_retry_after_seconds — admission-time sheds and
        in-queue unmeetable evictions alike."""
        cfg = self.cfg
        v = max(overshoot_ms / 1e3, cfg.retry_after_min_s)
        if not math.isfinite(v):
            v = cfg.retry_after_max_s
        v = round(min(v, cfg.retry_after_max_s), 3)
        RETRY_AFTER_SECONDS.observe(v)
        return v

    def assess(self, request: Any, endpoints: list[Any]) -> OverloadAssessment | None:
        """Feasibility verdict for one request, or None when overload
        control does not apply (kill-switch, exempt band, no SLO). The
        caller (director) applies the verdict: raises 429 on "shed",
        applies the degrade actions on "degrade", and stamps the hint for
        the flow-control queue either way."""
        cfg = self.cfg
        if not cfg.enabled:
            return None
        if request.objectives.priority > cfg.max_priority:
            return None
        if self.ledger is not None:
            slo_ttft, slo_tpot = self.ledger.resolve_targets(
                request.target_model, request.headers)
        else:
            from .slo import H_SLO_TPOT, H_SLO_TTFT, parse_slo_header_ms

            slo_ttft = parse_slo_header_ms(request.headers, H_SLO_TTFT)
            slo_tpot = parse_slo_header_ms(request.headers, H_SLO_TPOT)
        if slo_ttft <= 0 and slo_tpot <= 0:
            return None  # no SLO → nothing to protect

        est = (self.predictor.admission_estimate(request, endpoints)
               if self.predictor is not None else None)
        service_ttft = est[0] if est is not None else 0.0
        tpot = est[1] if est is not None else None
        queue_wait, rate = self._queue_wait_ms(slo_ttft)
        # Only a pessimistic bias folds in: an optimistic one (actual ran
        # FASTER than predicted) admitting extra load is how collapse
        # restarts. And it folds in only while there IS excess backlog —
        # the bias measures the overload tax, and a pipeline at or below
        # its steady population is the calibrated regime the ridge alone
        # predicts. Without this, a bias spike latches the gate shut while
        # the pipeline drains idle (bang-bang oscillation burning exactly
        # the capacity the controller is protecting).
        bias = (max(self._decayed_bias() or 0.0, 0.0)
                if queue_wait > 0 else 0.0)
        predicted_ttft = queue_wait + service_ttft + bias

        h = cfg.headroom_factor
        ttft_ok = slo_ttft <= 0 or predicted_ttft <= slo_ttft * h
        tpot_ok = slo_tpot <= 0 or tpot is None or tpot <= slo_tpot * h

        a = OverloadAssessment(
            action="admit",
            predicted_ttft_ms=predicted_ttft, service_ttft_ms=service_ttft,
            queue_wait_ms=queue_wait, bias_ms=bias, drain_rate_rps=rate,
            slo_ttft_ms=slo_ttft, predicted_tpot_ms=tpot,
            slo_tpot_ms=slo_tpot)
        if ttft_ok and tpot_ok:
            return a

        a.reason = REASON_TTFT if not ttft_ok else REASON_TPOT
        has_degrade = bool(cfg.degrade_max_tokens or cfg.degrade_model)
        marginal = (slo_ttft <= 0
                    or predicted_ttft <= slo_ttft * h * cfg.degrade_admit_ratio)
        # A TPOT-only miss is a per-token service property — clamping
        # max_tokens doesn't change it; only a model rewrite can.
        tpot_fixable = tpot_ok or bool(cfg.degrade_model)
        if has_degrade and marginal and tpot_fixable:
            a.action = "degrade"
            actions = []
            if cfg.degrade_max_tokens:
                actions.append("clamp_max_tokens")
            if cfg.degrade_model:
                actions.append("model_rewrite")
            a.degrade_actions = tuple(actions)
            return a

        a.action = "shed"
        if not ttft_ok:
            overshoot = predicted_ttft - slo_ttft * h
            a.detail = (f"overload shed: predicted TTFT "
                        f"{predicted_ttft:.0f}ms > SLO {slo_ttft:.0f}ms "
                        f"(queue wait {queue_wait:.0f}ms at "
                        f"{rate:.2f} req/s drain)")
        else:
            overshoot = 0.0
            a.detail = (f"overload shed: predicted TPOT {tpot:.2f}ms > "
                        f"SLO {slo_tpot:.0f}ms on every endpoint")
        a.retry_after_s = self.retry_after_s(overshoot)
        ADMISSION_SHED_TOTAL.labels(a.reason).inc()
        return a

    # ---- degrade ladder step 1 ------------------------------------------

    def apply_degrade(self, request: Any,
                      assessment: OverloadAssessment) -> list[str]:
        """Apply the configured degrade actions to the request in place.
        Returns the actions actually applied (a request already below the
        clamp / already on the cheap model degrades to a no-op)."""
        cfg = self.cfg
        applied: list[str] = []
        payload = request.body.payload if request.body is not None else None
        if (cfg.degrade_max_tokens > 0 and payload is not None
                and "embeddings" != _payload_kind(request.body)):
            cur = payload.get("max_tokens")
            if not isinstance(cur, (int, float)) or cur > cfg.degrade_max_tokens:
                payload["max_tokens"] = cfg.degrade_max_tokens
                applied.append("clamp_max_tokens")
        if cfg.degrade_model and request.target_model != cfg.degrade_model:
            request.target_model = cfg.degrade_model
            applied.append("model_rewrite")
        for action in applied:
            DEGRADED_REQUESTS_TOTAL.labels(action).inc()
        if applied:
            self.degraded_total += 1
            # The gateway must re-serialize the mutated payload instead of
            # forwarding the raw client bytes.
            request.degraded = True
        return applied

    # ---- hint stamping ---------------------------------------------------

    def stamp_hint(self, request: Any,
                   assessment: OverloadAssessment) -> None:
        """Carry the feasibility estimate onto the request so the
        flow-control admission can stamp the queued item (predicted
        service time + budget drive unmeetable eviction). The in-queue
        renege bar tracks the ADMISSION bar, never dropping below the raw
        SLO: a request admitted under headroomFactor > 1 (or via the
        degrade band, which knowingly tolerates predicted > SLO) must not
        be evicted by the very next sweep for exceeding a budget tighter
        than the one it was admitted at."""
        budget = assessment.slo_ttft_ms
        if budget > 0:
            bar = self.cfg.headroom_factor
            if assessment.action == "degrade":
                bar *= self.cfg.degrade_admit_ratio
            budget *= max(1.0, bar)
        setattr(request, HINT_ATTR, OverloadHint(
            service_ttft_ms=assessment.service_ttft_ms,
            slo_ttft_ms=budget,
            predicted_ttft_ms=assessment.predicted_ttft_ms))


def _payload_kind(body: Any) -> str:
    return "embeddings" if getattr(body, "embeddings", None) is not None \
        else "generate"
