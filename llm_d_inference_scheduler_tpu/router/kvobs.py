"""KV-cache & prefix-reuse observability: the predicted-vs-confirmed hit
ledger behind ``GET /debug/kv``.

The router routes on a *prediction* of prefix-cache reuse — the approx
producer's per-pod LRU and the precise scorer's event-fed KvBlockIndex both
estimate a hit depth before scheduling — and the engine computes the
*actual* matched depth at prefill admission (engine/core.py
``_note_prefix_hit``), but until this module the two numbers never met:
we routed on a prediction whose accuracy nobody could see. PPD
(arXiv:2603.13358) makes the stakes concrete — multi-turn routing quality
hinges on knowing the hit depth *before* scheduling, so the prefill
classifier ROADMAP item 2 builds must be judged against a *measured*
prediction error, not an assumed one.

One ``CacheObservation`` rides each scheduled InferenceRequest
(``request.cache``):

- opened by the gateway after scheduling: the per-candidate predicted hit
  depth (PrefixCacheMatchInfo attribute + precise-scorer raw scores) is
  stamped into the DecisionRecord as a ``cache`` block;
- joined exactly once with the engine-confirmed actual — the
  ``x-kv-hit-blocks`` / ``x-kv-hit-tokens`` response headers the sidecar
  relays from the prefill leg (``x-kv-prefiller`` names the pod the hit
  belongs to), or ``usage.prompt_tokens_details.cached_tokens`` on the
  streamed path;
- aggregated into per-pod hit-rate and signed-prediction-error EWMAs on the
  Datastore (``KvHitTable`` — readable by future scheduling plugins, the
  same contract as the TransferTable) and the metric families
  ``router_kv_predicted_hit_blocks`` / ``router_kv_hit_prediction_error`` /
  ``router_kv_actual_hit_ratio``.

``kvCache: {enabled: false}`` is the kill-switch: every hook degrades to a
single attribute check (``bench.py --kv-obs`` measures both sides against
the scheduling-cycle floor → benchmarks/KV_OBS.json). In fleet mode the
supervisor fans /debug/kv in per shard and derives the
``router_kv_index_divergence`` gauge — each shard's index view (replicated
confirmed entries + its own speculative stamps) measured against the
current leader's engine-confirmed KvBlockIndex (router/fleet.py). With
``fleet.replication`` on it reads ~0 steady-state; excursions mark stream
discontinuities (a joiner before its first checkpoint) or the
``replication: off`` kill-switch — the speculative-only state this gauge
was first built to measure.
"""

from __future__ import annotations

import dataclasses
import time
from collections import OrderedDict
from typing import Any

from .metrics import (
    KV_ACTUAL_HIT_RATIO,
    KV_HIT_PREDICTION_ERROR,
    KV_PREDICTED_HIT_BLOCKS,
)
from .plugins.attributes import PREFIX_ATTRIBUTE_KEY
from .slo import finite_float_or_none

# Engine-confirmed actual hit depth, stamped by the engine server on
# non-streaming responses and relayed by the sidecar from the prefill leg
# (or the local-decode fallback) beside x-prefill-duration-ms.
H_KV_HIT_BLOCKS = "x-kv-hit-blocks"
H_KV_HIT_TOKENS = "x-kv-hit-tokens"
# The pod the hit belongs to on the disagg path (the sidecar's
# served-prefiller stamp): the prefill engine measured the hit, not the
# decode endpoint the gateway proxied to.
H_KV_PREFILLER = "x-kv-prefiller"


@dataclasses.dataclass
class KvObsConfig:
    """The YAML ``kvCache:`` section — same shape as ``slo:``
    (router/slo.py). ``enabled: false`` is the kill-switch the overhead
    contract requires; ``capacity`` bounds the per-pod EWMA table (pod
    churn mints fresh ip:ports forever, same rationale as
    SloLedger.MAX_ENDPOINTS)."""

    enabled: bool = True
    capacity: int = 256
    # Ranked candidates whose predictions are recorded per request (the
    # DecisionRecord cache block must stay bounded on wide pools).
    top_candidates: int = 16

    @classmethod
    def from_spec(cls, spec: dict[str, Any] | None) -> "KvObsConfig":
        spec = spec or {}
        return cls(enabled=bool(spec.get("enabled", True)),
                   capacity=max(1, int(spec.get("capacity", 256))),
                   top_candidates=max(1, int(spec.get("topCandidates", 16))))


class CacheObservation:
    """One request's predicted-vs-confirmed cache observation. ``block`` is
    the SAME dict the DecisionRecord references, so the completion-time
    join lands in /debug/decisions/<id> without a second stamp."""

    __slots__ = ("predicted", "chosen", "block", "done")

    def __init__(self, predicted: dict[str, dict[str, Any]], chosen: str):
        self.predicted = predicted
        self.chosen = chosen
        self.block: dict[str, Any] = {"predicted": predicted,
                                      "chosen": chosen}
        self.done = False


class _ErrAgg:
    """Signed prediction-error accumulator. Two instances per ledger: one
    in blocks (raw depth — unit-skewed when the predictor hashes chars and
    the engine counts token blocks) and one in hit-ratio units (unit-free,
    the number the warm-vs-cold bench gates on)."""

    __slots__ = ("unit", "n", "sum_signed", "sum_abs")

    def __init__(self, unit: str = "blocks"):
        self.unit = unit
        self.n = 0
        self.sum_signed = 0.0
        self.sum_abs = 0.0

    def add(self, signed: float) -> None:
        self.n += 1
        self.sum_signed += signed
        self.sum_abs += abs(signed)

    def render(self) -> dict[str, Any]:
        if not self.n:
            return {"n": 0}
        return {"n": self.n,
                f"mae_{self.unit}": round(self.sum_abs / self.n, 4),
                f"mean_signed_{self.unit}": round(
                    self.sum_signed / self.n, 4)}


class _PodCacheStats:
    """EWMA cache observations for one pod."""

    __slots__ = ("n", "ewma_hit_ratio", "ewma_signed_error", "last_unix")

    def __init__(self):
        self.n = 0
        self.ewma_hit_ratio: float | None = None
        self.ewma_signed_error: float | None = None
        self.last_unix = 0.0

    def render(self) -> dict[str, Any]:
        doc: dict[str, Any] = {"n": self.n, "last_unix": self.last_unix}
        if self.ewma_hit_ratio is not None:
            doc["ewma_hit_ratio"] = round(self.ewma_hit_ratio, 4)
        if self.ewma_signed_error is not None:
            # predicted − actual, in hit-ratio units: positive = the
            # scorers promise more reuse than the engine finds.
            doc["ewma_signed_error"] = round(self.ewma_signed_error, 4)
        return doc


class _JudgeCounts:
    """Confusion-matrix counts for one pod's (or the whole pool's)
    classifier verdicts, judged against the engine-confirmed actual."""

    __slots__ = ("skip_correct", "skip_wrong", "keep_missed_skip",
                 "keep_necessary")

    def __init__(self):
        self.skip_correct = 0      # tp: skipped, and the turn WAS warm
        self.skip_wrong = 0        # fp: skipped a turn that was cold
        self.keep_missed_skip = 0  # fn: kept the hop on a warm turn
        self.keep_necessary = 0    # tn: kept the hop on a cold turn

    def add(self, *, skipped: bool, should_skip: bool) -> None:
        if skipped:
            if should_skip:
                self.skip_correct += 1
            else:
                self.skip_wrong += 1
        elif should_skip:
            self.keep_missed_skip += 1
        else:
            self.keep_necessary += 1

    def render(self) -> dict[str, Any]:
        tp, fp = self.skip_correct, self.skip_wrong
        fn, tn = self.keep_missed_skip, self.keep_necessary
        doc: dict[str, Any] = {
            "judged": tp + fp + fn + tn,
            "counts": {"skip_correct": tp, "skip_wrong": fp,
                       "keep_missed_skip": fn, "keep_necessary": tn},
        }
        if tp + fp:
            doc["precision"] = round(tp / (tp + fp), 4)
        if tp + fn:
            doc["recall"] = round(tp / (tp + fn), 4)
        return doc


class _ClassifierJudge:
    """Post-hoc accuracy of the prefill classifier (router/plugins/
    disagg.py): every skip/keep verdict is joined against the
    engine-confirmed actual hit depth the CacheLedger lands, yielding
    per-pod (and overall) precision/recall at /debug/kv.

    Precision is exact for skips — a skipped request is served by the very
    decode pod whose cache was predicted. Recall is a proxy for keeps: the
    hop ran, so the actual was measured on the PREFILL pod, which
    under-counts warm turns the decode pod could have served (documented
    in docs/disaggregation.md)."""

    MAX_PODS = 256

    def __init__(self):
        self.overall = _JudgeCounts()
        self._pods: OrderedDict[str, _JudgeCounts] = OrderedDict()

    def judge(self, cls: dict[str, Any], *, hit_tokens: int,
              prompt_tokens: int) -> None:
        """Judge one verdict block IN PLACE (the ``judged`` sub-block lands
        in /debug/decisions/<id> through the shared dict). The actual cold
        estimate is computed in the classifier's own units — the engine's
        token count and the router's estimate can differ by the chars/4
        heuristic, so the actual hit RATIO is applied to the router-side
        ``input_tokens`` rather than comparing raw engine tokens against a
        router-unit threshold."""
        thr = cls.get("threshold")
        input_est = cls.get("input_tokens") or 0
        if thr is None or input_est <= 0 or "judged" in cls:
            return
        if prompt_tokens > 0:
            actual_ratio = min(hit_tokens / prompt_tokens, 1.0)
            cold_actual = input_est * (1.0 - actual_ratio)
        else:
            actual_ratio = None
            cold_actual = max(input_est - hit_tokens, 0)
        should_skip = cold_actual < thr
        skipped = cls.get("verdict") == "skip"
        judged: dict[str, Any] = {
            "actual_hit_tokens": hit_tokens,
            "actual_cold_tokens": round(cold_actual, 1),
            "should_skip": should_skip,
            "correct": skipped == should_skip,
        }
        if actual_ratio is not None:
            judged["actual_ratio"] = round(actual_ratio, 4)
        cls["judged"] = judged
        self.overall.add(skipped=skipped, should_skip=should_skip)
        pod = cls.get("pod") or "(unknown)"
        counts = self._pods.get(pod)
        if counts is None:
            while len(self._pods) >= self.MAX_PODS:
                self._pods.popitem(last=False)
            counts = self._pods[pod] = _JudgeCounts()
        else:
            self._pods.move_to_end(pod)
        counts.add(skipped=skipped, should_skip=should_skip)

    def rows(self) -> dict[str, dict[str, Any]]:
        return {pod: c.render() for pod, c in self._pods.items()}


class KvHitTable:
    """Bounded LRU of per-pod hit-rate / prediction-error EWMAs. Lives on
    the Datastore (like the breaker registry and the TransferTable) so
    scheduling plugins — notably ROADMAP item 2's prefill classifier — can
    read measured reuse instead of assuming it. Writers run on the gateway
    event loop; no locking needed."""

    ALPHA = 0.2

    def __init__(self, max_pods: int = 256):
        self.max_pods = max_pods
        self._pods: OrderedDict[str, _PodCacheStats] = OrderedDict()
        # Pool-wide aggregate: every join also lands here. The prefill
        # classifier falls back to it for pods with no row of their own —
        # a decode pod that always rides the P/D hop never lands its own
        # joins (the actual is confirmed on the prefill pod), so without
        # the pool row the classifier could never bootstrap out of
        # always-disagg.
        self._overall = _PodCacheStats()

    def record(self, pod: str, *, hit_ratio: float | None,
               signed_error: float | None) -> None:
        stats = self._pods.get(pod)
        if stats is None:
            while len(self._pods) >= self.max_pods:
                self._pods.popitem(last=False)
            stats = self._pods[pod] = _PodCacheStats()
        else:
            self._pods.move_to_end(pod)
        for s in (stats, self._overall):
            s.n += 1
            s.last_unix = time.time()
            a = self.ALPHA
            if hit_ratio is not None:
                s.ewma_hit_ratio = (
                    hit_ratio if s.ewma_hit_ratio is None
                    else (1 - a) * s.ewma_hit_ratio + a * hit_ratio)
            if signed_error is not None:
                s.ewma_signed_error = (
                    signed_error if s.ewma_signed_error is None
                    else (1 - a) * s.ewma_signed_error + a * signed_error)

    def pod(self, pod: str) -> _PodCacheStats | None:
        """Plugin-facing lookup (no LRU touch: reading a pod's stats must
        not pin it against eviction)."""
        return self._pods.get(pod)

    def overall(self) -> _PodCacheStats:
        """Pool-wide aggregate row (never evicted; n == 0 until the first
        join lands anywhere)."""
        return self._overall

    def rows(self) -> dict[str, dict[str, Any]]:
        return {pod: stats.render() for pod, stats in self._pods.items()}

    def __len__(self) -> int:
        return len(self._pods)


class CacheLedger:
    """The gateway-level join point: schedule-time predictions in,
    engine-confirmed actuals out, /debug/kv rollup in the middle."""

    def __init__(self, cfg: KvObsConfig | None = None, *, datastore=None):
        self.cfg = cfg or KvObsConfig()
        self.datastore = datastore
        self.table: KvHitTable = (
            datastore.kv_obs if datastore is not None else KvHitTable())
        self.table.max_pods = self.cfg.capacity
        self._stamps = 0          # predictions recorded (speculative)
        self._joins = 0           # engine-confirmed actuals joined
        self._err = _ErrAgg("blocks")
        self._err_ratio = _ErrAgg("ratio")
        # Prefill-classifier accuracy (router/plugins/disagg.py): verdicts
        # judged against the engine-confirmed actual as each join lands.
        self.judge = _ClassifierJudge()
        # Index-occupancy sources discovered from the configured plugin set
        # (attach_plugins): approx producers expose per-pod LRU sizes,
        # precise scorers expose confirmed/speculative stamp counts.
        self._approx: list[Any] = []
        self._precise: list[Any] = []

    @property
    def enabled(self) -> bool:
        return self.cfg.enabled

    @property
    def stamps(self) -> int:
        """Predictions recorded (timeline sampler delta source)."""
        return self._stamps

    @property
    def joins(self) -> int:
        """Engine-confirmed actuals joined (timeline sampler delta
        source)."""
        return self._joins

    def attach_plugins(self, plugins) -> None:
        for p in plugins:
            if hasattr(p, "index_sizes"):
                self._approx.append(p)
            if hasattr(p, "index_counts"):
                self._precise.append(p)

    # ---- schedule-time: predicted hit depth per candidate ---------------

    def record_scheduled(self, request: Any, result: Any) -> None:
        """Stamp the per-candidate predicted hit depth into the request's
        DecisionRecord ``cache`` block. Called again on a failover
        reschedule: the fresh candidates MERGE into the block (the actual
        may be served by a pod the first pass never ranked)."""
        if not self.cfg.enabled or result is None:
            return
        precise: dict[str, float] = {}
        for pr in result.profile_results.values():
            for name, scores in pr.raw_scores.items():
                if "precise-prefix" in name:
                    precise.update(scores)
        predicted: dict[str, dict[str, Any]] = {}
        for ep in result.all_endpoints()[: self.cfg.top_candidates]:
            addr = ep.metadata.address_port
            entry: dict[str, Any] = {}
            info = ep.attributes.get(PREFIX_ATTRIBUTE_KEY)
            if info is not None:
                entry = {"blocks": info.match_blocks,
                         "total": info.total_blocks,
                         "ratio": round(info.hit_ratio, 4),
                         "block_tokens": info.block_size_tokens}
            if addr in precise:
                entry["precise_ratio"] = round(precise[addr], 4)
            if entry:
                predicted[addr] = entry
        if not predicted:
            return  # no prefix plugin produced a signal — nothing to join
        primary = result.primary().target_endpoints
        chosen = primary[0].metadata.address_port if primary else ""
        obs: CacheObservation | None = getattr(request, "cache", None)
        if obs is not None:
            if not obs.done:
                obs.predicted.update(predicted)
                obs.chosen = chosen
                obs.block["chosen"] = chosen
            return
        obs = CacheObservation(predicted, chosen)
        request.cache = obs
        self._stamps += 1
        cp = predicted.get(chosen)
        if cp is not None and "blocks" in cp:
            KV_PREDICTED_HIT_BLOCKS.observe(cp["blocks"])
        rec = getattr(request, "decision", None)
        if rec is not None and hasattr(rec, "record_cache"):
            rec.record_cache(obs.block)

    # ---- completion-time: engine-confirmed actual -----------------------

    def observe_response(self, request: Any, endpoint: Any, headers: Any,
                         usage: dict[str, Any] | None = None) -> None:
        """Join the engine-confirmed actual (first signal wins): the
        relayed ``x-kv-hit-*`` headers on non-streaming responses, or the
        terminal usage record's ``prompt_tokens_details.cached_tokens`` on
        streams. Called once when the response headers land (so the
        ``x-debug-decision`` summary echo can carry the verdict) and again
        from the proxy's terminal accounting with the parsed usage — a
        request with neither signal simply never joins."""
        obs: CacheObservation | None = getattr(request, "cache", None)
        if obs is None or obs.done:
            return
        ht = hb = None
        source = None
        v = finite_float_or_none(headers.get(H_KV_HIT_TOKENS)
                                 if headers is not None else None)
        if v is not None and v >= 0:
            ht = int(v)
            vb = finite_float_or_none(headers.get(H_KV_HIT_BLOCKS))
            hb = int(vb) if vb is not None and vb >= 0 else None
            source = "headers"
        else:
            details = (usage or {}).get("prompt_tokens_details") or {}
            ct = details.get("cached_tokens")
            if isinstance(ct, (int, float)) and ct >= 0:
                ht = int(ct)
                source = "usage"
        if ht is None:
            return
        obs.done = True
        self._joins += 1
        pod = ""
        if headers is not None:
            pod = headers.get(H_KV_PREFILLER) or ""
        if not pod and endpoint is not None:
            pod = endpoint.metadata.address_port
        pred = obs.predicted.get(pod)
        block_tokens = int((pred or {}).get("block_tokens") or 16)
        if hb is None:
            hb = ht // max(block_tokens, 1)
        prompt_tokens = int((usage or {}).get("prompt_tokens") or 0)
        ratio: float | None = None
        if prompt_tokens > 0:
            ratio = min(ht / prompt_tokens, 1.0)
        elif pred is not None and pred.get("total"):
            ratio = min(hb / pred["total"], 1.0)
        actual: dict[str, Any] = {"pod": pod, "blocks": hb, "tokens": ht,
                                  "source": source}
        if ratio is not None:
            actual["ratio"] = round(ratio, 4)
            KV_ACTUAL_HIT_RATIO.observe(ratio)
        signed_ratio: float | None = None
        if pred is not None:
            if "blocks" in pred:
                signed_blocks = pred["blocks"] - hb
                KV_HIT_PREDICTION_ERROR.observe(abs(signed_blocks))
                actual["prediction_error_blocks"] = signed_blocks
                self._err.add(signed_blocks)
            pr = pred.get("ratio")
            if pr is not None and ratio is not None:
                signed_ratio = pr - ratio
                self._err_ratio.add(signed_ratio)
        self.table.record(pod or "(unknown)", hit_ratio=ratio,
                          signed_error=signed_ratio)
        obs.block["actual"] = actual
        # Judge the prefill classifier's verdict against this
        # engine-confirmed actual (the `judged` sub-block lands in the
        # DecisionRecord's classifier block through the shared dict).
        cls = getattr(request, "classifier", None)
        if cls is not None:
            self.judge.judge(cls, hit_tokens=ht,
                             prompt_tokens=prompt_tokens)

    # ---- render ---------------------------------------------------------

    def snapshot(self) -> dict[str, Any]:
        """The /debug/kv payload: per-pod EWMAs + index occupancy +
        scraped engine counters, speculative-vs-confirmed stamp counts, and
        the prediction MAE. ``index_divergence`` is 0 in a process that
        holds its own engine-confirmed index (single-process router, fleet
        leader); the fleet supervisor recomputes it per follower shard."""
        pods: dict[str, dict[str, Any]] = {
            pod: dict(row) for pod, row in self.table.rows().items()}

        def _row(addr: str) -> dict[str, Any]:
            return pods.setdefault(addr, {})

        for addr, judged in self.judge.rows().items():
            _row(addr)["classifier"] = judged

        for producer in self._approx:
            for addr, blocks in producer.index_sizes().items():
                _row(addr)["approx_index_blocks"] = blocks
        confirmed_total = speculative_total = 0
        for scorer in self._precise:
            for addr, counts in scorer.index_counts().items():
                row = _row(addr)
                row["confirmed_blocks"] = counts["confirmed"]
                row["speculative_blocks"] = counts["speculative"]
                confirmed_total += counts["confirmed"]
                speculative_total += counts["speculative"]
        if self.datastore is not None:
            for ep in self.datastore.endpoint_list():
                m = ep.metrics
                if m.prefill_tokens < 0:
                    continue
                scraped: dict[str, Any] = {
                    "prefill_tokens": int(m.prefill_tokens),
                    "prefix_hit_tokens": int(max(m.prefix_hit_tokens, 0)),
                }
                if m.prefill_tokens > 0:
                    scraped["actual_hit_ratio"] = round(
                        max(m.prefix_hit_tokens, 0) / m.prefill_tokens, 4)
                _row(ep.metadata.address_port)["scraped"] = scraped
        return {
            "enabled": self.cfg.enabled,
            "predicted_stamps": self._stamps,
            "confirmed_joins": self._joins,
            "prediction": self._err.render(),
            "prediction_ratio": self._err_ratio.render(),
            # Prefill-classifier accuracy: skip/keep verdicts judged
            # against the engine-confirmed actual hit depth (per-pod rows
            # carry their own `classifier` sub-doc).
            "classifier": self.judge.overall.render(),
            "index": {"confirmed_blocks": confirmed_total,
                      "speculative_blocks": speculative_total},
            "pods": pods,
            "index_divergence": 0.0,
        }
