"""Control plane, standalone-first: reconcilers + leader election.

Reference: pkg/epp/controller/*.go (InferencePool/Pod/InferenceObjective/
InferenceModelRewrite reconcilers driving the datastore) and
cmd/epp/runner/runner.go:306-316 + server/controller_manager.go:81-90
(lease-based leader election, readiness coupled to leadership,
health.go:52-104).

TPU-native standalone redesign: no kube-apiserver in the loop, so the watch
sources are files —

- ``ConfigReconciler`` polls the EndpointPickerConfig YAML's mtime and
  resyncs pool endpoints / objectives / model rewrites into the datastore on
  change (the CRD-watch analogue: same converge-to-declared-state semantics,
  deletes included, datastore.go:405 podResyncAll).
- ``LeaseElector`` elects a leader through an atomically-replaced lease file
  shared by replicas on a host/NFS (the Lease-object analogue: holder id +
  expiry, renew loop, takeover after expiry; acquisition races resolve by
  re-reading after write, the file-system analogue of the resourceVersion
  conflict check). Readiness gates on leadership exactly like the reference:
  followers report not-ready so the fronting LB only routes to the leader.

When k8s IS present, these interfaces are where a client-go-style binding
slots in; the datastore contract (resync/objective_set/rewrite_set) is
already the same one the reference reconcilers drive.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
import os
import random
import time
import uuid
from typing import Any, Callable

log = logging.getLogger("router.controlplane")


# ---- leader election ----------------------------------------------------


@dataclasses.dataclass
class LeaseConfig:
    path: str
    holder_id: str = ""
    lease_duration_s: float = 5.0
    renew_interval_s: float = 1.0

    def __post_init__(self):
        if not self.holder_id:
            self.holder_id = f"epp-{uuid.uuid4().hex[:8]}"


class LeaseElector:
    """File-lease leader election with graceful release and expiry takeover."""

    def __init__(self, cfg: LeaseConfig,
                 on_started_leading: Callable[[], None] | None = None,
                 on_stopped_leading: Callable[[], None] | None = None):
        self.cfg = cfg
        self.is_leader = False
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._task: asyncio.Task | None = None
        self._rng = random.Random()

    # -- lease file primitives (atomic via tmp + os.replace) --

    def _read(self) -> dict[str, Any] | None:
        try:
            with open(self.cfg.path) as f:
                return json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return None

    def _write(self, record: dict[str, Any]) -> None:
        tmp = f"{self.cfg.path}.tmp.{self.cfg.holder_id}"
        with open(tmp, "w") as f:
            json.dump(record, f)
        os.replace(tmp, self.cfg.path)

    def _try_acquire_or_renew(self) -> bool:
        now = time.time()
        rec = self._read()
        if (rec is not None and rec.get("holder") != self.cfg.holder_id
                and float(rec.get("expires", 0)) > now):
            return False  # live foreign lease
        self._write({"holder": self.cfg.holder_id,
                     "expires": now + self.cfg.lease_duration_s})
        # Confirm ownership after the write: two expired-lease claimants can
        # race os.replace; the survivor is whoever the file names (the
        # file-system analogue of the k8s resourceVersion conflict).
        rec = self._read()
        return rec is not None and rec.get("holder") == self.cfg.holder_id

    def release(self) -> None:
        """Graceful handoff: zero the expiry so followers take over now."""
        rec = self._read()
        if rec is not None and rec.get("holder") == self.cfg.holder_id:
            self._write({"holder": self.cfg.holder_id, "expires": 0})
        self._set_leader(False)

    def _set_leader(self, leading: bool) -> None:
        if leading and not self.is_leader:
            self.is_leader = True
            log.info("leader election: %s started leading", self.cfg.holder_id)
            if self.on_started_leading:
                self.on_started_leading()
        elif not leading and self.is_leader:
            self.is_leader = False
            log.warning("leader election: %s stopped leading", self.cfg.holder_id)
            if self.on_stopped_leading:
                self.on_stopped_leading()

    async def _run(self):
        try:
            while True:
                try:
                    self._set_leader(self._try_acquire_or_renew())
                except OSError:
                    log.exception("lease file I/O failure; demoting")
                    self._set_leader(False)
                # Followers jitter their polls so expired-lease claims don't
                # repeatedly collide.
                delay = self.cfg.renew_interval_s
                if not self.is_leader:
                    delay += self._rng.uniform(0, self.cfg.renew_interval_s / 2)
                await asyncio.sleep(delay)
        except asyncio.CancelledError:
            pass

    async def start(self):
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self, *, graceful: bool = True):
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        if graceful:
            try:
                self.release()
            except OSError:
                pass


# ---- config reconciler --------------------------------------------------


class ConfigReconciler:
    """Converges the datastore to the declared state of the config file.

    The standalone analogue of the reference's four reconcilers
    (pkg/epp/controller): pool endpoints resync (adds, updates, deletes),
    objectives and model rewrites set/delete. Watch = mtime polling.
    """

    def __init__(self, path: str, datastore: Any, poll_interval_s: float = 1.0):
        self.path = path
        self.datastore = datastore
        self.poll_interval_s = poll_interval_s
        self._mtime: float | None = None
        self._task: asyncio.Task | None = None

    def reconcile_once(self) -> bool:
        """Reload + resync if the file changed; returns True when applied."""
        try:
            mtime = os.stat(self.path).st_mtime
        except OSError:
            return False
        if self._mtime is not None and mtime == self._mtime:
            return False
        try:
            with open(self.path) as f:
                text = f.read()
            self._apply(text)
        except Exception:
            log.exception("config reconcile failed; keeping last good state")
            return False
        self._mtime = mtime
        return True

    def _apply(self, text: str) -> None:
        from .config.loader import _endpoint_meta, load_raw_config
        from .datalayer.datastore import (
            InferenceModelRewrite,
            InferenceObjective,
            ModelRewriteTarget,
        )

        raw = load_raw_config(text)
        metas = [_endpoint_meta(e) for e in raw.pool.get("endpoints") or []]
        self.datastore.resync(metas)

        declared_obj = {o["name"] for o in raw.objectives}
        for o in raw.objectives:
            self.datastore.objective_set(
                InferenceObjective(name=o["name"],
                                   priority=int(o.get("priority", 0))))
        for name in [n for n in self.datastore.objective_names()
                     if n not in declared_obj]:
            self.datastore.objective_delete(name)

        declared_rw = {rw["source"] for rw in raw.model_rewrites}
        for rw in raw.model_rewrites:
            self.datastore.rewrite_set(InferenceModelRewrite(
                name=rw.get("name") or rw["source"],
                source_model=rw["source"],
                targets=[ModelRewriteTarget(model=t["model"],
                                            weight=int(t.get("weight", 1)))
                         for t in rw.get("targets") or []]))
        for source in [s for s in self.datastore.rewrite_sources()
                       if s not in declared_rw]:
            self.datastore.rewrite_delete(source)
        log.info("config reconciled: %d endpoints, %d objectives, %d rewrites",
                 len(metas), len(declared_obj), len(declared_rw))

    async def _run(self):
        try:
            while True:
                await asyncio.sleep(self.poll_interval_s)
                self.reconcile_once()
        except asyncio.CancelledError:
            pass

    async def start(self):
        # Prime the mtime so the initial (already-loaded) config isn't
        # re-applied; subsequent edits reconcile.
        try:
            self._mtime = os.stat(self.path).st_mtime
        except OSError:
            self._mtime = None
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self):
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
