"""EndpointPickerConfig loader: two-phase (raw YAML → instantiate/validate).

Mirrors /root/reference/pkg/epp/config/loader/{configloader.go:79-303,
defaults.go:42-340}: phase one parses the YAML and applies feature gates;
phase two instantiates plugins through the registry and injects system
defaults — the built-in default profile (queue w=2 + kv-cache-utilization w=2
+ prefix-cache w=3), single-profile-handler when one profile has no handler,
max-score-picker for picker-less profiles, weight 1.0 for weightless scorers,
openai-parser when none is configured, and the metrics source/extractor
unless injectDefaults is false.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import yaml

from ..datalayer.datastore import Datastore, EndpointPool
from ..datalayer.extractor import CoreMetricsExtractor
from ..datalayer.metrics_source import MetricsDataSource
from ..datalayer.runtime import DataLayerRuntime
from ..framework.datalayer import EndpointMetadata
from ..framework.plugin import PluginRegistry, global_registry
from ..scheduling.scheduler import Scheduler, SchedulerProfile, WeightedScorer

DEFAULT_PROFILE_PLUGINS = [
    # reference defaults.go:46-103
    {"type": "queue-scorer", "weight": 2},
    {"type": "kv-cache-utilization-scorer", "weight": 2},
    {"type": "prefix-cache-scorer", "weight": 3},
]


@dataclasses.dataclass
class RawConfig:
    feature_gates: dict[str, bool]
    plugins: list[dict[str, Any]]
    scheduling_profiles: list[dict[str, Any]]
    parser: dict[str, Any] | None
    data_layer: dict[str, Any]
    flow_control: dict[str, Any]
    scheduling: dict[str, Any]
    fleet: dict[str, Any]
    saturation_detector: dict[str, Any] | None
    resilience: dict[str, Any]
    decisions: dict[str, Any]
    slo: dict[str, Any]
    overload: dict[str, Any]
    kv_cache: dict[str, Any]
    disagg: dict[str, Any]
    timeline: dict[str, Any]
    shadow: dict[str, Any]
    rebalance: dict[str, Any]
    forecast: dict[str, Any]
    autoscale: dict[str, Any]
    tails: dict[str, Any]
    tls_client: dict[str, Any]
    pool: dict[str, Any]
    objectives: list[dict[str, Any]]
    model_rewrites: list[dict[str, Any]]
    # The parsed YAML document verbatim — /debug/config serves a redacted
    # view of it and router_config_info{hash} fingerprints it, so an
    # operator can see what config a running worker actually loaded.
    doc: dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class RouterConfig:
    scheduler: Scheduler
    plugins_by_name: dict[str, Any]
    producers: list[Any]
    admit_plugins: list[Any]
    pre_request_plugins: list[Any]
    response_received: list[Any]
    response_streaming: list[Any]
    response_complete: list[Any]
    feature_gates: dict[str, bool]
    parser_spec: dict[str, Any]
    flow_control: dict[str, Any]
    # scheduling: the concurrent scheduling engine knobs
    # (router/schedpool.py SchedulingConfig — {workers, maxBatch};
    # workers: 0 is the inline kill-switch; pickSeed seeds every picker's
    # tie-break RNG per request so picks are reproducible across worker
    # counts — applied to the pickers at instantiate time below).
    scheduling: dict[str, Any]
    # fleet: the multi-process sharded gateway knobs (router/fleet.py
    # FleetConfig — {workers, balancer, snapshotIpc, adminPort}; workers: 1
    # (the default) is the single-process router, bit-identical).
    fleet: dict[str, Any]
    saturation_detector_spec: dict[str, Any] | None
    resilience: dict[str, Any]
    # decisions: the decision flight recorder knobs (enabled/capacity/topK —
    # router/decisions.py DecisionConfig). tlsClient: verification policy for
    # the GATEWAY's own client legs (upstream proxy, /debug/traces +
    # /v1/models fan-out) — insecureSkipVerify (default true: pod-local
    # certs) or caCertPath (router/tlsutil.py client_verify). The metrics
    # scrape and kv-event SSE data sources take the same knobs as per-plugin
    # parameters instead (they are plugins, configured where they are
    # declared).
    decisions: dict[str, Any]
    # slo: the SLO & goodput ledger knobs (router/slo.py SloConfig —
    # {enabled, defaultTtftMs, defaultTpotMs, perModel}; enabled: false is
    # the kill-switch that removes the per-chunk ledger hook entirely).
    slo: dict[str, Any]
    # overload: the goodput-max overload controller knobs
    # (router/overload.py OverloadConfig — predictive SLO admission,
    # degrade ladder, Retry-After shedding, unmeetable queue eviction;
    # enabled: false (the default) is the kill-switch that keeps behavior
    # bit-identical to the pre-overload router).
    overload: dict[str, Any]
    # kvCache: the KV-cache & prefix-reuse observability knobs
    # (router/kvobs.py KvObsConfig — {enabled, capacity, topCandidates};
    # enabled: false is the kill-switch that removes the predicted-vs-
    # confirmed hit ledger's hooks entirely).
    kv_cache: dict[str, Any]
    # disagg: P/D-disaggregation placement knobs. `classifier:` configures
    # the session-aware prefill classifier (router/plugins/disagg.py
    # PdClassifierConfig — {enabled, coldTokenThreshold, minConfidence});
    # enabled: false (the default) keeps the disagg handler bit-identical
    # to the always-run-the-decider router. Applied post-instantiation to
    # every plugin exposing set_classifier (the pickSeed precedent).
    disagg: dict[str, Any]
    # timeline: the fleet flight recorder knobs (router/timeline.py
    # TimelineConfig — {enabled, tickS, retentionS, burnRate, rules,
    # incidents}; enabled: false is the kill-switch that removes the
    # sampler task and the /debug/timeline history entirely).
    timeline: dict[str, Any]
    # shadow: the counterfactual scheduling ledger knobs (router/shadow.py
    # ShadowConfig — {enabled, policies, sampleRate, capacity}; no policies
    # configured (the default) is inert, enabled: false is the hard
    # kill-switch. Policies evaluate every live scheduling cycle in shadow
    # and are judged against the measured feeds at /debug/shadow).
    shadow: dict[str, Any]
    # rebalance: the self-balancing pool knobs (router/rebalance.py
    # RebalanceConfig — {enabled, tickS, minDwellS, headroomTarget,
    # maxConcurrentFlips, advice, ...}; enabled: false (the default) is the
    # kill-switch — the pool's P/D role split stays bit-identical static
    # config).
    rebalance: dict[str, Any]
    # forecast: the traffic forecaster knobs (router/forecast.py
    # ForecastConfig — {enabled, horizons, seasonalPeriodS, intervals,
    # alpha, beta, gamma, damping, warmupTicks, errorWindow}; default-on,
    # enabled: false is the kill-switch — zero stamps, no model state.
    # The engine rides the timeline sampler's tick, so disabling the
    # timeline also silences the forecaster).
    forecast: dict[str, Any]
    # autoscale: the guarded elastic-fleet actuator knobs
    # (router/autoscale.py AutoscaleConfig — {enabled, tickS,
    # sustainTicks, requireLead, maxActionsPerWindow, windowS, dwellS,
    # observationWindowS, rollbackAttainment, spawnTimeoutS,
    # drainTimeoutS, minPodsPerRole, maxPodsPerRole, podsPerWorker};
    # enabled: false (the default) is the kill-switch — no task, zero
    # ticks, zero actions, bit-identical).
    autoscale: dict[str, Any]
    # tails: the tail-latency attribution observatory knobs
    # (router/tails.py TailsConfig — {enabled, capacity, tailQuantile,
    # exemplars}; default-on per the kvCache precedent, enabled: false is
    # the kill-switch — no waterfall object ever rides a request, every
    # layer hook degrades to one `is None` check, bit-identical).
    tails: dict[str, Any]
    # The parsed YAML verbatim: /debug/config serves a redacted view and
    # router_config_info{hash} fingerprints it.
    raw_doc: dict[str, Any]
    tls_client: dict[str, Any]
    static_endpoints: list[EndpointMetadata]
    pool: EndpointPool
    objectives: list[Any] = dataclasses.field(default_factory=list)
    model_rewrites: list[Any] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class Handle:
    """Shared services visible to plugin factories (reference plugin.Handle)."""

    datastore: Datastore | None = None
    dl_runtime: DataLayerRuntime | None = None


def load_raw_config(text: str | None) -> RawConfig:
    doc = yaml.safe_load(text) if text else {}
    doc = doc or {}
    return RawConfig(
        feature_gates=doc.get("featureGates") or {},
        plugins=doc.get("plugins") or [],
        scheduling_profiles=doc.get("schedulingProfiles") or [],
        parser=doc.get("parser"),
        data_layer=doc.get("dataLayer") or {},
        flow_control=doc.get("flowControl") or {},
        scheduling=doc.get("scheduling") or {},
        fleet=doc.get("fleet") or {},
        saturation_detector=doc.get("saturationDetector"),
        resilience=doc.get("resilience") or {},
        decisions=doc.get("decisions") or {},
        slo=doc.get("slo") or {},
        overload=doc.get("overload") or {},
        kv_cache=doc.get("kvCache") or {},
        disagg=doc.get("disagg") or {},
        timeline=doc.get("timeline") or {},
        shadow=doc.get("shadow") or {},
        rebalance=doc.get("rebalance") or {},
        forecast=doc.get("forecast") or {},
        autoscale=doc.get("autoscale") or {},
        tails=doc.get("tails") or {},
        tls_client=doc.get("tlsClient") or {},
        pool=doc.get("pool") or {},
        objectives=doc.get("objectives") or [],
        model_rewrites=doc.get("modelRewrites") or [],
        doc=doc,
    )


def _endpoint_meta(e: dict[str, Any]) -> EndpointMetadata:
    return EndpointMetadata(
        name=e.get("name") or f"{e['address']}:{e['port']}",
        address=e["address"],
        port=int(e["port"]),
        metrics_port=int(e["metricsPort"]) if e.get("metricsPort") else None,
        labels=e.get("labels") or {},
        scheme=str(e.get("scheme", "http")),
    )


def instantiate(raw: RawConfig, handle: Handle,
                registry: PluginRegistry | None = None) -> RouterConfig:
    registry = registry or global_registry

    plugin_specs = list(raw.plugins)
    profiles_spec = list(raw.scheduling_profiles)

    # --- system default injection (reference defaults.go:146-327) --------
    if not profiles_spec:
        for spec in DEFAULT_PROFILE_PLUGINS:
            if not any(p.get("type") == spec["type"] for p in plugin_specs):
                plugin_specs.append({"type": spec["type"]})
        profiles_spec = [{
            "name": "default",
            "plugins": [{"pluginRef": s["type"], "weight": s.get("weight", 1)}
                        for s in DEFAULT_PROFILE_PLUGINS],
        }]

    # Default P/D profile pairing: transfer-aware-pair-scorer joins every
    # disagg config's "prefill" profile unless already declared or
    # disabled (`disagg: {pairScorer: {enabled: false}}`). Shadow-proven
    # in the counterfactual ledger (docs/shadow.md: estimate/actual ratio
    # 0.97 against a live A/B arm), and safe as a default because of
    # unmeasured-pair neutrality: on a cold TransferTable the scorer
    # scores nothing, so totals and picks are bit-identical. The profile
    # SPEC is amended (not the built profile — SchedulerProfile freezes
    # its scorer metadata at construction) on a copy, never the raw doc
    # (/debug/config and router_config_info serve the doc verbatim).
    pair_spec = (raw.disagg or {}).get("pairScorer") or {}
    if bool(pair_spec.get("enabled", True)):
        has_disagg = any(spec.get("type") in ("disagg-profile-handler",
                                              "pd-profile-handler")
                         for spec in plugin_specs)
        pair_names = {spec.get("name") or spec["type"]
                      for spec in plugin_specs
                      if spec.get("type") == "transfer-aware-pair-scorer"}
        for i, pspec in enumerate(profiles_spec):
            if not has_disagg or pspec.get("name") != "prefill":
                continue
            refs = list(pspec.get("plugins") or [])
            if any(r.get("pluginRef") in pair_names
                   or r.get("pluginRef") == "transfer-aware-pair-scorer"
                   for r in refs):
                continue
            if not pair_names:
                plugin_specs.append({"type": "transfer-aware-pair-scorer"})
                pair_names.add("transfer-aware-pair-scorer")
            refs.append({"pluginRef": next(iter(pair_names)),
                         "weight": float(pair_spec.get("weight", 2.0))})
            profiles_spec[i] = {**pspec, "plugins": refs}

    # Instantiate declared plugins.
    plugins_by_name: dict[str, Any] = {}
    for spec in plugin_specs:
        ptype = spec["type"]
        name = spec.get("name") or ptype
        if name in plugins_by_name:
            raise ValueError(f"duplicate plugin name {name!r}")
        plugins_by_name[name] = registry.instantiate(
            ptype, name, spec.get("parameters") or {}, handle)

    def _ensure(type_name: str) -> Any:
        if type_name not in plugins_by_name:
            plugins_by_name[type_name] = registry.instantiate(type_name, type_name, {}, handle)
        return plugins_by_name[type_name]

    # Build profiles.
    profiles: dict[str, SchedulerProfile] = {}
    profile_handler = None
    for pspec in profiles_spec:
        pname = pspec.get("name") or "default"
        filters, scorers, picker = [], [], None
        for ref in pspec.get("plugins") or []:
            plugin = plugins_by_name.get(ref["pluginRef"])
            if plugin is None:
                raise ValueError(f"profile {pname!r} references unknown plugin "
                                 f"{ref['pluginRef']!r}")
            if hasattr(plugin, "pick"):
                picker = plugin
            elif hasattr(plugin, "score"):
                scorers.append(WeightedScorer(plugin, float(ref.get("weight", 1.0))))
            elif hasattr(plugin, "filter"):
                filters.append(plugin)
            else:
                raise ValueError(f"plugin {ref['pluginRef']!r} fits no profile role")
        if picker is None:
            picker = _ensure("max-score-picker")  # defaults.go: picker injection
        profiles[pname] = SchedulerProfile(pname, filters, scorers, picker)

    # scheduling.pickSeed: seed every picker's tie-break RNG with a
    # per-request derivation (plugins/pickers.py _rng_for) so picks are a
    # pure function of (seed, request) — reproducible across runs, worker
    # threads, AND fleet worker counts (the shard-parity contract of
    # benchmarks/SCHED_SCALEOUT.json). A per-picker `pickSeed` parameter
    # set where the plugin is declared wins over this profile-wide default.
    pick_seed = raw.scheduling.get("pickSeed") if raw.scheduling else None
    if pick_seed is not None:
        for prof in profiles.values():
            if (hasattr(prof.picker, "_rng_for")
                    and prof.picker.pick_seed is None):
                prof.picker.pick_seed = int(pick_seed)

    # disagg.classifier: the session-aware prefill classifier config is a
    # top-level section (it gates a placement *stage*, not one plugin
    # instance's parameters) applied to every handler exposing the
    # set_classifier hook — the scheduling.pickSeed application precedent.
    cls_spec = (raw.disagg or {}).get("classifier")
    if cls_spec is not None:
        from ..plugins.disagg import PdClassifierConfig

        classifier_cfg = PdClassifierConfig.from_spec(cls_spec)
        for plugin in plugins_by_name.values():
            if hasattr(plugin, "set_classifier"):
                plugin.set_classifier(classifier_cfg)

    # Profile handler: explicit plugin wins; else single-profile-handler.
    for plugin in plugins_by_name.values():
        if hasattr(plugin, "pick_profiles"):
            profile_handler = plugin
    if profile_handler is None:
        if len(profiles) > 1:
            raise ValueError("multiple scheduling profiles need an explicit "
                             "profile-handler plugin")
        profile_handler = _ensure("single-profile-handler")

    # Bucket request-control plugins by capability (reference
    # requestcontrol/request_control_config.go).
    producers = [p for p in plugins_by_name.values() if hasattr(p, "produce")]
    admit = [p for p in plugins_by_name.values() if hasattr(p, "admit")]
    pre_request = [p for p in plugins_by_name.values() if hasattr(p, "pre_request")]
    resp_received = [p for p in plugins_by_name.values() if hasattr(p, "response_received")]
    resp_streaming = [p for p in plugins_by_name.values() if hasattr(p, "response_streaming")]
    resp_complete = [p for p in plugins_by_name.values() if hasattr(p, "response_complete")]

    # Data layer: wire declared source→extractor pairs (reference
    # dataLayer.sources, configloader.go), register every declared data
    # source plugin, then inject the default metrics source unless disabled.
    if handle.dl_runtime is not None:
        dl_spec = raw.data_layer if isinstance(raw.data_layer, dict) else {}
        for src_spec in dl_spec.get("sources") or []:
            src = plugins_by_name.get(src_spec.get("pluginRef"))
            if src is None:
                raise ValueError(f"dataLayer source references unknown plugin "
                                 f"{src_spec.get('pluginRef')!r}")
            for ex_ref in src_spec.get("extractors") or []:
                ex_name = (ex_ref.get("pluginRef")
                           if isinstance(ex_ref, dict) else ex_ref)
                ex = plugins_by_name.get(ex_name)
                if ex is None:
                    raise ValueError(f"dataLayer extractor references unknown "
                                     f"plugin {ex_name!r}")
                src.add_extractor(ex)
        for plugin in plugins_by_name.values():
            if hasattr(plugin, "collect") and hasattr(plugin, "extractors"):
                handle.dl_runtime.register_source(plugin)
        inject_dl = dl_spec.get("injectDefaults", True)
        if inject_dl and not any(isinstance(s, MetricsDataSource)
                                 for s in handle.dl_runtime.sources):
            src = MetricsDataSource("metrics-data-source")
            src.add_extractor(CoreMetricsExtractor("core-metrics-extractor"))
            handle.dl_runtime.register_source(src)

    parser_spec = raw.parser or {"type": "openai-parser"}

    pool_spec = raw.pool
    pool = EndpointPool(
        name=pool_spec.get("name", "default-pool"),
        namespace=pool_spec.get("namespace", "default"),
    )
    static_endpoints = [_endpoint_meta(e) for e in pool_spec.get("endpoints") or []]

    from ..datalayer.datastore import (
        InferenceModelRewrite,
        InferenceObjective,
        ModelRewriteTarget,
    )

    objectives = [InferenceObjective(name=o["name"], priority=int(o.get("priority", 0)))
                  for o in raw.objectives]
    # "sourceModel" matches the CRD schema (deploy/crds/) and the kube
    # binding; "source" is the original file-config key — accept both.
    rewrites = [InferenceModelRewrite(
        name=rw.get("name") or rw.get("sourceModel") or rw["source"],
        source_model=rw.get("sourceModel") or rw["source"],
        targets=[ModelRewriteTarget(model=t["model"], weight=int(t.get("weight", 1)))
                 for t in rw.get("targets") or []])
        for rw in raw.model_rewrites]

    return RouterConfig(
        scheduler=Scheduler(profiles, profile_handler),
        plugins_by_name=plugins_by_name,
        producers=producers,
        admit_plugins=admit,
        pre_request_plugins=pre_request,
        response_received=resp_received,
        response_streaming=resp_streaming,
        response_complete=resp_complete,
        feature_gates=raw.feature_gates,
        parser_spec=parser_spec,
        flow_control=raw.flow_control,
        scheduling=raw.scheduling,
        fleet=raw.fleet,
        saturation_detector_spec=raw.saturation_detector,
        resilience=raw.resilience,
        decisions=raw.decisions,
        slo=raw.slo,
        overload=raw.overload,
        kv_cache=raw.kv_cache,
        disagg=raw.disagg,
        timeline=raw.timeline,
        shadow=raw.shadow,
        rebalance=raw.rebalance,
        forecast=raw.forecast,
        autoscale=raw.autoscale,
        tails=raw.tails,
        raw_doc=raw.doc,
        tls_client=raw.tls_client,
        static_endpoints=static_endpoints,
        pool=pool,
        objectives=objectives,
        model_rewrites=rewrites,
    )


def load_config(text: str | None, handle: Handle) -> RouterConfig:
    return instantiate(load_raw_config(text), handle)
