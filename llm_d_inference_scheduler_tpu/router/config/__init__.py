from .loader import RouterConfig, load_config, load_raw_config, instantiate

__all__ = ["RouterConfig", "load_config", "load_raw_config", "instantiate"]
