"""OTLP/HTTP trace exporter (reference: pkg/telemetry/tracing.go:52-129 —
OTel OTLP exporter configured from OTEL_* env vars).

Zero-dependency: encodes ExportTraceServiceRequest protobuf
(opentelemetry/proto/collector/trace/v1) with a hand-rolled writer — the
field layout below mirrors the public OTLP proto — and POSTs it to
`<OTEL_EXPORTER_OTLP_ENDPOINT>/v1/traces` from a background thread with
batching, so span finish never blocks on the network. Wire compatibility is
asserted in tests by decoding the emitted bytes with an independent reader.

Enable: OTEL_EXPORTER_OTLP_ENDPOINT=http://collector:4318 (+ optional
OTEL_SERVICE_NAME) — Tracer picks it up at construction via
maybe_start_otlp_exporter().
"""

from __future__ import annotations

import logging
import os
import struct
import threading
import time
import urllib.request
from typing import Any

log = logging.getLogger("router.otlp")

FLUSH_INTERVAL_S = 2.0
MAX_BATCH = 512


# ---- minimal protobuf writer -------------------------------------------


def _varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        out.append(b | (0x80 if v else 0))
        if not v:
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _ld(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def _str(field: int, s: str) -> bytes:
    return _ld(field, s.encode())


def _fixed64(field: int, v: int) -> bytes:
    return _tag(field, 1) + struct.pack("<Q", v)


def _anyvalue(v: Any) -> bytes:
    """opentelemetry.proto.common.v1.AnyValue: string=1, bool=2, int=3,
    double=4."""
    if isinstance(v, bool):
        return _tag(2, 0) + _varint(1 if v else 0)
    if isinstance(v, int):
        return _tag(3, 0) + _varint(v & ((1 << 64) - 1))
    if isinstance(v, float):
        return _tag(4, 1) + struct.pack("<d", v)
    return _str(1, str(v))


def _keyvalue(key: str, v: Any) -> bytes:
    return _str(1, key) + _ld(2, _anyvalue(v))


def encode_span(span: dict[str, Any], epoch_offset_ns: int) -> bytes:
    """opentelemetry.proto.trace.v1.Span: trace_id=1, span_id=2,
    parent_span_id=4, name=5, kind=6, start=7, end=8, attributes=9,
    events=11, status=15. Real per-span wall-clock start (tracing.py stamps
    start_unix_ns at span begin); epoch_offset_ns is only the fallback for
    records without one."""
    start_ns = int(span.get("start_unix_ns") or epoch_offset_ns)
    end_ns = start_ns + int(span.get("duration_ms", 0.0) * 1e6)
    out = bytearray()
    out += _ld(1, bytes.fromhex(span["trace_id"][:32].rjust(32, "0")))
    out += _ld(2, bytes.fromhex(span["span_id"][:16].rjust(16, "0")))
    if span.get("parent_id"):
        out += _ld(4, bytes.fromhex(span["parent_id"][:16].rjust(16, "0")))
    out += _str(5, span["name"])
    out += _tag(6, 0) + _varint(2)  # SPAN_KIND_SERVER
    out += _fixed64(7, start_ns)
    out += _fixed64(8, end_ns)
    for k, v in (span.get("attributes") or {}).items():
        out += _ld(9, _keyvalue(k, v))
    for ev in span.get("events") or ():
        # Span.Event: time_unix_nano=1, name=2, attributes=3 (the decision
        # flight recorder's phase summaries ride these).
        ev_bytes = bytearray()
        ev_bytes += _fixed64(1, int(ev.get("time_unix_ns") or start_ns))
        ev_bytes += _str(2, str(ev.get("name", "")))
        for k, v in (ev.get("attributes") or {}).items():
            ev_bytes += _ld(3, _keyvalue(k, v))
        out += _ld(11, bytes(ev_bytes))
    status = span.get("status", "ok")
    if status == "ok":
        out += _ld(15, _tag(3, 0) + _varint(1))   # code=STATUS_CODE_OK
    else:
        out += _ld(15, _str(2, status) + _tag(3, 0) + _varint(2))  # ERROR
    return bytes(out)


def encode_export_request(spans: list[dict[str, Any]],
                          service_name: str) -> bytes:
    """ExportTraceServiceRequest: resource_spans=1 → {resource=1
    {attributes=1}, scope_spans=2 → {spans=2}}."""
    now_ns = time.time_ns()
    span_bytes = b"".join(_ld(2, encode_span(s, now_ns)) for s in spans)
    scope_spans = span_bytes
    resource = _ld(1, _keyvalue("service.name", service_name))
    resource_spans = _ld(1, resource) + _ld(2, scope_spans)
    return _ld(1, resource_spans)


class OtlpHttpExporter:
    """Batching OTLP/HTTP exporter; hand off via export(span_dict)."""

    def __init__(self, endpoint: str, service_name: str = "llm-d-router-tpu",
                 flush_interval: float = FLUSH_INTERVAL_S):
        self.url = endpoint.rstrip("/") + "/v1/traces"
        self.service_name = service_name
        self.flush_interval = flush_interval
        self._buf: list[dict[str, Any]] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="otlp-exporter")
        self._thread.start()

    def export(self, span: dict[str, Any]) -> None:
        with self._lock:
            self._buf.append(span)
            if len(self._buf) > MAX_BATCH * 4:
                # Collector unreachable for a while: shed oldest.
                del self._buf[: MAX_BATCH * 2]

    def flush(self) -> None:
        with self._lock:
            batch, self._buf = self._buf[:MAX_BATCH], self._buf[MAX_BATCH:]
        if not batch:
            return
        body = encode_export_request(batch, self.service_name)
        req = urllib.request.Request(
            self.url, data=body,
            headers={"Content-Type": "application/x-protobuf"})
        try:
            urllib.request.urlopen(req, timeout=5).read()
        except Exception as e:
            log.debug("OTLP export failed (%s); %d spans dropped", e, len(batch))

    def _run(self) -> None:
        while not self._stop.wait(self.flush_interval):
            self.flush()
        self.flush()

    def shutdown(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)


def span_to_otlp_json(span: dict[str, Any], service_name: str) -> dict[str, Any]:
    """One finished span dict → the OTLP/JSON ExportTraceServiceRequest
    mapping (camelCase keys, hex ids, stringified u64 nanos — the encoding
    OTel collectors' file receivers and `otlp/json` ingest accept). Shared
    by every component's file sink so router and engine spans land in one
    uniform, collector-loadable stream."""
    start_ns = int(span.get("start_unix_ns") or time.time_ns())
    end_ns = start_ns + int(span.get("duration_ms", 0.0) * 1e6)

    def attr_value(v: Any) -> dict[str, Any]:
        if isinstance(v, bool):
            return {"boolValue": v}
        if isinstance(v, int):
            return {"intValue": str(v)}
        if isinstance(v, float):
            return {"doubleValue": v}
        return {"stringValue": str(v)}

    status = span.get("status", "ok")
    doc: dict[str, Any] = {
        "traceId": span["trace_id"][:32].rjust(32, "0"),
        "spanId": span["span_id"][:16].rjust(16, "0"),
        "name": span["name"],
        "kind": 2,  # SPAN_KIND_SERVER
        "startTimeUnixNano": str(start_ns),
        "endTimeUnixNano": str(end_ns),
        "attributes": [{"key": k, "value": attr_value(v)}
                       for k, v in (span.get("attributes") or {}).items()],
        "status": ({"code": 1} if status == "ok"
                   else {"code": 2, "message": status}),
    }
    if span.get("parent_id"):
        doc["parentSpanId"] = span["parent_id"][:16].rjust(16, "0")
    if span.get("events"):
        doc["events"] = [
            {"timeUnixNano": str(int(ev.get("time_unix_ns") or start_ns)),
             "name": str(ev.get("name", "")),
             "attributes": [{"key": k, "value": attr_value(v)}
                            for k, v in (ev.get("attributes") or {}).items()]}
            for ev in span["events"]]
    return {"resourceSpans": [{
        "resource": {"attributes": [{"key": "service.name",
                                     "value": {"stringValue": service_name}}]},
        "scopeSpans": [{"spans": [doc]}],
    }]}


class OtlpFileExporter:
    """JSONL file sink: one OTLP/JSON ExportTraceServiceRequest per finished
    span — genuine OTLP-shaped export in a zero-egress environment (any log
    shipper or `otelcol` file receiver can replay it). One append handle is
    held open for the exporter's lifetime: exporters run synchronously at
    span finish (often on the event loop), so per-span open/close churn is
    the part of the I/O cost worth avoiding."""

    def __init__(self, path: str, service_name: str = "llm-d-router-tpu"):
        self.path = path
        self.service_name = service_name
        self._f = open(path, "a")

    def export(self, span: dict[str, Any]) -> None:
        import json

        self._f.write(json.dumps(span_to_otlp_json(span, self.service_name))
                      + "\n")
        self._f.flush()

    def shutdown(self) -> None:
        self._f.close()


def maybe_start_otlp_exporter() -> OtlpHttpExporter | None:
    endpoint = os.environ.get("OTEL_EXPORTER_OTLP_ENDPOINT", "")
    if not endpoint:
        return None
    name = os.environ.get("OTEL_SERVICE_NAME", "llm-d-router-tpu")
    return OtlpHttpExporter(endpoint, name)


def env_exporters() -> list[Any]:
    """All env-gated OTLP-shaped sinks, for the Tracer to register at
    construction. Zero-egress default: with neither env var set the ring
    buffer stays the only sink.

    - OTEL_EXPORTER_OTLP_ENDPOINT → batching OTLP/HTTP POST (protobuf)
    - OTEL_EXPORTER_OTLP_TRACES_FILE → OTLP/JSON JSONL file
    Both honor OTEL_SERVICE_NAME, so router and engine processes tag their
    spans distinctly while sharing one encoder and (optionally) one file."""
    out: list[Any] = []
    name = os.environ.get("OTEL_SERVICE_NAME", "llm-d-router-tpu")
    path = os.environ.get("OTEL_EXPORTER_OTLP_TRACES_FILE", "")
    if path:
        out.append(OtlpFileExporter(path, name))
    http = maybe_start_otlp_exporter()
    if http is not None:
        out.append(http)
    return out
