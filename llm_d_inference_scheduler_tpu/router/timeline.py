"""Fleet flight recorder: the /debug/timeline telemetry history, the
SLO burn-rate monitor, and triggered incident snapshots.

Every observability surface the router has built so far — traces,
decisions, /debug/slo, /debug/kv — is point-in-time: ask "what happened at
t=40s of the overload ramp" and nothing can answer. P/D-Serve
(arXiv:2408.08147) argues fine-grained per-stage monitoring *over time* is
what makes disaggregated serving operable at scale, and the ROADMAP's
chaos-run and P/D-rebalancer items both need history — divergence bounds
"held" is a claim about a series, and the rebalancer's defining input is
the prefill:decode token mix *as it swings* mid-run.

Three pieces, one module:

- **TimelineSampler** — ticks on the event loop (``timeline: {enabled,
  tickS, retentionS}``, default-on like ``kvCache``) and appends one
  bounded-ring sample of the signals the closed loops already compute:
  drain rate + in-flight + per-band queue depth, served/shed/degraded
  deltas, goodput vs raw token deltas, the per-role prefill:decode token
  mix (the rebalancer input, derived from counter deltas), pool-level KV
  hit/signed-error EWMAs, transfer-pair EWMAs, loop lag (the tick's own
  sleep overshoot), snapshot epoch, and process self-telemetry (RSS, open
  FDs, GC pause). Served at ``GET /debug/timeline`` with raw ticks plus
  windowed aggregates (p50/p99, rate of change).
- **BurnRateMonitor** — SRE-style multi-window burn rate over the
  attainment series: burn = (1 − met/arrivals) / error budget, where
  arrivals include sheds (a shed burns the arrival-relative goodput
  budget even though /debug/slo's served-relative attainment excludes it
  — that asymmetry is deliberate: the monitor answers "are users getting
  goodput", the ledger answers "is the pool serving what it admitted").
  An incident trips only when BOTH the fast and slow windows exceed their
  thresholds — fast catches the onset, slow confirms it is not a blip.
- **IncidentRecorder** — bounded ``/debug/incidents`` ring. On a rule
  trip (burn rate, shed-rate spike, drain collapse, divergence bound) it
  captures the timeline window ±N ticks, the last K missed/shed
  DecisionRecords, and the /debug/slo + /debug/kv rollups at trigger
  time. Dedup/cooldown: a sustained overload extends ONE incident (ticks
  count + post-trigger window grow in place); a re-trip inside the
  cooldown window reopens the same incident instead of minting a new one.

Fleet mode fans both in (router/fleet.py): per-worker rings merge by
wall-clock bucket at the FleetAdmin — ticks are grid-aligned so the same
bucket index means the same wall second in every worker — with gaps marked
when a shard was down (no interpolation; the monotonic-merge precedent),
and a supervisor-side divergence series rides beside the worker buckets so
a kill-the-leader chaos run reads as one timeline with the divergence
excursion and the incident that recorded it.

``timeline: {enabled: false}`` is the kill-switch: no background task, no
ring, and ``tick()`` degrades to a single attribute check — ``bench.py
--timeline`` measures both sides against the SCHED_HOTPATH cycle floor.
"""

from __future__ import annotations

import asyncio
import contextlib
import dataclasses
import gc as _gc
import json
import os
import time
from collections import deque
from typing import Any, Callable

import xxhash

from .metrics import (
    GC_PAUSE_SECONDS,
    INCIDENTS_TOTAL,
    PROCESS_OPEN_FDS,
    PROCESS_RSS_BYTES,
    SLO_BURN_RATE,
    TIMELINE_TICKS,
)

# Incident rule names (the {rule} label on router_incidents_total —
# bounded cardinality).
RULE_BURN_RATE = "burn_rate"
RULE_SHED_RATE = "shed_rate"
RULE_DRAIN_COLLAPSE = "drain_collapse"
RULE_DIVERGENCE = "divergence"


@dataclasses.dataclass
class BurnRateConfig:
    """The ``timeline.burnRate:`` section. ``target`` is the SLO attainment
    objective the error budget derives from (budget = 1 − target); the
    fast window catches onset, the slow window confirms sustained burn."""

    target: float = 0.9
    fast_window_s: float = 60.0
    slow_window_s: float = 300.0
    fast_burn: float = 4.0
    slow_burn: float = 2.0

    @classmethod
    def from_spec(cls, spec: dict[str, Any] | None) -> "BurnRateConfig":
        spec = spec or {}
        cfg = cls(target=float(spec.get("target", 0.9)),
                  fast_window_s=float(spec.get("fastWindowS", 60.0)),
                  slow_window_s=float(spec.get("slowWindowS", 300.0)),
                  fast_burn=float(spec.get("fastBurn", 4.0)),
                  slow_burn=float(spec.get("slowBurn", 2.0)))
        if not 0.0 < cfg.target < 1.0:
            raise ValueError("timeline.burnRate.target must be in (0, 1)")
        if cfg.fast_window_s > cfg.slow_window_s:
            raise ValueError("timeline.burnRate: fastWindowS must be <= "
                             "slowWindowS")
        return cfg


@dataclasses.dataclass
class TimelineConfig:
    """The YAML ``timeline:`` section. Default-on (the ``kvCache``
    precedent); ``enabled: false`` is the kill-switch — no task, no ring,
    ``tick()`` is one attribute check."""

    enabled: bool = True
    tick_s: float = 1.0
    retention_s: float = 600.0
    burn: BurnRateConfig = dataclasses.field(default_factory=BurnRateConfig)
    # Bound rules (0 disables each): shed rate in sheds/s, drain collapse
    # (queued work waiting while the measured drain rate sits below the
    # floor), per-shard KV-index divergence (evaluated supervisor-side —
    # a worker cannot see its own divergence, the fan-in computes it).
    shed_rate_max: float = 0.0
    drain_min_rps: float = 0.0
    divergence_max: float = 0.0
    # Incident capture.
    incident_capacity: int = 64
    context_ticks: int = 10
    cooldown_s: float = 120.0
    max_decisions: int = 8

    @classmethod
    def from_spec(cls, spec: dict[str, Any] | None) -> "TimelineConfig":
        spec = spec or {}
        rules = spec.get("rules") or {}
        inc = spec.get("incidents") or {}
        cfg = cls(
            enabled=bool(spec.get("enabled", True)),
            tick_s=float(spec.get("tickS", 1.0)),
            retention_s=float(spec.get("retentionS", 600.0)),
            burn=BurnRateConfig.from_spec(spec.get("burnRate")),
            shed_rate_max=float(rules.get("shedRateMax", 0.0)),
            drain_min_rps=float(rules.get("drainMinRps", 0.0)),
            divergence_max=float(rules.get("divergenceMax", 0.0)),
            incident_capacity=max(1, int(inc.get("capacity", 64))),
            context_ticks=max(1, int(inc.get("contextTicks", 10))),
            cooldown_s=float(inc.get("cooldownS", 120.0)),
            max_decisions=max(1, int(inc.get("maxDecisions", 8))),
        )
        if cfg.tick_s <= 0:
            raise ValueError("timeline.tickS must be > 0")
        if cfg.retention_s < cfg.tick_s:
            raise ValueError("timeline.retentionS must be >= tickS")
        return cfg

    @property
    def ring_capacity(self) -> int:
        return max(1, int(self.retention_s / self.tick_s))


# ---------------------------------------------------------------------------
# Process self-telemetry: RSS, open FDs, GC pause time.
# ---------------------------------------------------------------------------

_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096

# One persistently-open fd for /proc/self/statm: procfs serves fresh
# content on every pread(fd, …, 0), so the per-sample cost is one syscall
# instead of open+read+close (the open dominates).
_STATM_FD: int | None = None
try:
    _STATM_FD = os.open("/proc/self/statm", os.O_RDONLY)
except OSError:
    _STATM_FD = None


def rss_bytes() -> int:
    """Current resident set size. /proc/self/statm is the live number on
    Linux; the resource module's ru_maxrss is the PEAK, so it is only the
    fallback (documented as such by reporting 0 when neither works)."""
    if _STATM_FD is not None:
        try:
            return int(os.pread(_STATM_FD, 128, 0).split()[1]) * _PAGE_SIZE
        except (OSError, ValueError, IndexError):
            pass
    try:
        import resource
        import sys

        # ru_maxrss units are platform-dependent: bytes on Darwin,
        # kilobytes on Linux/BSD — and Darwin is the platform where this
        # fallback actually runs (no /proc), so the unit guard matters.
        scale = 1 if sys.platform == "darwin" else 1024
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * scale
    except Exception:
        return 0


def open_fds() -> int:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return 0


class GcPauseTracker:
    """Cumulative stop-the-world GC pause time via ``gc.callbacks``. The
    callback is two clock reads — it must stay that cheap, it runs inside
    every collection. ``stop()`` removes the callback (tests boot many
    gateways in one process; a leaked callback would double-count)."""

    def __init__(self):
        self.pause_s_total = 0.0
        self._t0: float | None = None
        self._installed = False

    def _cb(self, phase: str, info: dict) -> None:
        if phase == "start":
            self._t0 = time.perf_counter()
        elif self._t0 is not None:
            self.pause_s_total += time.perf_counter() - self._t0
            self._t0 = None

    def start(self) -> None:
        if not self._installed:
            _gc.callbacks.append(self._cb)
            self._installed = True

    def stop(self) -> None:
        if self._installed:
            with contextlib.suppress(ValueError):
                _gc.callbacks.remove(self._cb)
            self._installed = False


# ---------------------------------------------------------------------------
# Redacted config snapshot (/debug/config).
# ---------------------------------------------------------------------------

# Key-name fragments whose values are masked outright (tokens, credentials,
# certificate material) — matched case-insensitively on the key.
_SECRET_KEY_FRAGMENTS = ("token", "secret", "password", "credential", "cert")
# Keys whose values are filesystem paths: the path layout leaks deployment
# internals (mount points, cluster names) the debug plane has no business
# serving; the basename stays so the operator can still tell WHICH file.
_PATH_KEY_SUFFIX = "path"

REDACTED = "***"


def redact_config(doc: Any) -> Any:
    """Deep-copy ``doc`` with secrets and paths masked. Secrets redact
    fully; path values keep their basename (``/etc/certs/ca.pem`` →
    ``***/ca.pem``) so the snapshot stays diagnosable without leaking the
    filesystem layout."""
    if isinstance(doc, dict):
        out = {}
        for k, v in doc.items():
            lk = str(k).lower()
            if any(f in lk for f in _SECRET_KEY_FRAGMENTS):
                out[k] = REDACTED if v is not None else None
            elif lk.endswith(_PATH_KEY_SUFFIX) and isinstance(v, str) and v:
                out[k] = f"{REDACTED}/{os.path.basename(v)}"
            else:
                out[k] = redact_config(v)
        return out
    if isinstance(doc, list):
        return [redact_config(v) for v in doc]
    if isinstance(doc, str) and doc.startswith("/") and "/" in doc[1:]:
        return f"{REDACTED}/{os.path.basename(doc)}"
    return doc


def config_hash(doc: Any) -> str:
    """Stable hash of the UNREDACTED effective config — two workers whose
    redacted views agree but whose secrets differ must NOT report the same
    hash (that mismatch is exactly what the fleet fan-in exists to catch).
    xxh64 over canonical JSON; process-stable (the flow_shard rationale)."""
    canon = json.dumps(doc, sort_keys=True, default=str)
    return xxhash.xxh64_hexdigest(canon.encode())


# ---------------------------------------------------------------------------
# Burn-rate monitor.
# ---------------------------------------------------------------------------

class _WindowSum:
    """One burn window: a bounded deque of per-tick deltas with RUNNING
    sums, so add() and burn() are O(1) — the tick path must stay well
    under the <1%-of-cycle-floor budget, and re-summing a 300-tick window
    by deque indexing every tick is O(n²)."""

    __slots__ = ("ticks", "_dq", "arrivals", "met")

    def __init__(self, ticks: int):
        self.ticks = ticks
        self._dq: deque[tuple[int, int]] = deque()
        self.arrivals = 0
        self.met = 0

    def add(self, arrivals: int, met: int) -> None:
        self._dq.append((arrivals, met))
        self.arrivals += arrivals
        self.met += met
        if len(self._dq) > self.ticks:
            oa, om = self._dq.popleft()
            self.arrivals -= oa
            self.met -= om

    def burn(self, budget: float) -> float:
        if self.arrivals <= 0:
            return 0.0
        return (1.0 - self.met / self.arrivals) / budget


class BurnRateMonitor:
    """Multi-window SLO burn rate over per-tick (arrivals, met) deltas.

    burn(window) = (1 − met/arrivals over the window) / (1 − target).
    Arrivals include sheds — see the module docstring for why the monitor
    burns arrival-relative while /debug/slo stays served-relative. A
    window with no arrivals reports burn 0 (an idle router is not burning
    budget)."""

    def __init__(self, cfg: TimelineConfig):
        self.cfg = cfg
        self._budget = max(1.0 - cfg.burn.target, 1e-6)
        self._fast = _WindowSum(
            max(1, int(cfg.burn.fast_window_s / cfg.tick_s)))
        self._slow = _WindowSum(
            max(1, int(cfg.burn.slow_window_s / cfg.tick_s)))

    def add(self, arrivals: int, met: int) -> None:
        self._fast.add(arrivals, met)
        self._slow.add(arrivals, met)

    def rates(self) -> tuple[float, float]:
        return self._fast.burn(self._budget), self._slow.burn(self._budget)

    def tripped(self, fast: float, slow: float) -> bool:
        return (fast >= self.cfg.burn.fast_burn
                and slow >= self.cfg.burn.slow_burn)


# ---------------------------------------------------------------------------
# Incident recorder.
# ---------------------------------------------------------------------------

class _RuleState:
    __slots__ = ("incident", "active", "cooldown_until")

    def __init__(self):
        self.incident: dict[str, Any] | None = None
        self.active = False
        self.cooldown_until = 0.0


class IncidentRecorder:
    """Bounded incident ring with per-rule dedup/cooldown.

    One rule, one live incident: while a rule keeps tripping on
    consecutive evaluations the SAME incident updates in place (tick
    count, last_unix, the post-trigger half of the ±N window); after it
    clears, a re-trip inside ``cooldownS`` reopens it rather than minting
    a new entry — a sustained overload is one incident, not four hundred."""

    def __init__(self, cfg: TimelineConfig, *,
                 slo_snapshot_fn: Callable[[], dict] | None = None,
                 kv_snapshot_fn: Callable[[], dict] | None = None,
                 decisions_fn: Callable[[int], list] | None = None,
                 forecast_fn: Callable[[], dict] | None = None,
                 wall: Callable[[], float] = time.time):
        self.cfg = cfg
        self._wall = wall
        self._slo_fn = slo_snapshot_fn
        self._kv_fn = kv_snapshot_fn
        self._decisions_fn = decisions_fn
        self._forecast_fn = forecast_fn
        self._ring: deque[dict[str, Any]] = deque(
            maxlen=cfg.incident_capacity)
        self._rules: dict[str, _RuleState] = {}
        self._seq = 0

    def __len__(self) -> int:
        return len(self._ring)

    def observe(self, tripped: dict[str, str], sample: dict[str, Any],
                context_fn: Callable[[], list[dict[str, Any]]]) -> None:
        """Evaluate one tick's rule verdicts. ``tripped`` maps rule name →
        human detail for rules firing THIS tick; rules absent from it
        clear (starting their cooldown). ``context_fn`` lazily yields the
        pre-trigger tail of the timeline ring (the −N half of the ±N
        window) — lazy because copying the ring tail every quiet tick
        would dominate the tick budget."""
        now = self._wall()
        for rule, detail in tripped.items():
            st = self._rules.get(rule)
            if st is None:
                st = self._rules[rule] = _RuleState()
            if st.active and st.incident is not None:
                self._extend(st.incident, sample, now)
            elif (st.incident is not None and now < st.cooldown_until
                  and st.incident in self._ring):
                # Re-trip inside the cooldown: the same episode flapping,
                # not a new incident.
                st.active = True
                st.incident["retrips"] = st.incident.get("retrips", 0) + 1
                self._extend(st.incident, sample, now)
            else:
                st.active = True
                st.incident = self._open(rule, detail, sample,
                                         context_fn(), now)
        for rule, st in self._rules.items():
            if rule not in tripped and st.active:
                st.active = False
                st.cooldown_until = now + self.cfg.cooldown_s
                if st.incident is not None:
                    st.incident["cleared_unix"] = now

    def _open(self, rule: str, detail: str, sample: dict[str, Any],
              context: list[dict[str, Any]], now: float) -> dict[str, Any]:
        self._seq += 1
        INCIDENTS_TOTAL.labels(rule).inc()
        incident: dict[str, Any] = {
            "id": f"inc-{self._seq}",
            "rule": rule,
            "detail": detail,
            "first_unix": now,
            "last_unix": now,
            "ticks": 1,
            "trigger": sample,
            # Pre-trigger context plus the trigger tick; the post-trigger
            # half fills in as the incident stays active (_extend), up to
            # ±N total.
            "window": list(context) + [sample],
        }
        if self._decisions_fn is not None:
            incident["decisions"] = self._decisions_fn(
                self.cfg.max_decisions)
        if self._slo_fn is not None:
            incident["slo"] = self._slo_fn()
        if self._kv_fn is not None:
            incident["kv"] = self._kv_fn()
        if self._forecast_fn is not None:
            # Was-this-predicted: the forecaster's active forecasts and
            # error rollups AT trigger time, frozen beside the slo/kv
            # state they would have warned about.
            incident["forecast"] = self._forecast_fn()
        self._ring.append(incident)
        return incident

    def _extend(self, incident: dict[str, Any], sample: dict[str, Any],
                now: float) -> None:
        incident["last_unix"] = now
        incident["ticks"] += 1
        window = incident["window"]
        if len(window) < 2 * self.cfg.context_ticks + 1:
            window.append(sample)

    def snapshot(self) -> dict[str, Any]:
        return {"count": len(self._ring),
                "incidents": list(reversed(self._ring))}


# ---------------------------------------------------------------------------
# The sampler.
# ---------------------------------------------------------------------------

class _Baseline:
    """Previous-tick counter values (delta computation)."""

    __slots__ = ("requests", "met", "shed", "out_tokens",
                 "good_tokens", "prompt_tokens", "degraded", "kv_stamps",
                 "kv_joins", "gc_pause_s", "by_role",
                 "shadow_eval", "shadow_div", "shadow_regret", "flips",
                 "as_actions", "as_refusals", "as_rollbacks",
                 "tails_closed", "tails_tail", "tails_dominant")

    def __init__(self):
        self.requests = 0
        self.met = 0
        self.shed = 0
        self.out_tokens = 0
        self.good_tokens = 0
        self.prompt_tokens = 0
        self.degraded = 0
        self.kv_stamps = 0
        self.kv_joins = 0
        self.gc_pause_s = 0.0
        self.by_role: dict[str, tuple[int, int]] = {}
        self.shadow_eval = 0
        self.shadow_div = 0
        self.shadow_regret = 0.0
        self.flips = 0
        self.as_actions = 0
        self.as_refusals = 0
        self.as_rollbacks = 0
        self.tails_closed = 0
        self.tails_tail = 0
        self.tails_dominant: dict[str, int] = {}


class TimelineSampler:
    """One bounded-ring telemetry history for this process.

    All sources are read on the event loop (the same single-writer
    discipline as the ledgers), so no locking. ``tick()`` is synchronous
    and injectable-clock testable; ``start()`` runs it on a grid-aligned
    asyncio task so fleet workers' buckets line up by wall clock."""

    # Transfer pairs inlined per sample before folding to a summary (a
    # 512-pair table copied 600 times would dominate ring memory); the
    # fold is logged in the sample itself (pairs_truncated) — no silent
    # caps.
    MAX_SAMPLE_PAIRS = 16
    # /proc self-telemetry cadence in ticks (see tick(): the open-FD walk
    # is a real syscall cost, the signal drifts on a minutes scale).
    PROC_SAMPLE_EVERY = 30

    def __init__(self, cfg: TimelineConfig, *,
                 slo_ledger: Any = None,
                 kv_ledger: Any = None,
                 datastore: Any = None,
                 flow: Any = None,
                 inflight_fn: Callable[[], int] | None = None,
                 drain_rate_fn: Callable[[], float] | None = None,
                 degraded_fn: Callable[[], int] | None = None,
                 decisions_fn: Callable[[int], list] | None = None,
                 divergence_fn: Callable[[], float] | None = None,
                 shadow: Any = None,
                 rebalance: Any = None,
                 forecast: Any = None,
                 autoscale: Any = None,
                 tails: Any = None,
                 wall: Callable[[], float] = time.time):
        self.cfg = cfg
        self.slo_ledger = slo_ledger
        self.kv_ledger = kv_ledger
        self.datastore = datastore
        self.flow = flow
        self.inflight_fn = inflight_fn
        self.drain_rate_fn = drain_rate_fn
        self.degraded_fn = degraded_fn
        self.divergence_fn = divergence_fn
        # Shadow evaluator (router/shadow.py): flat counters read per tick
        # — evaluated/diverged/regret deltas become the counterfactual
        # series the flight recorder correlates against goodput swings.
        self.shadow = shadow
        # Rebalance controller (router/rebalance.py): per-role headroom +
        # flip deltas become the series that explains a mid-run P:D
        # reshape next to the token-mix swing that caused it.
        self.rebalance = rebalance
        # Forecast engine (router/forecast.py): rides THIS tick — the
        # engine has no task of its own, so it inherits the grid
        # alignment that makes fleet buckets comparable.
        self.forecast = forecast
        # Elastic-fleet actuator (router/autoscale.py): flat counter
        # deltas + the freeze latch, so a scaling action (or rollback)
        # lands in the same ring tick as the traffic swing it answered.
        self.autoscale = autoscale
        # Tail observatory (router/tails.py): per-tick closed/tail deltas
        # plus the dominant-stage mix, so an incident snapshot embeds
        # WHICH stage the tail was at trigger time.
        self.tails = tails
        self._wall = wall
        self.ring: deque[dict[str, Any]] = deque(maxlen=cfg.ring_capacity)
        self.burn = BurnRateMonitor(cfg)
        self.incidents = IncidentRecorder(
            cfg,
            slo_snapshot_fn=(slo_ledger.snapshot if slo_ledger is not None
                             else None),
            kv_snapshot_fn=(kv_ledger.snapshot if kv_ledger is not None
                            else None),
            decisions_fn=decisions_fn,
            forecast_fn=(forecast.incident_context
                         if forecast is not None else None),
            wall=wall)
        self.gc_pause = GcPauseTracker()
        self._prev = _Baseline()
        self._task: asyncio.Task | None = None
        self._last_tick_mono: float | None = None
        # Label children resolved once: a .labels() call is a dict lookup
        # under a lock, too slow for a path budgeted at <1% of the cycle
        # floor.
        self._burn_fast_g = SLO_BURN_RATE.labels("fast")
        self._burn_slow_g = SLO_BURN_RATE.labels("slow")
        self._tick_count = 0
        self._proc_cache = (0, 0)  # (rss_bytes, open_fds)
        # One bound context thunk instead of a fresh closure per tick.
        self._context_fn = (
            lambda: list(self.ring)[-self.cfg.context_ticks - 1:-1])

    @property
    def enabled(self) -> bool:
        return self.cfg.enabled

    # ---- lifecycle ------------------------------------------------------

    def start(self) -> None:
        if not self.cfg.enabled or self._task is not None:
            return
        self.gc_pause.start()
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._task
            self._task = None
        self.gc_pause.stop()

    async def _run(self) -> None:
        tick = self.cfg.tick_s
        try:
            while True:
                # Grid alignment: sleep to the NEXT multiple of tickS on
                # the wall clock, so every fleet worker's samples land in
                # the same wall-clock bucket (merge_timeline keys on
                # round(t/tick)) without any cross-process coordination.
                now = self._wall()
                next_t = (int(now / tick) + 1) * tick
                await asyncio.sleep(max(next_t - now, 0.0))
                self.tick()
        except asyncio.CancelledError:
            pass

    # ---- one tick -------------------------------------------------------

    def tick(self, wall: float | None = None) -> dict[str, Any] | None:
        """Collect one sample, append it to the ring, feed the burn-rate
        monitor, and evaluate the incident rules. Kill-switch: one
        attribute check."""
        if not self.cfg.enabled:
            return None
        now = wall if wall is not None else self._wall()
        mono = time.monotonic()
        prev = self._prev
        sample: dict[str, Any] = {"t_unix": now}

        # Loop lag: the tick task slept toward a known wall-clock target;
        # the overshoot past the grid IS the loop's scheduling stall at
        # tick granularity (the LoopLagMonitor's heartbeat, reused free).
        if self._last_tick_mono is not None:
            gap = mono - self._last_tick_mono
            sample["loop_lag_ms"] = round(
                max(gap - self.cfg.tick_s, 0.0) * 1e3, 3)
        self._last_tick_mono = mono

        # Queue/backlog/drain (overload.py's inputs, historized).
        if self.inflight_fn is not None:
            sample["inflight"] = self.inflight_fn()
        if self.flow is not None:
            sample["queued"] = self.flow.queued_requests
            sample["queued_by_band"] = self.flow.queued_by_band()
        if self.drain_rate_fn is not None:
            sample["drain_rate_rps"] = round(self.drain_rate_fn(), 4)

        # SLO ledger deltas → rates (slo.py counters, read raw — calling
        # snapshot() per tick would render the whole rollup).
        arrivals = met = 0
        led = self.slo_ledger
        if led is not None:
            t = led.totals
            arrivals = t.requests - prev.requests
            met = t.slo_met - prev.met
            sample["requests"] = arrivals
            sample["slo_met"] = met
            sample["shed"] = t.shed - prev.shed
            sample["output_tokens"] = t.output_tokens - prev.out_tokens
            sample["goodput_tokens"] = (t.goodput_tokens
                                        - prev.good_tokens)
            prev.requests, prev.met, prev.shed = (t.requests, t.slo_met,
                                                  t.shed)
            prev.out_tokens, prev.good_tokens = (t.output_tokens,
                                                 t.goodput_tokens)
            served = arrivals - sample["shed"]
            sample["attainment"] = (round(met / served, 4)
                                    if served > 0 else None)
            # Per-role prefill:decode token mix — the P/D rebalancer's
            # controller input (ROADMAP item 5), as counter deltas.
            d_prompt = led.prompt_tokens_total - prev.prompt_tokens
            prev.prompt_tokens = led.prompt_tokens_total
            by_role: dict[str, dict[str, int]] = {}
            for role, (p_tot, c_tot) in led.tokens_by_role.items():
                bp, bc = prev.by_role.get(role, (0, 0))
                dp, dc = p_tot - bp, c_tot - bc
                prev.by_role[role] = (p_tot, c_tot)
                if dp or dc:
                    by_role[role] = {"prompt": dp, "completion": dc}
            d_completion = sample["output_tokens"]
            mix: dict[str, Any] = {"prefill_tokens": d_prompt,
                                   "decode_tokens": d_completion}
            if d_prompt + d_completion > 0:
                mix["prefill_fraction"] = round(
                    d_prompt / (d_prompt + d_completion), 4)
            if by_role:
                mix["by_role"] = by_role
            sample["token_mix"] = mix

        if self.degraded_fn is not None:
            d = self.degraded_fn()
            sample["degraded"] = d - prev.degraded
            prev.degraded = d

        # KV ledger: stamp/join deltas + the pool-level measured-reuse
        # EWMAs (per-pod rows are in /debug/kv; the timeline keeps the
        # pool series bounded).
        kv = self.kv_ledger
        if kv is not None and kv.enabled:
            row: dict[str, Any] = {
                "stamps": kv.stamps - prev.kv_stamps,
                "joins": kv.joins - prev.kv_joins,
            }
            prev.kv_stamps, prev.kv_joins = kv.stamps, kv.joins
            overall = kv.table.overall()
            if overall.ewma_hit_ratio is not None:
                row["ewma_hit_ratio"] = round(overall.ewma_hit_ratio, 4)
            if overall.ewma_signed_error is not None:
                row["ewma_signed_error"] = round(
                    overall.ewma_signed_error, 4)
            sample["kv"] = row

        # Transfer-pair EWMAs (datalayer TransferTable): inline while the
        # table is small, fold to a summary when it is not.
        ds = self.datastore
        if ds is not None:
            table = ds.transfers
            n_pairs = len(table)
            if n_pairs:
                if n_pairs <= self.MAX_SAMPLE_PAIRS:
                    sample["transfers"] = {
                        f"{p}->{d}": round(s.ewma_pull_ms, 3)
                        for (p, d), s in table._pairs.items()
                        if s.ewma_pull_ms is not None}
                else:
                    pulls = [s.ewma_pull_ms
                             for s in table._pairs.values()
                             if s.ewma_pull_ms is not None]
                    sample["transfers"] = {
                        "pairs": n_pairs,
                        "pairs_truncated": True,
                        "ewma_pull_ms_min": round(min(pulls), 3)
                        if pulls else None,
                        "ewma_pull_ms_max": round(max(pulls), 3)
                        if pulls else None,
                    }
            sample["snapshot_epoch"] = ds.snapshot_epoch

        if self.divergence_fn is not None:
            sample["kv_index_divergence"] = self.divergence_fn()

        # Shadow-policy counterfactual deltas (router/shadow.py): worker-
        # written flat counters, read as GIL-atomic loads — a tick racing
        # an in-flight judge lands the delta on the next tick instead.
        sh = self.shadow
        if sh is not None and sh.active:
            ev, dv, rg = (sh.evaluated_total, sh.diverged_total,
                          sh.regret_ms_sum)
            sample["shadow"] = {
                "evaluated": ev - prev.shadow_eval,
                "diverged": dv - prev.shadow_div,
                "regret_ms": round(rg - prev.shadow_regret, 3),
            }
            prev.shadow_eval, prev.shadow_div = ev, dv
            prev.shadow_regret = rg

        # Self-balancing pool (router/rebalance.py): per-role headroom +
        # completed-flip deltas — flat reads, the controller owns the math.
        rb = self.rebalance
        if rb is not None and rb.enabled:
            row: dict[str, Any] = {"flips": rb.flips_total - prev.flips,
                                   "draining": rb.active_count}
            prev.flips = rb.flips_total
            if rb.last_headroom:
                row["headroom"] = dict(rb.last_headroom)
            sample["rebalance"] = row

        # Elastic-fleet actuator (router/autoscale.py): action/refusal/
        # rollback deltas + the freeze latch — flat reads, the controller
        # owns the guard pipeline.
        ac = self.autoscale
        if ac is not None and ac.enabled:
            row = {"actions": ac.actions_total - prev.as_actions,
                   "refusals": ac.refusals_total - prev.as_refusals,
                   "rollbacks": ac.rollbacks_total - prev.as_rollbacks}
            prev.as_actions = ac.actions_total
            prev.as_refusals = ac.refusals_total
            prev.as_rollbacks = ac.rollbacks_total
            if ac.frozen:
                row["frozen"] = True
            sample["autoscale"] = row

        # Tail observatory (router/tails.py): closed/tail-cohort deltas +
        # the dominant-stage mix — flat counter reads, so an incident
        # snapshot embeds WHICH stage owned the tail at trigger time.
        to = self.tails
        if to is not None and to.enabled:
            row = {"closed": to.closed_total - prev.tails_closed,
                   "tail": to.tail_total - prev.tails_tail}
            prev.tails_closed = to.closed_total
            prev.tails_tail = to.tail_total
            dom: dict[str, int] = {}
            for stage, n in to.dominant_total.items():
                d = n - prev.tails_dominant.get(stage, 0)
                prev.tails_dominant[stage] = n
                if d:
                    dom[stage] = d
            if dom:
                row["dominant"] = dom
            sample["tails"] = row

        # Process self-telemetry (gauges + the timeline series). The /proc
        # reads are real syscalls (~15-25µs together), so they run every
        # PROC_SAMPLE_EVERY ticks and the cached values ride the ticks in
        # between — RSS/FD drift is a minutes-scale signal, the tick
        # budget is microseconds. GC pause accumulates per tick regardless
        # (reading the tracker's float is free).
        if self._tick_count % self.PROC_SAMPLE_EVERY == 0:
            rss, fds = rss_bytes(), open_fds()
            self._proc_cache = (rss, fds)
            PROCESS_RSS_BYTES.set(rss)
            PROCESS_OPEN_FDS.set(fds)
        else:
            rss, fds = self._proc_cache
        self._tick_count += 1
        pause = self.gc_pause.pause_s_total
        d_pause = pause - prev.gc_pause_s
        prev.gc_pause_s = pause
        if d_pause > 0:
            GC_PAUSE_SECONDS.inc(d_pause)
        sample["process"] = {"rss_bytes": rss, "open_fds": fds,
                             "gc_pause_ms": round(d_pause * 1e3, 3)}

        # Burn rate (fed BEFORE rule evaluation so the trip sees the tick
        # that crossed the threshold).
        self.burn.add(arrivals, met)
        fast, slow = self.burn.rates()
        sample["burn"] = {"fast": round(fast, 3), "slow": round(slow, 3)}
        self._burn_fast_g.set(fast)
        self._burn_slow_g.set(slow)

        # Forecast engine: judge + update + stamp against this complete
        # sample, and embed the compact per-tick row (stamps/joins/gaps)
        # so the ring itself shows the forecaster working. Runs BEFORE
        # rule evaluation so an incident opening this tick captures the
        # post-observe forecast state.
        fc = self.forecast
        if fc is not None:
            fc_row = fc.observe(sample)
            if fc_row is not None:
                sample["forecast"] = fc_row

        self.ring.append(sample)
        TIMELINE_TICKS.inc()
        self._evaluate_rules(sample, fast, slow)
        return sample

    def _evaluate_rules(self, sample: dict[str, Any], fast: float,
                        slow: float) -> None:
        """Build the tick's tripped-rule map and hand it to the incident
        recorder (which owns dedup/cooldown)."""
        cfg = self.cfg
        tripped: dict[str, str] = {}
        if self.burn.tripped(fast, slow):
            tripped[RULE_BURN_RATE] = (
                f"burn rate fast={fast:.2f} (>= {cfg.burn.fast_burn}) and "
                f"slow={slow:.2f} (>= {cfg.burn.slow_burn}) over target "
                f"{cfg.burn.target}")
        shed = sample.get("shed", 0)
        if cfg.shed_rate_max > 0 and shed / cfg.tick_s > cfg.shed_rate_max:
            tripped[RULE_SHED_RATE] = (
                f"shed rate {shed / cfg.tick_s:.2f}/s > "
                f"{cfg.shed_rate_max}/s")
        if (cfg.drain_min_rps > 0 and sample.get("queued", 0) > 0
                and sample.get("drain_rate_rps", 0.0) < cfg.drain_min_rps):
            tripped[RULE_DRAIN_COLLAPSE] = (
                f"{sample['queued']} queued with drain "
                f"{sample.get('drain_rate_rps', 0.0):.3f} rps < "
                f"{cfg.drain_min_rps}")
        div = sample.get("kv_index_divergence")
        if cfg.divergence_max > 0 and div is not None \
                and div > cfg.divergence_max:
            tripped[RULE_DIVERGENCE] = (
                f"kv index divergence {div:.4f} > {cfg.divergence_max}")
        # The context tail copy is deferred into the recorder: it only
        # materializes when an incident actually OPENS (excluding the
        # trigger tick itself, which the recorder appends).
        self.incidents.observe(tripped, sample, self._context_fn)

    # ---- render ---------------------------------------------------------

    def snapshot(self, *, window_s: float | None = None,
                 series: list[str] | None = None,
                 step_s: float | None = None) -> dict[str, Any]:
        """The /debug/timeline payload: raw ticks plus windowed aggregates
        (p50/p99/min/max and rate of change per numeric series) over the
        requested window (default: the whole retained ring).

        ``series`` keeps only the named top-level keys per sample (plus
        ``t_unix``); ``step_s`` downsamples ticks into coarser buckets
        (numeric keys average, nested maps drop — select without step_s
        for full fidelity). Both exist so a long-retention query stops
        shipping every sample of every series. Aggregates stay computed
        over the FULL-resolution (post-selection) ticks; a step bucket no
        tick landed in is simply absent — a gap, never interpolated."""
        cfg = self.cfg
        samples = list(self.ring)
        if window_s is not None and samples:
            cutoff = samples[-1]["t_unix"] - window_s
            samples = [s for s in samples if s["t_unix"] >= cutoff]
        if series:
            keep = set(series)
            samples = [{k: v for k, v in s.items()
                        if k == "t_unix" or k in keep}
                       for s in samples]
        downsample = step_s is not None and step_s > cfg.tick_s
        doc: dict[str, Any] = {
            "enabled": cfg.enabled,
            "tick_s": cfg.tick_s,
            "retention_s": cfg.retention_s,
            "ticks": len(samples),
            "samples": (_downsample(samples, step_s) if downsample
                        else samples),
            "aggregates": _aggregates(samples),
            "incident_count": len(self.incidents),
        }
        if series:
            doc["series"] = sorted(set(series))
        if downsample:
            doc["step_s"] = step_s
        if samples:
            fast, slow = self.burn.rates()
            doc["burn"] = {"fast": round(fast, 3), "slow": round(slow, 3),
                           "target": cfg.burn.target}
        return doc


def _downsample(samples: list[dict[str, Any]],
                step_s: float) -> list[dict[str, Any]]:
    """Fold tick samples into step_s-wide buckets: per bucket, the mean
    of every numeric top-level key present (each key averaged over the
    ticks that carried it) plus ``n`` (ticks folded in). Buckets nothing
    landed in do not appear — downsampling must not manufacture data
    where the ring has a gap."""
    acc: dict[int, tuple[dict[str, list], list[int]]] = {}
    order: list[int] = []
    for s in samples:
        b = int(s["t_unix"] // step_s)
        row = acc.get(b)
        if row is None:
            row = acc[b] = ({}, [0])
            order.append(b)
        keys, count = row
        count[0] += 1
        for k, v in s.items():
            if k != "t_unix" and isinstance(v, (int, float)) \
                    and not isinstance(v, bool):
                cell = keys.get(k)
                if cell is None:
                    keys[k] = [v, 1]
                else:
                    cell[0] += v
                    cell[1] += 1
    out = []
    for b in order:
        keys, count = acc[b]
        row: dict[str, Any] = {"t_unix": round(b * step_s, 3),
                               "n": count[0]}
        for k, (total, n) in keys.items():
            row[k] = round(total / n, 4)
        out.append(row)
    return out


def _percentile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(int(q * (len(sorted_vals) - 1) + 0.5), len(sorted_vals) - 1)
    return sorted_vals[idx]


def _aggregates(samples: list[dict[str, Any]]) -> dict[str, Any]:
    """Windowed aggregates over every top-level numeric series: n, min,
    max, p50, p99, and rate of change (last − first over the window's
    span). Computed at render time — the per-tick path never pays for
    them."""
    if len(samples) < 2:
        return {}
    series: dict[str, list[tuple[float, float]]] = {}
    for s in samples:
        t = s["t_unix"]
        for k, v in s.items():
            if k != "t_unix" and isinstance(v, (int, float)) \
                    and not isinstance(v, bool):
                series.setdefault(k, []).append((t, float(v)))
    out: dict[str, Any] = {}
    for k, pts in series.items():
        if len(pts) < 2:
            continue
        vals = sorted(v for _, v in pts)
        span = pts[-1][0] - pts[0][0]
        out[k] = {
            "n": len(vals),
            "min": round(vals[0], 4),
            "max": round(vals[-1], 4),
            "p50": round(_percentile(vals, 0.5), 4),
            "p99": round(_percentile(vals, 0.99), 4),
            "rate_per_s": (round((pts[-1][1] - pts[0][1]) / span, 4)
                           if span > 0 else None),
        }
    return out


# ---------------------------------------------------------------------------
# Fleet fan-in: merge per-worker rings by wall-clock bucket.
# ---------------------------------------------------------------------------

def merge_timeline(docs: list[tuple[int, dict[str, Any]]], *,
                   workers: int,
                   supervisor: list[dict[str, Any]] | None = None
                   ) -> dict[str, Any]:
    """Merge N workers' /debug/timeline payloads into one wall-clock
    bucketed view. Ticks are grid-aligned in every worker, so the bucket
    index round(t/tick) names the same wall second everywhere. A bucket a
    shard did not report is a GAP — marked, never interpolated (the
    monotonic-merge precedent: inventing samples for a dead shard would
    hide exactly the outage the timeline exists to show). A worker that
    restarts loses its pre-restart ring, so the merged view honestly shows
    its whole down-and-before window as gaps for that shard.

    Downsampled payloads (``step_s`` set — the ?step_s= query rode the
    fan-out to every shard) bucket on the step instead of the tick: the
    downsampled bucket timestamps are step-aligned, and a step bucket a
    shard did not report stays a gap exactly like a missing tick."""
    tick_s = next((d.get("step_s") or d.get("tick_s") for _, d in docs
                   if d.get("step_s") or d.get("tick_s")), 1.0)
    enabled = any(d.get("enabled") for _, d in docs)
    buckets: dict[int, dict[str, Any]] = {}
    responding = {shard for shard, _ in docs}
    # Two of one shard's ticks can round into the same bucket (a stalled
    # loop firing late, then the next tick on time). Keep the sample
    # closest to the bucket center and COUNT the displaced one — losing a
    # sample silently would read as "covered" when it wasn't, and
    # overwriting blindly could leave the previous bucket a false gap for
    # a shard that was up.
    collapsed: dict[str, int] = {}
    for shard, doc in docs:
        key = str(shard)
        for s in doc.get("samples") or []:
            b = int(round(s["t_unix"] / tick_s))
            row = buckets.get(b)
            if row is None:
                row = buckets[b] = {"t_unix": round(b * tick_s, 3),
                                    "shards": {}}
            existing = row["shards"].get(key)
            if existing is None:
                row["shards"][key] = s
            else:
                center = row["t_unix"]
                if (abs(s["t_unix"] - center)
                        < abs(existing["t_unix"] - center)):
                    row["shards"][key] = s
                collapsed[key] = collapsed.get(key, 0) + 1
    all_shards = set(range(workers))
    merged = []
    for b in sorted(buckets):
        row = buckets[b]
        missing = sorted(all_shards
                         - {int(k) for k in row["shards"]})
        if missing:
            row["gaps"] = missing
        merged.append(row)
    out: dict[str, Any] = {
        "workers": workers,
        "responding": sorted(responding),
        "enabled": enabled,
        "tick_s": tick_s,
        "buckets": merged,
        "gap_buckets": sum(1 for r in merged if r.get("gaps")),
    }
    step_s = next((d.get("step_s") for _, d in docs if d.get("step_s")),
                  None)
    if step_s:
        out["step_s"] = step_s
    if collapsed:
        out["collapsed_samples"] = collapsed
    if supervisor:
        out["supervisor"] = supervisor
    return out
