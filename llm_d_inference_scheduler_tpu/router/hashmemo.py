"""Shared prefix-hash memo: the ONLY sanctioned path from router-side
plugins to ``chain_block_hashes``.

One scheduling cycle used to recompute the full xxhash chain once per
endpoint per consumer — ``ApproxPrefixCacheProducer.produce`` inside its
per-endpoint loop, again in its ``pre_request``, and
``PrecisePrefixCacheScorer`` a third and fourth time — O(endpoints × blocks)
xxh64 work for a value that depends only on (model, prompt, block size).
Two layers collapse that to at most one computation per (mode, block size)
per request:

- **Per-request memo** (``PrefixHashMemo``, riding
  ``InferenceRequest.prefix_hashes``): every producer/scorer/pre_request
  hook of the cycle — and any failover *reschedule* of the same request —
  reuses the first computation. Entries remember whether they were computed
  from token ids or from text, so when ``TokenProducer`` upgrades the
  request from char-based to token-based hashing mid-cycle the stale
  char-based chain is recomputed, never served.
- **Global LRU** keyed by ``(model, mode, prompt-fingerprint, block_size)``:
  repeat prompts, retries, and reschedules that build a fresh request
  object skip xxhash entirely. The fingerprint is one xxh64 pass over the
  prompt (itself memoized per request), so the key never pins prompt text.

Returned hash lists are shared between the LRU, the memo, and callers —
treat them as immutable.

``scripts/verify_hotpath.py`` (make verify-hotpath) lints that no other
router module calls ``chain_block_hashes`` directly.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

from ..utils.hashing import (
    chain_block_hashes,
    text_fingerprint,
    token_fingerprint,
)

GLOBAL_LRU_CAPACITY = 1024

# Written from the event loop only, but guarded anyway: the lock is one
# uncontended acquire per *request* per block size, noise next to the chain
# computation it saves, and keeps the memo safe if a producer ever moves to
# a worker thread.
_global_lock = threading.Lock()
_global_lru: OrderedDict[tuple, list[int]] = OrderedDict()


def global_lru_clear() -> None:
    """Test hook: reset the cross-request LRU."""
    with _global_lock:
        _global_lru.clear()


class PrefixHashMemo:
    """Memoized prefix-hash chains for one request's scheduling lifetime."""

    __slots__ = ("_entries", "_fp")

    def __init__(self):
        # block_size -> (token_based, hashes); mode -> prompt fingerprint
        self._entries: dict[int, tuple[bool, list[int]]] = {}
        self._fp: dict[bool, int] = {}

    def hashes(self, model: str, body, block_size: int) -> list[int]:
        # Truthiness, not `is not None`: an engine render reply of [] must
        # fall back to char-based hashing exactly like the direct
        # chain_block_hashes call does (`if token_ids:`), not produce an
        # empty chain that zeroes every prefix score.
        token_based = bool(body.tokenized_prompt)
        ent = self._entries.get(block_size)
        if ent is not None and ent[0] == token_based:
            return ent[1]
        # A char-based entry after tokenization landed is stale (the chains
        # live in different hash spaces); fall through and recompute.
        fp = self._fp.get(token_based)
        if fp is None:
            fp = (token_fingerprint(body.tokenized_prompt) if token_based
                  else text_fingerprint(body.prompt_text()))
            self._fp[token_based] = fp
        key = (model, token_based, fp, block_size)
        with _global_lock:
            hashes = _global_lru.get(key)
            if hashes is not None:
                _global_lru.move_to_end(key)
        if hashes is None:
            hashes = chain_block_hashes(
                model, body.tokenized_prompt,
                "" if token_based else body.prompt_text(), block_size)
            with _global_lock:
                _global_lru[key] = hashes
                while len(_global_lru) > GLOBAL_LRU_CAPACITY:
                    _global_lru.popitem(last=False)
        self._entries[block_size] = (token_based, hashes)
        return hashes


def request_prefix_hashes(request, block_size: int) -> list[int]:
    """Hash chain for ``request`` at ``block_size``, memoized on the request
    (lazily attached to ``InferenceRequest.prefix_hashes``)."""
    memo = request.prefix_hashes
    if memo is None:
        memo = request.prefix_hashes = PrefixHashMemo()
    return memo.hashes(request.target_model, request.body, block_size)
