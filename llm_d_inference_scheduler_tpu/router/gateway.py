"""Standalone EPP gateway: the router's HTTP data plane.

Plays the role of Envoy+EPP fused into one process (the reference's
standalone mode, chart at config/charts/standalone/ — SURVEY §L0/L1): parses
OpenAI requests, runs the Director (admission → producers → scheduling),
proxies to the picked engine, streams the response back, and feeds the
response hooks. The ext-proc gRPC server for a real Envoy data plane layers
on the same Director.

Wire behavior kept from the reference:
- x-gateway-destination-endpoint set from the scheduling result
  (handlers/request.go), echoed back as x-gateway-destination-endpoint-served
- unparseable bodies fall back to a random endpoint (server.go:335-342)
- 429/503 rejections carry x-removal-reason (pkg/common/error)
- response bodies rewrite "model" back to the client-facing name when a
  rewrite was applied (server.go:471-485)
"""

from __future__ import annotations

import asyncio
import json
import logging
import sys
import time
import uuid
from typing import Any

import aiohttp
import httpx
from aiohttp import web
from prometheus_client import generate_latest

from .config.loader import Handle, RouterConfig, load_config
from .datalayer.datastore import Datastore
from .datalayer.runtime import DataLayerRuntime
from .decisions import SCHEMA_VERSION, DecisionConfig, DecisionRecorder
from .framework.scheduling import InferenceRequest
from .handlers.parsers import make_parser
from .metrics import (
    DEADLINE_EXCEEDED_TOTAL,
    KV_TRANSFER_EXPOSED_MS,
    KV_TRANSFER_MS,
    POOL_AVG_KV_CACHE,
    POOL_AVG_QUEUE,
    POOL_READY_ENDPOINTS,
    REGISTRY,
    REQUEST_DURATION,
    RETRIES_TOTAL,
    RETRY_BUDGET_EXHAUSTED_TOTAL,
    TTFT_SECONDS,
    INPUT_TOKENS,
    OUTPUT_TOKENS,
    UPSTREAM_STREAM_ABORTED_TOTAL,
)
from .requestcontrol.admission import AdmissionError, X_REMOVAL_REASON
from .resilience import (
    DEADLINE_EXCEEDED_REASON,
    Deadline,
    H_REQUEST_TIMEOUT,
    RETRY_BUDGET_REASON,
    ResilienceConfig,
    RetryBudget,
    UpstreamFailure,
)
from .requestcontrol.director import (
    Director,
    H_DESTINATION,
    H_DESTINATION_SERVED,
    H_REQUEST_ID,
    RequestError,
)
from .kvobs import H_KV_HIT_BLOCKS, H_KV_HIT_TOKENS, CacheLedger, KvObsConfig
from .overload import DrainRateEstimator, OverloadConfig, OverloadController
from .autoscale import ActuatorController, AutoscaleConfig
from .forecast import ForecastConfig, ForecastEngine
from .rebalance import RebalanceConfig, RebalanceController
from .schedpool import LoopLagMonitor, SchedulerPool, SchedulingConfig
from .shadow import ShadowConfig, ShadowEvaluator
from .slo import SloConfig, SloLedger, finite_float_or_none
from .tails import TailsConfig, TailsObservatory
from .timeline import (
    TimelineConfig,
    TimelineSampler,
    config_hash,
    redact_config,
)
from .datalayer.data_graph import validate_and_order_producers

log = logging.getLogger("router.gateway")

FORWARD_HEADERS = ("x-prefiller-host-port", "x-encoder-hosts-ports",
                   "x-data-parallel-host-port", "x-request-id", "content-type")
ROUTER_OWNED_HEADERS = ("x-prefiller-host-port", "x-encoder-hosts-ports",
                        "x-data-parallel-host-port",
                        "x-gateway-destination-endpoint")

# Decision flight recorder opt-in: a request carrying
# `x-debug-decision: summary` gets the compact one-line verdict echoed in
# the response's x-decision-summary header (curl-level debugging; the full
# record stays on /debug/decisions/<request-id>).
H_DEBUG_DECISION = "x-debug-decision"
H_DECISION_SUMMARY = "x-decision-summary"

# Fleet shard identity echoed on proxied responses (router/fleet.py): which
# worker process served this request — the per-request twin of the
# supervisor's router_shard_* families.
H_ROUTER_SHARD = "x-router-shard"

# Engine queue-wait stamp (engine/server.py, sim parity in engine/sim.py;
# the sidecar relays it on the disagg path): per-request admission-to-
# first-step wait, separating engine queueing from compute in the
# waterfall's decode residual (router/tails.py). Non-streaming responses
# only — a streamed response's headers leave before admission completes.
H_ENGINE_QUEUE = "x-engine-queue-ms"

# Request bodies at or above this size have their JSON parse routed through
# the scheduler pool's workers instead of the event loop (json.loads of a
# multi-megabyte long-context body is a multi-millisecond loop stall —
# larger than the scheduling cycle the pool exists to offload). Small
# bodies parse inline: the executor hop costs more than the parse.
LARGE_BODY_PARSE_BYTES = 16 << 10


class Gateway:
    def __init__(self, cfg: RouterConfig, datastore: Datastore,
                 dl_runtime: DataLayerRuntime, *, host: str = "127.0.0.1",
                 port: int = 8081, grpc_health_port: int | None = None,
                 grpc_ext_proc_port: int | None = None,
                 lease_path: str | None = None,
                 config_watch_path: str | None = None,
                 kube_binding=None, kube_elector=None,
                 secure_serving: bool = False,
                 cert_path: str | None = None,
                 enable_cert_reload: bool = False,
                 fleet=None):
        self.cfg = cfg
        # Fleet worker identity (router/fleet.py FleetWorkerSpec): when set,
        # this gateway is one shard of a multi-process fleet — it may share
        # the listen port via SO_REUSEPORT, serve a private admin listener
        # for the supervisor's fan-in plane, and (as a follower) replicate
        # the leader's pool snapshots instead of scraping. None (the
        # default, and fleet.workers: 1) is the single-process router,
        # bit-identical to the pre-fleet gateway.
        self.fleet = fleet
        # Secure serving (reference runserver.go:136-171): one identity for
        # the HTTP listener and the ext-proc gRPC port; self-signed fallback
        # when no cert dir is mounted.
        self.tls = None
        if secure_serving:
            from .tlsutil import TlsServing

            self.tls = TlsServing(cert_path, enable_cert_reload)
        self.datastore = datastore
        self.dl_runtime = dl_runtime
        self.host, self.port = host, port
        self.parser = make_parser(cfg.parser_spec)

        # Resilience: retry/failover policy, token-bucket retry budget, and
        # the datastore-shared breaker registry (router/resilience.py).
        self.resilience = ResilienceConfig.from_spec(cfg.resilience)
        self.retry_budget = RetryBudget(
            ratio=self.resilience.retry_budget_ratio,
            min_per_sec=self.resilience.retry_budget_min_per_sec,
            burst=self.resilience.retry_budget_burst)
        datastore.breakers.configure(self.resilience)

        # Decision flight recorder (router/decisions.py): default-on bounded
        # ring; `decisions: {enabled: false}` is the kill-switch that
        # restores the zero-overhead baseline.
        self.decision_recorder = DecisionRecorder(
            DecisionConfig.from_spec(cfg.decisions))

        # SLO & goodput ledger (router/slo.py): per-request serving outcomes
        # closing the predict→observe loop. `slo: {enabled: false}` removes
        # the per-chunk hook from the streaming path entirely.
        self.slo_ledger = SloLedger(SloConfig.from_spec(cfg.slo))

        # Tail-latency attribution observatory (router/tails.py): the
        # per-request critical-path waterfall + body-vs-tail cohort ledger
        # behind /debug/tails. Default-on (the kvCache precedent); `tails:
        # {enabled: false}` means no waterfall object ever rides a request.
        self.tails_obs = TailsObservatory(TailsConfig.from_spec(cfg.tails))

        # KV-cache & prefix-reuse observability (router/kvobs.py): the
        # predicted-vs-confirmed hit ledger behind /debug/kv. `kvCache:
        # {enabled: false}` is the kill-switch; the per-pod EWMA table
        # lives on the datastore (plugins can read measured reuse).
        self.kv_ledger = CacheLedger(KvObsConfig.from_spec(cfg.kv_cache),
                                     datastore=datastore)
        self.kv_ledger.attach_plugins(cfg.plugins_by_name.values())

        # Shadow policy evaluation (router/shadow.py): the counterfactual
        # scheduling ledger behind /debug/shadow. Default-on but inert
        # until `shadow: {policies: [...]}` lists a policy; the live path
        # pays only an enqueue onto the shadow worker.
        self.shadow_eval = ShadowEvaluator(ShadowConfig.from_spec(cfg.shadow),
                                           datastore=datastore)

        # Goodput-max overload controller (router/overload.py): predictive
        # SLO admission, degrade ladder, Retry-After shedding. Disabled by
        # default (`overload: {enabled: true}` opts in); the predictor is
        # the predicted-latency producer when one is configured.
        producers = validate_and_order_producers(cfg.producers)
        self.overload = OverloadController(
            OverloadConfig.from_spec(cfg.overload),
            ledger=self.slo_ledger,
            predictor=next((p for p in producers
                            if hasattr(p, "admission_estimate")), None))
        if self.overload.enabled:
            # Little's-law backlog: the in-flight counter sees the queue a
            # new arrival actually stands behind (flow queue + scheduled +
            # streaming), before engine scrapes or saturation ever move.
            self.overload.inflight_fn = lambda: self._inflight

        # Outbound TLS verification policy for router-side client legs
        # (upstream proxy, /debug/traces + /v1/models fan-out). Default:
        # skip-verify (in-cluster pod-local certs); `tlsClient.caCertPath`
        # turns real verification on (ADVICE r5).
        from .tlsutil import client_verify

        tc = cfg.tls_client or {}
        self._client_tls_verify = client_verify(
            insecure_skip_verify=bool(tc.get("insecureSkipVerify", True)),
            ca_cert_path=tc.get("caCertPath") or None)
        # aiohttp form of the same policy: None = stock verification,
        # SSLContext = CA bundle or permissive skip-verify context.
        self._upstream_ssl = (None if self._client_tls_verify is True
                              else self._client_tls_verify)

        # saturation detector: explicit spec or default utilization-detector
        from .framework.plugin import global_registry
        det_spec = cfg.saturation_detector_spec or {"type": "utilization-detector"}
        self.detector = global_registry.instantiate(
            det_spec.get("type", "utilization-detector"),
            det_spec.get("name", "saturation-detector"),
            det_spec.get("parameters") or {}, None)

        from .flowcontrol.eviction import RequestEvictor

        self.evictor = RequestEvictor()
        self.flow_controller = None
        if cfg.feature_gates.get("flowControl"):
            from .flowcontrol import (
                FlowControlAdmissionController,
                FlowControlConfig,
                FlowController,
            )

            fc_cfg = FlowControlConfig.from_spec(cfg.flow_control or {})
            self.flow_controller = FlowController(
                fc_cfg,
                saturation_fn=lambda: self.detector.saturation(
                    self.datastore.endpoint_list()))
            admission = FlowControlAdmissionController(
                self.flow_controller, evictor=self.evictor,
                overload=self.overload if self.overload.enabled else None,
                shard=fleet.index if fleet is not None else None)
            if self.overload.enabled:
                # Queue depth + measured drain rate feed the feasibility
                # estimate; the queues gain unmeetable eviction + priority
                # decay (all gated on the same kill-switch).
                self.overload.attach_flow(self.flow_controller)
        else:
            from .requestcontrol.admission import LegacyAdmissionController

            admission = LegacyAdmissionController(self.detector)

        # Concurrent scheduling engine (router/schedpool.py): worker threads
        # run scheduling cycles over copy-on-write pool snapshots when
        # `scheduling: {workers: N>0}`; workers: 0 (default) = inline path.
        # The pool's executor doubles as the CPU-offload pool for scrape
        # parsing (data layer) and large-body request parsing (below).
        self.sched_pool = SchedulerPool(
            cfg.scheduler, SchedulingConfig.from_spec(cfg.scheduling))
        dl_runtime.offload = self.sched_pool.executor
        if self.flow_controller is not None and self.sched_pool.offloaded:
            # Batched flow-control dispatch: one shard wake hands up to
            # maxBatch co-dispatched requests to the pool; they share one
            # snapshot epoch and one scrape-state view.
            self.flow_controller.cfg.dispatch_batch = max(
                self.flow_controller.cfg.dispatch_batch,
                self.sched_pool.cfg.max_batch)
        self.loop_lag = LoopLagMonitor()

        self.director = Director(
            datastore, cfg.scheduler, admission=admission,
            producers=producers,
            admit_plugins=cfg.admit_plugins,
            pre_request_plugins=cfg.pre_request_plugins,
            response_received=cfg.response_received,
            response_streaming=cfg.response_streaming,
            response_complete=cfg.response_complete,
            recorder=self.decision_recorder,
            sched_pool=self.sched_pool,
            overload=self.overload if self.overload.enabled else None,
            shadow=self.shadow_eval if self.shadow_eval.active else None)

        # Fleet flight recorder (router/timeline.py): the /debug/timeline
        # history + burn-rate monitor + /debug/incidents ring. Default-on
        # (the kvCache precedent); `timeline: {enabled: false}` removes the
        # sampler task entirely — the disabled sampler object only exists
        # so /debug/timeline still answers JSON.
        tl_cfg = TimelineConfig.from_spec(cfg.timeline)
        rb_cfg = RebalanceConfig.from_spec(cfg.rebalance)
        drain_fn = None
        if (tl_cfg.enabled or rb_cfg.enabled) \
                and self.flow_controller is not None:
            if self.overload.enabled:
                # The overload controller already measures drain; reuse it.
                drain_fn = self.overload.drain.rate
            else:
                # Overload off: the timeline/rebalancer keep one shared
                # estimator on the dispatch observer (single slot, nothing
                # else owns it when overload is disabled).
                est = DrainRateEstimator()
                self.flow_controller.dispatch_observer = est.note
                drain_fn = est.rate

        # Self-balancing pool (router/rebalance.py): dynamic P/D role
        # rebalancing through drain-cycle flips + scaling advice. Disabled
        # by default (`rebalance: {enabled: true}` opts in); in fleet mode
        # only the datalayer-owning worker acts — a follower's flip would
        # be overwritten by the next leader snapshot (promote() arms it on
        # leader re-election).
        disagg_handlers = [p for p in cfg.plugins_by_name.values()
                           if hasattr(p, "hop_skips")]
        self.rebalancer = RebalanceController(
            rb_cfg,
            datastore=datastore,
            slo_ledger=self.slo_ledger,
            flow=self.flow_controller,
            drain_rate_fn=drain_fn,
            hop_skips_fn=((lambda: sum(p.hop_skips
                                       for p in disagg_handlers))
                          if disagg_handlers else None),
            acting=(fleet is None or fleet.runs_datalayer))

        # Traffic forecaster (router/forecast.py): judged multi-horizon
        # prediction over the flight recorder. No task of its own — it
        # rides the sampler's tick (so `forecast.enabled: false` OR
        # `timeline.enabled: false` means zero stamps), and qualifies
        # the rebalancer's advice with time-to-saturation leads.
        fc_cfg = ForecastConfig.from_spec(cfg.forecast)
        self.forecaster = ForecastEngine(fc_cfg, tick_s=tl_cfg.tick_s)
        fc_live = fc_cfg.enabled and tl_cfg.enabled

        # Guarded elastic-fleet actuator (router/autoscale.py): consumes
        # the rebalancer's sustained, lead-qualified advice and
        # spawns/retires pods (and workers, when a scaler is wired)
        # through the preflight/budget/watchdog/rollback pipeline.
        # Default-OFF kill-switch; the pod launcher is injected by the
        # embedding harness (bench, tests, a k8s reconciler) — without
        # one the actuator runs dry (refusals only). In fleet mode only
        # the datalayer-owning worker acts (promote() arms it).
        as_cfg = AutoscaleConfig.from_spec(cfg.autoscale)
        # Worker dimension in fleet mode: the acting worker drives the
        # supervisor's POST /fleet/scale (token shared via the worker
        # spec). Single-process or podsPerWorker:0 -> pods only.
        worker_scaler = None
        if (as_cfg.enabled and as_cfg.pods_per_worker > 0
                and fleet is not None
                and getattr(fleet, "sup_admin_port", 0)):
            from .autoscale import HttpWorkerScaler

            worker_scaler = HttpWorkerScaler(
                "127.0.0.1", fleet.sup_admin_port, fleet.control_token)
        self.autoscaler = ActuatorController(
            as_cfg,
            datastore=datastore,
            advice_fn=self.rebalancer.advice,
            worker_scaler=worker_scaler,
            burn_fn=self._burn_tripped,
            attainment_fn=self._last_attainment,
            acting=(fleet is None or fleet.runs_datalayer))

        self.timeline = TimelineSampler(
            tl_cfg,
            slo_ledger=self.slo_ledger,
            kv_ledger=self.kv_ledger,
            datastore=datastore,
            flow=self.flow_controller,
            inflight_fn=lambda: self._inflight,
            drain_rate_fn=drain_fn,
            degraded_fn=(lambda: self.overload.degraded_total)
            if self.overload.enabled else None,
            decisions_fn=self._recent_bad_decisions,
            shadow=self.shadow_eval if self.shadow_eval.active else None,
            rebalance=self.rebalancer if self.rebalancer.enabled else None,
            forecast=self.forecaster if fc_live else None,
            autoscale=self.autoscaler if self.autoscaler.enabled else None,
            tails=self.tails_obs if self.tails_obs.enabled else None)
        if fc_live and self.rebalancer.enabled:
            self.rebalancer.forecast = self.forecaster

        # Effective-config identity: the hash covers the UNREDACTED loaded
        # doc (config skew across fleet shards must show even when only
        # secrets differ); /debug/config serves the redacted snapshot.
        self.config_hash = config_hash(cfg.raw_doc)
        from .metrics import CONFIG_INFO

        CONFIG_INFO.labels(self.config_hash).set(1)

        self.app = web.Application()
        self.app.add_routes([
            web.post("/v1/completions", self.handle_inference),
            web.post("/v1/chat/completions", self.handle_inference),
            web.post("/v1/responses", self.handle_inference),
            web.post("/v1/embeddings", self.handle_inference),
            web.get("/metrics", self.metrics),
            web.get("/health", self.health),
            web.get("/v1/models", self.models),
            web.get("/debug/traces", self.traces),
            web.get("/debug/profile", self.profile),
            web.get("/debug/decisions", self.decisions),
            web.get("/debug/decisions/{request_id}", self.decision_detail),
            web.get("/debug/slo", self.slo),
            web.get("/debug/tails", self.tails_view),
            web.get("/debug/transfers", self.transfers),
            web.get("/debug/kv", self.kv),
            web.get("/debug/shadow", self.shadow_view),
            web.get("/debug/timeline", self.timeline_view),
            web.get("/debug/incidents", self.incidents_view),
            web.get("/debug/rebalance", self.rebalance_view),
            web.get("/debug/forecast", self.forecast_view),
            web.get("/debug/autoscale", self.autoscale_view),
            web.get("/debug/config", self.config_view),
            # Fleet control plane (router/fleet.py, loopback-guarded): the
            # supervisor's leader-election notices — promote this follower
            # to datalayer leader / re-aim the snapshot subscriber at a
            # freshly-elected leader's socket.
            web.post("/fleet/promote", self.fleet_promote),
            web.post("/fleet/retarget", self.fleet_retarget),
        ])
        self._runner: web.AppRunner | None = None
        # Fleet snapshot IPC endpoints (router/fleet.py): the datalayer
        # leader publishes PoolSnapshot epochs, followers apply them.
        self._snapshot_pub = None
        self._snapshot_sub = None
        self._client: httpx.AsyncClient | None = None
        self.draining = False   # SIGTERM drain: readiness flips not-ready
        self._inflight = 0      # live proxied requests (drain gate)
        self._models_fallback_cache: tuple[float, list] = (0.0, [])
        self._flusher: asyncio.Task | None = None
        self._profile_lock = asyncio.Lock()
        self.grpc_health = None
        if grpc_health_port is not None:
            from .health_grpc import HealthServer

            self.grpc_health = HealthServer(
                ready_fn=self._ready, host=host, port=grpc_health_port,
                tls=self.tls)
        # HA leader election + config reconciliation (controlplane.py —
        # reference runner.go:306-316 lease election with readiness coupling,
        # pkg/epp/controller reconcilers).
        self.elector = None
        if kube_elector is not None:
            # coordination.k8s.io/v1 Lease election (reference
            # controller_manager.go:84-91) — no shared volume required.
            self.elector = kube_elector
        elif lease_path is not None:
            from .controlplane import LeaseConfig, LeaseElector

            self.elector = LeaseElector(LeaseConfig(path=lease_path))
        self.reconciler = None
        if config_watch_path is not None:
            from .controlplane import ConfigReconciler

            self.reconciler = ConfigReconciler(config_watch_path, datastore)
        # k8s list+watch binding (router/kube.py) — replaces the static
        # pool / file reconciler when the gateway runs against an API server.
        self.kube_binding = kube_binding
        self.grpc_ext_proc = None
        if grpc_ext_proc_port is not None:
            from .handlers.extproc_grpc import ExtProcServer

            self.grpc_ext_proc = ExtProcServer(
                self.director, self.parser, evictor=self.evictor,
                host=host, port=grpc_ext_proc_port, tls=self.tls)

    # ---- lifecycle ------------------------------------------------------

    async def start(self):
        for meta in self.cfg.static_endpoints:
            self.datastore.endpoint_add_or_update(meta)
        self.datastore.pool_set(self.cfg.pool)
        for obj in self.cfg.objectives:
            self.datastore.objective_set(obj)
        for rw in self.cfg.model_rewrites:
            self.datastore.rewrite_set(rw)
        if self.fleet is None or self.fleet.runs_datalayer:
            await self.dl_runtime.start()
            if self.fleet is not None and self.fleet.ipc_path is not None:
                # Datalayer leader: the ONLY process scraping the engines;
                # every snapshot epoch broadcasts to the follower workers.
                await self._start_snapshot_publisher(self.fleet.ipc_path)
        else:
            # Fleet follower: pool state (membership + scrape metrics +
            # producer attributes) arrives as leader-published PoolSnapshot
            # epochs over IPC — no collectors, no per-worker SSE
            # subscriptions, so N workers impose 1x load on every engine.
            # With fleet.replication the same stream carries the leader's
            # engine-confirmed KvBlockIndex deltas + checkpoints, applied
            # into this worker's own index so precise-prefix scoring (and
            # everything built on it) behaves identically in every shard.
            from .fleet import SnapshotSubscriber

            self._snapshot_sub = SnapshotSubscriber(
                self.datastore, self.fleet.ipc_path,
                kv_index=(self._precise_index()
                          if self.fleet.replication else None))
            self._snapshot_sub.start()
        if self.flow_controller is not None:
            await self.flow_controller.start()
        # Verification policy from tlsClient config (default skip-verify:
        # pod-local certs — no longer hardcoded, ADVICE r5).
        self._client = httpx.AsyncClient(timeout=httpx.Timeout(300.0, connect=5.0),
                                         verify=self._client_tls_verify)
        # The proxy hop uses aiohttp's client: its C http parser costs a
        # fraction of httpx/h11 per chunk, and iter_any() coalesces SSE
        # events under load — together worth >30% through-router throughput
        # at 128 concurrent streams (VERDICT r4 weak #4; measured with
        # scripts/profile_router_sse.py).
        import aiohttp as _aiohttp

        self._upstream = _aiohttp.ClientSession(
            timeout=_aiohttp.ClientTimeout(total=300.0, sock_connect=5.0))
        # Bounded handler shutdown: stop() must not sit out aiohttp's 60 s
        # default waiting on SSE proxy handlers after a drain timeout.
        self._runner = web.AppRunner(self.app, shutdown_timeout=5.0)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port,
                           reuse_port=(True if self.fleet is not None
                                       and self.fleet.reuse_port else None),
                           ssl_context=self.tls.ssl_context
                           if self.tls else None)
        await site.start()
        if self.fleet is not None and self.fleet.admin_port is not None:
            # Private per-worker admin listener: under SO_REUSEPORT the
            # supervisor cannot address one worker through the shared data
            # port, so the fan-in plane (merged /metrics, /debug lookups)
            # reaches each shard here. Same app — every route, loopback
            # only.
            admin_site = web.TCPSite(self._runner, self.fleet.admin_host,
                                     self.fleet.admin_port)
            await admin_site.start()
        self._flusher = asyncio.get_running_loop().create_task(self._flush_pool_gauges())
        # Loop-lag heartbeat: the stall token relays experience, live on
        # /metrics (router_loop_lag_seconds) — the number the scheduler
        # offload exists to shrink.
        self.loop_lag.start()
        # Fleet flight recorder: grid-aligned sampler ticks (no-op under
        # the timeline kill-switch).
        self.timeline.start()
        # Self-balancing pool controller (no-op when disabled or when this
        # worker is a fleet follower — promote() arms it on re-election).
        self.rebalancer.start()
        # Guarded elastic-fleet actuator (kill-switch: no task at all).
        self.autoscaler.start()
        if self.grpc_health is not None:
            await self.grpc_health.start()
        if self.grpc_ext_proc is not None:
            await self.grpc_ext_proc.start()
        if self.elector is not None:
            await self.elector.start()
        if self.reconciler is not None:
            await self.reconciler.start()
        if self.kube_binding is not None:
            await self.kube_binding.start()
        log.info("gateway listening on %s:%s (%d endpoints)",
                 self.host, self.port, len(self.datastore.endpoint_list()))

    async def stop(self):
        self.loop_lag.stop()
        await self.timeline.stop()
        await self.rebalancer.stop()
        await self.autoscaler.stop()
        if self._flusher:
            self._flusher.cancel()
        if self.grpc_health is not None:
            await self.grpc_health.stop()
        if self.grpc_ext_proc is not None:
            await self.grpc_ext_proc.stop()
        if self.kube_binding is not None:
            await self.kube_binding.stop()
        if self.reconciler is not None:
            await self.reconciler.stop()
        if self.elector is not None:
            await self.elector.stop()
        if self.flow_controller is not None:
            await self.flow_controller.stop()
        if self._snapshot_pub is not None:
            await self._snapshot_pub.stop()
        if self._snapshot_sub is not None:
            await self._snapshot_sub.stop()
        if self._runner:
            await self._runner.cleanup()
        if self._client:
            await self._client.aclose()
        if getattr(self, "_upstream", None) is not None:
            await self._upstream.close()
        await self.dl_runtime.stop()
        self.shadow_eval.stop()
        self.sched_pool.shutdown()
        if self.tls is not None:
            self.tls.close()

    async def _flush_pool_gauges(self):
        # reference: periodic pool-gauge flusher (datalayer/logger.go:38-124)
        try:
            while True:
                eps = self.datastore.endpoint_list()
                POOL_READY_ENDPOINTS.set(len(eps))
                if eps:
                    POOL_AVG_KV_CACHE.set(
                        sum(e.metrics.kv_cache_usage_percent for e in eps) / len(eps))
                    POOL_AVG_QUEUE.set(
                        sum(e.metrics.waiting_queue_size for e in eps) / len(eps))
                await asyncio.sleep(1.0)
        except asyncio.CancelledError:
            pass

    # ---- handlers ---------------------------------------------------------

    async def traces(self, request: web.Request) -> web.Response:
        """Finished-span ring buffer. With ?merge=1, fan out to every pool
        endpoint's /debug/traces and merge (dedup by span_id), so one call
        assembles cross-process gateway→sidecar→engine trace trees — the
        parent links survive because every hop propagates traceparent."""
        from .tracing import tracer

        spans = list(tracer.snapshot())
        if request.query.get("merge") not in (None, "", "0"):
            seen = {s["span_id"] for s in spans}

            async def fetch(ep):
                try:
                    r = await self._client.get(
                        ep.metadata.url + "/debug/traces", timeout=2.0)
                    return (r.json().get("spans") or []) if r.status_code == 200 else []
                except Exception:
                    return []

            for remote in await asyncio.gather(
                    *[fetch(ep) for ep in self.datastore.endpoint_list()]):
                for s in remote:
                    if isinstance(s, dict) and s.get("span_id") not in seen:
                        seen.add(s.get("span_id"))
                        spans.append(s)
        return web.json_response({"spans": spans})

    async def decisions(self, request: web.Request) -> web.Response:
        """Recent decision records (compact). ?n=N bounds the page (default
        50); each entry carries the one-line summary plus admission/final
        sections — the full record lives at /debug/decisions/<request-id>.
        Operator filters (decisions.record_matches): ?verdict=met|missed|
        error|shed (the SLO ledger's serving verdict), ?endpoint=<ip:port>
        (the destination that served), ?outcome=miss|shed (convenience
        aliases), ?profile=prefill|decode|skip-hop (the disaggregation
        shape the request took — skip-hop isolates the prefill
        classifier's skipped P/D hops), ?stage=<dominant-stage> (tail
        attribution: records whose waterfall landed in the cohort tail
        with that dominant stage, router/tails.py) — so records are
        findable without client-side scans."""
        from .decisions import record_matches

        try:
            n = int(request.query.get("n", "50"))
        except ValueError:
            n = 50
        n = max(1, n)
        verdict = request.query.get("verdict") or None
        endpoint = request.query.get("endpoint") or None
        outcome = request.query.get("outcome") or None
        profile = request.query.get("profile") or None
        stage = request.query.get("stage") or None
        # ?divergent=1 — shadow-policy counterfactual filter: only records
        # where a registered shadow policy would have picked differently
        # (?divergent=0 inverts; any other value matches nothing,
        # loudly-by-empty — the sibling filters' convention).
        # router/shadow.py, docs/shadow.md.
        div_q = request.query.get("divergent")
        divergent: Any = (None if div_q in (None, "")
                          else True if div_q in ("1", "true")
                          else False if div_q in ("0", "false")
                          else "invalid")
        filtered = verdict is not None or endpoint is not None \
            or outcome is not None or profile is not None \
            or divergent is not None or stage is not None
        # Filtering scans the WHOLE ring (the n newest matches, not the
        # matches within the n newest); the unfiltered path keeps the
        # cheap bounded snapshot.
        recs = self.decision_recorder.snapshot(None if filtered else n)
        docs = []
        for r in recs:
            doc = r.to_dict(compact=True)
            if filtered:
                # The endpoint filter also matches the attempt trail and
                # the profile filter the per-round profile sections — both
                # omitted from the compact form. Graft the raw lists onto
                # the probe (zero-copy; record_matches only reads
                # a["endpoint"] / each round's profile outcome) so
                # failed-over pods and P/D shapes are findable too.
                probe = doc
                if endpoint is not None or profile is not None:
                    probe = dict(doc)
                    if endpoint is not None:
                        probe["attempts"] = r.attempts
                    if profile is not None:
                        probe["rounds"] = r.rounds
                if not record_matches(probe, verdict=verdict,
                                      endpoint=endpoint, outcome=outcome,
                                      profile=profile, divergent=divergent,
                                      stage=stage):
                    continue
            docs.append(doc)
            if len(docs) >= n:
                break
        return web.json_response({
            "schema_version": SCHEMA_VERSION,
            "enabled": self.decision_recorder.enabled,
            "count": len(self.decision_recorder),
            "decisions": docs,
        })

    def _recent_bad_decisions(self, k: int) -> list[dict[str, Any]]:
        """The last K missed/shed DecisionRecords (compact), newest first —
        the incident recorder embeds them in each snapshot so "what broke"
        comes with "which requests it broke"."""
        out: list[dict[str, Any]] = []
        for rec in self.decision_recorder.snapshot(None):
            outcome = rec.outcome or {}
            verdict = outcome.get("verdict")
            if verdict in ("missed", "shed", "error"):
                out.append(rec.to_dict(compact=True))
                if len(out) >= k:
                    break
        return out

    def _burn_tripped(self) -> bool:
        """The actuator's rollback trigger: is the PR 12 multi-window
        burn-rate monitor tripped right now? (False under the timeline
        kill-switch — no monitor, no trigger.)"""
        if not self.timeline.enabled:
            return False
        burn = self.timeline.burn
        return burn.tripped(*burn.rates())

    def _last_attainment(self) -> float | None:
        """The most recent timeline tick's SLO attainment (None when the
        tick had no served arrivals, or under the timeline kill-switch)."""
        if not self.timeline.enabled or not self.timeline.ring:
            return None
        return self.timeline.ring[-1].get("attainment")

    async def timeline_view(self, request: web.Request) -> web.Response:
        """Fleet flight recorder history (router/timeline.py): raw ticks
        plus windowed aggregates; ?window_s=N bounds the returned window
        (default: the whole retained ring), ?series=a,b keeps only the
        named top-level keys, ?step_s=N downsamples ticks into coarser
        mean buckets (gap-aware: empty buckets stay absent)."""
        window_s = finite_float_or_none(request.query.get("window_s"))
        series_q = request.query.get("series")
        series = ([s for s in (p.strip() for p in series_q.split(","))
                   if s] if series_q else None)
        step_s = finite_float_or_none(request.query.get("step_s"))
        return web.json_response(self.timeline.snapshot(
            window_s=window_s if window_s and window_s > 0 else None,
            series=series or None,
            step_s=step_s if step_s and step_s > 0 else None))

    async def incidents_view(self, request: web.Request) -> web.Response:
        """Triggered incident snapshots (router/timeline.py): timeline
        window ±N ticks, the last K missed/shed DecisionRecords, and the
        /debug/slo + /debug/kv rollups captured at trigger time."""
        return web.json_response({
            "enabled": self.timeline.enabled,
            **self.timeline.incidents.snapshot(),
        })

    async def rebalance_view(self, request: web.Request) -> web.Response:
        """Self-balancing pool controller (router/rebalance.py): per-role
        headroom series, flip history with full DecisionRecord-style
        inputs, active drain cycles, and the current scaling advice."""
        return web.json_response(self.rebalancer.snapshot())

    async def forecast_view(self, request: web.Request) -> web.Response:
        """Traffic forecaster (router/forecast.py): per-series model
        state, the latest stamped forecast per horizon, the judged error
        ledger (MAE/MAPE/bias/coverage + skill vs persistence), and the
        capacity observatory's per-role saturation projections.
        ?joins=N inlines the N most recent judged rows per cell."""
        joins_q = request.query.get("joins")
        try:
            joins_n = max(0, min(int(joins_q), 1000)) if joins_q else None
        except ValueError:
            joins_n = None
        return web.json_response(self.forecaster.snapshot(
            joins_n=joins_n or None))

    async def autoscale_view(self, request: web.Request) -> web.Response:
        """Guarded elastic-fleet actuator (router/autoscale.py): the
        judged action ledger — every action, refusal, timeout, and
        rollback with its preflight inputs (advice, lead_s, headroom,
        budgets) and post-hoc outcome — plus the live budget window,
        breaker states, and the rollback-freeze latch."""
        return web.json_response(self.autoscaler.snapshot())

    async def config_view(self, request: web.Request) -> web.Response:
        """Redacted effective-config snapshot: what config THIS worker
        actually loaded (secrets masked, paths reduced to basenames), plus
        the hash router_config_info carries — the fleet fan-in compares it
        across shards."""
        return web.json_response({
            "hash": self.config_hash,
            "shard": self.fleet.index if self.fleet is not None else None,
            "config": redact_config(self.cfg.raw_doc),
        })

    # ---- fleet control plane (router/fleet.py leader election) ---------

    def _precise_index(self):
        """The precise-prefix scorer's engine-confirmed KvBlockIndex, when
        one is configured — the replication unit of fleet.replication
        (same discovery contract as CacheLedger.attach_plugins)."""
        found = [p for p in self.cfg.plugins_by_name.values()
                 if hasattr(p, "index_counts") and hasattr(p, "index")]
        if len(found) > 1:
            log.warning("fleet.replication: %d precise-prefix scorers "
                        "configured; replicating only %r",
                        len(found), found[0].name)
        return found[0].index if found else None

    async def _start_snapshot_publisher(self, path: str) -> None:
        from .fleet import KvReplicationSource, SnapshotPublisher

        kv_source = None
        if self.fleet.replication:
            index = self._precise_index()
            if index is not None:
                kv_source = KvReplicationSource(index)
        self._snapshot_pub = SnapshotPublisher(
            self.datastore, path, kv_source=kv_source,
            kv_checkpoint_s=self.fleet.kv_checkpoint_s,
            wire=self.fleet.wire)
        await self._snapshot_pub.start()

    def _fleet_request_allowed(self, request: web.Request) -> str | None:
        """Guard for the supervisor-only control routes: fleet mode with
        snapshot IPC, loopback peers, AND the per-fleet-run shared token —
        the loopback check alone is spoofable through the hash balancer's
        splice (the worker sees the balancer's loopback address, not the
        client's), and the same app serves the public data port."""
        if self.fleet is None or self.fleet.ipc_path is None:
            return "not a fleet worker (no snapshot IPC)"
        peer = (request.transport.get_extra_info("peername")
                if request.transport is not None else None)
        if (isinstance(peer, (tuple, list)) and peer
                and peer[0] not in ("127.0.0.1", "::1", "localhost")):
            return f"fleet control refused for non-loopback peer {peer[0]}"
        token = getattr(self.fleet, "control_token", None)
        if token and request.headers.get("x-fleet-token") != token:
            return "fleet control refused: bad or missing x-fleet-token"
        return None

    async def fleet_promote(self, request: web.Request) -> web.Response:
        """Supervisor promotion notice (leader re-election): this follower
        becomes the datalayer leader — start the scrape collectors +
        kv-event SSE lifecycle, resume local snapshot-epoch minting
        (continuing the dead leader's numbering), and publish on the fresh
        socket the supervisor advertises. Idempotent: a re-delivered
        promotion for the path already served returns 200."""
        err = self._fleet_request_allowed(request)
        if err is not None:
            return web.json_response({"error": err}, status=403)
        try:
            path = str((await request.json())["ipcPath"])
        except Exception:
            return web.json_response({"error": "ipcPath required"},
                                     status=400)
        if self.fleet.role == "leader" and self._snapshot_pub is not None:
            if self._snapshot_pub.path != path:
                # Re-promotion onto a fresh socket (e.g. a supervisor
                # retry that lost the first ack): move the publisher.
                await self._snapshot_pub.stop()
                self._snapshot_pub = None
                await self._start_snapshot_publisher(path)
            self.fleet.ipc_path = path
            return web.json_response({"role": "leader", "ipcPath": path})
        log.warning("promoted to datalayer leader (publishing on %s)", path)
        if self._snapshot_sub is not None:
            await self._snapshot_sub.stop()
            self._snapshot_sub = None
        self.datastore.resume_local_snapshots()
        # The lifecycle plugins build_gateway skipped for followers (per-pod
        # kv-event subscribers, LRU teardown) register now — and their
        # endpoint_added hooks fire for the pool that already exists, since
        # the datastore events that normally drive them are long past.
        for plugin in self.cfg.plugins_by_name.values():
            if (hasattr(plugin, "endpoint_added")
                    or hasattr(plugin, "endpoint_removed")):
                # Guard against a supervisor promote retry that lost the
                # first ack mid-setup: registration must stay idempotent.
                if plugin in self.dl_runtime.lifecycle_plugins:
                    continue
                self.dl_runtime.register_lifecycle(plugin)
                added = getattr(plugin, "endpoint_added", None)
                if added is not None:
                    for ep in self.datastore.endpoint_list():
                        try:
                            added(ep)
                        except Exception:
                            log.exception("lifecycle plugin failure "
                                          "(promotion add)")
        await self.dl_runtime.start()
        self.fleet.role = "leader"
        self.fleet.ipc_path = path
        await self._start_snapshot_publisher(path)
        # The promoted worker now owns the datalayer, so the rebalance
        # controller and the elastic-fleet actuator (if configured) may
        # act on pool metadata.
        self.rebalancer.promote()
        self.autoscaler.promote()
        return web.json_response({"role": "leader", "ipcPath": path})

    async def fleet_retarget(self, request: web.Request) -> web.Response:
        """Supervisor re-target notice: a new leader was elected on a
        fresh snapshot socket; aim the subscriber there NOW (event-driven —
        not after an exponential backoff against the dead socket)."""
        err = self._fleet_request_allowed(request)
        if err is not None:
            return web.json_response({"error": err}, status=403)
        try:
            path = str((await request.json())["ipcPath"])
        except Exception:
            return web.json_response({"error": "ipcPath required"},
                                     status=400)
        self.fleet.ipc_path = path
        if self._snapshot_sub is not None:
            self._snapshot_sub.retarget(path)
        return web.json_response({"role": self.fleet.role, "ipcPath": path})

    async def shadow_view(self, request: web.Request) -> web.Response:
        """Shadow-policy counterfactual ledger rollup (router/shadow.py):
        per-policy agreement rate, coverage, signed estimated-regret ms,
        and the recent-divergence ring — every registered policy's regret
        curve, measured in shadow before a config activates it live."""
        return web.json_response(self.shadow_eval.snapshot())

    async def kv(self, request: web.Request) -> web.Response:
        """KV-cache & prefix-reuse observability rollup (router/kvobs.py):
        per-pod measured hit-rate and signed-prediction-error EWMAs, index
        occupancy (approx LRU blocks, precise confirmed/speculative stamp
        counts), scraped engine hit counters, and the prediction MAE over
        all predicted→confirmed joins."""
        return web.json_response(self.kv_ledger.snapshot())

    async def slo(self, request: web.Request) -> web.Response:
        """Fleet SLO/goodput rollup (router/slo.py): per-endpoint and
        per-band attainment, predictor signed error + MAE, goodput vs raw
        token counts, bounded miss-reason tallies."""
        return web.json_response(self.slo_ledger.snapshot())

    async def tails_view(self, request: web.Request) -> web.Response:
        """Tail-latency attribution observatory (router/tails.py): per-
        (model, band, shape) body-vs-tail cohort split with per-stage
        p50/p95/p99, dominant-stage attribution of the tail cohort's
        excess time with culprit drill-down (endpoint / transfer pair /
        shed rung), and bounded exemplar request-ids linking into
        /debug/decisions/<id>."""
        return web.json_response(self.tails_obs.snapshot())

    async def transfers(self, request: web.Request) -> web.Response:
        """Per-(prefill, decode)-pair KV-transfer EWMA table
        (datalayer/transfers.py): pull duration, bytes, derived wire speed,
        and prefill-leg duration per pair."""
        return web.json_response(self.datastore.transfers.snapshot())

    async def decision_detail(self, request: web.Request) -> web.Response:
        """Full schema-versioned DecisionRecord for one request id:
        admission → flow control → per-profile filter drops + scorer tables +
        picker pick → retry/failover attempt trail."""
        rid = request.match_info["request_id"]
        rec = self.decision_recorder.get(rid)
        if rec is None:
            return web.json_response(
                {"error": f"no decision record for request id {rid!r}",
                 "enabled": self.decision_recorder.enabled}, status=404)
        return web.json_response(rec.to_dict())

    async def profile(self, request: web.Request) -> web.Response:
        """CPU profile of the router process for ?seconds=N (pprof analogue;
        reference mounts pprof handlers behind --enable-pprof, SURVEY §5).
        ``?format=json`` returns the top-N cumulative rows as structured
        data instead of the pstats text dump (machine-readable for CI and
        the verify-debug probe, which drives this route's REAL path)."""
        import cProfile
        import io
        import pstats

        import math

        try:
            seconds = min(float(request.query.get("seconds", "5")), 60.0)
        except ValueError:
            seconds = float("nan")
        if not math.isfinite(seconds) or seconds <= 0:
            return web.json_response(
                {"error": "seconds must be a positive finite number"}, status=400)
        if self._profile_lock.locked():
            return web.json_response(
                {"error": "a profile is already running"}, status=409)
        async with self._profile_lock:
            prof = cProfile.Profile()
            prof.enable()
            try:
                await asyncio.sleep(seconds)
            finally:
                # Cancellation/shutdown must not leave the C profile hook
                # installed on the event-loop thread.
                prof.disable()
        try:
            top_n = max(1, min(int(request.query.get("n", "40")), 500))
        except ValueError:
            top_n = 40
        if request.query.get("format") == "json":
            stats = pstats.Stats(prof)
            rows = []
            for (fname, line, func), (cc, nc, tt, ct, _callers) in \
                    stats.stats.items():  # type: ignore[attr-defined]
                rows.append({
                    "function": f"{fname}:{line}({func})",
                    "ncalls": nc,
                    "primitive_calls": cc,
                    "tottime_s": round(tt, 6),
                    "cumtime_s": round(ct, 6),
                })
            rows.sort(key=lambda r: r["cumtime_s"], reverse=True)
            return web.json_response({
                "seconds": seconds,
                "functions_profiled": len(rows),
                "rows": rows[:top_n],
            })
        buf = io.StringIO()
        pstats.Stats(prof, stream=buf).sort_stats("cumulative").print_stats(top_n)
        return web.Response(text=buf.getvalue(), content_type="text/plain")

    async def handle_inference(self, request: web.Request) -> web.StreamResponse:
        from .tracing import tracer

        self._inflight += 1
        try:
            # Joins the client's W3C trace context when a traceparent header
            # arrives; otherwise roots a fresh trace (sampling applies).
            with tracer.span_from_headers("gateway.request", request.headers,
                                          path=request.path) as span:
                resp = await self._handle_inference(request, span)
                span.set_attribute("status", resp.status)
                return resp
        finally:
            self._inflight -= 1

    async def _handle_inference(self, request: web.Request,
                                span=None) -> web.StreamResponse:
        t_start = time.monotonic()
        raw = await request.read()
        headers = {k.lower(): v for k, v in request.headers.items()}
        # Router-owned routing headers must never be client-controlled: only
        # scheduling plugins (e.g. DisaggProfileHandler.pre_request) may set
        # them, else a client could SSRF the sidecar into arbitrary targets.
        for h in ROUTER_OWNED_HEADERS:
            headers.pop(h, None)
        headers.setdefault(H_REQUEST_ID, f"req-{uuid.uuid4().hex[:12]}")

        # End-to-end deadline: client x-request-timeout (float seconds) or
        # the configured default; decremented across hops from here on.
        deadline = Deadline.from_headers(
            headers, default_s=self.resilience.default_timeout_s,
            max_s=self.resilience.max_timeout_s)
        if deadline is not None and deadline.expired:
            DEADLINE_EXCEEDED_TOTAL.inc()
            return web.json_response(
                {"error": "deadline exceeded"}, status=504,
                headers={X_REMOVAL_REASON: DEADLINE_EXCEEDED_REASON})

        # Large bodies parse off-loop (the parsers are stateless): a
        # multi-megabyte long-context JSON body is pure CPU that would
        # otherwise stall every live SSE relay for milliseconds.
        if (len(raw) >= LARGE_BODY_PARSE_BYTES
                and self.sched_pool.executor is not None):
            import functools

            parse = await asyncio.get_running_loop().run_in_executor(
                self.sched_pool.executor,
                functools.partial(self.parser.parse, raw, headers,
                                  path=request.path))
        else:
            parse = self.parser.parse(raw, headers, path=request.path)
        if parse.error:
            return web.json_response({"error": parse.error}, status=400)

        if parse.skip:
            ep = self.director.get_random_endpoint()
            if ep is None:
                return web.json_response({"error": "no endpoints"}, status=503)
            return await self._proxy_with_failover(
                request, None, [ep], raw, headers, t_start,
                original_model="", deadline=deadline)

        ireq = InferenceRequest(
            request_id=headers[H_REQUEST_ID],
            target_model=parse.model,
            body=parse.body,
            headers=headers,
            request_size_bytes=len(raw))
        original_model = parse.model
        # SLO ledger: opened BEFORE orchestration so the flow-control
        # admission hook can stamp queue time and the predicted-latency
        # PreRequest hook can stamp this request's prediction.
        self.slo_ledger.start(ireq, t_start)
        # Waterfall (router/tails.py): opened beside the SLO observation so
        # every layer hook past this point can stamp its stage.
        self.tails_obs.start(ireq, t_start)

        try:
            result = await self.director.handle_request(None, ireq)
        except RequestError as e:
            # Director error finalization (no endpoints, admission shed,
            # admit-plugin reject, scheduling failure): the ledger records
            # slo_met=false with the reason — an absent field would
            # overcount attainment. Overload sheds are the distinct ledger
            # verdict and carry a finite computed Retry-After header.
            shed = getattr(e, "shed", False)
            retry_after = getattr(e, "retry_after_s", None)
            self.slo_ledger.complete(ireq, status=e.code, reason=e.reason,
                                     shed=shed)
            self.tails_obs.complete(ireq, status=e.code, reason=e.reason,
                                    shed=shed)
            body: dict[str, Any] = {"error": e.reason}
            headers = {X_REMOVAL_REASON: e.reason,
                       **self._decision_headers(ireq)}
            if retry_after is not None:
                # HTTP delta-seconds is an integer; never hand out 0.
                headers["Retry-After"] = str(max(int(round(retry_after)), 1))
                body["retry_after_s"] = retry_after
            return web.json_response(body, status=e.code, headers=headers)

        # Cache ledger (router/kvobs.py): stamp the per-candidate predicted
        # hit depth the scorers just routed on; the engine-confirmed actual
        # joins it on completion.
        self.kv_ledger.record_scheduled(ireq, result)

        # Repackage through the parser (director.go:289-306) only when the
        # bytes must change: model rewrite, or a translating (non-OpenAI)
        # parser; otherwise forward the raw body untouched (hot path).
        body_out = raw
        payload = ireq.body.payload
        needs_repackage = (payload is not None
                           and (ireq.target_model != original_model
                                # Degrade ladder (router/overload.py): the
                                # controller mutated the payload (e.g.
                                # max_tokens clamp) — the raw client bytes
                                # no longer match what must be served.
                                or getattr(ireq, "degraded", False)
                                or self.parser.typed_name().type
                                not in ("openai-parser", "passthrough-parser")))
        if needs_repackage:
            if ireq.target_model != original_model:
                payload["model"] = ireq.target_model
            body_out = self.parser.serialize(ireq.body)

        # Register for mid-flight eviction: sheddable in-flight requests can be
        # cancelled to admit higher-priority work (reference eviction channel →
        # ImmediateResponse(429), handlers/server.go:266-284).
        task = asyncio.current_task()
        evict_key = self.evictor.register(ireq.request_id,
                                          ireq.objectives.priority, task.cancel)
        stream_state = {"started": False}
        try:
            return await self._proxy_with_failover(
                request, ireq, list(result.primary().target_endpoints),
                body_out, ireq.headers, t_start,
                original_model=original_model, stream_state=stream_state,
                deadline=deadline)
        except asyncio.CancelledError:
            if self.evictor.was_evicted(evict_key) and not stream_state["started"]:
                from .flowcontrol.eviction import EVICTED_REASON

                if ireq.decision is not None:
                    ireq.decision.record_event("evicted_inflight")
                    ireq.decision.finalize(429, reason=EVICTED_REASON)
                self.slo_ledger.complete(ireq, status=429,
                                         reason=EVICTED_REASON)
                self.tails_obs.complete(ireq, status=429,
                                        reason=EVICTED_REASON)
                self.shadow_eval.observe_response(ireq, transfer=None,
                                                  status=429)
                return web.json_response(
                    {"error": EVICTED_REASON}, status=429,
                    headers={X_REMOVAL_REASON: EVICTED_REASON,
                             **self._decision_headers(ireq)})
            # Mid-stream eviction (or external cancel): the 200 status line is
            # already on the wire — the only clean signal is the dropped
            # connection, so propagate (the ledger still closes: an aborted
            # stream is slo_met=false, not an absent row).
            self.slo_ledger.complete(ireq, status=499,
                                     reason="cancelled-mid-stream")
            self.tails_obs.complete(ireq, status=499,
                                    reason="cancelled-mid-stream")
            self.shadow_eval.observe_response(ireq, transfer=None,
                                              status=499)
            raise
        finally:
            self.evictor.deregister(evict_key)

    @staticmethod
    def _decision_headers(ireq: InferenceRequest | None) -> dict[str, str]:
        """The x-decision-summary echo, present only when the client opted
        in with `x-debug-decision: summary` and a record exists."""
        if (ireq is not None and ireq.decision is not None
                and ireq.headers.get(H_DEBUG_DECISION, "").lower() == "summary"):
            return {H_DECISION_SUMMARY: ireq.decision.summary_line()}
        return {}

    def _dp_override(self, ireq: InferenceRequest, target) -> str | None:
        """DP rank routing: when a profile handler picked a rank, route to
        the pod's rank-specific listener (what Envoy does with the
        reference's x-data-parallel-host-port) after validating it belongs
        to the target pod."""
        from .plugins.disagg import DataParallelProfileHandler
        from .requestcontrol.director import H_DATA_PARALLEL

        dp_target = ireq.headers.get(H_DATA_PARALLEL)
        if not dp_target:
            return None
        try:
            host, _, port = dp_target.rpartition(":")
            port = int(port)
            dp_size = int(target.metadata.labels.get(
                DataParallelProfileHandler.DP_SIZE_LABEL, "1"))
        except ValueError:
            host, port, dp_size = "", -1, 1
        if (host == target.metadata.address
                and target.metadata.port <= port < target.metadata.port + dp_size):
            # Consumed for routing; the rank listener itself encodes the
            # rank, so don't forward the header downstream.
            ireq.headers.pop(H_DATA_PARALLEL, None)
            return f"http://{host}:{port}"
        return None

    async def _proxy_with_failover(self, request: web.Request,
                                   ireq: InferenceRequest | None,
                                   candidates: list, body: bytes,
                                   headers: dict[str, str], t_start: float,
                                   *, original_model: str,
                                   stream_state: dict | None = None,
                                   deadline: Deadline | None = None
                                   ) -> web.StreamResponse:
        """Dispatch with retry + failover: walk the scheduling result's
        ranked candidates on pre-stream failures (connect errors, retryable
        502/503 such as ``x-removal-reason: sidecar-draining``), then
        re-schedule ONCE with the failed endpoints excluded. Bounded by the
        per-request attempt cap and the token-bucket retry budget so retries
        cannot amplify an outage; a response whose stream has started is
        never retried (the status line is on the wire). Endpoint outcomes
        feed the passive circuit breakers."""
        res = self.resilience
        breakers = self.datastore.breakers
        self.retry_budget.deposit()
        rec = ireq.decision if ireq is not None else None
        # Waterfall attempts stage (router/tails.py): time burned in FAILED
        # dispatch attempts — the serving attempt's own time lands in the
        # downstream stages, so only the walk's dead ends are charged here.
        wf = getattr(ireq, "waterfall", None) if ireq is not None else None
        attempted: set[str] = set()
        rescheduled = ireq is None  # only scheduled requests can re-schedule
        failure: UpstreamFailure | None = None
        budget_exhausted = False
        blocked: set[str] = set()  # breaker-denied this request
        last_target = None
        attempt = 0
        while attempt < res.max_attempts:
            if deadline is not None and deadline.expired:
                failure = UpstreamFailure(
                    "deadline", 504, DEADLINE_EXCEEDED_REASON)
                if rec is not None:
                    rec.record_event("deadline_exceeded")
                break
            target = None
            for ep in candidates:
                k = ep.metadata.address_port
                if k in attempted or k in blocked:
                    continue
                if not breakers.allow(k):
                    blocked.add(k)
                    if rec is not None:
                        rec.record_event("breaker_denied", endpoint=k)
                    continue
                target = ep
                break
            if target is None and not rescheduled:
                rescheduled = True
                # Breaker-denied endpoints join the exclusion set: without
                # them the scheduler can re-pick the same open endpoint
                # (it looks idle) and the request dies with healthy pods
                # available.
                result = self.director.reschedule(None, ireq,
                                                  exclude=attempted | blocked)
                if result is not None:
                    # Fresh candidates merge into the cache block: the
                    # actual may be confirmed by a pod the first scheduling
                    # pass never ranked.
                    self.kv_ledger.record_scheduled(ireq, result)
                    candidates = list(result.primary().target_endpoints)
                    continue
            if target is None:
                break
            key = target.metadata.address_port
            if attempt > 0:
                if not self.retry_budget.try_spend():
                    RETRY_BUDGET_EXHAUSTED_TOTAL.inc()
                    budget_exhausted = True
                    # allow() above may have claimed the half-open probe
                    # slot; this attempt never dispatches, so free it.
                    breakers.release_probe(key)
                    if rec is not None:
                        rec.record_event("retry_budget_exhausted",
                                         endpoint=key)
                    break
                RETRIES_TOTAL.labels(failure.kind if failure
                                     else "other").inc()
            attempt += 1
            last_target = target
            override = (self._dp_override(ireq, target)
                        if ireq is not None else None)
            attempt_t0 = time.monotonic() if wf is not None else 0.0
            try:
                resp = await self._proxy(
                    request, ireq, target, body, headers, t_start,
                    original_model=original_model,
                    stream_state=stream_state, url_override=override,
                    deadline=deadline)
            except UpstreamFailure as f:
                if wf is not None:
                    wf.attempts_ms += (time.monotonic() - attempt_t0) * 1e3
                failure = f
                attempted.add(key)
                breakers.record_failure(key)
                if rec is not None:
                    rec.record_attempt(key, f.kind,
                                       status=f.status or None,
                                       reason=f.reason)
                log.warning("upstream %s failed pre-stream (%s: %s); %s",
                            key, f.kind, f.detail or f.reason,
                            "retrying" if attempt < res.max_attempts
                            else "attempt cap reached")
                continue
            except asyncio.CancelledError:
                # Eviction / client cancel mid-attempt: no outcome to
                # record, but the probe slot must not leak.
                breakers.release_probe(key)
                raise
            # Relayed responses feed the breaker: sub-500 is endpoint
            # health; a relayed 500 is endpoint brokenness. Other relayed
            # 5xx (an engine-side deadline 504, a 501 unimplemented
            # surface) reflect the REQUEST, not the pod — recording them as
            # failures would let short-deadline traffic eject healthy
            # endpoints fleet-wide, so they only release the probe slot.
            if resp.status < 500:
                breakers.record_success(key)
            elif resp.status == 500:
                breakers.record_failure(key)
            else:
                breakers.release_probe(key)
            return resp
        # Out of options: close the request-control bracket exactly once
        # (handle_request incremented the running counter) and surface the
        # last failure with the canonical x-removal-reason contract.
        if ireq is not None:
            self.director.handle_response_complete(None, ireq, last_target, {})
            # Shadow judge on the FAILED terminal too: a sampled
            # divergence on a request that then timed out must not stay
            # unjudged forever — that would bias the regret curve toward
            # successful requests. No transfer row; the judge's EWMA
            # fallback exists for exactly this.
            self.shadow_eval.observe_response(
                ireq, transfer=None,
                status=failure.status if failure is not None else 503)
        dec_headers = self._decision_headers(ireq)
        if failure is not None and failure.kind == "deadline":
            DEADLINE_EXCEEDED_TOTAL.inc()
            if rec is not None:
                rec.finalize(504, reason=DEADLINE_EXCEEDED_REASON)
            if ireq is not None:
                self.slo_ledger.complete(ireq, status=504,
                                         reason=DEADLINE_EXCEEDED_REASON)
                self.tails_obs.complete(ireq, status=504,
                                        reason=DEADLINE_EXCEEDED_REASON)
            return web.json_response(
                {"error": "deadline exceeded"}, status=504,
                headers={X_REMOVAL_REASON: DEADLINE_EXCEEDED_REASON,
                         **dec_headers})
        # Budget-suppressed fast-fails are marked in the body so operators
        # (and tests) can tell them from ordinary upstream errors; the
        # x-removal-reason header keeps the upstream's own cause.
        extra = {"retry": RETRY_BUDGET_REASON} if budget_exhausted else {}
        if failure is not None and failure.kind in ("connect", "read"):
            if rec is not None:
                rec.finalize(502, reason=failure.reason)
            if ireq is not None:  # retry-exhausted terminal
                self.slo_ledger.complete(ireq, status=502,
                                         reason=failure.reason)
                self.tails_obs.complete(ireq, status=502,
                                        reason=failure.reason)
            return web.json_response(
                {"error": f"upstream {failure.kind} failed: {failure.detail}",
                 **extra},
                status=502, headers={X_REMOVAL_REASON: failure.reason,
                                     **dec_headers})
        if failure is not None:  # retryable status, relayed as-is
            if rec is not None:
                rec.finalize(failure.status, reason=failure.reason)
            if ireq is not None:
                self.slo_ledger.complete(ireq, status=failure.status,
                                         reason=failure.reason)
                self.tails_obs.complete(ireq, status=failure.status,
                                        reason=failure.reason)
            return web.json_response(
                {"error": failure.reason, **extra}, status=failure.status,
                headers={X_REMOVAL_REASON: failure.reason, **dec_headers})
        if rec is not None:
            rec.finalize(503, reason="no-upstream-available")
        if ireq is not None:
            self.slo_ledger.complete(ireq, status=503,
                                     reason="no-upstream-available")
            self.tails_obs.complete(ireq, status=503,
                                    reason="no-upstream-available")
        return web.json_response(
            {"error": "no upstream endpoint available"}, status=503,
            headers={X_REMOVAL_REASON: "no-upstream-available", **dec_headers})

    async def _proxy(self, request: web.Request, ireq: InferenceRequest | None,
                     endpoint, body: bytes, headers: dict[str, str],
                     t_start: float, original_model: str,
                     stream_state: dict | None = None,
                     url_override: str | None = None,
                     deadline: Deadline | None = None) -> web.StreamResponse:
        url = (url_override or endpoint.metadata.url) + request.path
        fwd = {k: v for k, v in headers.items() if k in FORWARD_HEADERS}
        fwd["content-type"] = "application/json"
        # Propagate the trace context downstream (sidecar/engine join it):
        # the gateway.request span is current here, so it becomes the parent
        # of the next hop's server span.
        from .tracing import tracer

        tracer.inject_headers(fwd)
        model_label = (ireq.target_model if ireq else "") or "unknown"

        kwargs = {}
        if deadline is not None:
            # The downstream leg inherits the REMAINING budget: stamped on
            # the wire for the next hop, and enforced locally as the
            # attempt's total timeout (covers connect + full body relay).
            remaining = max(deadline.remaining_s, 0.001)
            fwd[H_REQUEST_TIMEOUT] = deadline.header_value()
            kwargs["timeout"] = aiohttp.ClientTimeout(
                total=remaining, sock_connect=min(5.0, remaining))
        try:
            # TLS legs follow the tlsClient verification policy (default: a
            # skip-verify context for pod-local certs — engines started with
            # --secure-serving; a configured CA bundle verifies for real).
            resp = await self._upstream.post(
                url, data=body, headers=fwd,
                ssl=self._upstream_ssl if url.startswith("https") else None,
                **kwargs)
        except Exception as e:
            raise UpstreamFailure("connect", 0, "upstream-connect-error",
                                  str(e)) from e

        # Pre-stream retryable failures: nothing has been relayed to the
        # client yet, so a 502/503 (e.g. x-removal-reason: sidecar-draining
        # from PR 1's drain path) walks to the next candidate instead of
        # becoming client-visible.
        if resp.status in (502, 503):
            reason = (resp.headers.get(X_REMOVAL_REASON)
                      or f"upstream-{resp.status}")
            resp.release()
            raise UpstreamFailure("status", resp.status, reason)

        streaming_body = "text/event-stream" in resp.headers.get("content-type", "")
        data = None
        if not streaming_body:
            # The full body read is still pre-stream from the client's view
            # (headers go out only with the assembled web.Response below), so
            # an upstream dying mid-body stays retryable too.
            try:
                data = await resp.read()
            except Exception as e:
                resp.release()
                raise UpstreamFailure("read", 0, "upstream-read-error",
                                      str(e)) from e

        # Non-streaming responses hold their full body (and so the usage
        # record) before any header goes out: parse it once here — the
        # cache-ledger join below and the token metrics in `finally` both
        # reuse it.
        usage: dict[str, int] = {}
        if not streaming_body and data is not None:
            usage = _usage_from_json(data) or {}
        if ireq is not None:
            self.director.handle_response_received(None, ireq, endpoint, resp.status)
            if not streaming_body:
                # Join the engine-confirmed hit NOW, with the exact
                # prompt_tokens from the parsed usage, so the actual ratio
                # is token-exact and the x-decision-summary echo built
                # below shows predicted vs actual in one line. Streamed
                # responses join once in the terminal accounting instead
                # (their usage arrives with the final SSE event, and the
                # relayed hit headers are still in hand there).
                self.kv_ledger.observe_response(ireq, endpoint, resp.headers,
                                                usage)
            if ireq.decision is not None:
                # The relayed attempt is recorded BEFORE the response headers
                # are built so the x-decision-summary echo below agrees with
                # the /debug/decisions record (same attempt count/outcome).
                ireq.decision.record_attempt(
                    endpoint.metadata.address_port, "ok", status=resp.status)
                ireq.decision.finalize(
                    resp.status, destination=endpoint.metadata.address_port)

        out_headers = {
            H_DESTINATION_SERVED: endpoint.metadata.address_port,
            "content-type": resp.headers.get("content-type", "application/json"),
        }
        # Relay the engine-confirmed prefix-hit depth to the client beside
        # the served-endpoint echo (curl-level cache debugging; the full
        # predicted-vs-actual join is on /debug/decisions/<id>).
        for h in (H_KV_HIT_BLOCKS, H_KV_HIT_TOKENS):
            v = resp.headers.get(h)
            if v is not None:
                out_headers[h] = v
        if self.fleet is not None:
            out_headers[H_ROUTER_SHARD] = str(self.fleet.index)
        out_headers.update(self._decision_headers(ireq))  # x-debug-decision echo
        if ireq is not None and "x-session-token" in ireq.headers:
            # Session stickiness: return the (scheduling-stamped) encoded
            # token to the client (reference session_affinity.go ResponseBody).
            out_headers["x-session-token"] = ireq.headers["x-session-token"]
        first_byte_at: float | None = None
        # SLO-ledger observation: None when the kill-switch is off, so the
        # per-chunk hook below costs exactly one `is None` check.
        obs = ireq.outcome if ireq is not None else None

        # Per-pair KV-transfer landing at HEADER time — for streams too:
        # the pair row's headers travel with the status line, so waiting
        # for the terminal usage chunk (the pre-PR-18 behavior) left a
        # mid-incident stream's transfer invisible in /debug/transfers
        # until it finished — the gap PR 10's header-time-join hardening
        # noted. The `finally` below reuses this row; calling
        # _record_transfer there again would double-count the EWMA table.
        transfer: dict[str, Any] | None = None
        wf = getattr(ireq, "waterfall", None) if ireq is not None else None
        if ireq is not None:
            transfer = self._record_transfer(ireq, endpoint, resp.headers)
            if wf is not None:
                # Waterfall stage stamps (router/tails.py): every stage the
                # engine/sidecar measured rides the response headers, in
                # hand before any byte is relayed.
                v = finite_float_or_none(resp.headers.get(H_ENGINE_QUEUE))
                if v is not None and v > 0:
                    wf.engine_queue_ms = v
                v = finite_float_or_none(
                    resp.headers.get("x-prefill-duration-ms"))
                if v is not None and v > 0:
                    wf.prefill_ms = v
                v = finite_float_or_none(
                    resp.headers.get("x-kv-transfer-ms"))
                if v is not None and v > 0:
                    wf.kv_transfer_ms = v
                    # Pipelined P/D pulls stamp exposed (non-overlapped)
                    # time separately: the waterfall's kv_transfer stage
                    # holds ONLY the exposed cost so stage sums reconcile
                    # against TTFT, with the hidden remainder in
                    # overlap_ms (excluded from accounted_ms()).
                    ve = finite_float_or_none(
                        resp.headers.get("x-kv-transfer-exposed-ms"))
                    if ve is not None and 0 <= ve <= v:
                        wf.kv_transfer_ms = ve
                        wf.overlap_ms = v - ve
                v = finite_float_or_none(
                    resp.headers.get("x-kv-transfer-bytes"))
                if v is not None:
                    wf.kv_bytes = int(v)
                if transfer is not None:
                    wf.pair = f"{transfer['prefill']}→{transfer['decode']}"

        try:
            if streaming_body:
                ws = web.StreamResponse(status=resp.status, headers=out_headers)
                if stream_state is not None:
                    stream_state["started"] = True
                await ws.prepare(request)
                sse_carry = b""
                sse_tail = b""
                stream_hook = (self.director.handle_response_streaming
                               if ireq is not None
                               and self.cfg.response_streaming else None)
                # Upstream reads and client writes fail differently: an
                # upstream disconnect mid-stream is counted (and closed
                # cleanly — the 200 status line is already on the wire, so
                # no retry is possible and a traceback'd 500 would corrupt
                # the stream), while a client hanging up is routine and
                # must not pollute the upstream-abort metric or blame the
                # (healthy) endpoint in logs.
                upstream_iter = resp.content.iter_any()
                while True:
                    try:
                        chunk = await upstream_iter.__anext__()
                    except StopAsyncIteration:
                        break
                    except (aiohttp.ClientError, ConnectionResetError,
                            asyncio.TimeoutError) as e:
                        UPSTREAM_STREAM_ABORTED_TOTAL.inc()
                        if obs is not None:
                            obs.abort_reason = "upstream-stream-aborted"
                        log.warning("upstream stream aborted mid-relay from "
                                    "%s: %s",
                                    endpoint.metadata.address_port, e)
                        break
                    # TTFT counts the first *token-bearing* event: a
                    # role-only chat delta (no content) would otherwise
                    # flatter the metric. Events split across transport
                    # chunks are reassembled via the carry; unparseable
                    # events count (fail-open).
                    if first_byte_at is None:
                        found, sse_carry = _sse_scan_for_token(sse_carry, chunk)
                        if found:
                            first_byte_at = time.monotonic()
                            TTFT_SECONDS.labels(model_label).observe(first_byte_at - t_start)
                            if obs is not None:
                                # Reuses the monotonic read TTFT just paid.
                                obs.first_token(first_byte_at)
                    elif obs is not None and _token_bearing(chunk):
                        # Per-token inter-arrival capture: one clock read +
                        # a few adds per transport chunk (<1% of the 5ms
                        # token cadence; benchmarks/SLO_OBS.json). Framing
                        # chunks are not token arrivals — counting them
                        # would stretch last_token_at past the real last
                        # token and inflate actual TPOT into a false SLO
                        # miss.
                        obs.on_chunk()
                    if stream_hook is not None:
                        stream_hook(None, ireq, endpoint, chunk)
                    # Usage rides the FINAL SSE event: keep a bounded tail
                    # of COMPLETE events and scan once at stream end.
                    # Trimming on event boundaries (not a fixed byte
                    # window) means a large terminal usage-bearing event
                    # survives intact instead of being silently truncated
                    # to {}.
                    sse_tail = _sse_tail_append(sse_tail, chunk)
                    try:
                        await ws.write(chunk)
                    except (ConnectionResetError, ConnectionError) as e:
                        if obs is not None:
                            obs.abort_reason = "client-disconnect"
                        log.debug("client closed stream mid-relay: %s", e)
                        break
                usage = _usage_from_sse(sse_tail) or {}
                try:
                    await ws.write_eof()
                except (ConnectionResetError, ConnectionError):
                    pass  # client already gone
                return ws
            else:
                first_byte_at = time.monotonic()
                TTFT_SECONDS.labels(model_label).observe(first_byte_at - t_start)
                data = _rewrite_model_name(data, ireq, original_model)
                return web.Response(body=data, status=resp.status,
                                    headers=out_headers)
        finally:
            # Fully-consumed bodies return the connection to the keep-alive
            # pool; an abandoned stream closes it.
            resp.release()
            if ireq is not None:
                self.director.handle_response_complete(None, ireq, endpoint, usage)
                if self.flow_controller is not None:
                    # Backend capacity freed: wake saturated dispatch shards
                    # immediately instead of waiting out their backoff poll.
                    self.flow_controller.notify_capacity()
                # An exception unwinding through this finally (eviction /
                # client-disconnect CancelledError from the relay loop —
                # not in any caught tuple above) is an aborted stream: the
                # ledger must not stamp it as a met 200. The outer 499
                # complete() can't fix it later — complete is first-wins.
                if (obs is not None and obs.abort_reason is None
                        and sys.exc_info()[0] is not None):
                    obs.abort_reason = "cancelled-mid-stream"
                # Terminal ledger accounting: the per-pair KV-transfer row
                # landed at header time above (streams included), then the
                # SLO verdict (met/missed, or error for relayed 4xx/5xx
                # and aborts) and the waterfall close ride the same spot.
                # Streamed responses confirm the hit via the terminal usage
                # record (prompt_tokens_details.cached_tokens); the early
                # header-time join above already marked non-streamed ones
                # done, so this is one attribute check for them.
                self.kv_ledger.observe_response(ireq, endpoint, resp.headers,
                                                usage)
                self.slo_ledger.complete(ireq, status=resp.status,
                                         endpoint=endpoint, usage=usage,
                                         transfer=transfer)
                self.tails_obs.complete(ireq, status=resp.status,
                                        endpoint=endpoint, usage=usage)
                # Shadow judge (router/shadow.py): hand the measured
                # outcome to the counterfactual ledger — one attribute
                # check for unsampled requests, an enqueue otherwise.
                self.shadow_eval.observe_response(ireq, transfer=transfer,
                                                  status=resp.status)
                if (self.overload.enabled and resp.status < 400
                        and (obs is None or obs.abort_reason is None)):
                    # Served-outcome feedback for the overload controller:
                    # the healthy-e2e Little's-law anchor plus the
                    # observed-vs-predicted TTFT bias corrector. Aborted /
                    # evicted streams are excluded — their truncated e2e
                    # would drag the healthy anchor down and make the
                    # controller shed MORE exactly when eviction pressure
                    # is highest (a self-reinforcing loop).
                    self.overload.note_served(
                        ireq, (time.monotonic() - t_start) * 1e3)
                REQUEST_DURATION.labels(model_label).observe(time.monotonic() - t_start)
                if usage.get("prompt_tokens"):
                    INPUT_TOKENS.labels(model_label).observe(usage["prompt_tokens"])
                if usage.get("completion_tokens"):
                    OUTPUT_TOKENS.labels(model_label).observe(usage["completion_tokens"])

    def _record_transfer(self, ireq: InferenceRequest, endpoint,
                         resp_headers) -> dict[str, Any] | None:
        """Land the sidecar-relayed per-pair KV-transfer stats
        (``x-kv-transfer-ms``/``-bytes`` from the decode engine's measured
        pull, ``x-kv-prefiller`` for the pair identity, and the existing
        ``x-prefill-duration-ms``) into the datastore's EWMA table. Returns
        the row for the DecisionRecord outcome block, or None when the
        response carries no disagg telemetry."""
        pull = resp_headers.get("x-kv-transfer-ms")
        prefill = resp_headers.get("x-prefill-duration-ms")
        if not pull and not prefill:
            return None
        # Pair identity comes ONLY from the sidecar's served-prefiller stamp:
        # on fallback-to-decode the sidecar sends x-prefill-duration-ms (the
        # wasted walk time) with no x-kv-prefiller, and attributing that to
        # a routing-header candidate that never served would poison the
        # per-pair EWMAs the transfer-cost scorer will read.
        prefiller = resp_headers.get("x-kv-prefiller")
        if not prefiller:
            return None
        pull_ms = finite_float_or_none(pull)
        prefill_ms = finite_float_or_none(prefill)
        # Exposed (non-overlapped) pull cost from pipelined P/D pulls.
        # Clamped into [0, pull_ms] — both stamps ride the same engine
        # clock, so anything outside that range is a malformed relay, and
        # landing it would poison the exposed EWMA pair scorers read.
        exposed_ms = finite_float_or_none(
            resp_headers.get("x-kv-transfer-exposed-ms"))
        if exposed_ms is not None and (
                pull_ms is None or not 0 <= exposed_ms <= pull_ms):
            exposed_ms = None
        nbytes = finite_float_or_none(resp_headers.get("x-kv-transfer-bytes"))
        nbytes = int(nbytes) if nbytes is not None else None
        decode = endpoint.metadata.address_port
        self.datastore.transfers.record(prefiller, decode, pull_ms=pull_ms,
                                        nbytes=nbytes, prefill_ms=prefill_ms,
                                        exposed_ms=exposed_ms)
        if pull_ms is not None:
            KV_TRANSFER_MS.observe(pull_ms)
        if exposed_ms is not None:
            KV_TRANSFER_EXPOSED_MS.observe(exposed_ms)
        row: dict[str, Any] = {"prefill": prefiller, "decode": decode}
        if pull_ms is not None:
            row["pull_ms"] = pull_ms
        if exposed_ms is not None:
            row["exposed_ms"] = exposed_ms
        if nbytes is not None:
            row["bytes"] = nbytes
        if prefill_ms is not None:
            row["prefill_ms"] = prefill_ms
        return row

    async def metrics(self, request: web.Request) -> web.Response:
        return web.Response(body=generate_latest(REGISTRY),
                            content_type="text/plain", charset="utf-8")

    def _ready(self) -> bool:
        """Readiness couples to leadership (reference health.go:52-104): a
        follower replica reports not-ready so the LB routes to the leader;
        a draining replica reports not-ready so traffic moves off before
        SIGTERM teardown."""
        if self.draining:
            return False
        if self.elector is not None and not self.elector.is_leader:
            return False
        return self.datastore.pool_ready and bool(self.datastore.endpoint_list())

    async def health(self, request: web.Request) -> web.Response:
        ready = self._ready()
        follower = self.elector is not None and not self.elector.is_leader
        return web.json_response(
            {"status": "ok" if ready else ("follower" if follower else "not-ready"),
             "endpoints": len(self.datastore.endpoint_list())},
            status=200 if ready else 503)

    async def models(self, request: web.Request) -> web.Response:
        """Union of served models across the pool. Prefer the datastore's
        models-data-source attribute (heterogeneous pools serve different
        models — reading one endpoint under-reports); fall back to live
        fetches from every endpoint when the source isn't configured."""
        from .datalayer.models_source import endpoint_models

        eps = self.datastore.endpoint_list()
        merged: dict[str, dict] = {}
        unpolled = []
        for ep in eps:
            models = endpoint_models(ep)
            if models is None:
                unpolled.append(ep)
                continue
            for m in models:
                merged.setdefault(m["id"], {"id": m["id"], "object": "model",
                                            **({"parent": m["parent"]}
                                               if m.get("parent") else {})})
        if unpolled:
            # Live-fetch fallback (models-data-source not configured). The
            # fan-out is pool-wide, so cache it briefly: a client polling
            # /v1/models must not multiply into N upstream requests/s.
            now = time.monotonic()
            expiry, cached = self._models_fallback_cache
            if now >= expiry:
                import asyncio as _aio

                async def fetch(ep):
                    try:
                        r = await self._client.get(ep.metadata.url + "/v1/models")
                        return (r.json().get("data") or []) if r.status_code == 200 else []
                    except Exception:
                        return []

                cached = [m for data in
                          await _aio.gather(*[fetch(ep) for ep in unpolled])
                          for m in data if isinstance(m, dict) and m.get("id")]
                self._models_fallback_cache = (now + 5.0, cached)
            for m in cached:
                merged.setdefault(str(m["id"]), m)
        return web.json_response({"object": "list",
                                  "data": sorted(merged.values(),
                                                 key=lambda m: m["id"])})


def _rewrite_model_name(data: bytes, ireq: InferenceRequest | None,
                        original_model: str) -> bytes:
    """Rewrite "model" in responses back to the client-facing name
    (reference server.go:471-485)."""
    if ireq is None or not original_model or ireq.target_model == original_model:
        return data
    try:
        doc = json.loads(data)
        if isinstance(doc, dict) and "model" in doc:
            doc["model"] = original_model
            return json.dumps(doc).encode()
    except Exception:
        pass
    return data


def _token_bearing(chunk: bytes) -> bool:
    """Cheap streaming-relay classification: count the transport chunk as a
    token arrival unless it is pure framing — keep-alive comment, blank
    heartbeat, or the [DONE] sentinel. iter_any() chunks can split an SSE
    event mid-separator, so leading CR/LF is stripped before classifying:
    a token event arriving as '\\ndata: …' must still advance the TPOT
    clock. (A usage-only terminal event still counts: telling it apart
    needs a JSON parse the per-chunk budget can't afford, and engines emit
    it back-to-back with the final token.)"""
    if chunk[:1] in (b"\n", b"\r"):
        chunk = chunk.lstrip(b"\r\n")
    b0 = chunk[:1]
    return bool(b0) and b0 != b":" and not chunk.startswith(b"data: [DONE]")


def _usage_from_json(data: bytes) -> dict[str, int] | None:
    try:
        doc = json.loads(data)
        u = doc.get("usage")
        return u if isinstance(u, dict) else None
    except Exception:
        return None


def _sse_scan_for_token(carry: bytes, chunk: bytes) -> tuple[bool, bytes]:
    """Scan complete SSE lines in ``carry + chunk`` for generated output
    (completion text or a chat delta with content) — role-only/handshake
    deltas don't count toward TTFT. Returns (saw_token, new_carry) where
    new_carry is the trailing partial line, so events split across transport
    chunks are reassembled instead of misclassified. Complete-but-unparseable
    data lines count, so unknown engines keep the old first-byte semantics."""
    data = carry + chunk
    lines = data.split(b"\n")
    carry = lines.pop()  # trailing partial line ('' when chunk ends on \n)
    if len(carry) > 1 << 20:
        # A megabyte with no newline is not an SSE event stream; fail open
        # rather than buffer unboundedly.
        return True, b""
    for line in lines:
        line = line.rstrip(b"\r")
        if not line.startswith(b"data: ") or line == b"data: [DONE]":
            continue
        try:
            doc = json.loads(line[6:])
        except Exception:
            return True, carry
        for choice in doc.get("choices") or []:
            if choice.get("text"):
                return True, carry
            delta = choice.get("delta") or {}
            if delta.get("content") or delta.get("tool_calls"):
                return True, carry
        if "choices" not in doc:
            return True, carry  # not an OpenAI chunk shape — fail open
    return False, carry


# Rolling-tail target for end-of-stream usage extraction: the terminal usage
# event plus the [DONE] line are a few hundred bytes; 4 KiB leaves wide
# margin without per-chunk memory growth. Trimming respects event boundaries,
# so one oversized trailing event may exceed the target (bounded by the hard
# cap — a tail that big with no event boundary is not a sane SSE stream).
_USAGE_TAIL = 4096
_USAGE_TAIL_HARD = 1 << 20


def _sse_tail_append(tail: bytes, chunk: bytes) -> bytes:
    """Append a transport chunk to the rolling SSE tail, trimming whole
    events from the front. The tail always starts at an event boundary (or
    the stream start), so the final usage-bearing event is never cut mid-
    event no matter how large it is, up to the 1 MiB fail-safe."""
    tail += chunk
    if len(tail) <= _USAGE_TAIL:
        return tail
    # Resume at the start of the event CONTAINING the window edge: whole
    # events ahead of it drop, but an event straddling (or overflowing) the
    # window is kept from its own start — never cut mid-event. SSE permits
    # LF or CRLF event terminators; honor both.
    edge = len(tail) - _USAGE_TAIL
    lf = tail.rfind(b"\n\n", 0, edge)
    crlf = tail.rfind(b"\r\n\r\n", 0, edge)
    start = max(lf + 2 if lf != -1 else 0,
                crlf + 4 if crlf != -1 else 0)
    if start:
        tail = tail[start:]
    if len(tail) > _USAGE_TAIL_HARD:
        tail = tail[-_USAGE_TAIL_HARD:]
    return tail


def _usage_from_sse(tail: bytes) -> dict[str, int] | None:
    """Extract the usage record from the final bytes of an SSE stream. The
    caller hands the end-of-stream tail, so events split across transport
    chunks arrive reassembled here (a truncated leading line simply fails
    the JSON parse and is skipped)."""
    if b'"usage"' not in tail:
        return None
    usage = None
    for line in tail.split(b"\n"):
        line = line.rstrip(b"\r")
        if line.startswith(b"data: ") and line != b"data: [DONE]":
            try:
                doc = json.loads(line[6:])
                u = doc.get("usage")
                if isinstance(u, dict):
                    usage = u  # last one wins (the terminal event's record)
            except Exception:
                continue
    return usage


def build_gateway(config_text: str | None, *, host: str = "127.0.0.1",
                  port: int = 8081, poll_interval: float = 0.05,
                  grpc_health_port: int | None = None,
                  grpc_ext_proc_port: int | None = None,
                  lease_path: str | None = None,
                  config_watch_path: str | None = None,
                  kube: dict | None = None,
                  secure_serving: bool = False,
                  cert_path: str | None = None,
                  enable_cert_reload: bool = False,
                  fleet=None) -> Gateway:
    datastore = Datastore()
    dl_runtime = DataLayerRuntime(datastore, poll_interval=poll_interval)
    handle = Handle(datastore=datastore, dl_runtime=dl_runtime)
    import llm_d_inference_scheduler_tpu.router.plugins  # noqa: F401 (register)
    import llm_d_inference_scheduler_tpu.router.plugins.saturation  # noqa: F401
    import llm_d_inference_scheduler_tpu.router.requestcontrol.producers  # noqa: F401
    cfg = load_config(config_text, handle)
    # Endpoint lifecycle plugins (per-pod subscribers, LRU teardown — the
    # reference's EndpointExtractors, runtime.go:361) ride datastore events.
    # Fleet followers skip them: a per-pod SSE subscription in every worker
    # would put the N x engine load back that the snapshot IPC removes.
    # Engine-CONFIRMED kv-event state (the precise scorer's KvBlockIndex)
    # reaches followers anyway: with `fleet.replication` (default on) the
    # leader appends confirmed-index deltas + periodic checkpoints to the
    # snapshot stream and the follower's SnapshotSubscriber applies them
    # into its own index (docs/performance.md §Scale-out). A promoted
    # follower registers these plugins at /fleet/promote time instead
    # (leader re-election, docs/resilience.md §Fleet failover).
    if fleet is None or fleet.runs_datalayer:
        for plugin in cfg.plugins_by_name.values():
            if hasattr(plugin, "endpoint_added") or hasattr(plugin, "endpoint_removed"):
                dl_runtime.register_lifecycle(plugin)
    kube_binding = None
    # Endpoint discovery needs a pool to scope the pod selector; a kube dict
    # without one is lease-only (HA election against the API server while
    # endpoints still come from the config file). The CLI rejects an
    # api-url with neither pool nor lease, so nothing silently no-ops.
    if kube and kube.get("pool_name"):
        from .kube import KubeApiClient, KubeBinding

        if config_watch_path is not None:
            # Two writers calling datastore.resync() would flap the endpoint
            # set between the file pool and the k8s pool on every event.
            log.warning("--watch-config ignored: the k8s binding owns the "
                        "endpoint set when --kube-pool-name is given")
            config_watch_path = None
        client = KubeApiClient(kube["api_url"],
                               token_path=kube.get("token_path"))
        kube_binding = KubeBinding(datastore, client,
                                   kube.get("namespace", "default"),
                                   pool_name=kube.get("pool_name"))
    kube_elector = None
    if kube and kube.get("lease_name"):
        from .kube import KubeApiClient, KubeLeaseElector

        if lease_path is not None:
            log.warning("--ha-lease-path ignored: Lease-object election "
                        "active (--kube-lease-name)")
            lease_path = None
        # Separate client: the elector must keep renewing even when the
        # informers' connection pool is saturated mid-relist.
        kube_elector = KubeLeaseElector(
            KubeApiClient(kube["api_url"], token_path=kube.get("token_path")),
            kube.get("namespace", "default"), kube["lease_name"])
    return Gateway(cfg, datastore, dl_runtime, host=host, port=port,
                   grpc_health_port=grpc_health_port,
                   grpc_ext_proc_port=grpc_ext_proc_port,
                   kube_binding=kube_binding,
                   lease_path=lease_path,
                   kube_elector=kube_elector,
                   config_watch_path=config_watch_path,
                   secure_serving=secure_serving,
                   cert_path=cert_path,
                   enable_cert_reload=enable_cert_reload,
                   fleet=fleet)


async def run_gateway(gw: Gateway, drain_timeout_s: float = 30.0):
    """Serve until SIGTERM/SIGINT, then drain: readiness flips not-ready
    (LB + ext-proc health pull this replica; stopping the elector releases
    leadership so a standby takes over fast), in-flight proxied requests
    finish bounded by ``drain_timeout_s``, then the gateway stops."""
    import signal

    await gw.start()
    stop_ev = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, stop_ev.set)
        except (NotImplementedError, RuntimeError):
            pass
    try:
        await stop_ev.wait()
        gw.draining = True
        if gw.elector is not None:
            await gw.elector.stop()
            gw.elector = None
        log.info("SIGTERM: draining %d in-flight requests", gw._inflight)
        deadline = loop.time() + drain_timeout_s
        while loop.time() < deadline and gw._inflight > 0:
            await asyncio.sleep(0.25)
        if gw._inflight:
            log.warning("drain timeout with %d requests still in flight; "
                        "closing", gw._inflight)
    except asyncio.CancelledError:
        pass
    await gw.stop()


def main(argv: list[str] | None = None):
    import argparse

    p = argparse.ArgumentParser(description="TPU inference router gateway (standalone EPP)")
    p.add_argument("--config-file", default=None)
    p.add_argument("--config-text", default=None)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8081)
    p.add_argument("--endpoints", default=None,
                   help="comma-separated host:port[:role] static pool "
                        "(overrides config pool)")
    p.add_argument("--grpc-ext-proc-port", type=int, default=None,
                   help="serve the Envoy ext-proc FULL_DUPLEX_STREAMED gRPC "
                        "service on this port (the EPP wire surface)")
    p.add_argument("--grpc-health-port", type=int, default=None,
                   help="serve grpc.health.v1.Health on this port")
    p.add_argument("--ha-lease-path", default=None,
                   help="enable leader election via this shared lease file; "
                        "followers report not-ready until they take over")
    p.add_argument("--watch-config", action="store_true",
                   help="reconcile pool/objectives/rewrites live when "
                        "--config-file changes on disk")
    p.add_argument("--kube-api-url", default=None,
                   help="k8s API server base URL; combine with "
                        "--kube-pool-name for the list+watch endpoint "
                        "binding and/or --kube-lease-name for Lease-object "
                        "HA election")
    p.add_argument("--kube-namespace", default="default")
    p.add_argument("--kube-pool-name", default=None,
                   help="InferencePool name to watch for selector/ports")
    p.add_argument("--kube-token-path", default=None,
                   help="bearer token file (defaults to the in-cluster "
                        "service-account path when unset)")
    p.add_argument("--kube-lease-name", default=None,
                   help="coordination.k8s.io/v1 Lease name for HA leader "
                        "election (reference id shape: "
                        "epp-<ns>-<pool>.llm-d.ai); requires --kube-api-url "
                        "and supersedes --ha-lease-path")
    p.add_argument("--secure-serving", action="store_true",
                   help="serve HTTP and ext-proc gRPC over TLS; without "
                        "--cert-path a self-signed certificate is minted "
                        "(runserver.go:136-171)")
    p.add_argument("--cert-path", default=None,
                   help="directory holding tls.crt + tls.key (the "
                        "kubernetes.io/tls Secret mount layout)")
    p.add_argument("--enable-cert-reload", action="store_true",
                   help="re-read --cert-path on change so cert-manager "
                        "rotations apply without a restart (certs.go)")
    p.add_argument("--drain-timeout", type=float, default=30.0,
                   help="seconds to let in-flight proxied requests finish "
                        "after SIGTERM (readiness flips not-ready and the "
                        "lease is released immediately)")
    p.add_argument("--fleet-workers", type=int, default=None,
                   help="override fleet.workers: >1 runs the multi-process "
                        "sharded fleet (router/fleet.py) instead of a "
                        "single gateway process")
    args = p.parse_args(argv)

    text = args.config_text
    if args.config_file:
        with open(args.config_file) as f:
            text = f.read()

    # Multi-process fleet delegation (router/fleet.py): `fleet.workers > 1`
    # (or --fleet-workers) spawns N full gateway workers behind one port.
    # workers: 1 — the default — continues below, bit-identical to the
    # pre-fleet router.
    from .config.loader import load_raw_config
    from .fleet import FleetConfig

    fleet_spec = dict(load_raw_config(text).fleet)
    if args.fleet_workers is not None:
        fleet_spec["workers"] = args.fleet_workers
    fleet_cfg = FleetConfig.from_spec(fleet_spec)
    if fleet_cfg.workers > 1:
        unsupported = {
            "--grpc-ext-proc-port": args.grpc_ext_proc_port,
            "--grpc-health-port": args.grpc_health_port,
            "--kube-api-url": args.kube_api_url,
            "--ha-lease-path": args.ha_lease_path,
            "--secure-serving": args.secure_serving or None,
            "--watch-config": args.watch_config or None,
            "--endpoints": args.endpoints,
        }
        bad = [flag for flag, v in unsupported.items() if v]
        if bad:
            p.error(f"fleet mode (workers={fleet_cfg.workers}) does not "
                    f"support {', '.join(bad)} yet; run workers: 1 or drop "
                    "the flag(s)")
        from .fleet import run_fleet

        logging.basicConfig(level=logging.INFO)
        run_fleet(text, host=args.host, port=args.port, fleet=fleet_cfg,
                  drain_timeout_s=args.drain_timeout)
        return

    from .kube import DEFAULT_TOKEN_PATH

    kube = None
    if args.kube_api_url:
        if not (args.kube_pool_name or args.kube_lease_name):
            p.error("--kube-api-url needs --kube-pool-name (endpoint "
                    "discovery) and/or --kube-lease-name (HA election)")
        kube = {"api_url": args.kube_api_url,
                "namespace": args.kube_namespace,
                "pool_name": args.kube_pool_name,
                "lease_name": args.kube_lease_name,
                "token_path": args.kube_token_path or DEFAULT_TOKEN_PATH}
    elif args.kube_lease_name:
        p.error("--kube-lease-name requires --kube-api-url")
    gw = build_gateway(text, host=args.host, port=args.port,
                       grpc_health_port=args.grpc_health_port,
                       grpc_ext_proc_port=args.grpc_ext_proc_port,
                       lease_path=args.ha_lease_path,
                       config_watch_path=(args.config_file
                                          if args.watch_config else None),
                       kube=kube,
                       secure_serving=args.secure_serving,
                       cert_path=args.cert_path,
                       enable_cert_reload=args.enable_cert_reload)
    if args.endpoints:
        from .framework.datalayer import EndpointMetadata
        metas = []
        for spec in args.endpoints.split(","):
            parts = spec.strip().split(":")
            labels = {"llm-d.ai/role": parts[2]} if len(parts) > 2 else {}
            metas.append(EndpointMetadata(name=spec, address=parts[0],
                                          port=int(parts[1]), labels=labels))
        gw.cfg.static_endpoints = metas

    logging.basicConfig(level=logging.INFO)

    asyncio.run(run_gateway(gw, drain_timeout_s=args.drain_timeout))


if __name__ == "__main__":
    main()
