"""Flow-control-backed admission (reference: requestcontrol/admission.go:149-237
FlowControlAdmissionController): adapts the inference request into a
FlowControlRequest, blocks in EnqueueAndWait, and maps QueueOutcome to
client-facing error codes with x-removal-reason semantics."""

from __future__ import annotations

import time
from typing import Any

from ..framework.datalayer import Endpoint
from ..framework.scheduling import InferenceRequest
from ..requestcontrol.admission import AdmissionError
from .controller import FlowController
from .types import FlowControlRequest, FlowKey, QueueOutcome

FAIRNESS_HEADER = "x-gateway-inference-fairness-id"
DEFAULT_FLOW = "default-flow"  # reference handlers/request.go:37-65

_OUTCOME_ERRORS = {
    QueueOutcome.REJECTED_CAPACITY: (429, "queue capacity exceeded"),
    QueueOutcome.REJECTED_OTHER: (429, "rejected by flow control"),
    QueueOutcome.EVICTED_TTL: (429, "queue wait exceeded TTL"),
    QueueOutcome.EVICTED_CONTEXT_CANCELLED: (499, "client cancelled while queued"),
    QueueOutcome.EVICTED_SHED: (429, "shed under saturation"),
}


class FlowControlAdmissionController:
    def __init__(self, controller: FlowController, evictor: Any = None):
        self.controller = controller
        self.evictor = evictor

    async def admit(self, ctx: Any, request: InferenceRequest,
                    endpoints: list[Endpoint]) -> None:
        flow_id = request.headers.get(FAIRNESS_HEADER, DEFAULT_FLOW)
        item = FlowControlRequest(
            request_id=request.request_id,
            flow_key=FlowKey(flow_id, request.objectives.priority),
            size_bytes=max(request.request_size_bytes, 1),
        )
        rec = request.decision  # decision flight recorder (may be None)
        obs = getattr(request, "outcome", None)  # SLO ledger (may be None)
        t0 = time.monotonic() if rec is not None or obs is not None else 0.0
        retried_after_shed = False
        outcome = await self.controller.enqueue_and_wait(item)
        if (outcome == QueueOutcome.REJECTED_CAPACITY
                and request.objectives.priority >= 0):
            # Make room: shed queued sheddable items (frees queue capacity for
            # the retry) and evict an in-flight sheddable request (frees
            # backend capacity so the queue drains).
            freed_queue_slot = self.controller.shed_queued(1) > 0
            if self.evictor is not None:
                self.evictor.evict_n(1)
            if freed_queue_slot:
                retried_after_shed = True
                retry = FlowControlRequest(
                    request_id=request.request_id,
                    flow_key=item.flow_key,
                    size_bytes=item.size_bytes)
                outcome = await self.controller.enqueue_and_wait(retry)
        if rec is not None or obs is not None:
            queue_ms = (time.monotonic() - t0) * 1e3
            if rec is not None:
                rec.record_admission(
                    "flow-control", outcome.value, flow_id=flow_id,
                    priority_band=request.objectives.priority,
                    queue_ms=queue_ms,
                    retried_after_shed=retried_after_shed)
            if obs is not None:
                # The SLO ledger's queue-time component: admission wait is
                # part of the client-observed TTFT budget.
                obs.queue_ms = queue_ms
        if outcome != QueueOutcome.DISPATCHED:
            code, reason = _OUTCOME_ERRORS.get(outcome, (429, outcome.value))
            raise AdmissionError(code, reason)
