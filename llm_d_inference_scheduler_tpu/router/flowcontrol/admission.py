"""Flow-control-backed admission (reference: requestcontrol/admission.go:149-237
FlowControlAdmissionController): adapts the inference request into a
FlowControlRequest, blocks in EnqueueAndWait, and maps QueueOutcome to
client-facing error codes with x-removal-reason semantics."""

from __future__ import annotations

import time
from typing import Any

from ..framework.datalayer import Endpoint
from ..framework.scheduling import InferenceRequest
from ..overload import HINT_ATTR
from ..requestcontrol.admission import AdmissionError
from .controller import FlowController
from .types import FlowControlRequest, FlowKey, QueueOutcome

FAIRNESS_HEADER = "x-gateway-inference-fairness-id"
DEFAULT_FLOW = "default-flow"  # reference handlers/request.go:37-65

_OUTCOME_ERRORS = {
    QueueOutcome.REJECTED_CAPACITY: (429, "queue capacity exceeded"),
    QueueOutcome.REJECTED_OTHER: (429, "rejected by flow control"),
    QueueOutcome.EVICTED_TTL: (429, "queue wait exceeded TTL"),
    QueueOutcome.EVICTED_CONTEXT_CANCELLED: (499, "client cancelled while queued"),
    QueueOutcome.EVICTED_SHED: (429, "shed under saturation"),
    QueueOutcome.EVICTED_UNMEETABLE: (
        429, "shed in queue: remaining SLO budget below predicted service time"),
}


class FlowControlAdmissionController:
    def __init__(self, controller: FlowController, evictor: Any = None,
                 overload: Any = None, shard: int | None = None):
        self.controller = controller
        self.evictor = evictor
        # OverloadController (router/overload.py) — None or disabled keeps
        # every path here bit-identical to the pre-overload behavior.
        self.overload = overload
        # Fleet shard ownership (router/fleet.py): this worker's shard
        # index, stamped into every admission record so /debug/decisions
        # shows which worker's flow-control queues owned the flow. None in
        # the single-process router (no extra field on the record).
        self.shard = shard

    def _make_item(self, request: InferenceRequest,
                   flow_key: FlowKey) -> FlowControlRequest:
        item = FlowControlRequest(
            request_id=request.request_id,
            flow_key=flow_key,
            size_bytes=max(request.request_size_bytes, 1))
        hint = getattr(request, HINT_ATTR, None)
        if hint is not None:
            # Overload stamp: marks the queued item eligible for
            # predicted-unmeetable eviction (controller.py sweep).
            item.slo_ttft_ms = hint.slo_ttft_ms
            item.predicted_service_ms = hint.service_ttft_ms
        return item

    async def admit(self, ctx: Any, request: InferenceRequest,
                    endpoints: list[Endpoint]) -> None:
        flow_id = request.headers.get(FAIRNESS_HEADER, DEFAULT_FLOW)
        flow_key = FlowKey(flow_id, request.objectives.priority)
        item = self._make_item(request, flow_key)
        rec = request.decision  # decision flight recorder (may be None)
        obs = getattr(request, "outcome", None)  # SLO ledger (may be None)
        wf = getattr(request, "waterfall", None)  # tails.py (may be None)
        t0 = (time.monotonic()
              if rec is not None or obs is not None or wf is not None
              else 0.0)
        retried_after_shed = False
        shed_victims: list[str] = []
        outcome = await self.controller.enqueue_and_wait(item)
        if (outcome == QueueOutcome.REJECTED_CAPACITY
                and request.objectives.priority >= 0):
            # Make room: shed queued sheddable items (frees queue capacity for
            # the retry) and evict an in-flight sheddable request (frees
            # backend capacity so the queue drains). The victims' request ids
            # land in THIS request's admission record so /debug/decisions
            # explains who was sacrificed and why.
            queue_victims = self.controller.shed_queued(1)
            if self.evictor is not None:
                shed_victims = queue_victims + self.evictor.evict_n(1)
            else:
                shed_victims = queue_victims
            if queue_victims:
                # Retry only when a QUEUE slot was actually freed (an
                # in-flight eviction frees backend capacity, not the queue
                # capacity this rejection was about).
                retried_after_shed = True
                item = self._make_item(request, flow_key)
                outcome = await self.controller.enqueue_and_wait(item)
        if rec is not None or obs is not None or wf is not None:
            queue_ms = (time.monotonic() - t0) * 1e3
            if rec is not None:
                rec.record_admission(
                    "flow-control", outcome.value, flow_id=flow_id,
                    priority_band=request.objectives.priority,
                    queue_ms=queue_ms,
                    retried_after_shed=retried_after_shed,
                    shed_victims=shed_victims or None,
                    shard=self.shard)
            if obs is not None:
                # The SLO ledger's queue-time component: admission wait is
                # part of the client-observed TTFT budget.
                obs.queue_ms = queue_ms
            if wf is not None:
                # The waterfall's queue stage (router/tails.py).
                wf.queue_ms = queue_ms
        if outcome != QueueOutcome.DISPATCHED:
            code, reason = _OUTCOME_ERRORS.get(outcome, (429, outcome.value))
            if (outcome == QueueOutcome.EVICTED_UNMEETABLE
                    and self.overload is not None):
                # In-queue shed: explain it like an admission-time shed —
                # a shed block on the record (predicted vs SLO vs drain)
                # plus a finite Retry-After, and the distinct ledger
                # verdict.
                overshoot = (item.predicted_service_ms
                             + (time.monotonic() - item.enqueue_time) * 1e3
                             - item.slo_ttft_ms)
                retry_after = self.overload.retry_after_s(overshoot)
                if rec is not None and hasattr(rec, "record_shed"):
                    # escalate: a degraded-then-admitted request may already
                    # carry its degrade block — the eviction supersedes it.
                    rec.record_shed({
                        "action": "evict_unmeetable",
                        "predicted_ttft_ms": round(
                            item.predicted_service_ms, 3),
                        "slo_ttft_ms": item.slo_ttft_ms,
                        "queue_wait_ms": round(
                            (time.monotonic() - item.enqueue_time) * 1e3, 3),
                        "drain_rate_rps": round(
                            self.overload.drain.rate(), 3),
                        "reason": "queue_unmeetable",
                        "retry_after_s": retry_after,
                    }, escalate=True)
                raise AdmissionError(code, reason,
                                     retry_after_s=retry_after, shed=True)
            if (outcome == QueueOutcome.EVICTED_SHED
                    and self.overload is not None):
                # A capacity-shed victim is equally a deliberate control
                # action that consumed no serving capacity: under overload
                # control it gets the same distinct ledger verdict and a
                # finite Retry-After as the other shed paths (with the
                # kill-switch off, self.overload is None and the pre-PR
                # "error" verdict is bit-identical).
                raise AdmissionError(code, reason,
                                     retry_after_s=self.overload.retry_after_s(),
                                     shed=True)
            raise AdmissionError(code, reason)
