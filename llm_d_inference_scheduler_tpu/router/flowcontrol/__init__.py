from .types import FlowKey, QueueOutcome, FlowControlRequest
from .controller import FlowController, FlowControlConfig
from .admission import FlowControlAdmissionController

__all__ = ["FlowKey", "QueueOutcome", "FlowControlRequest", "FlowController",
           "FlowControlConfig", "FlowControlAdmissionController"]
