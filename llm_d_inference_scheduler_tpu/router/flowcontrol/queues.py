"""Queue plugins (reference: flowcontrol/framework/plugins/queue):
listqueue (FIFO) and maxminheap (priority heap ordered by a comparator)."""

from __future__ import annotations

import heapq
import itertools
from collections import deque
from typing import Callable

from .types import FlowControlRequest


class ListQueue:
    """FIFO linked-list queue (reference listqueue)."""

    NAME = "listqueue"

    def __init__(self):
        self._dq: deque[FlowControlRequest] = deque()
        self.bytes = 0

    def add(self, item: FlowControlRequest) -> None:
        self._dq.append(item)
        self.bytes += item.size_bytes

    def peek(self) -> FlowControlRequest | None:
        return self._dq[0] if self._dq else None

    def pop(self) -> FlowControlRequest | None:
        if not self._dq:
            return None
        item = self._dq.popleft()
        self.bytes -= item.size_bytes
        return item

    def remove(self, item: FlowControlRequest) -> bool:
        try:
            self._dq.remove(item)
        except ValueError:
            return False
        self.bytes -= item.size_bytes
        return True

    def items(self) -> list[FlowControlRequest]:
        """Snapshot of queued items (TTL sweep support)."""
        return list(self._dq)

    def __len__(self):
        return len(self._dq)


class MaxMinHeap:
    """Heap queue ordered by a key function (reference maxminheap); backs the
    EDF / SLO-deadline ordering policies."""

    NAME = "maxminheap"

    def __init__(self, key: Callable[[FlowControlRequest], float]):
        self._key = key
        self._heap: list[tuple[float, int, FlowControlRequest]] = []
        self._removed: set[int] = set()
        self._counter = itertools.count()
        self.bytes = 0
        self._live = 0

    def add(self, item: FlowControlRequest) -> None:
        heapq.heappush(self._heap, (self._key(item), next(self._counter), item))
        self.bytes += item.size_bytes
        self._live += 1

    def _prune(self) -> None:
        while self._heap and id(self._heap[0][2]) in self._removed:
            _, _, item = heapq.heappop(self._heap)
            self._removed.discard(id(item))

    def peek(self) -> FlowControlRequest | None:
        self._prune()
        return self._heap[0][2] if self._heap else None

    def pop(self) -> FlowControlRequest | None:
        self._prune()
        if not self._heap:
            return None
        _, _, item = heapq.heappop(self._heap)
        self.bytes -= item.size_bytes
        self._live -= 1
        return item

    def remove(self, item: FlowControlRequest) -> bool:
        for _, _, it in self._heap:
            if it is item and id(it) not in self._removed:
                self._removed.add(id(it))
                self.bytes -= item.size_bytes
                self._live -= 1
                return True
        return False

    def items(self) -> list[FlowControlRequest]:
        """Snapshot of live queued items (TTL sweep support)."""
        return [it for _, _, it in self._heap if id(it) not in self._removed]

    def __len__(self):
        return self._live
