"""Fairness (inter-flow) and ordering (intra-flow) policies.

Reference: framework/plugins/flowcontrol/{fairness,ordering} — fairness picks
which flow dispatches next (global-strict: highest priority band, round-robin
within; round-robin: cycle all flows), ordering picks which item within a flow
(fcfs; edf earliest-deadline-first; slo-deadline).
"""

from __future__ import annotations

import time
from typing import Callable

from .queues import ListQueue, MaxMinHeap
from .types import FlowControlRequest, FlowKey


# ---- ordering policies -------------------------------------------------

class FcfsOrdering:
    NAME = "fcfs-ordering-policy"

    def make_queue(self):
        return ListQueue()


class EdfOrdering:
    """Earliest deadline first; items without a deadline sort last."""

    NAME = "edf-ordering-policy"

    def make_queue(self):
        return MaxMinHeap(key=lambda it: it.deadline if it.deadline is not None
                          else float("inf"))


class SloDeadlineOrdering:
    """Least slack first. Slack = deadline − now, and `now` is common to every
    queued item at dispatch time, so ranking by absolute deadline IS the
    least-slack order; kept as a distinct type for config parity with the
    reference's slo-deadline-ordering-policy."""

    NAME = "slo-deadline-ordering-policy"

    def make_queue(self):
        return MaxMinHeap(key=lambda it: it.deadline if it.deadline is not None
                          else float("inf"))


ORDERING_POLICIES = {p.NAME: p for p in (FcfsOrdering, EdfOrdering, SloDeadlineOrdering)}


# ---- fairness policies -------------------------------------------------

class GlobalStrictFairness:
    """Strict priority bands; round-robin among flows within the top band
    (reference global-strict-fairness-policy)."""

    NAME = "global-strict-fairness-policy"

    def __init__(self):
        self._rr: dict[int, int] = {}

    def pick_flow(self, queues: dict[FlowKey, object]) -> FlowKey | None:
        non_empty = [k for k, q in queues.items() if len(q)]
        if not non_empty:
            return None
        top = max(k.priority for k in non_empty)
        band = sorted([k for k in non_empty if k.priority == top],
                      key=lambda k: k.flow_id)
        idx = self._rr.get(top, 0) % len(band)
        self._rr[top] = idx + 1
        return band[idx]


class RoundRobinFairness:
    """Cycle through all non-empty flows regardless of priority
    (reference round-robin-fairness-policy)."""

    NAME = "round-robin-fairness-policy"

    def __init__(self):
        self._idx = 0

    def pick_flow(self, queues: dict[FlowKey, object]) -> FlowKey | None:
        non_empty = sorted([k for k, q in queues.items() if len(q)],
                           key=lambda k: (k.priority, k.flow_id))
        if not non_empty:
            return None
        key = non_empty[self._idx % len(non_empty)]
        self._idx += 1
        return key


FAIRNESS_POLICIES = {p.NAME: p for p in (GlobalStrictFairness, RoundRobinFairness)}


def decayed_priority(priority: int, enqueue_time: float, now: float,
                     decay_per_s: float) -> float:
    """Age-decayed effective priority for overload victim selection
    (router/overload.py): a queued sheddable item loses ``decay_per_s``
    bands per second of queue age, so long-waiting work ranks below fresh
    feasible work when the shed path picks a victim. Lower = shed first."""
    return priority - decay_per_s * max(now - enqueue_time, 0.0)
