"""FlowController: sharded queuing with fairness/ordering policies.

Reference shape (pkg/epp/flowcontrol/{controller,registry} — SURVEY §2.6):
- `EnqueueAndWait` is the single public entry: callers block until the request
  is dispatched, rejected (capacity), or evicted (TTL / caller cancelled).
- Work is distributed over shard processors; each shard is a single-owner
  actor (here: one asyncio task — the event loop provides the actor model the
  reference builds with goroutines) running the enqueue→capacity→dispatch
  cycle: inter-flow fairness picks the flow, intra-flow ordering picks the
  item.
- Dispatch is gated by a saturation signal: items drain while the pool has
  headroom, pause while saturated (the reference's saturation-detector
  coupling), with a small poll interval.
- Connection-leasing note: the reference registry pins flows with
  reference-counted leases (registry/leasing.go) because its enqueue path
  and GC run on different goroutines. Here each shard is a single-owner
  asyncio actor — enqueue, dispatch and GC all mutate shard state on the
  shard's own task, and GC only collects EMPTY queues idle past the window,
  so the lease ceremony is structurally unnecessary (same guarantee, no
  refcounts). Dynamic priority bands are likewise implicit: band state is
  derived per-priority from live queues, so an idle band vanishes with its
  last flow (the reference needs a second 10-min GC for its materialized
  band objects, config.go:48-60).
- Per-priority-band byte capacity (default 1 GB) and optional global caps
  (registry/config.go:40-125).
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import time
from typing import Any, Callable

from ..metrics import (
    FLOW_CONTROL_QUEUE_SECONDS,
    FLOW_CONTROL_QUEUE_SIZE,
    SCHED_BATCH_SIZE,
)
from ..overload import DISABLED_QUEUE_POLICY
from .policies import (
    FAIRNESS_POLICIES,
    ORDERING_POLICIES,
    FcfsOrdering,
    GlobalStrictFairness,
    decayed_priority,
)
from .types import FlowControlRequest, FlowKey, QueueOutcome

log = logging.getLogger("router.flowcontrol")

DEFAULT_BAND_CAPACITY_BYTES = 1 << 30  # reference registry/config.go:48-60
DEFAULT_TTL_S = 30.0
DISPATCH_POLL_S = 0.01
SATURATION_BACKOFF_MAX_S = 0.25  # saturated-poll ceiling (nudges wake sooner)
DEFAULT_FLOW_GC_S = 300.0        # reference registry/config.go: flow GC 5 min
SWEEP_INTERVAL_S = 0.05          # full TTL sweep cadence (not per dispatch)


@dataclasses.dataclass
class FlowControlConfig:
    shards: int = 1
    fairness: str = GlobalStrictFairness.NAME
    ordering: str = FcfsOrdering.NAME
    band_capacity_bytes: int = DEFAULT_BAND_CAPACITY_BYTES
    max_global_bytes: int | None = None
    max_global_requests: int | None = None
    # static-usage-limit-policy (reference framework/plugins/flowcontrol/
    # usagelimits): per-flow queued-capacity caps.
    per_flow_max_requests: int | None = None
    per_flow_max_bytes: int | None = None
    default_ttl_s: float = DEFAULT_TTL_S
    flow_gc_s: float = DEFAULT_FLOW_GC_S
    # Batched dispatch (ISSUE 5): items popped per shard wake, fairness
    # order preserved. 1 = the historical one-pop-one-yield cycle; the
    # gateway raises it to scheduling.maxBatch when the scheduler pool is
    # offloaded so co-dispatched requests share one snapshot epoch.
    dispatch_batch: int = 1

    @classmethod
    def from_spec(cls, spec: dict[str, Any]) -> "FlowControlConfig":
        return cls(
            shards=int(spec.get("shards", 1)),
            fairness=spec.get("fairnessPolicy", GlobalStrictFairness.NAME),
            ordering=spec.get("orderingPolicy", FcfsOrdering.NAME),
            band_capacity_bytes=int(spec.get("bandCapacityBytes",
                                             DEFAULT_BAND_CAPACITY_BYTES)),
            max_global_bytes=spec.get("maxGlobalBytes"),
            max_global_requests=spec.get("maxGlobalRequests"),
            per_flow_max_requests=spec.get("perFlowMaxRequests"),
            per_flow_max_bytes=spec.get("perFlowMaxBytes"),
            default_ttl_s=float(spec.get("defaultTTLSeconds", DEFAULT_TTL_S)),
            flow_gc_s=float(spec.get("flowGCSeconds", DEFAULT_FLOW_GC_S)),
            dispatch_batch=max(1, int(spec.get("dispatchBatch", 1))),
        )


class _Shard:
    """Single-owner shard: all state mutated only from its dispatch task's
    loop context (+ synchronous enqueue on the same event loop)."""

    def __init__(self, idx: int, cfg: FlowControlConfig,
                 saturation_fn: Callable[[], float], owner: Any = None):
        self.idx = idx
        self.cfg = cfg
        self.saturation_fn = saturation_fn
        # The FlowController: read live for the overload coupling
        # (queue_policy + dispatch_observer land after construction via
        # OverloadController.attach_flow).
        self.owner = owner
        self.fairness = FAIRNESS_POLICIES[cfg.fairness]()
        self._ordering = ORDERING_POLICIES[cfg.ordering]()
        self.queues: dict[FlowKey, Any] = {}
        self.last_active: dict[FlowKey, float] = {}  # flow GC bookkeeping
        self.total_requests = 0
        self.total_bytes = 0
        self._wake = asyncio.Event()
        self._task: asyncio.Task | None = None
        self._last_sweep = 0.0

    # ---- metrics helpers ----

    def band_bytes(self, priority: int) -> int:
        return sum(q.bytes for k, q in self.queues.items() if k.priority == priority)

    # ---- enqueue (called from EnqueueAndWait) ----

    def try_enqueue(self, item: FlowControlRequest) -> QueueOutcome | None:
        cfg = self.cfg
        if (cfg.max_global_requests is not None
                and self.total_requests >= cfg.max_global_requests):
            return QueueOutcome.REJECTED_CAPACITY
        if (cfg.max_global_bytes is not None
                and self.total_bytes + item.size_bytes > cfg.max_global_bytes):
            return QueueOutcome.REJECTED_CAPACITY
        if self.band_bytes(item.flow_key.priority) + item.size_bytes > cfg.band_capacity_bytes:
            return QueueOutcome.REJECTED_CAPACITY
        q = self.queues.get(item.flow_key)
        if q is None:
            q = self.queues[item.flow_key] = self._ordering.make_queue()
        q.add(item)
        self.last_active[item.flow_key] = time.monotonic()
        self.total_requests += 1
        self.total_bytes += item.size_bytes
        self._wake.set()
        return None

    def notify_capacity(self) -> None:
        """Backpressure-aware wakeup: capacity likely freed (a proxied request
        completed, an eviction ran) — interrupt the saturated backoff sleep
        instead of waiting out the poll interval."""
        self._wake.set()

    def _drop(self, item: FlowControlRequest, outcome: QueueOutcome) -> None:
        q = self.queues.get(item.flow_key)
        if q is not None and q.remove(item):
            self.total_requests -= 1
            self.total_bytes -= item.size_bytes
        item.resolve(outcome)

    def shed_queued(self, n: int) -> list[str]:
        """Evict up to n queued sheddable items (priority < 0), lowest
        priority first — frees queue capacity for higher-priority arrivals.
        Returns the victims' request ids so the beneficiary's
        DecisionRecord can explain who was sacrificed.

        With overload control active (queue_policy.decay_per_s > 0) victim
        selection uses the AGE-DECAYED effective priority: a long-waiting
        sheddable item loses its slot to fresh feasible work even from a
        nominally lower band."""
        pol = (self.owner.queue_policy if self.owner is not None
               else DISABLED_QUEUE_POLICY)
        victims: list[str] = []
        if pol.decay_per_s > 0:
            now = time.monotonic()
            while len(victims) < n:
                best_key = best_score = None
                for key, q in self.queues.items():
                    if key.priority >= 0:
                        continue
                    head = q.peek()
                    if head is None:
                        continue
                    score = decayed_priority(key.priority, head.enqueue_time,
                                             now, pol.decay_per_s)
                    if best_score is None or score < best_score:
                        best_key, best_score = key, score
                if best_key is None:
                    break
                item = self.queues[best_key].pop()
                if item is None:
                    continue
                self.total_requests -= 1
                self.total_bytes -= item.size_bytes
                item.resolve(QueueOutcome.EVICTED_SHED)
                victims.append(item.request_id)
            return victims
        for key in sorted((k for k in self.queues if k.priority < 0),
                          key=lambda k: k.priority):
            q = self.queues[key]
            while len(victims) < n:
                item = q.pop()
                if item is None:
                    break
                self.total_requests -= 1
                self.total_bytes -= item.size_bytes
                item.resolve(QueueOutcome.EVICTED_SHED)
                victims.append(item.request_id)
            if len(victims) >= n:
                break
        return victims

    # ---- dispatch loop ----

    def start(self):
        self._task = asyncio.get_running_loop().create_task(self._run())

    def stop(self):
        if self._task:
            self._task.cancel()

    async def _wait_wake(self, timeout: float) -> None:
        """Sleep until a wakeup (new work / capacity nudge) or the timeout."""
        self._wake.clear()
        try:
            await asyncio.wait_for(self._wake.wait(), timeout=timeout)
        except asyncio.TimeoutError:
            pass

    async def _run(self):
        backoff = DISPATCH_POLL_S
        try:
            while True:
                if self.total_requests == 0:
                    # Idle: wake on enqueue, or time out on the GC cadence so
                    # idle FlowKeys still disappear with no traffic at all.
                    await self._wait_wake(max(self.cfg.flow_gc_s / 4, 0.5))
                    self._gc_idle_flows()
                    continue
                self._sweep_expired()
                if self.total_requests == 0:
                    continue
                if self.saturation_fn() >= 1.0:
                    # Saturated: back off exponentially instead of hot-polling
                    # (VERDICT r1: O(shards × endpoints × 100/s)); a capacity
                    # nudge (notify_capacity) interrupts the sleep, and the
                    # sleep never overshoots the earliest queued deadline.
                    await self._wait_wake(self._bounded_backoff(backoff))
                    backoff = min(backoff * 2, SATURATION_BACKOFF_MAX_S)
                    continue
                backoff = DISPATCH_POLL_S
                # Batched dispatch: pop up to dispatch_batch items across
                # flows per wake, fairness-order preserved (pick_flow is
                # consulted per item, so strict-priority / round-robin
                # semantics are identical to the one-pop cycle), then yield
                # ONCE. The saturation gate above was checked for the whole
                # batch, so co-dispatched requests proceed under one
                # scrape-state view — and, downstream, one pool-snapshot
                # epoch (the director's snapshot rebuilds at most once per
                # dirty event, not per request).
                dispatched = 0
                while dispatched < self.cfg.dispatch_batch:
                    key = self.fairness.pick_flow(self.queues)
                    if key is None:
                        break
                    item = self.queues[key].pop()
                    if item is None:
                        break
                    self.last_active[key] = time.monotonic()
                    self.total_requests -= 1
                    self.total_bytes -= item.size_bytes
                    FLOW_CONTROL_QUEUE_SECONDS.observe(
                        time.monotonic() - item.enqueue_time)
                    item.resolve(QueueOutcome.DISPATCHED)
                    dispatched += 1
                if dispatched:
                    SCHED_BATCH_SIZE.observe(dispatched)
                    obs = (self.owner.dispatch_observer
                           if self.owner is not None else None)
                    if obs is not None:
                        # Overload drain-rate estimator (router/overload.py):
                        # one call per wake, not per item.
                        obs(dispatched)
                await asyncio.sleep(0)  # yield so dispatched work can start
        except asyncio.CancelledError:
            for q in self.queues.values():
                while (item := q.pop()) is not None:
                    item.resolve(QueueOutcome.EVICTED_SHED)

    def _bounded_backoff(self, backoff: float) -> float:
        """Cap the saturated sleep near the earliest queued TTL deadline so
        expired items are evicted on schedule, not when saturation lifts.

        O(flows), not O(backlog): only queue HEADS are consulted (exact for
        EDF/SLO ordering and for FIFO with uniform TTLs; a deeper earlier
        deadline under mixed-TTL FIFO is still caught by the rate-limited
        full sweep within backoff+SWEEP_INTERVAL_S)."""
        now = time.monotonic()
        next_deadline = None
        for q in self.queues.values():
            head = q.peek()
            if head is not None and head.deadline is not None:
                if next_deadline is None or head.deadline < next_deadline:
                    next_deadline = head.deadline
        if next_deadline is None:
            return backoff
        return max(min(backoff, next_deadline - now), 0.001)

    def _sweep_expired(self):
        """Full-queue TTL sweep (reference processor.go cleanup cycle): with
        fcfs ordering a long-TTL head must not shield expired items deeper in
        the queue from eviction (VERDICT r1 weak #3). Rate-limited to a
        cadence — a per-dispatch full scan would make backlog drain O(n²)."""
        now = time.monotonic()
        if now - self._last_sweep < SWEEP_INTERVAL_S:
            return
        self._last_sweep = now
        pol = (self.owner.queue_policy if self.owner is not None
               else DISABLED_QUEUE_POLICY)
        for key in list(self.queues):
            q = self.queues[key]
            expired: list[tuple[FlowControlRequest, QueueOutcome]] = []
            for it in q.items():
                if it.deadline is not None and it.deadline < now:
                    expired.append((it, QueueOutcome.EVICTED_TTL))
                elif (pol.eviction_enabled and it.slo_ttft_ms > 0
                      and (now - it.enqueue_time) * 1e3
                      + it.predicted_service_ms > it.slo_ttft_ms):
                    # Predicted-unmeetable (router/overload.py): the
                    # remaining SLO budget is smaller than the predicted
                    # service time — evict BEFORE the TTL fires, freeing
                    # the slot for meetable work.
                    expired.append((it, QueueOutcome.EVICTED_UNMEETABLE))
            for item, outcome in expired:
                if q.remove(item):
                    self.total_requests -= 1
                    self.total_bytes -= item.size_bytes
                    item.resolve(outcome)
                    if outcome is QueueOutcome.EVICTED_UNMEETABLE:
                        pol.note_unmeetable()
        self._gc_idle_flows()

    def _gc_idle_flows(self):
        """Drop empty queues whose flow has been idle past the GC window
        (reference registry: flow GC 5 min default) so abandoned FlowKeys
        don't accumulate state forever."""
        cutoff = time.monotonic() - self.cfg.flow_gc_s
        for key in list(self.queues):
            if len(self.queues[key]) == 0 and self.last_active.get(key, 0) < cutoff:
                del self.queues[key]
                self.last_active.pop(key, None)


class FlowController:
    def __init__(self, cfg: FlowControlConfig,
                 saturation_fn: Callable[[], float]):
        self.cfg = cfg
        # Overload coupling (router/overload.py OverloadController
        # .attach_flow): drain-rate observer + queue policy (unmeetable
        # eviction, priority decay). The disabled defaults keep the shard
        # hot path at one attribute check and pre-overload semantics.
        self.dispatch_observer: Callable[[int], None] | None = None
        self.queue_policy = DISABLED_QUEUE_POLICY
        self.shards = [_Shard(i, cfg, saturation_fn, owner=self)
                       for i in range(cfg.shards)]
        self._started = False

    async def start(self):
        for s in self.shards:
            s.start()
        self._started = True

    async def stop(self):
        for s in self.shards:
            s.stop()
        self._started = False

    def _least_loaded_shard(self) -> _Shard:
        # reference controller.go:393-425 least-loaded candidate selection
        return min(self.shards, key=lambda s: s.total_requests)

    @property
    def queued_requests(self) -> int:
        return sum(s.total_requests for s in self.shards)

    def queued_by_band(self) -> dict[int, int]:
        """Queued items per priority band across shards (bands are
        implicit — derived from live queues, so an idle band is simply
        absent). Read by the timeline sampler once per tick."""
        bands: dict[int, int] = {}
        for s in self.shards:
            for key, q in s.queues.items():
                n = len(q)
                if n:
                    bands[key.priority] = bands.get(key.priority, 0) + n
        return bands

    def shed_queued(self, n: int) -> list[str]:
        """Shed up to n queued sheddable items across shards; returns the
        victims' request ids."""
        victims: list[str] = []
        for s in self.shards:
            if len(victims) >= n:
                break
            victims.extend(s.shed_queued(n - len(victims)))
        return victims

    def notify_capacity(self) -> None:
        """Wake saturated shards: backend capacity has (likely) freed."""
        for s in self.shards:
            s.notify_capacity()

    async def enqueue_and_wait(self, item: FlowControlRequest) -> QueueOutcome:
        """Block until dispatched/rejected/evicted (controller.go:218)."""
        assert self._started, "FlowController not started"
        loop = asyncio.get_running_loop()
        item.future = loop.create_future()
        if item.deadline is None:
            item.deadline = time.monotonic() + self.cfg.default_ttl_s

        # Per-flow usage caps (static-usage-limit-policy) are GLOBAL across
        # shards — least-loaded placement would otherwise multiply the cap by
        # the shard count — and apply from the flow's very first request.
        cfg = self.cfg
        if cfg.per_flow_max_requests is not None or cfg.per_flow_max_bytes is not None:
            flow_requests = flow_bytes = 0
            for s in self.shards:
                fq = s.queues.get(item.flow_key)
                if fq is not None:
                    flow_requests += len(fq)
                    flow_bytes += fq.bytes
            if (cfg.per_flow_max_requests is not None
                    and flow_requests >= cfg.per_flow_max_requests):
                return QueueOutcome.REJECTED_CAPACITY
            if (cfg.per_flow_max_bytes is not None
                    and flow_bytes + item.size_bytes > cfg.per_flow_max_bytes):
                return QueueOutcome.REJECTED_CAPACITY

        shard = self._least_loaded_shard()
        rejection = shard.try_enqueue(item)
        FLOW_CONTROL_QUEUE_SIZE.set(self.queued_requests)
        if rejection is not None:
            return rejection
        try:
            outcome = await item.future
        except asyncio.CancelledError:
            shard._drop(item, QueueOutcome.EVICTED_CONTEXT_CANCELLED)
            raise
        finally:
            FLOW_CONTROL_QUEUE_SIZE.set(self.queued_requests)
        return outcome
