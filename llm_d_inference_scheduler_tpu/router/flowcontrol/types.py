"""Flow-control contracts (reference: pkg/epp/flowcontrol/{contracts,types}).

FlowKey{id, priority} identifies a flow; QueueOutcome enumerates terminal
request states (types/ QueueOutcome enum — Dispatched / RejectedCapacity /
EvictedTTL / EvictedContextCancelled / …).
"""

from __future__ import annotations

import asyncio
import dataclasses
import enum
import time
from typing import Any


@dataclasses.dataclass(frozen=True)
class FlowKey:
    flow_id: str
    priority: int


class QueueOutcome(str, enum.Enum):
    DISPATCHED = "dispatched"
    REJECTED_CAPACITY = "rejected_capacity"
    REJECTED_OTHER = "rejected_other"
    EVICTED_TTL = "evicted_ttl"
    EVICTED_CONTEXT_CANCELLED = "evicted_context_cancelled"
    EVICTED_SHED = "evicted_shed"
    # Overload control (router/overload.py): the item's remaining SLO
    # budget fell below its predicted service time while queued — evicted
    # before the TTL fires so its slot goes to meetable work.
    EVICTED_UNMEETABLE = "evicted_unmeetable"


@dataclasses.dataclass
class FlowControlRequest:
    """One queued admission request."""

    request_id: str
    flow_key: FlowKey
    size_bytes: int = 0
    deadline: float | None = None  # monotonic; EDF/SLO ordering + TTL eviction
    enqueue_time: float = dataclasses.field(default_factory=time.monotonic)
    future: asyncio.Future | None = None
    context: Any = None  # carries cancellation (e.g. client connection)
    # Overload-control stamp (flowcontrol/admission.py, from the director's
    # OverloadAssessment): slo_ttft_ms > 0 marks the item eligible for
    # predicted-unmeetable eviction — evict once
    # waited + predicted_service_ms > slo_ttft_ms. 0 = exempt (the
    # pre-overload default, and every item while the kill-switch is off).
    slo_ttft_ms: float = 0.0
    predicted_service_ms: float = 0.0

    def resolve(self, outcome: QueueOutcome) -> None:
        if self.future is not None and not self.future.done():
            self.future.set_result(outcome)
