"""Mid-flight request eviction.

Reference: pkg/epp/flowcontrol/eviction (SURVEY §2.6) — the RequestEvictor
tracks in-flight requests via PreRequest/ResponseComplete-style hooks; EvictN
pops candidates ordered by the priority-then-time policy, filtered to
sheddable requests (priority < 0), and cancels them so the protocol layer can
answer 429 with x-removal-reason (the reference arms an eviction channel into
the ext-proc loop; here the cancel callback unwinds the gateway's proxy task).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Callable

from ..metrics import REGISTRY
from prometheus_client import Counter

log = logging.getLogger("router.eviction")

EVICTIONS_TOTAL = Counter(
    "inference_extension_request_evictions_total",
    "In-flight requests evicted to make room", registry=REGISTRY)

EVICTED_REASON = "evicted to admit higher-priority work"


@dataclasses.dataclass
class _InFlight:
    request_id: str
    priority: int
    start_time: float
    cancel: Callable[[], None]


class RequestEvictor:
    """Tracks in-flight requests; evicts sheddable ones on demand.

    Entries are keyed by a server-generated unique key (returned from
    ``register``), NOT the client-supplied x-request-id: two concurrent
    requests reusing an id must stay independently trackable (the id is kept
    only as a log label).
    """

    def __init__(self):
        self._inflight: dict[str, _InFlight] = {}
        self._evicted: set[str] = set()
        self._seq = 0

    def register(self, request_id: str, priority: int,
                 cancel: Callable[[], None]) -> str:
        self._seq += 1
        key = f"{request_id}#{self._seq}"
        self._inflight[key] = _InFlight(
            request_id, priority, time.monotonic(), cancel)
        return key

    def deregister(self, key: str) -> None:
        self._inflight.pop(key, None)
        self._evicted.discard(key)

    def was_evicted(self, key: str) -> bool:
        return key in self._evicted

    @property
    def inflight_count(self) -> int:
        return len(self._inflight)

    def evict_n(self, n: int) -> list[str]:
        """Cancel up to n sheddable in-flight requests (lowest priority first,
        oldest first within a priority — the reference's
        priority-then-time-eviction-order-policy + sheddable-eviction-filter).
        Returns the evicted request ids so the beneficiary's DecisionRecord
        can name its victims.
        """
        sheddable = sorted(
            ((k, r) for k, r in self._inflight.items() if r.priority < 0),
            key=lambda kv: (kv[1].priority, kv[1].start_time))
        evicted: list[str] = []
        for key, rec in sheddable[:n]:
            self._evicted.add(key)
            self._inflight.pop(key, None)
            try:
                rec.cancel()
            except Exception:
                log.exception("evict cancel failed for %s", rec.request_id)
                continue
            EVICTIONS_TOTAL.inc()
            evicted.append(rec.request_id)
            log.info("evicted in-flight request %s (priority %d)",
                     rec.request_id, rec.priority)
        return evicted
