"""Mid-flight request eviction.

Reference: pkg/epp/flowcontrol/eviction (SURVEY §2.6) — the RequestEvictor
tracks in-flight requests via PreRequest/ResponseComplete-style hooks; EvictN
pops candidates ordered by the priority-then-time policy, filtered to
sheddable requests (priority < 0), and cancels them so the protocol layer can
answer 429 with x-removal-reason (the reference arms an eviction channel into
the ext-proc loop; here the cancel callback unwinds the gateway's proxy task).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Callable

from ..metrics import REGISTRY
from prometheus_client import Counter

log = logging.getLogger("router.eviction")

EVICTIONS_TOTAL = Counter(
    "inference_extension_request_evictions_total",
    "In-flight requests evicted to make room", registry=REGISTRY)

EVICTED_REASON = "evicted to admit higher-priority work"


@dataclasses.dataclass
class _InFlight:
    request_id: str
    priority: int
    start_time: float
    cancel: Callable[[], None]


class RequestEvictor:
    """Tracks in-flight requests; evicts sheddable ones on demand."""

    def __init__(self):
        self._inflight: dict[str, _InFlight] = {}
        self._evicted: set[str] = set()

    def register(self, request_id: str, priority: int,
                 cancel: Callable[[], None]) -> None:
        self._inflight[request_id] = _InFlight(
            request_id, priority, time.monotonic(), cancel)

    def deregister(self, request_id: str) -> None:
        self._inflight.pop(request_id, None)
        self._evicted.discard(request_id)

    def was_evicted(self, request_id: str) -> bool:
        return request_id in self._evicted

    @property
    def inflight_count(self) -> int:
        return len(self._inflight)

    def evict_n(self, n: int) -> int:
        """Cancel up to n sheddable in-flight requests (lowest priority first,
        oldest first within a priority — the reference's
        priority-then-time-eviction-order-policy + sheddable-eviction-filter).
        """
        sheddable = sorted(
            (r for r in self._inflight.values() if r.priority < 0),
            key=lambda r: (r.priority, r.start_time))
        evicted = 0
        for rec in sheddable[:n]:
            self._evicted.add(rec.request_id)
            self._inflight.pop(rec.request_id, None)
            try:
                rec.cancel()
            except Exception:
                log.exception("evict cancel failed for %s", rec.request_id)
                continue
            EVICTIONS_TOTAL.inc()
            evicted += 1
            log.info("evicted in-flight request %s (priority %d)",
                     rec.request_id, rec.priority)
        return evicted
