"""TLS serving: self-signed fallback, cert-dir loading, and live reload.

Mirrors the reference's secure-serving stack
(/root/reference/internal/tls/tls.go:33 CreateSelfSignedTLSCertificate,
/root/reference/pkg/common/certs.go NewCertReloader,
/root/reference/pkg/epp/server/runserver.go:136-171 SecureServing wiring):

- With no cert path, a process-local self-signed certificate is minted at
  startup (10-year validity, serverAuth EKU) so TLS is never a deployment
  prerequisite.
- With a cert path, ``<path>/tls.crt`` + ``<path>/tls.key`` are loaded —
  the mount layout of a kubernetes.io/tls Secret.
- With reload enabled, the pair is re-read when its mtime changes
  (debounced), so cert-manager rotations take effect without a restart.
  The reference watches with fsnotify; here a 1 s mtime poll drives
  ``SSLContext.load_cert_chain`` on the live context — new handshakes pick
  up the new pair, established connections are untouched (same semantics
  as the reference's GetCertificate indirection).
"""

from __future__ import annotations

import datetime
import logging
import os
import ssl
import tempfile
import threading
from typing import Any

log = logging.getLogger("router.tls")

CERT_FILE = "tls.crt"
KEY_FILE = "tls.key"
_RELOAD_POLL_S = 1.0


def create_self_signed_cert(common_name: str = "llm-d-tpu",
                            org: str = "Inference Ext",
                            ) -> tuple[bytes, bytes]:
    """Mint a self-signed server certificate (tls.go:33-86): 10-year
    validity, digitalSignature+keyEncipherment, serverAuth EKU. SANs for
    localhost loopback are added so in-cluster health probes can pin the
    cert if they want to (clients normally skip verification for the
    self-signed fallback, as the reference's do)."""
    import ipaddress

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import ExtendedKeyUsageOID, NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([
        x509.NameAttribute(NameOID.ORGANIZATION_NAME, org),
        x509.NameAttribute(NameOID.COMMON_NAME, common_name),
    ])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now)
        .not_valid_after(now + datetime.timedelta(days=3650))
        .add_extension(x509.KeyUsage(
            digital_signature=True, key_encipherment=True,
            content_commitment=False, data_encipherment=False,
            key_agreement=False, key_cert_sign=False, crl_sign=False,
            encipher_only=False, decipher_only=False), critical=True)
        .add_extension(x509.ExtendedKeyUsage(
            [ExtendedKeyUsageOID.SERVER_AUTH]), critical=False)
        .add_extension(x509.BasicConstraints(ca=False, path_length=None),
                       critical=True)
        .add_extension(x509.SubjectAlternativeName([
            x509.DNSName("localhost"),
            x509.DNSName(common_name),
            x509.IPAddress(ipaddress.ip_address("127.0.0.1")),
        ]), critical=False)
        .sign(key, hashes.SHA256())
    )
    cert_pem = cert.public_bytes(serialization.Encoding.PEM)
    key_pem = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption())
    return cert_pem, key_pem


class TlsServing:
    """One serving identity: cert-dir or self-signed, optional reload.

    Exposes both transports used on the serving path: an ``ssl.SSLContext``
    for aiohttp listeners (gateway HTTP, sidecar) and gRPC server
    credentials (ext-proc), from the same certificate pair.
    """

    def __init__(self, cert_path: str | None = None,
                 enable_reload: bool = False,
                 common_name: str = "llm-d-tpu"):
        self.cert_path = cert_path or None
        # Reload needs real files to watch (runserver.go:159 gates reload on
        # CertPath being set the same way).
        self.enable_reload = bool(enable_reload and cert_path)
        self._tmpdir: tempfile.TemporaryDirectory | None = None
        if self.cert_path:
            self._crt = os.path.join(self.cert_path, CERT_FILE)
            self._key = os.path.join(self.cert_path, KEY_FILE)
        else:
            cert_pem, key_pem = create_self_signed_cert(common_name)
            self._tmpdir = tempfile.TemporaryDirectory(prefix="llmd-tls-")
            self._crt = os.path.join(self._tmpdir.name, CERT_FILE)
            self._key = os.path.join(self._tmpdir.name, KEY_FILE)
            with open(self._crt, "wb") as f:
                f.write(cert_pem)
            with open(self._key, "wb") as f:
                f.write(key_pem)
            log.info("TLS: using a self-signed certificate (no cert path)")
        self._ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        self._ctx.minimum_version = ssl.TLSVersion.TLSv1_2
        self._ctx.load_cert_chain(self._crt, self._key)
        self._mtimes = self._stat()
        self._stop = threading.Event()
        self._watcher: threading.Thread | None = None
        if self.enable_reload:
            self._watcher = threading.Thread(
                target=self._watch, name="cert-reload", daemon=True)
            self._watcher.start()

    # ---- server-side material -------------------------------------------

    @property
    def ssl_context(self) -> ssl.SSLContext:
        return self._ctx

    def cert_pem(self) -> bytes:
        with open(self._crt, "rb") as f:
            return f.read()

    def key_pem(self) -> bytes:
        with open(self._key, "rb") as f:
            return f.read()

    def grpc_server_credentials(self) -> Any:
        """gRPC creds for add_secure_port. With reload, the certificate
        configuration is re-fetched per handshake (the grpc-python analogue
        of the reference's GetCertificate callback)."""
        import grpc

        if not self.enable_reload:
            return grpc.ssl_server_credentials(
                [(self.key_pem(), self.cert_pem())])

        def fetch():
            try:
                # Validate the pair first: mid-rotation one file may be new
                # while the other is still old (the poll path debounces for
                # the same reason) — a mismatched pair would fail every
                # handshake until both land. load_cert_chain raises on
                # mismatch, and grpc then keeps serving the previous config.
                probe = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
                probe.load_cert_chain(self._crt, self._key)
                return grpc.ssl_server_certificate_configuration(
                    [(self.key_pem(), self.cert_pem())])
            except Exception as e:  # keep serving the previous pair
                log.warning("cert fetch failed: %s", e)
                return None

        return grpc.dynamic_ssl_server_credentials(
            fetch(), lambda: fetch(), require_client_authentication=False)

    # ---- reload ----------------------------------------------------------

    def _stat(self):
        try:
            return (os.stat(self._crt).st_mtime_ns,
                    os.stat(self._key).st_mtime_ns)
        except OSError:
            return None

    def _watch(self):
        # Debounce like certs.go:33 (250 ms): a rotation writes two files;
        # reload once both settle.
        pending_since = None
        while not self._stop.wait(_RELOAD_POLL_S):
            now = self._stat()
            if now is None or now == self._mtimes:
                if pending_since is not None:
                    try:
                        self._ctx.load_cert_chain(self._crt, self._key)
                        self._mtimes = self._stat()
                        pending_since = None
                        log.info("TLS: reloaded certificate from %s",
                                 self.cert_path)
                    except Exception as e:
                        # Mid-rotation partial write: retry next tick.
                        log.warning("TLS reload failed (will retry): %s", e)
                continue
            self._mtimes = now
            pending_since = True

    def close(self):
        self._stop.set()
        if self._watcher is not None:
            self._watcher.join(timeout=3)
        if self._tmpdir is not None:
            self._tmpdir.cleanup()
            self._tmpdir = None


def client_verify(insecure_skip_verify: bool = False,
                  ca_cert_path: str | None = None) -> Any:
    """The httpx ``verify`` argument for a TLS client leg
    (proxy_helpers.go client transport): a CA bundle path, a permissive
    context when verification is skipped, or stock verification.

    A CA bundle TAKES PRECEDENCE over the skip flag: the router-side config
    surfaces default ``insecureSkipVerify`` to true (pod-local certs), so an
    operator setting only ``caCertPath`` means "verify against this bundle"
    — silently keeping CERT_NONE there would be a believed-but-absent
    security property."""
    if ca_cert_path:
        return ssl.create_default_context(cafile=ca_cert_path)
    if insecure_skip_verify:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        return ctx
    return True
