"""Copy-on-write pool snapshots: the scheduling-time view of the endpoint
pool.

Scheduling used to read the LIVE ``Endpoint`` objects the data-layer
collectors mutate in place — safe only because every reader and writer
shared the gateway's event loop. Moving scheduling cycles off-loop
(router/schedpool.py) breaks that invariant two ways:

- a scrape landing mid-cycle could hand one scorer pre-scrape queue depth
  and the next scorer post-scrape KV usage (torn pool view);
- data producers write per-request attributes (prefix match info, in-flight
  load) onto the SHARED endpoint attribute map, so two concurrently
  scheduled requests would clobber each other's producer outputs.

``PoolSnapshot`` fixes both: an immutable, epoch-versioned copy of
(metadata, metrics, attributes) per endpoint, published copy-on-write by
the Datastore — endpoint add/delete/resync and scrape landings mark it
dirty; the next ``Datastore.snapshot()`` call rebuilds it once and every
caller until the next dirty event shares the same epoch (so a co-dispatched
flow-control batch schedules against ONE scrape-state view). ``view()``
hands each request its own ``SnapshotEndpoint`` list: shared immutable
metadata, the snapshot's point-in-time metrics, and a per-request overlay
attribute map (producer writes land in the overlay; reads fall through to
the snapshot base with the same clone-on-read contract as ``AttributeMap``).

P/D-Serve (arXiv:2408.08147) and RTP-LLM (arXiv:2605.29639) isolate
routing-decision state from the streaming data plane the same way; see
docs/performance.md §Concurrency model.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Iterable

from .framework.datalayer import Endpoint, EndpointMetadata, Metrics


def _copy_dict(d: dict) -> dict:
    """Copy a dict that a worker thread may be mutating concurrently (the
    offloaded scrape extractors write endpoint attributes off-loop).
    ``dict(d)`` of a plain dict is a single C-level copy under the GIL —
    atomic w.r.t. concurrent inserts, never a torn read."""
    return dict(d)


def _copy_metrics(m: Metrics) -> Metrics:
    """Point-in-time metrics copy. Field reads are GIL-atomic; the two
    model dicts are copied with the concurrent-mutation retry. Much cheaper
    than ``Metrics.clone()`` (deepcopy) — the snapshot rebuilds on every
    scrape landing under load."""
    return dataclasses.replace(
        m,
        active_models=_copy_dict(m.active_models),
        waiting_models=_copy_dict(m.waiting_models))


class OverlayAttributes:
    """Per-request attribute view over a shared snapshot base: writes go to
    the request-private overlay, reads check the overlay then fall through
    to the base. Clone-on-read matches ``AttributeMap`` (values exposing
    ``.clone()`` are cloned; plain values are treated as immutable)."""

    __slots__ = ("_base", "_data")

    _MISS = object()

    def __init__(self, base: dict[str, Any]):
        self._base = base
        self._data: dict[str, Any] = {}

    def put(self, key: str, value: Any) -> None:
        self._data[key] = value

    def get(self, key: str, default: Any = None) -> Any:
        # Two-step with a sentinel: scorers read attributes per endpoint
        # per cycle, and after producers run the overlay hit is the common
        # case — don't pay the base lookup for it.
        v = self._data.get(key, self._MISS)
        if v is self._MISS:
            v = self._base.get(key, self._MISS)
            if v is self._MISS:
                return default
        if hasattr(v, "clone"):
            return v.clone()
        return v

    def keys(self) -> Iterable[str]:
        return {**self._base, **self._data}.keys()

    def __contains__(self, key: str) -> bool:
        return key in self._data or key in self._base


class SnapshotEndpoint:
    """Scorer-visible endpoint view carved from a PoolSnapshot: shared
    immutable metadata, the snapshot's metrics copy, a per-request overlay
    attribute map. Duck-compatible with ``framework.datalayer.Endpoint`` —
    filters/scorers/pickers, the director's prepare step, and the gateway's
    proxy leg all read only ``metadata`` / ``metrics`` / ``attributes``."""

    __slots__ = ("metadata", "metrics", "attributes", "snapshot_epoch")

    def __init__(self, metadata: EndpointMetadata, metrics: Metrics,
                 attrs_base: dict[str, Any], epoch: int):
        self.metadata = metadata
        self.metrics = metrics
        self.attributes = OverlayAttributes(attrs_base)
        self.snapshot_epoch = epoch

    def __repr__(self) -> str:
        return (f"SnapshotEndpoint({self.metadata.address_port}, "
                f"epoch={self.snapshot_epoch})")


class PoolSnapshot:
    """One epoch of the pool: immutable after construction. ``view()``
    builds fresh per-request SnapshotEndpoints (cheap: three slot stores
    per endpoint) so concurrent cycles never share a mutable object."""

    __slots__ = ("epoch", "built_at", "_entries")

    def __init__(self, epoch: int, endpoints: Iterable[Endpoint]):
        self.epoch = epoch
        self.built_at = time.monotonic()
        # (metadata ref, metrics copy, attributes base copy) per endpoint.
        self._entries: list[tuple[EndpointMetadata, Metrics, dict]] = [
            (ep.metadata, _copy_metrics(ep.metrics),
             _copy_dict(ep.attributes._data))
            for ep in endpoints]

    @classmethod
    def from_entries(cls, epoch: int,
                     entries: Iterable[tuple[EndpointMetadata, Metrics, dict]]
                     ) -> "PoolSnapshot":
        """Rehydrate a snapshot from already-materialized entries — the
        fleet's snapshot-IPC path (router/fleet.py): a follower worker
        installs the leader's published epoch verbatim instead of rebuilding
        its own, so a batch dispatched in any worker schedules against the
        same epoch it would have seen single-process."""
        snap = cls.__new__(cls)
        snap.epoch = epoch
        snap.built_at = time.monotonic()
        snap._entries = [(meta, metrics, dict(attrs))
                         for meta, metrics, attrs in entries]
        return snap

    def entries(self) -> list[tuple[EndpointMetadata, Metrics, dict]]:
        """The raw (metadata, metrics, attrs) entries — the serialization
        unit the fleet's snapshot publisher pickles onto the IPC socket.
        Treat as immutable: the tuples are shared with live views."""
        return self._entries

    def __len__(self) -> int:
        return len(self._entries)

    def view(self) -> list[SnapshotEndpoint]:
        """A fresh scheduling view: one overlay endpoint per pool member."""
        epoch = self.epoch
        return [SnapshotEndpoint(meta, metrics, attrs, epoch)
                for meta, metrics, attrs in self._entries]
