"""Copy-on-write pool snapshots: the scheduling-time view of the endpoint
pool.

Scheduling used to read the LIVE ``Endpoint`` objects the data-layer
collectors mutate in place — safe only because every reader and writer
shared the gateway's event loop. Moving scheduling cycles off-loop
(router/schedpool.py) breaks that invariant two ways:

- a scrape landing mid-cycle could hand one scorer pre-scrape queue depth
  and the next scorer post-scrape KV usage (torn pool view);
- data producers write per-request attributes (prefix match info, in-flight
  load) onto the SHARED endpoint attribute map, so two concurrently
  scheduled requests would clobber each other's producer outputs.

``PoolSnapshot`` fixes both: an immutable, epoch-versioned copy of
(metadata, metrics, attributes) per endpoint, published copy-on-write by
the Datastore — endpoint add/delete/resync and scrape landings mark it
dirty; the next ``Datastore.snapshot()`` call rebuilds it once and every
caller until the next dirty event shares the same epoch (so a co-dispatched
flow-control batch schedules against ONE scrape-state view). ``view()``
hands each request its own ``SnapshotEndpoint`` list: shared immutable
metadata, the snapshot's point-in-time metrics, and a per-request overlay
attribute map (producer writes land in the overlay; reads fall through to
the snapshot base with the same clone-on-read contract as ``AttributeMap``).

P/D-Serve (arXiv:2408.08147) and RTP-LLM (arXiv:2605.29639) isolate
routing-decision state from the streaming data plane the same way; see
docs/performance.md §Concurrency model.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Iterable

import numpy as np

from .framework.datalayer import (
    DRAINING_LABEL,
    ROLE_LABEL,
    Endpoint,
    EndpointMetadata,
    Metrics,
)


def _copy_dict(d: dict) -> dict:
    """Copy a dict that a worker thread may be mutating concurrently (the
    offloaded scrape extractors write endpoint attributes off-loop).
    ``dict(d)`` of a plain dict is a single C-level copy under the GIL —
    atomic w.r.t. concurrent inserts, never a torn read."""
    return dict(d)


def _copy_metrics(m: Metrics) -> Metrics:
    """Point-in-time metrics copy. Field reads are GIL-atomic; the two
    model dicts are copied with the concurrent-mutation retry. Much cheaper
    than ``Metrics.clone()`` (deepcopy) — the snapshot rebuilds on every
    scrape landing under load."""
    if not isinstance(m, Metrics):
        # Fleet-follower promotion edge: live endpoints may still carry
        # column-backed ColumnMetrics proxies when local snapshot building
        # resumes — materialize a real dataclass copy.
        return m.materialize()
    return dataclasses.replace(
        m,
        active_models=_copy_dict(m.active_models),
        waiting_models=_copy_dict(m.waiting_models))


# ---------------------------------------------------------------------------
# Columnar pool view (vectorized scheduling + binary snapshot IPC).
#
# One row per endpoint; every numeric Metrics field becomes a float64 column
# (ints fit exactly — the pool's counts stay far below 2**53), the two role
# labels collapse to small code arrays, and the non-numeric remainder
# (metadata, model dicts, attribute dicts) stays as per-row object refs.
# Built at most once per snapshot epoch and shared by every scheduling cycle
# of that epoch; vectorized filter/scorer kernels index these arrays instead
# of looping endpoint objects, and the fleet's binary snapshot frames
# (router/snapwire.py) serialize the arrays as raw buffers.
# ---------------------------------------------------------------------------

# Column order is part of the binary wire format (router/snapwire.py):
# append only, never reorder — the frame VERSION must bump otherwise.
NUMERIC_FIELDS = (
    "waiting_queue_size", "running_requests_size", "kv_cache_usage_percent",
    "kv_cache_max_token_capacity", "cache_block_size", "cache_num_blocks",
    "free_kv_blocks", "prefill_tokens", "prefix_hit_tokens",
    "max_active_models", "update_time",
)
_INT_FIELDS = frozenset((
    "waiting_queue_size", "running_requests_size",
    "kv_cache_max_token_capacity", "cache_block_size", "cache_num_blocks",
    "free_kv_blocks", "max_active_models",
))

# Role-label codes for the int8 role column. Codes are part of the wire
# format too. Any role outside this table maps to ROLE_OTHER: the in-tree
# role filters can never match it, exactly like the scalar `role in ROLES`
# test on an unknown label.
ROLE_CODES = {"": 0, "decode": 1, "prefill": 2, "both": 3, "encode": 4}
ROLE_OTHER = 5
N_ROLE_CODES = 6


def role_code_for(labels: dict[str, str]) -> int:
    role = labels.get(ROLE_LABEL)
    if role in (None, ""):
        return 0
    return ROLE_CODES.get(role, ROLE_OTHER)


def role_mask_table(roles: tuple[str, ...], match_unlabeled: bool) -> np.ndarray:
    """Boolean lookup table over role codes for a role-filter class:
    ``table[role_code]`` ⇔ the scalar ``role in ROLES or (unlabeled and
    MATCH_UNLABELED)`` test."""
    table = np.zeros(N_ROLE_CODES, dtype=bool)
    for r in roles:
        code = ROLE_CODES.get(r)
        if code is not None:
            table[code] = True
    table[0] = bool(match_unlabeled) or table[0]
    return table


class PoolColumns:
    """The columnar half of one snapshot epoch: numeric metrics as float64
    arrays (one row per endpoint), role/draining as code arrays, and object
    refs (metadata, model dicts, attribute dicts) per row. Immutable after
    construction — a metrics-only update produces a NEW PoolColumns via
    ``with_arrays`` so in-flight cycles keep their torn-free view."""

    __slots__ = ("n", "keys", "metas", "attrs", "models", "role_code",
                 "draining", "num", "base_id", "_row_of")

    def __init__(self, n: int, keys: list[str],
                 metas: list[EndpointMetadata], attrs: list[dict],
                 models: list[tuple[dict, dict]], role_code: np.ndarray,
                 draining: np.ndarray, num: dict[str, np.ndarray],
                 base_id: int = 0):
        self.n = n
        self.keys = keys
        self.metas = metas
        self.attrs = attrs
        self.models = models
        self.role_code = role_code
        self.draining = draining
        self.num = num
        # Identity of the full frame these columns were carved from (binary
        # IPC: a delta frame only applies over its own base).
        self.base_id = base_id
        self._row_of: dict[str, int] | None = None

    @classmethod
    def from_entries(cls, entries: list[tuple[EndpointMetadata, Metrics, dict]]
                     ) -> "PoolColumns":
        n = len(entries)
        num = {f: np.empty(n, dtype=np.float64) for f in NUMERIC_FIELDS}
        role_code = np.empty(n, dtype=np.int8)
        draining = np.empty(n, dtype=bool)
        keys: list[str] = []
        metas: list[EndpointMetadata] = []
        attrs: list[dict] = []
        models: list[tuple[dict, dict]] = []
        cols = [num[f] for f in NUMERIC_FIELDS]
        for i, (meta, m, a) in enumerate(entries):
            keys.append(meta.address_port)
            metas.append(meta)
            attrs.append(a)
            models.append((m.active_models, m.waiting_models))
            labels = meta.labels
            role_code[i] = role_code_for(labels)
            draining[i] = bool(labels.get(DRAINING_LABEL))
            for arr, f in zip(cols, NUMERIC_FIELDS):
                arr[i] = getattr(m, f)
        return cls(n, keys, metas, attrs, models, role_code, draining, num)

    # Duck-compat with ColumnsRef: ColumnMetrics resolves `src.cols`, which
    # is the live holder's current columns or — bound to a frozen snapshot —
    # these columns themselves.
    @property
    def cols(self) -> "PoolColumns":
        return self

    def row_of(self) -> dict[str, int]:
        m = self._row_of
        if m is None:
            m = self._row_of = {k: i for i, k in enumerate(self.keys)}
        return m

    def with_arrays(self, num: dict[str, np.ndarray]) -> "PoolColumns":
        """Metrics-only successor (binary delta frame): new numeric arrays,
        everything else shared by reference."""
        return PoolColumns(self.n, self.keys, self.metas, self.attrs,
                           self.models, self.role_code, self.draining,
                           num, base_id=self.base_id)

    def _metrics_at(self, row: int) -> Metrics:
        kwargs: dict[str, Any] = {}
        for f in NUMERIC_FIELDS:
            v = float(self.num[f][row])
            if f in _INT_FIELDS and v == v and float(int(v)) == v:
                kwargs[f] = int(v)
            else:
                kwargs[f] = v
        active, waiting = self.models[row]
        return Metrics(active_models=dict(active),
                       waiting_models=dict(waiting), **kwargs)

    def materialize_entries(self) -> list[tuple[EndpointMetadata, Metrics, dict]]:
        return [(self.metas[i], self._metrics_at(i), self.attrs[i])
                for i in range(self.n)]


class ColumnsRef:
    """Mutable holder the fleet follower swaps on each delta frame: live
    ``Endpoint.metrics`` proxies bound to this ref always read the newest
    applied columns (O(1) per frame), while snapshot views bind the frozen
    PoolColumns directly."""

    __slots__ = ("cols",)

    def __init__(self, cols: "PoolColumns"):
        self.cols = cols


def _num_prop(field: str, as_int: bool):
    if as_int:
        def get(self):
            v = float(self._src.cols.num[field][self._row])
            if v != v:  # NaN passes through un-cast
                return v
            i = int(v)
            return i if i == v else v
    else:
        def get(self):
            return float(self._src.cols.num[field][self._row])
    return property(get)


class ColumnMetrics:
    """Column-backed read-only stand-in for ``Metrics``: one (source, row)
    pair instead of a 13-field dataclass copy. Duck-compatible with every
    metrics READER in the tree (scorers, saturation detector, pool gauges);
    writers must ``materialize()`` first — followers have no scrape
    collectors, and leader promotion re-materializes live endpoints
    (Datastore.resume_local_snapshots)."""

    __slots__ = ("_src", "_row")

    def __init__(self, src: Any, row: int):
        # src: a ColumnsRef (live endpoints — tracks delta applies) or a
        # PoolColumns (frozen snapshot views).
        self._src = src
        self._row = row

    @property
    def active_models(self) -> dict:
        return self._src.cols.models[self._row][0]

    @property
    def waiting_models(self) -> dict:
        return self._src.cols.models[self._row][1]

    @property
    def fresh(self) -> bool:
        ut = float(self._src.cols.num["update_time"][self._row])
        return (time.monotonic() - ut) < 5.0 if ut else False

    def materialize(self) -> Metrics:
        return self._src.cols._metrics_at(self._row)

    def clone(self) -> Metrics:
        return self.materialize()

    def __repr__(self) -> str:
        return f"ColumnMetrics(row={self._row})"


for _f in NUMERIC_FIELDS:
    setattr(ColumnMetrics, _f, _num_prop(_f, _f in _INT_FIELDS))
del _f


class OverlayAttributes:
    """Per-request attribute view over a shared snapshot base: writes go to
    the request-private overlay, reads check the overlay then fall through
    to the base. Clone-on-read matches ``AttributeMap`` (values exposing
    ``.clone()`` are cloned; plain values are treated as immutable)."""

    __slots__ = ("_base", "_data")

    _MISS = object()

    def __init__(self, base: dict[str, Any]):
        self._base = base
        self._data: dict[str, Any] = {}

    def put(self, key: str, value: Any) -> None:
        self._data[key] = value

    def get(self, key: str, default: Any = None) -> Any:
        # Two-step with a sentinel: scorers read attributes per endpoint
        # per cycle, and after producers run the overlay hit is the common
        # case — don't pay the base lookup for it.
        v = self._data.get(key, self._MISS)
        if v is self._MISS:
            v = self._base.get(key, self._MISS)
            if v is self._MISS:
                return default
        if hasattr(v, "clone"):
            return v.clone()
        return v

    def peek(self, key: str, default: Any = None) -> Any:
        """Read WITHOUT the clone-on-read copy: a read-only borrow for
        vectorized scorer kernels that extract one numeric field per row —
        the clone (a dataclasses.replace per endpoint per cycle) is the
        dominant cost of attribute-driven scoring at pool scale. Callers
        must not mutate the returned value."""
        v = self._data.get(key, self._MISS)
        if v is self._MISS:
            v = self._base.get(key, self._MISS)
            if v is self._MISS:
                return default
        return v

    def keys(self) -> Iterable[str]:
        return {**self._base, **self._data}.keys()

    def __contains__(self, key: str) -> bool:
        return key in self._data or key in self._base


class SnapshotEndpoint:
    """Scorer-visible endpoint view carved from a PoolSnapshot: shared
    immutable metadata, the snapshot's metrics copy, a per-request overlay
    attribute map. Duck-compatible with ``framework.datalayer.Endpoint`` —
    filters/scorers/pickers, the director's prepare step, and the gateway's
    proxy leg all read only ``metadata`` / ``metrics`` / ``attributes``."""

    __slots__ = ("metadata", "metrics", "attributes", "snapshot_epoch")

    def __init__(self, metadata: EndpointMetadata, metrics: Metrics,
                 attrs_base: dict[str, Any], epoch: int):
        self.metadata = metadata
        self.metrics = metrics
        self.attributes = OverlayAttributes(attrs_base)
        self.snapshot_epoch = epoch

    def __repr__(self) -> str:
        return (f"SnapshotEndpoint({self.metadata.address_port}, "
                f"epoch={self.snapshot_epoch})")


class PoolSnapshot:
    """One epoch of the pool: immutable after construction. ``view()``
    builds fresh per-request SnapshotEndpoints (cheap: three slot stores
    per endpoint) so concurrent cycles never share a mutable object."""

    __slots__ = ("epoch", "built_at", "_entries", "_columns")

    def __init__(self, epoch: int, endpoints: Iterable[Endpoint]):
        self.epoch = epoch
        self.built_at = time.monotonic()
        # (metadata ref, metrics copy, attributes base copy) per endpoint.
        self._entries: list[tuple[EndpointMetadata, Metrics, dict]] | None = [
            (ep.metadata, _copy_metrics(ep.metrics),
             _copy_dict(ep.attributes._data))
            for ep in endpoints]
        self._columns: PoolColumns | None = None

    @classmethod
    def from_entries(cls, epoch: int,
                     entries: Iterable[tuple[EndpointMetadata, Metrics, dict]]
                     ) -> "PoolSnapshot":
        """Rehydrate a snapshot from already-materialized entries — the
        fleet's snapshot-IPC path (router/fleet.py): a follower worker
        installs the leader's published epoch verbatim instead of rebuilding
        its own, so a batch dispatched in any worker schedules against the
        same epoch it would have seen single-process."""
        snap = cls.__new__(cls)
        snap.epoch = epoch
        snap.built_at = time.monotonic()
        snap._entries = [(meta, metrics, dict(attrs))
                         for meta, metrics, attrs in entries]
        snap._columns = None
        return snap

    @classmethod
    def from_columns(cls, epoch: int, cols: PoolColumns) -> "PoolSnapshot":
        """Install decoded binary-frame columns directly as the scheduling
        view (fleet follower, router/snapwire.py): no per-endpoint
        re-marshal — entries materialize lazily only if something (e.g. a
        promotion-time republish) actually asks for them."""
        snap = cls.__new__(cls)
        snap.epoch = epoch
        snap.built_at = time.monotonic()
        snap._entries = None
        snap._columns = cols
        return snap

    def entries(self) -> list[tuple[EndpointMetadata, Metrics, dict]]:
        """The raw (metadata, metrics, attrs) entries — the serialization
        unit the fleet's snapshot publisher pickles onto the IPC socket.
        Treat as immutable: the tuples are shared with live views."""
        if self._entries is None:
            self._entries = self._columns.materialize_entries()
        return self._entries

    def columns(self) -> PoolColumns:
        """The columnar view of this epoch, built lazily once and shared by
        every scheduling cycle against it (vectorized kernels index these
        arrays). Benign to race: two threads may both build; both results
        are equivalent and immutable."""
        cols = self._columns
        if cols is None:
            cols = self._columns = PoolColumns.from_entries(self._entries)
        return cols

    def __len__(self) -> int:
        if self._entries is None:
            return self._columns.n
        return len(self._entries)

    def view(self) -> list[SnapshotEndpoint]:
        """A fresh scheduling view: one overlay endpoint per pool member.
        Columns-backed snapshots (fleet follower) hand out column-metrics
        proxies instead of dataclass copies — same reads, zero re-marshal."""
        epoch = self.epoch
        if self._entries is None:
            cols = self._columns
            return [SnapshotEndpoint(cols.metas[i], ColumnMetrics(cols, i),
                                     cols.attrs[i], epoch)
                    for i in range(cols.n)]
        return [SnapshotEndpoint(meta, metrics, attrs, epoch)
                for meta, metrics, attrs in self._entries]

    def view_at(self, i: int) -> SnapshotEndpoint:
        """One pool member's overlay view without materializing the rest —
        the vectorized cycle's picked-rows path (EndpointBatch.view_row)."""
        if self._entries is None:
            cols = self._columns
            return SnapshotEndpoint(cols.metas[i], ColumnMetrics(cols, i),
                                    cols.attrs[i], self.epoch)
        meta, metrics, attrs = self._entries[i]
        return SnapshotEndpoint(meta, metrics, attrs, self.epoch)


class EndpointBatch:
    """The candidate set handed to a vectorized scheduling cycle: the
    snapshot's shared PoolColumns plus an optional base row restriction
    (Envoy subset hint). List-duck-compatible — ``len``/iteration/indexing
    materialize per-request ``SnapshotEndpoint`` views lazily, so scalar
    consumers (producers, fallback plugins, the proxy leg) keep working
    while vectorized kernels index the arrays and never build views at
    all."""

    __slots__ = ("snapshot", "columns", "base_rows", "_views", "_row_views")

    def __init__(self, snapshot: PoolSnapshot,
                 base_rows: np.ndarray | None = None):
        self.snapshot = snapshot
        self.columns = snapshot.columns()
        # None = every pool row; else an int64 row-index array (subset).
        self.base_rows = base_rows
        self._views: list[SnapshotEndpoint] | None = None
        # Sparse row → view cache: a pure-kernel cycle that only needs its
        # few PICKED endpoints must not pay O(pool) view construction.
        # Identity-stable with views(): a row's view is built once per
        # batch whichever path asks first, so overlay writes stay shared.
        self._row_views: dict[int, SnapshotEndpoint] = {}

    def all_rows(self) -> np.ndarray:
        if self.base_rows is not None:
            return self.base_rows
        return np.arange(self.columns.n, dtype=np.int64)

    def view_row(self, r: int) -> SnapshotEndpoint:
        """This batch's overlay view of pool row ``r`` (built on demand)."""
        v = self._views
        if v is not None:
            return v[r]
        view = self._row_views.get(r)
        if view is None:
            view = self._row_views[r] = self.snapshot.view_at(r)
        return view

    def views(self) -> list[SnapshotEndpoint]:
        """Full-pool per-request views, materialized once per batch (the
        producer/scalar-fallback path; overlay writes land here)."""
        v = self._views
        if v is None:
            cache = self._row_views
            v = self._views = [
                cache.get(i) if i in cache else self.snapshot.view_at(i)
                for i in range(self.columns.n)]
        return v

    def endpoints_at(self, rows) -> list[SnapshotEndpoint]:
        rs = rows.tolist() if isinstance(rows, np.ndarray) else rows
        v = self._views
        if v is not None:
            return [v[r] for r in rs]
        return [self.view_row(r) for r in rs]

    def keys_at(self, rows) -> list[str]:
        ks = self.columns.keys
        return [ks[r] for r in rows.tolist()] if isinstance(rows, np.ndarray) \
            else [ks[r] for r in rows]

    def subset(self, allowed: set[str]) -> "EndpointBatch":
        """Restrict to the address_ports in ``allowed`` (subset hint),
        sharing the materialized views so overlay writes stay visible."""
        keys = self.columns.keys
        rows = np.fromiter((r for r in self.all_rows().tolist()
                            if keys[r] in allowed), dtype=np.int64)
        nb = EndpointBatch.__new__(EndpointBatch)
        nb.snapshot = self.snapshot
        nb.columns = self.columns
        nb.base_rows = rows
        nb._views = self._views
        nb._row_views = self._row_views
        return nb

    def __len__(self) -> int:
        if self.base_rows is not None:
            return len(self.base_rows)
        return self.columns.n

    def __iter__(self):
        if self.base_rows is None:
            return iter(self.views())
        return iter(self.endpoints_at(self.base_rows))

    def __getitem__(self, i):
        if self.base_rows is None:
            return self.views()[i]
        return self.views()[int(self.base_rows[i])]

    def __repr__(self) -> str:
        return (f"EndpointBatch(n={len(self)}, "
                f"epoch={self.snapshot.epoch})")
