"""vllmgrpc-parser: vLLM gRPC protobuf bodies (reference:
framework/plugins/requesthandling/parsers/vllmgrpc — Generate/Embed paths of
api/proto/vllm_engine.proto, gRPC length-prefixed framing).

TPU-native redesign: the reference links ~2.5k lines of protoc-generated Go;
here a ~100-line protobuf wire-format reader decodes exactly the fields the
router needs (request id, prompt text/token ids, sampling knobs) — no codegen,
no grpcio dependency, same wire bytes. Unknown paths → ParseResult.skip →
random-endpoint fallback, matching the reference (vllmgrpc.go ParseRequest).
"""

from __future__ import annotations

import json
import struct
from typing import Any, Iterator

from ..framework.plugin import PluginBase, register_plugin
from ..framework.scheduling import InferenceRequestBody
from .parsers import ParseResult

GENERATE_PATH = "/vllm.grpc.engine.VllmEngine/Generate"
EMBED_PATH = "/vllm.grpc.engine.VllmEngine/Embed"
METHOD_PATH_HEADER = ":path"  # H2C pseudo-header carrying the gRPC method


# ---- minimal protobuf wire reader --------------------------------------


def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated varint")
        b = buf[pos]
        result |= (b & 0x7F) << shift
        pos += 1
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def _fields(buf: bytes) -> Iterator[tuple[int, int, Any]]:
    """Yields (field_number, wire_type, value) over a protobuf message."""
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 0x7
        if wire == 0:        # varint
            value, pos = _read_varint(buf, pos)
        elif wire == 1:      # fixed64
            value = buf[pos:pos + 8]
            if len(value) != 8:
                raise ValueError("truncated fixed64 field")
            pos += 8
        elif wire == 2:      # length-delimited
            ln, pos = _read_varint(buf, pos)
            value = buf[pos:pos + ln]
            if len(value) != ln:
                raise ValueError("truncated length-delimited field")
            pos += ln
        elif wire == 5:      # fixed32
            value = buf[pos:pos + 4]
            if len(value) != 4:
                raise ValueError("truncated fixed32 field")
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, value


def _packed_uint32(value: bytes | int, wire: int) -> list[int]:
    if wire == 0:  # unpacked single element
        return [int(value)]
    out, pos = [], 0
    while pos < len(value):
        v, pos = _read_varint(value, pos)
        out.append(v)
    return out


def _f32(value: bytes | int, wire: int) -> float:
    if wire == 5:
        return struct.unpack("<f", value)[0]
    raise ValueError("expected fixed32 float")


def parse_grpc_frame(body: bytes) -> bytes:
    """Strip the gRPC length-prefixed frame: 1-byte compressed flag +
    4-byte big-endian message length."""
    if len(body) < 5:
        raise ValueError("gRPC frame too short")
    compressed = body[0]
    if compressed:
        raise ValueError("compressed gRPC frames are not supported")
    (length,) = struct.unpack(">I", body[1:5])
    msg = body[5:5 + length]
    if len(msg) != length:
        raise ValueError("truncated gRPC frame")
    return msg


def iter_grpc_frames(body: bytes) -> Iterator[bytes]:
    """All length-prefixed messages in a (possibly coalesced) DATA buffer —
    a streamed response's final body chunk routinely carries several frames
    ([token chunk][final chunk with counts])."""
    pos = 0
    while pos + 5 <= len(body):
        if body[pos]:
            raise ValueError("compressed gRPC frames are not supported")
        (length,) = struct.unpack(">I", body[pos + 1:pos + 5])
        msg = body[pos + 5:pos + 5 + length]
        if len(msg) != length:
            raise ValueError("truncated gRPC frame")
        yield msg
        pos += 5 + length


def _parse_tokenized(buf: bytes) -> tuple[str, list[int]]:
    text, ids = "", []
    for field, wire, value in _fields(buf):
        if field == 1 and wire == 2:
            text = value.decode("utf-8", "replace")
        elif field == 2 and wire in (0, 2):
            ids.extend(_packed_uint32(value, wire))
    return text, ids


def _parse_sampling(buf: bytes) -> dict[str, Any]:
    out: dict[str, Any] = {}
    stop: list[str] = []
    stop_ids: list[int] = []
    for field, wire, value in _fields(buf):
        if field == 1 and wire == 5:
            out["temperature"] = _f32(value, wire)
        elif field == 2 and wire == 5:
            out["top_p"] = _f32(value, wire)
        elif field == 3 and wire == 0:
            out["top_k"] = int(value)
        elif field == 8 and wire == 0:
            out["max_tokens"] = int(value)
        elif field == 10 and wire == 2:
            stop.append(value.decode("utf-8", "replace"))
        elif field == 11 and wire in (0, 2):
            stop_ids.extend(_packed_uint32(value, wire))
        elif field == 14 and wire == 0:
            out["ignore_eos"] = bool(value)
    if stop:
        out["stop"] = stop
    if stop_ids:
        out["stop_token_ids"] = stop_ids
    return out


def parse_generate_request(msg: bytes) -> dict[str, Any]:
    """GenerateRequest (vllm_engine.proto): request_id=1, tokenized=2,
    text=3, sampling_params=4, stream=5."""
    doc: dict[str, Any] = {}
    for field, wire, value in _fields(msg):
        if field == 1 and wire == 2:
            doc["request_id"] = value.decode("utf-8", "replace")
        elif field == 2 and wire == 2:
            text, ids = _parse_tokenized(value)
            if ids:
                doc["prompt_token_ids"] = ids
            if text and "prompt" not in doc:
                doc["prompt"] = text
        elif field == 3 and wire == 2:
            doc["prompt"] = value.decode("utf-8", "replace")
        elif field == 4 and wire == 2:
            doc.update(_parse_sampling(value))
        elif field == 5 and wire == 0:
            doc["stream"] = bool(value)
    return doc


def parse_generate_response(msg: bytes) -> dict[str, int] | None:
    """GenerateResponse (vllm_engine.proto:159-179): oneof chunk=1 |
    complete=2. Usage is populated only when the message carries token
    counts (streaming chunks leave them empty until the last one) —
    reference vllmgrpc.go:146-170."""
    for field, wire, value in _fields(msg):
        if field == 1 and wire == 2:      # GenerateStreamChunk
            counts = {2: 0, 3: 0, 4: 0}   # prompt, completion, cached
        elif field == 2 and wire == 2:    # GenerateComplete
            counts = {3: 0, 4: 0, 5: 0}
        else:
            continue
        keys = sorted(counts)
        for f2, w2, v2 in _fields(value):
            if f2 in counts and w2 == 0:
                counts[f2] = int(v2)
        prompt, completion, cached = (counts[k] for k in keys)
        if prompt <= 0 and completion <= 0:
            return None
        return {
            "prompt_tokens": prompt,
            "completion_tokens": completion,
            "total_tokens": prompt + completion,
            "prompt_tokens_details": {"cached_tokens": cached},
        }
    return None


def parse_embed_response(msg: bytes) -> dict[str, int] | None:
    """EmbedResponse (vllm_engine.proto:190-194): embedding=1 (packed
    floats), prompt_tokens=2."""
    prompt_tokens = 0
    for field, wire, value in _fields(msg):
        if field == 2 and wire == 0:
            prompt_tokens = int(value)
    if prompt_tokens <= 0:
        return None
    return {"prompt_tokens": prompt_tokens, "completion_tokens": 0,
            "total_tokens": prompt_tokens}


def parse_embed_request(msg: bytes) -> dict[str, Any]:
    doc: dict[str, Any] = {}
    for field, wire, value in _fields(msg):
        if field == 1 and wire == 2:
            doc["request_id"] = value.decode("utf-8", "replace")
        elif field == 2 and wire == 2:
            text, ids = _parse_tokenized(value)
            if ids:
                doc["input_token_ids"] = ids
            if text:
                doc["input"] = text
    return doc


@register_plugin("vllmgrpc-parser")
class VllmGrpcParser(PluginBase):
    """Parses gRPC-framed vLLM engine protobufs into the scheduler body."""

    def parse(self, raw: bytes, headers: dict[str, str], path: str = "") -> ParseResult:
        method = headers.get(METHOD_PATH_HEADER) or path
        if method not in (GENERATE_PATH, EMBED_PATH):
            return ParseResult(body=InferenceRequestBody(raw=raw), skip=True)
        try:
            msg = parse_grpc_frame(raw)
            if method == EMBED_PATH:
                doc = parse_embed_request(msg)
                body = InferenceRequestBody(embeddings=doc, raw=raw)
                if doc.get("input_token_ids"):
                    body.tokenized_prompt = doc["input_token_ids"]
            else:
                doc = parse_generate_request(msg)
                body = InferenceRequestBody(completions=doc, raw=raw)
                if doc.get("prompt_token_ids"):
                    body.tokenized_prompt = doc["prompt_token_ids"]
            return ParseResult(body=body, model=str(doc.get("model", "")))
        except (ValueError, struct.error, TypeError, AttributeError) as e:
            # Broad by design: attacker-supplied bytes must never 500 the
            # gateway — wire types are validated per field above, and any
            # residual decode mismatch degrades to a parse error (400).
            return ParseResult(body=None, error=f"invalid gRPC payload: {e}")

    def parse_response(self, raw: bytes, headers: dict[str, str],
                       end_of_stream: bool = True) -> dict[str, int] | None:
        """Usage extraction from gRPC response frames (the reference's
        Parser.ParseResponse, vllmgrpc.go:122-170): GenerateResponse
        chunk/complete first, EmbedResponse fallback. Walks every frame in
        the buffer and keeps the LAST usage seen — streamed responses leave
        counts empty until the final chunk."""
        usage = None
        try:
            for msg in iter_grpc_frames(raw):
                u = parse_generate_response(msg)
                if u is None:
                    u = parse_embed_response(msg)
                if u is not None:
                    usage = u
        except (ValueError, struct.error, TypeError):
            pass
        return usage

    def serialize(self, body: InferenceRequestBody) -> bytes:
        # The wire bytes are authoritative: the router never mutates protobuf
        # bodies (no model rewrite on gRPC paths), so forward them untouched.
        if body.raw is not None:
            return body.raw
        return json.dumps(body.payload or {}).encode()
