from .parsers import OpenAIParser, PassthroughParser, ParseResult, make_parser

__all__ = ["OpenAIParser", "PassthroughParser", "ParseResult", "make_parser"]
