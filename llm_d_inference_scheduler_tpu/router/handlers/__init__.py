from .parsers import OpenAIParser, PassthroughParser, ParseResult, make_parser
from . import vllmgrpc  # noqa: F401 (registers vllmgrpc-parser)

__all__ = ["OpenAIParser", "PassthroughParser", "ParseResult", "make_parser"]
