"""Request parsers (reference: framework/plugins/requesthandling/parsers;
interface at framework/interface/requesthandling/plugins.go:28-67).

ParseResult.skip routes opaque bodies to a random endpoint (the reference's
passthrough-parser fallback semantics, handlers/server.go:335-342).
"""

from __future__ import annotations

import dataclasses
import json
import re
from typing import Any

from ..framework.plugin import PluginBase, register_plugin
from ..framework.scheduling import InferenceRequestBody


@dataclasses.dataclass
class ParseResult:
    body: InferenceRequestBody | None
    model: str = ""
    skip: bool = False
    error: str | None = None


def parse_json_usage(raw: bytes) -> dict[str, int] | None:
    """Default ParseResponse behavior: OpenAI-shaped JSON usage extraction
    (reference ParsedResponse.Usage, framework/interface/requesthandling).
    Parser plugins override ``parse_response`` for non-JSON wire formats."""
    try:
        doc = json.loads(raw)
        u = doc.get("usage")
        return u if isinstance(u, dict) else None
    except Exception:
        return None


@register_plugin("openai-parser")
class OpenAIParser(PluginBase):
    """OpenAI /v1/completions + /v1/chat/completions (+ SSE stream awareness)."""

    def parse(self, raw: bytes, headers: dict[str, str], path: str = "") -> ParseResult:
        try:
            doc = json.loads(raw)
        except Exception as e:
            return ParseResult(body=None, error=f"invalid JSON body: {e}")
        if not isinstance(doc, dict):
            return ParseResult(body=None, error="body must be a JSON object")
        model = str(doc.get("model", ""))
        # Path first: /v1/responses bodies carry "input" exactly like
        # /v1/embeddings, so shape alone cannot distinguish them
        # (reference routes by API path, types.go:64-88).
        if "responses" in path:
            body = InferenceRequestBody(responses=doc, raw=raw)
        elif "conversations" in path:
            body = InferenceRequestBody(conversations=doc, raw=raw)
        elif "messages" in doc:
            body = InferenceRequestBody(chat_completions=doc, raw=raw)
        elif "prompt" in doc or "completions" in path:
            body = InferenceRequestBody(completions=doc, raw=raw)
        elif "input" in doc and ("instructions" in doc or "tools" in doc):
            body = InferenceRequestBody(responses=doc, raw=raw)
        elif "input" in doc:
            body = InferenceRequestBody(embeddings=doc, raw=raw)
        else:
            body = InferenceRequestBody(completions=doc, raw=raw)
        return ParseResult(body=body, model=model)

    def serialize(self, body: InferenceRequestBody) -> bytes:
        payload = body.payload  # includes embeddings (scheduling.py payload)
        if payload is None:
            return body.raw or b""
        return json.dumps(payload).encode()


@register_plugin("vertexai-parser")
class VertexAIParser(PluginBase):
    """Vertex AI prediction shape: {"instances": [...], "parameters": {...}}
    (reference parsers/vertexai). The first instance's prompt/messages map to
    the OpenAI body the scheduler plugins understand; parameters carry
    sampling knobs (maxOutputTokens, temperature)."""

    def parse(self, raw: bytes, headers: dict[str, str], path: str = "") -> ParseResult:
        try:
            doc = json.loads(raw)
        except Exception as e:
            return ParseResult(body=None, error=f"invalid JSON body: {e}")
        instances = doc.get("instances")
        if not isinstance(instances, list) or not instances:
            return ParseResult(body=None, error="vertexai body needs instances[]")
        if len(instances) > 1:
            return ParseResult(
                body=None,
                error="vertexai multi-instance batches are not supported; "
                      "send one instance per request")
        inst = instances[0]
        if isinstance(inst, str):
            inst = {"prompt": inst}  # Vertex allows bare-string instances
        if not isinstance(inst, dict):
            return ParseResult(body=None, error="vertexai instance must be an "
                                                "object or string")
        params = doc.get("parameters") or {}
        model = str(doc.get("model", ""))
        if not model:
            # Vertex carries the model in the :predict URL, not the body.
            m = re.search(r"models/([^/:]+)", path or "")
            if m:
                model = m.group(1)
        mapped: dict[str, Any] = {"model": model}
        if "maxOutputTokens" in params:
            mapped["max_tokens"] = params["maxOutputTokens"]
        if "temperature" in params:
            mapped["temperature"] = params["temperature"]
        if "messages" in inst:
            mapped["messages"] = inst["messages"]
            return ParseResult(
                body=InferenceRequestBody(chat_completions=mapped, raw=raw),
                model=model)
        mapped["prompt"] = inst.get("prompt", inst.get("content", ""))
        return ParseResult(
            body=InferenceRequestBody(completions=mapped, raw=raw), model=model)

    def serialize(self, body: InferenceRequestBody) -> bytes:
        return json.dumps(body.payload or {}).encode()


@register_plugin("passthrough-parser")
class PassthroughParser(PluginBase):
    """Opaque bodies → ParseResult.skip → random-endpoint fallback."""

    def parse(self, raw: bytes, headers: dict[str, str], path: str = "") -> ParseResult:
        return ParseResult(body=InferenceRequestBody(raw=raw), skip=True)

    def serialize(self, body: InferenceRequestBody) -> bytes:
        return body.raw or b""


def make_parser(spec: dict[str, Any], handle: Any = None):
    from ..framework.plugin import global_registry

    ptype = spec.get("type", "openai-parser")
    return global_registry.instantiate(ptype, spec.get("name") or ptype,
                                       spec.get("parameters") or {}, handle)
