"""ext-proc protocol state machine (reference L1: pkg/epp/handlers).

Implements the behavior of the reference's StreamingServer.Process
(/root/reference/pkg/epp/handlers/server.go:168-598) against an abstract
message model mirroring Envoy's ext-proc FULL_DUPLEX_STREAMED protocol:

- strict Header→Body→Trailer response ordering (updateStateAndSendIfNeeded,
  server.go:489-598);
- request body accumulated across chunks until end_of_stream, then parsed and
  scheduled; header mutation carries x-gateway-destination-endpoint and the
  dynamic-metadata analogue;
- bodyless requests (end_of_stream on headers) and unparseable bodies fall
  back to a random endpoint (server.go:335-342, request.go:40-47);
- scheduling/admission failures produce an ImmediateResponse with
  x-removal-reason (server.go:493-517);
- response phases run the ResponseReceived/Streaming/Complete hooks and
  rewrite the model name back to the client-facing one (server.go:471-485).

The Envoy gRPC wire binding is a codec layer over these dataclasses; tests
and the standalone gateway drive the same machine directly.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import logging
import uuid
from typing import Any

from ..framework.scheduling import InferenceRequest
from ..requestcontrol.admission import X_REMOVAL_REASON
from ..requestcontrol.director import (
    H_DESTINATION,
    H_DESTINATION_SERVED,
    H_REQUEST_ID,
    RequestError,
)

log = logging.getLogger("router.extproc")


# ---- message model (ext-proc ProcessingRequest analogue) -----------------

@dataclasses.dataclass
class RequestHeaders:
    headers: dict[str, str]
    end_of_stream: bool = False
    path: str = "/v1/completions"


@dataclasses.dataclass
class RequestBody:
    chunk: bytes
    end_of_stream: bool = False


@dataclasses.dataclass
class RequestTrailers:
    trailers: dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ResponseHeaders:
    headers: dict[str, str]
    status: int = 200


@dataclasses.dataclass
class ResponseBody:
    chunk: bytes
    end_of_stream: bool = False


# ---- response model (ProcessingResponse analogue) ------------------------

@dataclasses.dataclass
class HeaderMutation:
    set_headers: dict[str, str] = dataclasses.field(default_factory=dict)
    remove_headers: list[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class CommonResponse:
    phase: str  # request_headers | request_body | response_headers | response_body
    header_mutation: HeaderMutation | None = None
    body: bytes | None = None  # replacement body (request_body/response_body)
    # Whether the replacement body completes the stream direction. The wire
    # binding stamps it onto the final StreamedBodyResponse chunk: request
    # bodies are always complete once scheduled (reference
    # envoy/request.go:25-27 setEos=true); response bodies carry the
    # incoming chunk's end_of_stream through (handlers/response.go:91-92).
    body_eos: bool = False
    # Destination header changed after Envoy computed its route — the
    # headers response that carries x-gateway-destination-endpoint sets this
    # (reference request.go:100 ClearRouteCache: true).
    clear_route_cache: bool = False
    dynamic_metadata: dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ImmediateResponse:
    status: int
    headers: dict[str, str] = dataclasses.field(default_factory=dict)
    body: bytes = b""


class StreamState(enum.Enum):
    # reference StreamRequestState (server.go:98-160)
    AWAITING_REQUEST = enum.auto()
    REQUEST_HEADERS_DONE = enum.auto()
    REQUEST_BODY_DONE = enum.auto()
    RESPONSE_HEADERS_DONE = enum.auto()
    COMPLETE = enum.auto()


class ExtProcSession:
    """One per ext-proc stream (i.e. per proxied request)."""

    def __init__(self, director: Any, parser: Any):
        self.director = director
        self.parser = parser
        self.state = StreamState.AWAITING_REQUEST
        self.headers: dict[str, str] = {}
        self.path = "/v1/completions"
        self._body = bytearray()
        self.request: InferenceRequest | None = None
        self.original_model = ""
        self.target_endpoint = None
        self.usage: dict[str, int] = {}
        self._scheduled = False  # handle_request succeeded (hooks armed)

    # ---- request phase -------------------------------------------------

    async def on_request_headers(self, msg: RequestHeaders):
        """Returns None when a body follows: the reference defers the
        request-headers response until the body is complete and scheduled
        (server.go:314 breaks with no send; reqHeaderResp is generated at
        body EOS, server.go:362). In FULL_DUPLEX_STREAMED mode Envoy holds
        the request until the headers response arrives, so answering early
        would route before a destination is chosen."""
        if self.state is not StreamState.AWAITING_REQUEST:
            raise ProtocolError("request headers after request phase started")
        self.state = StreamState.REQUEST_HEADERS_DONE
        self.headers = {k.lower(): v for k, v in msg.headers.items()}
        from ..gateway import ROUTER_OWNED_HEADERS

        for h in ROUTER_OWNED_HEADERS:
            self.headers.pop(h, None)
        self.headers.setdefault(H_REQUEST_ID, f"req-{uuid.uuid4().hex[:12]}")
        self.path = msg.path
        if msg.end_of_stream:
            # Bodyless request: random-endpoint fallback (request.go:40-47).
            self.state = StreamState.REQUEST_BODY_DONE
            return self._fallback_response()
        return None

    async def on_request_body(self, msg: RequestBody):
        """Mid-stream chunks are buffered with no response (server.go:
        315-318). The terminal chunk parses + schedules and returns TWO
        responses — the deferred headers response (destination header
        mutation + dynamic metadata, clear_route_cache) followed by the
        mutated body (server.go:362-363); the wire binding re-chunks the
        body to ≤62 KB frames."""
        if self.state is not StreamState.REQUEST_HEADERS_DONE:
            raise ProtocolError("request body before headers / after EOS")
        self._body.extend(msg.chunk)
        if not msg.end_of_stream:
            return None
        self.state = StreamState.REQUEST_BODY_DONE

        raw = bytes(self._body)
        parse = self.parser.parse(raw, self.headers, path=self.path)
        if parse.error:
            return ImmediateResponse(
                status=400, headers={X_REMOVAL_REASON: parse.error},
                body=json.dumps({"error": parse.error}).encode())
        if parse.skip:
            return self._fallback_response(body=raw)

        self.request = InferenceRequest(
            request_id=self.headers[H_REQUEST_ID],
            target_model=parse.model,
            body=parse.body,
            headers=self.headers,
            request_size_bytes=len(raw))
        self.original_model = parse.model
        try:
            result = await self.director.handle_request(None, self.request)
        except RequestError as e:
            return ImmediateResponse(
                status=e.code, headers={X_REMOVAL_REASON: e.reason},
                body=json.dumps({"error": e.reason}).encode())

        self.target_endpoint = result.primary().target_endpoints[0]
        self._scheduled = True
        body_out = raw
        payload = self.request.body.payload
        if payload is not None and self.request.target_model != self.original_model:
            payload = dict(payload)
            payload["model"] = self.request.target_model
            body_out = json.dumps(payload).encode()

        mutation = HeaderMutation(set_headers={
            H_DESTINATION: self.request.headers[H_DESTINATION],
            # Body mutation changes the length (request.go:120-129).
            "content-length": str(len(body_out)),
            **{h: self.request.headers[h] for h in (
                "x-prefiller-host-port", "x-encoder-hosts-ports",
                "x-data-parallel-host-port") if h in self.request.headers},
        })
        return [
            CommonResponse(
                phase="request_headers",
                header_mutation=mutation,
                clear_route_cache=True,
                dynamic_metadata={"envoy.lb": {
                    H_DESTINATION: self.request.headers[H_DESTINATION]}}),
            CommonResponse(phase="request_body", body=body_out,
                           body_eos=True),
        ]

    async def on_request_trailers(self, msg: RequestTrailers):
        return CommonResponse(phase="request_trailers")

    # ---- response phase ------------------------------------------------

    async def on_response_headers(self, msg: ResponseHeaders):
        if self.state is not StreamState.REQUEST_BODY_DONE:
            raise ProtocolError("response headers before request completed")
        self.state = StreamState.RESPONSE_HEADERS_DONE
        if self.request is not None:
            self.director.handle_response_received(
                None, self.request, self.target_endpoint, msg.status)
        mutation = HeaderMutation(set_headers={
            H_DESTINATION_SERVED: (self.target_endpoint.metadata.address_port
                                   if self.target_endpoint else "")})
        if self.request is not None and "x-session-token" in self.request.headers:
            # Return the scheduling-stamped session token to the client
            # (reference session_affinity.go ResponseBody).
            mutation.set_headers["x-session-token"] = \
                self.request.headers["x-session-token"]
        return CommonResponse(phase="response_headers", header_mutation=mutation)

    async def on_response_body(self, msg: ResponseBody):
        if self.state is not StreamState.RESPONSE_HEADERS_DONE:
            raise ProtocolError("response body before response headers")
        body = msg.chunk
        if self.request is not None:
            self.director.handle_response_streaming(
                None, self.request, self.target_endpoint, body)
        if msg.end_of_stream:
            self.state = StreamState.COMPLETE
            body = self._rewrite_model(body)
            self.usage = self._extract_usage(body) or self.usage
            if self.request is not None:
                self.director.handle_response_complete(
                    None, self.request, self.target_endpoint, self.usage)
            return CommonResponse(phase="response_body", body=body,
                                  body_eos=True,
                                  dynamic_metadata={"usage": self.usage})
        return CommonResponse(phase="response_body", body=body)

    def abandon(self) -> None:
        """Stream ended without a terminal response body (client reset, Envoy
        abort): run forced completion (reference server.go:232-254 defer) so
        director-side per-request state — streaming-plugin workers, dispatch
        counters — tears down instead of leaking. Idempotent."""
        if (self._scheduled and self.request is not None
                and self.state is not StreamState.COMPLETE):
            self.state = StreamState.COMPLETE
            self.director.handle_response_complete(
                None, self.request, self.target_endpoint, self.usage)

    # ---- helpers -------------------------------------------------------

    def _fallback_response(self, body: bytes | None = None):
        """Random-endpoint fallback (request.go:69-84): a headers response
        carrying the destination, plus the unmodified body when one was
        buffered (skip-parse path)."""
        ep = self.director.get_random_endpoint()
        if ep is None:
            return ImmediateResponse(
                status=503, headers={X_REMOVAL_REASON: "no ready endpoints"},
                body=b'{"error": "no ready endpoints"}')
        self.target_endpoint = ep
        headers_resp = CommonResponse(
            phase="request_headers",
            header_mutation=HeaderMutation(
                set_headers={H_DESTINATION: ep.metadata.address_port}),
            clear_route_cache=True,
            dynamic_metadata={"envoy.lb": {H_DESTINATION: ep.metadata.address_port}})
        if body is None:
            return headers_resp
        return [headers_resp,
                CommonResponse(phase="request_body", body=body, body_eos=True)]

    def _rewrite_model(self, body: bytes) -> bytes:
        if (self.request is None or not self.original_model
                or self.request.target_model == self.original_model):
            return body
        try:
            doc = json.loads(body)
            if isinstance(doc, dict) and "model" in doc:
                doc["model"] = self.original_model
                return json.dumps(doc).encode()
        except Exception:
            pass
        return body

    def _extract_usage(self, body: bytes) -> dict[str, int] | None:
        # The configured parser owns the response wire format (the reference's
        # Parser.ParseResponse, vllmgrpc.go:122-170); JSON usage extraction is
        # the default for OpenAI-shaped bodies.
        pr = getattr(self.parser, "parse_response", None)
        if pr is not None:
            try:
                return pr(body, self.headers, end_of_stream=True)
            except Exception:
                return None
        from .parsers import parse_json_usage

        return parse_json_usage(body)


class ProtocolError(Exception):
    pass
