"""Envoy ext-proc gRPC wire binding (FULL_DUPLEX_STREAMED).

Reference: /root/reference/pkg/epp/handlers/server.go:168-287 — the EPP's
actual product surface is `envoy.service.ext_proc.v3.ExternalProcessor/
Process`, a bidirectional gRPC stream of ProcessingRequest/ProcessingResponse.
This module is a pure codec + transport layer over the wire-agnostic state
machine in handlers/extproc.py: the image ships grpcio but no generated Envoy
protobufs, so the v3 messages are encoded/decoded by hand against the stable
published schema (envoy/service/ext_proc/v3/external_processor.proto field
numbers cited inline), the same approach as router/health_grpc.py.

Mid-stream eviction mirrors the reference's armed evict channel
(server.go:266-284, 353-356): after scheduling, the stream loop waits on
{next frame, evict event} and answers an eviction with ImmediateResponse(429)
+ x-removal-reason.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, AsyncIterator

import grpc
import grpc.aio

from ..flowcontrol.eviction import EVICTED_REASON
from ..requestcontrol.admission import X_REMOVAL_REASON
from .extproc import (
    CommonResponse,
    ExtProcSession,
    HeaderMutation,
    ImmediateResponse,
    ProtocolError,
    RequestBody,
    RequestHeaders,
    RequestTrailers,
    ResponseBody,
    ResponseHeaders,
)
from .vllmgrpc import _fields, _read_varint  # shared protobuf wire reader

log = logging.getLogger("router.extproc_grpc")

EXT_PROC_SERVICE = "envoy.service.ext_proc.v3.ExternalProcessor"

# ProcessingRequest oneof request field numbers — NOTE the interleaved
# request/response pairing of the published envoy schema
# (external_processor.proto): headers 2/3, bodies 4/5, trailers 6/7.
REQ_REQUEST_HEADERS = 2
REQ_RESPONSE_HEADERS = 3
REQ_REQUEST_BODY = 4
REQ_RESPONSE_BODY = 5
REQ_REQUEST_TRAILERS = 6
REQ_RESPONSE_TRAILERS = 7

# ProcessingResponse oneof response field numbers (same interleaving).
RESP_REQUEST_HEADERS = 1
RESP_RESPONSE_HEADERS = 2
RESP_REQUEST_BODY = 3
RESP_RESPONSE_BODY = 4
RESP_REQUEST_TRAILERS = 5
RESP_RESPONSE_TRAILERS = 6
RESP_IMMEDIATE = 7
RESP_DYNAMIC_METADATA = 8

# Max bytes per streamed body chunk. Envoy caps streamed chunks at 64 KB;
# the reference stays deliberately under it (pkg/common/envoy/chunking.go:
# 24-27 BodyByteLimit) so a mutated body never gets rejected on the wire.
BODY_BYTE_LIMIT = 62000


# ---- protobuf writer helpers -------------------------------------------


def _varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        out.append(b | (0x80 if v else 0))
        if not v:
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _ld(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def _vi(field: int, value: int) -> bytes:
    return _tag(field, 0) + _varint(value)


# ---- HeaderMap / HeaderMutation codec ----------------------------------


def _decode_header_map(buf: bytes) -> dict[str, str]:
    """config.core.v3.HeaderMap { repeated HeaderValue headers = 1; }
    HeaderValue { string key = 1; string value = 2; bytes raw_value = 3; }"""
    out: dict[str, str] = {}
    for field, wire, value in _fields(buf):
        if field == 1 and wire == 2:
            key = val = raw = None
            for f2, w2, v2 in _fields(value):
                if f2 == 1:
                    key = v2.decode("utf-8", "replace")
                elif f2 == 2:
                    val = v2.decode("utf-8", "replace")
                elif f2 == 3:
                    raw = v2.decode("utf-8", "replace")
            if key is not None:
                out[key] = raw if raw is not None else (val or "")
    return out


def _encode_header_value(key: str, value: str) -> bytes:
    # raw_value (3) is what Envoy expects from modern ext-proc servers.
    return _ld(1, key.encode()) + _ld(3, value.encode())


def _encode_header_mutation(m: HeaderMutation) -> bytes:
    """HeaderMutation { repeated HeaderValueOption set_headers = 1;
    repeated string remove_headers = 2; }; HeaderValueOption.header = 1
    (default append_action OVERWRITE_IF_EXISTS_OR_ADD)."""
    out = b""
    for k, v in m.set_headers.items():
        out += _ld(1, _ld(1, _encode_header_value(k, v)))
    for k in m.remove_headers:
        out += _ld(2, k.encode())
    return out


# ---- google.protobuf.Struct codec (dynamic_metadata) --------------------


def _encode_value(v: Any) -> bytes:
    """google.protobuf.Value: null=1, number=2(double), string=3, bool=4,
    struct=5, list=6."""
    import struct as _s

    if v is None:
        return _vi(1, 0)
    if isinstance(v, bool):
        return _vi(4, int(v))
    if isinstance(v, (int, float)):
        return _tag(2, 1) + _s.pack("<d", float(v))
    if isinstance(v, str):
        return _ld(3, v.encode())
    if isinstance(v, dict):
        return _ld(5, _encode_struct(v))
    if isinstance(v, (list, tuple)):
        payload = b"".join(_ld(1, _encode_value(x)) for x in v)
        return _ld(6, payload)
    return _ld(3, str(v).encode())


def _encode_struct(d: dict[str, Any]) -> bytes:
    """Struct { map<string, Value> fields = 1; } — map entries are nested
    messages {key=1, value=2}."""
    out = b""
    for k, v in d.items():
        entry = _ld(1, str(k).encode()) + _ld(2, _encode_value(v))
        out += _ld(1, entry)
    return out


# ---- ProcessingRequest decode ------------------------------------------


def decode_processing_request(data: bytes):
    """Returns the extproc.py dataclass for the request's set oneof member."""
    for field, wire, value in _fields(data):
        if field in (REQ_REQUEST_HEADERS, REQ_RESPONSE_HEADERS) and wire == 2:
            headers: dict[str, str] = {}
            eos = False
            for f2, w2, v2 in _fields(value):
                if f2 == 1 and w2 == 2:      # HeaderMap
                    headers = _decode_header_map(v2)
                elif f2 == 3 and w2 == 0:    # end_of_stream
                    eos = bool(v2)
            if field == REQ_REQUEST_HEADERS:
                return RequestHeaders(headers=headers, end_of_stream=eos,
                                      path=headers.get(":path", "/v1/completions"))
            try:
                status = int(headers.get(":status", "200"))
            except ValueError:
                status = 200
            return ResponseHeaders(headers=headers, status=status)
        if field in (REQ_REQUEST_BODY, REQ_RESPONSE_BODY) and wire == 2:
            body, eos = b"", False
            for f2, w2, v2 in _fields(value):
                if f2 == 1 and w2 == 2:
                    body = v2
                elif f2 == 2 and w2 == 0:
                    eos = bool(v2)
            cls = RequestBody if field == REQ_REQUEST_BODY else ResponseBody
            return cls(chunk=body, end_of_stream=eos)
        if field == REQ_REQUEST_TRAILERS and wire == 2:
            trailers = {}
            for f2, w2, v2 in _fields(value):
                if f2 == 1 and w2 == 2:
                    trailers = _decode_header_map(v2)
            return RequestTrailers(trailers=trailers)
        if field == REQ_RESPONSE_TRAILERS and wire == 2:
            return RequestTrailers(trailers={})  # no-op phase; ack only
    return None  # unknown/empty frame


# ---- ProcessingResponse encode -----------------------------------------

_PHASE_TO_FIELD = {
    "request_headers": RESP_REQUEST_HEADERS,
    "request_body": RESP_REQUEST_BODY,
    "request_trailers": RESP_REQUEST_TRAILERS,
    "response_headers": RESP_RESPONSE_HEADERS,
    "response_body": RESP_RESPONSE_BODY,
}


def _encode_streamed_body_mutation(chunk: bytes, eos: bool) -> bytes:
    """BodyMutation { StreamedBodyResponse streamed_response = 3
    { bytes body = 1; bool end_of_stream = 2; } } — the mutation shape Envoy
    requires in FULL_DUPLEX_STREAMED mode (reference chunking.go:40-46)."""
    streamed = _ld(1, chunk)
    if eos:
        streamed += _vi(2, 1)
    return _ld(3, _ld(3, streamed))


def encode_processing_responses(
        resp: CommonResponse | ImmediateResponse) -> list[bytes]:
    """Encode one logical response as the wire frames to send, splitting a
    mutated body into ≤BODY_BYTE_LIMIT streamed chunks (reference
    chunking.go:29-58, handlers/response.go:91-110): the header mutation
    rides the first frame, end_of_stream + dynamic metadata the last."""
    if (isinstance(resp, ImmediateResponse) or resp.body is None
            or len(resp.body) <= BODY_BYTE_LIMIT):
        return [encode_processing_response(resp)]
    field = _PHASE_TO_FIELD[resp.phase]
    chunks = [resp.body[i:i + BODY_BYTE_LIMIT]
              for i in range(0, len(resp.body), BODY_BYTE_LIMIT)]
    frames = []
    for i, chunk in enumerate(chunks):
        last = i == len(chunks) - 1
        common = b""
        if i == 0 and resp.header_mutation is not None:
            common += _ld(2, _encode_header_mutation(resp.header_mutation))
        common += _encode_streamed_body_mutation(chunk,
                                                 resp.body_eos and last)
        if i == 0 and resp.clear_route_cache:
            common += _vi(5, 1)
        frame = _ld(field, _ld(1, common))
        if last and resp.dynamic_metadata:
            frame += _ld(RESP_DYNAMIC_METADATA,
                         _encode_struct(resp.dynamic_metadata))
        frames.append(frame)
    return frames


def encode_processing_response(resp: CommonResponse | ImmediateResponse) -> bytes:
    if isinstance(resp, ImmediateResponse):
        # ImmediateResponse { HttpStatus status = 1 {code=1}; HeaderMutation
        # headers = 2; body = 3; }
        payload = _ld(1, _vi(1, resp.status))
        if resp.headers:
            payload += _ld(2, _encode_header_mutation(
                HeaderMutation(set_headers=dict(resp.headers))))
        if resp.body:
            payload += _ld(3, resp.body)
        return _ld(RESP_IMMEDIATE, payload)

    # CommonResponse { status = 1 (CONTINUE=0); header_mutation = 2;
    # body_mutation = 3; trailers = 4; clear_route_cache = 5; }
    common = b""
    if resp.header_mutation is not None:
        common += _ld(2, _encode_header_mutation(resp.header_mutation))
    if resp.body is not None:
        common += _encode_streamed_body_mutation(resp.body, resp.body_eos)
    if resp.clear_route_cache:
        common += _vi(5, 1)
    field = _PHASE_TO_FIELD[resp.phase]
    if field == RESP_REQUEST_TRAILERS:
        # TrailersResponse { HeaderMutation header_mutation = 1; }
        out = _ld(field, b"")
    else:
        # HeadersResponse/BodyResponse { CommonResponse response = 1; }
        out = _ld(field, _ld(1, common))
    if resp.dynamic_metadata:
        out += _ld(RESP_DYNAMIC_METADATA, _encode_struct(resp.dynamic_metadata))
    return out


# ---- the gRPC service ---------------------------------------------------


class ExtProcServer:
    """Serves ExternalProcessor/Process: one ExtProcSession per stream."""

    def __init__(self, director: Any, parser: Any, *, evictor: Any = None,
                 host: str = "127.0.0.1", port: int = 0, tls: Any = None):
        self.director = director
        self.parser = parser
        self.evictor = evictor
        self.host, self.port = host, port
        # Secure serving (runserver.go:136-171): a TlsServing identity —
        # cert dir or self-signed fallback, optional per-handshake reload.
        self.tls = tls
        self._server: grpc.aio.Server | None = None

    async def _process(self, request_iterator: AsyncIterator[bytes], context):
        session = ExtProcSession(self.director, self.parser)
        evicted = asyncio.Event()
        evict_key = None
        it = request_iterator.__aiter__()
        try:
            while True:
                recv = asyncio.ensure_future(it.__anext__())
                waiters = [recv]
                evict_waiter = None
                if evict_key is not None:
                    evict_waiter = asyncio.ensure_future(evicted.wait())
                    waiters.append(evict_waiter)
                done, pending = await asyncio.wait(
                    waiters, return_when=asyncio.FIRST_COMPLETED)
                if evict_waiter is not None and evict_waiter in done and not recv.done():
                    # Mid-stream eviction (server.go:266-284): 429 + reason.
                    recv.cancel()
                    yield encode_processing_response(ImmediateResponse(
                        status=429, headers={X_REMOVAL_REASON: EVICTED_REASON},
                        body=b'{"error": "evicted"}'))
                    return
                if evict_waiter is not None and not evict_waiter.done():
                    evict_waiter.cancel()
                try:
                    data = recv.result()
                except StopAsyncIteration:
                    return
                msg = decode_processing_request(data)
                if msg is None:
                    continue  # ignore unknown frames (forward-compat)
                try:
                    if isinstance(msg, RequestHeaders):
                        resp = await session.on_request_headers(msg)
                    elif isinstance(msg, RequestBody):
                        resp = await session.on_request_body(msg)
                        if (self.evictor is not None and evict_key is None
                                and session.request is not None
                                and not isinstance(resp, ImmediateResponse)):
                            evict_key = self.evictor.register(
                                session.request.request_id,
                                session.request.objectives.priority,
                                evicted.set)
                    elif isinstance(msg, RequestTrailers):
                        resp = await session.on_request_trailers(msg)
                    elif isinstance(msg, ResponseHeaders):
                        resp = await session.on_response_headers(msg)
                    else:
                        resp = await session.on_response_body(msg)
                except ProtocolError as e:
                    await context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                                        f"ext-proc protocol violation: {e}")
                    return
                # A handler may defer (None — buffering), answer once, or
                # emit several logical responses (deferred headers + body).
                responses = (resp if isinstance(resp, list)
                             else [resp] if resp is not None else [])
                for r in responses:
                    for frame in encode_processing_responses(r):
                        yield frame
                if any(isinstance(r, ImmediateResponse) for r in responses):
                    return
        finally:
            if evict_key is not None and self.evictor is not None:
                self.evictor.deregister(evict_key)
            # Streams that end without a terminal response (reset mid-flight)
            # still tear down director state (forced completion).
            try:
                session.abandon()
            except Exception:
                log.exception("session abandon failed")

    async def start(self) -> int:
        self._server = grpc.aio.server()
        handlers = grpc.method_handlers_generic_handler(EXT_PROC_SERVICE, {
            "Process": grpc.stream_stream_rpc_method_handler(
                self._process,
                request_deserializer=lambda b: b,    # codec handled above
                response_serializer=lambda b: b),
        })
        self._server.add_generic_rpc_handlers((handlers,))
        addr = f"{self.host}:{self.port}"
        if self.tls is not None:
            self.port = self._server.add_secure_port(
                addr, self.tls.grpc_server_credentials())
        else:
            self.port = self._server.add_insecure_port(addr)
        await self._server.start()
        log.info("ext-proc gRPC (FULL_DUPLEX_STREAMED) on %s:%d%s",
                 self.host, self.port, " (TLS)" if self.tls else "")
        return self.port

    async def stop(self):
        if self._server:
            await self._server.stop(grace=0.5)
