"""SLO & goodput ledger: per-request serving outcomes closing the predict →
observe loop.

The router *predicts* TTFT/TPOT at scheduling time
(requestcontrol/predicted_latency.py) and *records* every scheduling decision
(router/decisions.py), but neither says whether the request actually met its
SLO, how wrong the predictor was, or what the fleet's goodput is. P/D-Serve
(arXiv:2408.08147) runs its gateway on exactly this feedback — goodput, not
throughput, is the fleet objective — and NetKV (arXiv:2606.03910) needs
measured per-pair transfer cost before transfer-aware pairing can exist.

One ``RequestObservation`` rides each InferenceRequest (``request.outcome``):

- opened by the gateway before orchestration (captures queue time via the
  flow-control admission hook and the predictor's per-request prediction via
  the predicted-latency PreRequest hook);
- fed per transport chunk on the streaming path (one monotonic read + a few
  adds — the <1% of the 5 ms token cadence contract ``bench.py --slo-ramp``
  measures; the ``slo: {enabled: false}`` kill-switch reduces the per-chunk
  hook to one ``is None`` check);
- closed exactly once on EVERY terminal path — success, admission shed,
  retry-exhausted, deadline, mid-stream abort — computing actual TTFT / TPOT
  / e2e / queue time and an ``slo_met`` verdict against ``x-slo-ttft-ms`` /
  ``x-slo-tpot-ms`` (or configured per-model defaults).

The verdict is stamped back into the request's DecisionRecord (so
``/debug/decisions/<id>`` shows predicted vs actual vs SLO side by side),
aggregated into the fleet rollup served at ``/debug/slo`` (per-endpoint /
per-band attainment, predictor signed error + MAE, goodput vs raw token
rate), and exported as metric families (``router_slo_attainment``,
``router_goodput_tokens_total`` vs ``router_output_tokens_total``,
``router_predictor_error_ms{kind,role}``). ``scripts/verify_slo.py`` asserts
every terminal path stamps the ledger.
"""

from __future__ import annotations

import dataclasses
import math
import time
from collections import OrderedDict
from typing import Any

from .framework.datalayer import ROLE_LABEL
from .metrics import (
    GOODPUT_TOKENS_TOTAL,
    OUTPUT_TOKENS_TOTAL,
    PREDICTOR_ERROR_MS,
    SLO_ATTAINMENT,
    SLO_REQUESTS_TOTAL,
)

# SLO request headers (reference latencyslo/plugin.go:38-40); the
# predicted-latency producer consumes the same contract.
H_SLO_TTFT = "x-slo-ttft-ms"
H_SLO_TPOT = "x-slo-tpot-ms"

# Inter-arrival gap buckets (ms) for the streaming path: cheap fixed-size
# integer counters instead of a per-chunk Prometheus observe (~20x cheaper).
GAP_BUCKET_BOUNDS_MS = (2.5, 10.0, 50.0, 250.0)


@dataclasses.dataclass
class SloTargets:
    ttft_ms: float = 0.0
    tpot_ms: float = 0.0


@dataclasses.dataclass
class SloConfig:
    """The YAML ``slo:`` section. ``enabled: false`` is the kill-switch the
    overhead contract requires (per-chunk hook degrades to one ``is None``
    check). Per-model defaults apply when the request carries no SLO
    headers; 0 means "no SLO on that axis"."""

    enabled: bool = True
    default_ttft_ms: float = 0.0
    default_tpot_ms: float = 0.0
    per_model: dict[str, SloTargets] = dataclasses.field(default_factory=dict)

    @classmethod
    def from_spec(cls, spec: dict[str, Any] | None) -> "SloConfig":
        spec = spec or {}
        per_model = {}
        for model, t in (spec.get("perModel") or {}).items():
            per_model[model] = SloTargets(
                ttft_ms=float(t.get("ttftMs", 0.0)),
                tpot_ms=float(t.get("tpotMs", 0.0)))
        return cls(enabled=bool(spec.get("enabled", True)),
                   default_ttft_ms=float(spec.get("defaultTtftMs", 0.0)),
                   default_tpot_ms=float(spec.get("defaultTpotMs", 0.0)),
                   per_model=per_model)


class RequestObservation:
    """One request's serving observation. Mutated in place by the layer
    hooks; the ledger's ``complete()`` computes the verdict exactly once."""

    __slots__ = ("request_id", "model", "band", "t_start",
                 "slo_ttft_ms", "slo_tpot_ms",
                 "predicted_ttft_ms", "predicted_tpot_ms",
                 "endpoint", "role", "queue_ms",
                 "first_token_at", "last_token_at", "token_events",
                 "gap_sum_ms", "gap_max_ms", "gap_buckets",
                 "streamed", "abort_reason", "done")

    def __init__(self, request_id: str, model: str, band: int,
                 t_start: float, slo_ttft_ms: float, slo_tpot_ms: float):
        self.request_id = request_id
        self.model = model
        self.band = band
        self.t_start = t_start
        self.slo_ttft_ms = slo_ttft_ms
        self.slo_tpot_ms = slo_tpot_ms
        self.predicted_ttft_ms: float | None = None
        self.predicted_tpot_ms: float | None = None
        self.endpoint = ""
        self.role = ""
        self.queue_ms = 0.0
        self.first_token_at: float | None = None
        self.last_token_at: float | None = None
        self.token_events = 0
        self.gap_sum_ms = 0.0
        self.gap_max_ms = 0.0
        self.gap_buckets = [0, 0, 0, 0, 0]
        self.streamed = False
        self.abort_reason: str | None = None
        self.done = False

    # ---- streaming hot path --------------------------------------------
    #
    # first_token() reuses the monotonic read the gateway's TTFT observation
    # already paid for; on_chunk() is the only per-chunk cost the ledger
    # adds to the token relay — one clock read plus a handful of float ops
    # (microbenched in benchmarks/SLO_OBS.json against the 5 ms cadence).

    def first_token(self, now: float) -> None:
        self.first_token_at = now
        self.last_token_at = now
        self.token_events = 1
        self.streamed = True

    def on_chunk(self) -> None:
        now = time.monotonic()
        gap = (now - self.last_token_at) * 1e3
        self.last_token_at = now
        self.token_events += 1
        self.gap_sum_ms += gap
        if gap > self.gap_max_ms:
            self.gap_max_ms = gap
        b = self.gap_buckets
        if gap < GAP_BUCKET_BOUNDS_MS[0]:
            b[0] += 1
        elif gap < GAP_BUCKET_BOUNDS_MS[1]:
            b[1] += 1
        elif gap < GAP_BUCKET_BOUNDS_MS[2]:
            b[2] += 1
        elif gap < GAP_BUCKET_BOUNDS_MS[3]:
            b[3] += 1
        else:
            b[4] += 1


class _ErrAgg:
    """Signed-error accumulator for one (kind) of predictor error."""

    __slots__ = ("n", "sum_signed_ms", "sum_abs_ms")

    def __init__(self):
        self.n = 0
        self.sum_signed_ms = 0.0
        self.sum_abs_ms = 0.0

    def add(self, signed_ms: float) -> None:
        self.n += 1
        self.sum_signed_ms += signed_ms
        self.sum_abs_ms += abs(signed_ms)

    def render(self) -> dict[str, Any]:
        if not self.n:
            return {"n": 0}
        return {"n": self.n,
                "mae_ms": round(self.sum_abs_ms / self.n, 3),
                "mean_signed_ms": round(self.sum_signed_ms / self.n, 3)}


class _Agg:
    """Attainment + goodput accumulator (one per endpoint / band / total)."""

    __slots__ = ("requests", "slo_met", "shed", "output_tokens",
                 "goodput_tokens", "ttft_err", "tpot_err")

    def __init__(self):
        self.requests = 0
        self.slo_met = 0
        self.shed = 0
        self.output_tokens = 0
        self.goodput_tokens = 0
        self.ttft_err = _ErrAgg()
        self.tpot_err = _ErrAgg()

    def render(self, *, predictor: bool = True) -> dict[str, Any]:
        # Shed-at-admission is a DISTINCT verdict, not an SLO miss: a shed
        # request consumed no serving capacity and generated no tokens, so
        # attainment is judged over the requests the router actually tried
        # to serve. The shed count stays visible beside it.
        served = self.requests - self.shed
        doc: dict[str, Any] = {
            "requests": self.requests,
            "slo_met": self.slo_met,
            "shed": self.shed,
            "attainment": (round(self.slo_met / served, 4)
                           if served > 0 else None),
            "output_tokens": self.output_tokens,
            "goodput_tokens": self.goodput_tokens,
        }
        if predictor:
            doc["predictor"] = {"ttft": self.ttft_err.render(),
                                "tpot": self.tpot_err.render()}
        return doc


class SloLedger:
    """Fleet-level rollup of per-request serving outcomes.

    All writers run on the gateway's event loop (admission hook, PreRequest,
    the proxy's terminal paths), so the rollup needs no locking; the
    ``/debug/slo`` reader renders a point-in-time view."""

    # Endpoint-keyed state must survive pod churn without growing forever:
    # a rescheduled pod arrives under a fresh ip:port, so "endpoints ever
    # served" is unbounded even though the live pool is small. Same
    # rationale as TransferTable.MAX_PAIRS; eviction also drops the
    # router_slo_attainment gauge child so the series count stays bounded.
    MAX_ENDPOINTS = 256

    def __init__(self, cfg: SloConfig | None = None):
        self.cfg = cfg or SloConfig()
        self._totals = _Agg()
        self._by_endpoint: OrderedDict[str, _Agg] = OrderedDict()
        self._by_band: dict[int, _Agg] = {}
        self._miss_reasons: dict[str, int] = {}
        self._shed_reasons: dict[str, int] = {}
        self._start_unix = time.time()
        # Flat counters the timeline sampler (router/timeline.py) reads
        # every tick: prompt-token total and the per-role prompt/completion
        # token split — the prefill:decode mix is the P/D rebalancer's
        # controller input (ROADMAP item 5), and reading raw counters
        # keeps the tick path off the full snapshot() render.
        self.prompt_tokens_total = 0
        self.tokens_by_role: dict[str, tuple[int, int]] = {}
        # Per-WORKLOAD-CLASS aggregates ("prefill"-heavy vs "decode"-heavy
        # requests, classified by their own prompt:completion token split
        # at completion). Distinct from the per-serving-role split above:
        # a P/D request terminates on its decode pod, so serving-role
        # attainment can never say "prefill-shaped traffic is missing its
        # SLO" — which is exactly the starvation signal the rebalance
        # controller (router/rebalance.py) keys its per-role headroom on.
        # Public flat state, read per tick (the tokens_by_role precedent).
        self.by_workload: dict[str, _Agg] = {}

    @property
    def enabled(self) -> bool:
        return self.cfg.enabled

    @property
    def totals(self) -> _Agg:
        """The cumulative rollup accumulator (requests / slo_met / shed /
        output_tokens / goodput_tokens) — the timeline sampler's per-tick
        delta source."""
        return self._totals

    # ---- open -----------------------------------------------------------

    def resolve_targets(self, model: str,
                        headers: dict[str, str]) -> tuple[float, float]:
        """Request SLO targets: explicit headers win; per-model config, then
        global defaults fill the gaps. 0 = no SLO on that axis."""
        per_model = self.cfg.per_model.get(model)
        ttft = parse_slo_header_ms(headers, H_SLO_TTFT)
        if ttft <= 0:
            ttft = per_model.ttft_ms if per_model else self.cfg.default_ttft_ms
        tpot = parse_slo_header_ms(headers, H_SLO_TPOT)
        if tpot <= 0:
            tpot = per_model.tpot_ms if per_model else self.cfg.default_tpot_ms
        return ttft, tpot

    def start(self, request: Any, t_start: float) -> RequestObservation | None:
        """Open an observation (None when the kill-switch is off — every
        layer hook then degrades to a single ``is None`` check)."""
        if not self.cfg.enabled:
            return None
        ttft, tpot = self.resolve_targets(request.target_model,
                                          request.headers)
        obs = RequestObservation(request.request_id, request.target_model,
                                 request.objectives.priority, t_start,
                                 ttft, tpot)
        request.outcome = obs
        return obs

    # ---- close ----------------------------------------------------------

    def complete(self, request: Any, *, status: int,
                 endpoint: Any = None, usage: dict[str, int] | None = None,
                 reason: str | None = None,
                 transfer: dict[str, Any] | None = None,
                 shed: bool = False) -> None:
        """Terminal accounting: exactly once per request (first call wins —
        error paths may overlap with the proxy's finally)."""
        obs: RequestObservation | None = getattr(request, "outcome", None)
        if obs is None or obs.done:
            return
        obs.done = True
        now = time.monotonic()
        # Priority band re-read at completion: start() runs before the
        # director resolves the x-objective header onto the request, so the
        # open-time value would file all objective-classified traffic under
        # band 0.
        objectives = getattr(request, "objectives", None)
        if objectives is not None:
            obs.band = objectives.priority
        # Model re-read for the same reason: the director's weighted /
        # header rewrite lands after start(), and the token counters must
        # share label values with the serving-model families. Explicit
        # header targets survive re-resolution (headers win); only the
        # per-model defaults move to the serving name.
        model = getattr(request, "target_model", obs.model)
        if model != obs.model:
            obs.model = model
            obs.slo_ttft_ms, obs.slo_tpot_ms = self.resolve_targets(
                model, getattr(request, "headers", None) or {})
        if endpoint is not None:
            served = endpoint.metadata.address_port
            if obs.endpoint and obs.endpoint != served:
                # Pre-stream failover walks the ranked candidate list
                # WITHOUT re-running PreRequest (only a full reschedule
                # does), so the stamped prediction/role belong to the
                # rank-1 candidate. Charging them to the endpoint that
                # actually served would inflate its calibration MAE exactly
                # during failover incidents — drop them instead.
                obs.predicted_ttft_ms = None
                obs.predicted_tpot_ms = None
                obs.role = ""
            obs.endpoint = served
            # The predicted-latency producer may already have stamped the
            # role via its configurable endpointRoleLabel — don't clobber it
            # with the default-label lookup.
            if not obs.role:
                role = endpoint.metadata.labels.get(ROLE_LABEL)
                if role:
                    obs.role = role

        e2e_ms = (now - obs.t_start) * 1e3
        tokens = int((usage or {}).get("completion_tokens") or 0)
        actual_ttft_ms: float | None = None
        actual_tpot_ms: float | None = None
        if obs.first_token_at is not None:
            actual_ttft_ms = (obs.first_token_at - obs.t_start) * 1e3
            if tokens > 1 and obs.last_token_at is not None:
                actual_tpot_ms = ((obs.last_token_at - obs.first_token_at)
                                  * 1e3 / (tokens - 1))
        elif status < 400 and reason is None and obs.abort_reason is None:
            # Non-streaming completion: e2e IS the first (and only) byte —
            # record e2e-as-TTFT and a whole-response TPOT so the ledger
            # isn't stream-only.
            actual_ttft_ms = e2e_ms
            if tokens > 0:
                actual_tpot_ms = e2e_ms / tokens

        # Verdict: errors/aborts are slo_met=false with a reason — leaving
        # the field absent would overcount attainment ratios.
        slo_defined = obs.slo_ttft_ms > 0 or obs.slo_tpot_ms > 0
        if reason is None and obs.abort_reason is not None:
            reason = obs.abort_reason
        if reason is None and status >= 400:
            reason = f"http-{status}"
        if shed:
            # Overload shed (router/overload.py): the request was refused
            # BEFORE capacity was spent — a deliberate control action, not
            # an SLO miss and not a serving error. Distinct verdict so
            # attainment/goodput stay honest under admission control.
            met, verdict = False, "shed"
            reason = reason or "shed-at-admission"
        elif reason is not None:
            met, verdict = False, "error"
        else:
            met = True
            if obs.slo_ttft_ms > 0 and actual_ttft_ms is not None \
                    and actual_ttft_ms > obs.slo_ttft_ms:
                met = False
                reason = (f"ttft {actual_ttft_ms:.1f}ms > "
                          f"slo {obs.slo_ttft_ms:.0f}ms")
            if met and obs.slo_tpot_ms > 0 and actual_tpot_ms is not None \
                    and actual_tpot_ms > obs.slo_tpot_ms:
                met = False
                reason = (f"tpot {actual_tpot_ms:.2f}ms > "
                          f"slo {obs.slo_tpot_ms:.0f}ms")
            verdict = "met" if met else "missed"
        SLO_REQUESTS_TOTAL.labels(verdict).inc()
        if tokens:
            OUTPUT_TOKENS_TOTAL.labels(obs.model).inc(tokens)
            if met:
                GOODPUT_TOKENS_TOTAL.labels(obs.model).inc(tokens)
        # Token-mix counters for the timeline (prompt tokens ≈ prefill
        # work, completion tokens ≈ decode work; per serving role so a
        # disagg pool's P:D split is readable as counter deltas).
        prompt_tokens = int((usage or {}).get("prompt_tokens") or 0)
        if prompt_tokens or tokens:
            self.prompt_tokens_total += prompt_tokens
            role_key = obs.role or "default"
            p, c = self.tokens_by_role.get(role_key, (0, 0))
            self.tokens_by_role[role_key] = (p + prompt_tokens, c + tokens)

        # Predictor calibration: signed error feeds the rollup (bias), the
        # absolute error feeds the histogram family. Only meaningful when
        # the prediction targeted the endpoint that actually served (the
        # PreRequest hook re-stamps on failover reschedules), and only when
        # actual and predicted measure the same quantity:
        # - the TTFT ridge is dispatch-relative (predicted_latency's
        #   rc.start is set post-admission), so the flow-control queue wait
        #   inside the client-observed TTFT is subtracted — otherwise the
        #   MAE under load reports queue time, not model error;
        # - the TPOT ridge trains exclusively on streamed inter-token
        #   cadence, so the non-streamed whole-response average (which
        #   folds in prefill) must not feed kind=tpot.
        # The SLO verdict above deliberately stays client-observed.
        role_label = obs.role or "default"
        ttft_signed = tpot_signed = None
        if obs.predicted_ttft_ms is not None and actual_ttft_ms is not None:
            ttft_signed = ((actual_ttft_ms - obs.queue_ms)
                           - obs.predicted_ttft_ms)
            PREDICTOR_ERROR_MS.labels("ttft", role_label).observe(
                abs(ttft_signed))
        if obs.predicted_tpot_ms is not None and actual_tpot_ms is not None \
                and obs.streamed:
            tpot_signed = actual_tpot_ms - obs.predicted_tpot_ms
            PREDICTOR_ERROR_MS.labels("tpot", role_label).observe(
                abs(tpot_signed))

        # Workload class: which pool role's capacity this request mostly
        # consumed — prompt-dominant requests are prefill-pool work,
        # completion-dominant ones decode-pool work (the rebalance
        # controller's per-role attainment input; see by_workload above).
        # Requests with no token evidence (errors, sheds) file under
        # decode: they cannot claim prefill starvation.
        workload = "prefill" if prompt_tokens > tokens else "decode"

        # Rollup.
        for agg in (self._totals,
                    self._endpoint_agg(obs.endpoint or "(unrouted)"),
                    self._agg(self._by_band, obs.band),
                    self._agg(self.by_workload, workload)):
            agg.requests += 1
            if shed:
                agg.shed += 1
            if met:
                agg.slo_met += 1
            agg.output_tokens += tokens
            if met:
                agg.goodput_tokens += tokens
            if ttft_signed is not None:
                agg.ttft_err.add(ttft_signed)
            if tpot_signed is not None:
                agg.tpot_err.add(tpot_signed)
        if shed and reason:
            key = reason.split(" ")[0]  # bounded cardinality: drop numbers
            self._shed_reasons[key] = self._shed_reasons.get(key, 0) + 1
        elif not met and reason:
            key = reason.split(" ")[0]  # bounded cardinality: drop numbers
            self._miss_reasons[key] = self._miss_reasons.get(key, 0) + 1
        if obs.endpoint:
            ep_agg = self._by_endpoint[obs.endpoint]
            served = ep_agg.requests - ep_agg.shed
            if served > 0:
                SLO_ATTAINMENT.labels(obs.endpoint).set(
                    ep_agg.slo_met / served)

        # Stamp the outcome block into the decision record so
        # /debug/decisions/<id> shows predicted vs actual vs SLO.
        rec = getattr(request, "decision", None)
        if rec is not None and hasattr(rec, "record_outcome"):
            actual: dict[str, Any] = {
                "e2e_ms": round(e2e_ms, 3),
                "queue_ms": round(obs.queue_ms, 3),
                "tokens": tokens,
            }
            if actual_ttft_ms is not None:
                actual["ttft_ms"] = round(actual_ttft_ms, 3)
            if actual_tpot_ms is not None:
                actual["tpot_ms"] = round(actual_tpot_ms, 3)
            if obs.streamed:
                actual["gap_max_ms"] = round(obs.gap_max_ms, 3)
                if obs.token_events > 1:
                    actual["gap_mean_ms"] = round(
                        obs.gap_sum_ms / (obs.token_events - 1), 3)
                actual["gap_buckets_ms"] = dict(zip(
                    [f"<{b:g}" for b in GAP_BUCKET_BOUNDS_MS] + ["inf"],
                    obs.gap_buckets))
            block: dict[str, Any] = {
                "predicted": {
                    "ttft_ms": (round(obs.predicted_ttft_ms, 3)
                                if obs.predicted_ttft_ms is not None else None),
                    "tpot_ms": (round(obs.predicted_tpot_ms, 3)
                                if obs.predicted_tpot_ms is not None else None),
                },
                "actual": actual,
                "slo": {"ttft_ms": obs.slo_ttft_ms,
                        "tpot_ms": obs.slo_tpot_ms,
                        "defined": slo_defined},
                "slo_met": met,
                # The ledger's verdict enum (met | missed | error | shed),
                # spelled out so /debug/decisions list filters don't have
                # to re-derive it from slo_met/reason/shed.
                "verdict": verdict,
                "streamed": obs.streamed,
            }
            if shed:
                block["shed"] = True
            if reason:
                block["reason"] = reason
            if transfer:
                block["transfer"] = transfer
            rec.record_outcome(block)

    @staticmethod
    def _agg(table: dict, key) -> _Agg:
        agg = table.get(key)
        if agg is None:
            agg = table[key] = _Agg()
        return agg

    def _endpoint_agg(self, key: str) -> _Agg:
        table = self._by_endpoint
        agg = table.get(key)
        if agg is not None:
            table.move_to_end(key)
            return agg
        if len(table) >= self.MAX_ENDPOINTS:
            evicted, _ = table.popitem(last=False)
            try:
                SLO_ATTAINMENT.remove(evicted)
            except KeyError:
                pass
        agg = table[key] = _Agg()
        return agg

    # ---- render ---------------------------------------------------------

    def snapshot(self) -> dict[str, Any]:
        """The /debug/slo payload: cumulative attainment/goodput rollup with
        predictor calibration, per endpoint and per priority band."""
        t = self._totals
        doc: dict[str, Any] = {
            "enabled": self.cfg.enabled,
            "since_unix": self._start_unix,
            "window_s": round(time.time() - self._start_unix, 1),
            "totals": t.render(),
            "endpoints": {ep: a.render()
                          for ep, a in sorted(self._by_endpoint.items())},
            "bands": {str(b): a.render(predictor=False)
                      for b, a in sorted(self._by_band.items())},
            # Prefill-heavy vs decode-heavy attainment (the rebalance
            # controller's starvation signal — see by_workload).
            "workloads": {w: a.render(predictor=False)
                          for w, a in sorted(self.by_workload.items())},
            "miss_reasons": dict(sorted(self._miss_reasons.items())),
            "shed_reasons": dict(sorted(self._shed_reasons.items())),
        }
        if t.output_tokens:
            doc["totals"]["goodput_ratio"] = round(
                t.goodput_tokens / t.output_tokens, 4)
        return doc


def finite_float_or_none(v: str | None) -> float | None:
    """The one parser for float telemetry/SLO headers (gateway KV-transfer
    landing and the sidecar relay share it): None for absent, garbage, or
    non-finite input — 'nan' would dodge every <=0/>0 guard, propagate
    through EWMAs (0.8·NaN + 0.2·x stays NaN) and histogram sums forever,
    and serialize as literal NaN in the JSON debug payloads; 'inf' would
    mint an always-met SLO."""
    if not v:
        return None
    try:
        f = float(v)
    except ValueError:
        return None
    return f if math.isfinite(f) else None


def parse_slo_header_ms(headers: dict[str, str], name: str) -> float:
    """SLO header contract (shared with the predicted-latency producer and
    the latency-slo admitter): float ms, absent/blank/garbage/non-finite →
    0 = no SLO on that axis (configured defaults then apply)."""
    v = finite_float_or_none(headers.get(name))
    return v if v is not None else 0.0
