"""Shadow policy evaluation: the counterfactual scheduling ledger.

Every placement change so far shipped with its own one-off A/B bench; the
ROADMAP's remaining placement items — transfer-cost-aware joint P/D pairing
(NetKV, arXiv:2606.03910) and the self-balancing pool (P/D-Serve,
arXiv:2408.08147) — all change *what the router picks*, which until this
module could only be evaluated by flipping the policy live and hoping. The
missing observability layer is counterfactual: run a candidate policy in
shadow on every live scheduling cycle, record where it diverges from the
live pick, and judge its estimated benefit against the measured ground
truth the ledgers already collect (TransferTable pull EWMAs, KvHitTable hit
EWMAs, the SLO ledger's measured outcomes) — so every future placement PR
lands with its regret curve already measured instead of argued.

Mechanics:

- the Director submits every scheduling result to the ``ShadowEvaluator``
  (``shadow: {enabled, policies, sampleRate, capacity}``; no policies
  configured = inert, one attribute check — the kvCache/timeline
  default-on precedent). The hot path pays only an enqueue: evaluation,
  judging, and every rollup mutation run on ONE dedicated shadow worker
  thread (single-writer ledger discipline — the PR 5 scheduler pool has N
  workers, so funnelling through it would need locks on every counter);
- the shadow policy re-scores over the SAME immutable inputs the live
  cycle produced: the per-profile weighted totals (``ProfileRunResult
  .totals``, frozen after the cycle) over the PR 5 snapshot views, plus
  the measured feeds on the Datastore. No second scheduling cycle, no
  metric pollution, bit-reproducible;
- the shadow pick, win margin, and divergence land as a ``shadow`` block
  on the DecisionRecord (``/debug/decisions/<id>``, ``shadow=`` in the
  summary echo, ``?divergent=1`` list filter);
- the judge **never assumes**: on agreement the request's measured outcome
  credits both arms; on divergence the shadow arm's cost is estimated from
  the measured feeds (per-pair TransferTable pull EWMAs) while the live
  arm uses this request's own measured ``x-kv-transfer-ms`` where present.
  Per-policy agreement rate, coverage, and signed estimated-regret ms roll
  up at ``GET /debug/shadow`` with ``router_shadow_decisions_total``
  / ``router_shadow_regret_ms`` families, a timeline series, and fleet
  fan-in (``merge_shadow``, n-weighted across shards).

The first registered policy is ROADMAP item 2 itself: the transfer-cost-
aware joint P/D pair scorer. The decode pick stays fixed (it is driven by
cache affinity — overriding it in shadow would discard the reuse the
session/prefix scorers placed for); the PREFILL leg is re-picked by pair
score = live prefill profile total + weight × measured-pull-cost score for
the (candidate, chosen-decode) pair. Its live twin —
``transfer-aware-pair-scorer`` (plugins/scorers.py) — computes the SAME
score as a config-activatable scheduling plugin, so a future PR activates
the policy by adding one pluginRef to the prefill profile;
``bench.py --shadow`` validates that the shadow ledger's estimated regret
agrees (sign + documented error band) with a live A/B arm running exactly
that activation. See docs/shadow.md.
"""

from __future__ import annotations

import dataclasses
import logging
import queue
import threading
from collections import deque
from typing import Any

import xxhash

from .metrics import SHADOW_DECISIONS_TOTAL, SHADOW_REGRET_MS

log = logging.getLogger("router.shadow")

# Score handed to a (prefill, decode) pair with no measured transfer row
# yet: neutral — an unmeasured pair is neither punished nor favored over
# the measured field (exploration stays with the base scorers).
UNMEASURED_PAIR_SCORE = 0.5


def transfer_pair_scores(table: Any, decode: str,
                         candidates: list[str]) -> dict[str, float] | None:
    """Normalized [0, 1] transfer-cost scores for pairing each PREFILL
    candidate with the chosen ``decode`` pod — higher = cheaper measured
    pull. The single scoring function shared by the shadow transfer-pair
    policy and its live ``transfer-aware-pair-scorer`` twin, so the shadow
    verdict is exactly the live activation's behavior.

    Returns None when NO candidate pair has a measured pull EWMA (no
    signal — the policy abstains rather than scoring noise); pairs without
    their own row score ``UNMEASURED_PAIR_SCORE``. Costs are the pair's
    EXPOSED pull EWMA when pipelined observations exist (``cost_ms``):
    scoring the raw wall time would penalize a pair whose transfer hides
    entirely behind prefill compute.
    """
    costs: dict[str, float] = {}
    for p in candidates:
        stats = table.pair(p, decode)
        if stats is not None:
            cost = stats.cost_ms()
            if cost is not None:
                costs[p] = cost
    if not costs:
        return None
    lo, hi = min(costs.values()), max(costs.values())
    if hi == lo:
        # One distinct measured cost carries no COMPARATIVE signal — score
        # everything neutral. Awarding the sole measured pair 1.0 over
        # unmeasured 0.5 would self-reinforce: the (possibly slow)
        # measured pair keeps winning, stays the only measured pair, and
        # faster pairs are never explored.
        return {p: UNMEASURED_PAIR_SCORE for p in candidates}
    out: dict[str, float] = {}
    for p in candidates:
        c = costs.get(p)
        out[p] = (UNMEASURED_PAIR_SCORE if c is None
                  else (hi - c) / (hi - lo))
    return out


@dataclasses.dataclass
class ShadowConfig:
    """The YAML ``shadow:`` section. Default-on but inert until a policy is
    listed (the kvCache precedent: the kill-switch restores the
    zero-overhead baseline, and an empty policy list IS the baseline).

    - ``policies``: list of policy specs — a bare name (``transfer-pair``)
      or ``{type, parameters}``;
    - ``sampleRate``: fraction of scheduling cycles evaluated, derived
      deterministically from the request id (process-stable, the
      flow_shard rationale) so fleet shards sample identically;
    - ``capacity``: per-policy bound on the recent-divergence ring served
      at /debug/shadow.
    """

    enabled: bool = True
    policies: list[Any] = dataclasses.field(default_factory=list)
    sample_rate: float = 1.0
    capacity: int = 128

    @classmethod
    def from_spec(cls, spec: dict[str, Any] | None) -> "ShadowConfig":
        spec = spec or {}
        rate = float(spec.get("sampleRate", 1.0))
        if not 0.0 <= rate <= 1.0:
            raise ValueError("shadow.sampleRate must be in [0, 1]")
        return cls(enabled=bool(spec.get("enabled", True)),
                   policies=list(spec.get("policies") or []),
                   sample_rate=rate,
                   capacity=max(1, int(spec.get("capacity", 128))))


class TransferAwarePairPolicy:
    """ROADMAP item 2 in shadow: score the (prefill, decode) *pair*, not
    the legs. The decode pick is kept (cache affinity placed it); the
    prefill leg is re-picked by ``live prefill total + weight ×
    transfer_pair_scores`` — byte-identical to what the live profile would
    compute with ``transfer-aware-pair-scorer`` appended at ``weight``.

    Judge semantics (docs/shadow.md): regret is the estimated KV-pull
    delta in ms per diverging request — the live arm's measured
    ``x-kv-transfer-ms`` (falling back to the live pair's pull EWMA on
    streamed responses, which carry no engine pull stats) minus the shadow
    pair's pull EWMA. Positive regret = the live policy paid more than the
    shadow pair would have.
    """

    name = "transfer-pair"

    def __init__(self, params: dict[str, Any] | None, datastore: Any):
        params = params or {}
        self.datastore = datastore
        self.weight = float(params.get("weight", 2.0))
        self.prefill_profile = str(params.get("prefillProfile", "prefill"))
        self.decode_profile = str(params.get("decodeProfile", "decode"))

    # ---- evaluation (shadow worker thread) ------------------------------

    def evaluate(self, request: Any, result: Any) -> dict[str, Any] | None:
        """One counterfactual pass over the live cycle's frozen outputs.
        Returns the explainable entry dict (stamped into the
        DecisionRecord shadow block), or None when the request is
        ineligible (no P/D hop — decode-only, classifier skip)."""
        pr = result.profile_results.get(self.prefill_profile)
        dr = result.profile_results.get(self.decode_profile)
        if (pr is None or dr is None or not pr.target_endpoints
                or not dr.target_endpoints or not pr.totals):
            return None
        decode = dr.target_endpoints[0].metadata.address_port
        live = pr.target_endpoints[0].metadata.address_port
        totals = pr.totals
        entry: dict[str, Any] = {
            "live": {"prefill": live, "decode": decode},
        }
        tscores = transfer_pair_scores(self.datastore.transfers, decode,
                                       list(totals))
        if tscores is None:
            entry["verdict"] = "no_signal"
            return entry
        # When the live twin (transfer-aware-pair-scorer) is ALREADY in
        # the profile, the live totals include its weighted contribution —
        # re-adding it would score base + 2w×t and mint false divergences
        # against the very policy that is live. The counterfactual then
        # IS the live policy: evaluate the totals as-is (activation
        # monitoring — verdicts degenerate to agreement unless something
        # else, e.g. a failover, moved the pick).
        live_twin = any("transfer-aware-pair-scorer" in name
                        for name in pr.raw_scores)
        if live_twin:
            entry["live_twin_active"] = True
            shadow_totals = dict(totals)
        else:
            shadow_totals = {p: totals[p] + self.weight * tscores[p]
                             for p in totals}
        # Stable argmax with the live pick winning ties: a tie must never
        # mint a divergence (there is no counterfactual benefit to judge).
        best, best_v = live, shadow_totals.get(live, float("-inf"))
        for p, v in shadow_totals.items():
            if v > best_v + 1e-12:
                best, best_v = p, v
        live_v = shadow_totals.get(live, 0.0)
        entry["shadow"] = {"prefill": best}
        entry["margin"] = round(best_v - live_v, 6)
        entry["verdict"] = "diverge" if best != live else "agree"
        return entry

    # ---- judge (shadow worker thread, at terminal accounting) -----------

    def judge(self, entry: dict[str, Any],
              outcome: dict[str, Any]) -> tuple[str, float | None] | None:
        """Judge one entry against the measured outcome, mutating the
        SAME dict (the ``judged`` sub-block lands in /debug/decisions/<id>
        through the shared reference — the kvobs precedent). Returns
        (verdict, value): agreement value = the measured pull crediting
        both arms; divergence value = signed estimated-regret ms, or None
        when no estimate exists for the shadow pair."""
        if entry.get("verdict") == "no_signal" or "judged" in entry:
            return None
        table = self.datastore.transfers
        decode = entry["live"]["decode"]
        transfer = outcome.get("transfer") or {}
        # Pipelined pulls carry exposed (non-overlapped) time — the cost a
        # request actually waited — beside the raw wall time; regret is
        # computed in exposed terms so both arms price what TTFT paid.
        live_ms = transfer.get("exposed_ms", transfer.get("pull_ms"))
        live_source = "measured"
        if live_ms is None:
            # Streamed responses carry no engine pull stats — fall back to
            # the live pair's own measured EWMA.
            stats = table.pair(entry["live"]["prefill"], decode)
            live_ms = stats.cost_ms() if stats is not None else None
            live_source = "ewma"
        if entry["verdict"] == "agree":
            judged: dict[str, Any] = {"agreed": True}
            if live_ms is not None:
                judged["pull_ms"] = round(live_ms, 3)
                judged["source"] = live_source
            entry["judged"] = judged
            # Only a genuinely MEASURED pull credits the agree-measured
            # tally — feeding the EWMA fallback back in would blend the
            # table's own estimates into a field documented as measured.
            return ("agree",
                    live_ms if live_source == "measured" else None)
        stats = table.pair(entry["shadow"]["prefill"], decode)
        est_shadow = stats.cost_ms() if stats is not None else None
        if live_ms is None or est_shadow is None:
            entry["judged"] = {"estimate": "unavailable"}
            return ("diverge", None)
        regret = live_ms - est_shadow
        entry["judged"] = {
            "live_pull_ms": round(live_ms, 3),
            "live_source": live_source,
            "shadow_est_pull_ms": round(est_shadow, 3),
            "est_regret_ms": round(regret, 3),
        }
        return ("diverge", regret)


# Shadow policy registry: name → factory(params, datastore). Future
# placement PRs register here and flip on via `shadow.policies` config.
SHADOW_POLICIES: dict[str, Any] = {
    TransferAwarePairPolicy.name: TransferAwarePairPolicy,
}


class _PolicyStats:
    """One policy's rollup. Mutated ONLY on the shadow worker thread
    (single-writer); /debug/shadow renders a point-in-time view from the
    event loop (int/float reads are GIL-atomic)."""

    __slots__ = ("evaluated", "agreements", "divergences", "no_signal",
                 "judged_agree", "judged_diverge", "estimate_missing",
                 "regret_n", "regret_sum", "regret_abs",
                 "agree_measured_n", "agree_measured_sum", "ring")

    def __init__(self, capacity: int):
        self.evaluated = 0
        self.agreements = 0
        self.divergences = 0
        self.no_signal = 0
        self.judged_agree = 0
        self.judged_diverge = 0
        self.estimate_missing = 0
        self.regret_n = 0
        self.regret_sum = 0.0
        self.regret_abs = 0.0
        self.agree_measured_n = 0
        self.agree_measured_sum = 0.0
        self.ring: deque[dict[str, Any]] = deque(maxlen=capacity)

    def render(self, submitted: int) -> dict[str, Any]:
        decided = self.agreements + self.divergences
        doc: dict[str, Any] = {
            "evaluated": self.evaluated,
            "agreements": self.agreements,
            "divergences": self.divergences,
            "no_signal": self.no_signal,
            "agreement_rate": (round(self.agreements / decided, 4)
                               if decided else None),
            # Coverage: fraction of submitted scheduling cycles this policy
            # produced a verdict for (sampling × eligibility × signal).
            "coverage": (round(decided / submitted, 4) if submitted
                         else None),
            "judged": {"agreements": self.judged_agree,
                       "divergences": self.judged_diverge,
                       "estimate_missing": self.estimate_missing},
        }
        if self.regret_n:
            doc["est_regret_ms"] = {
                "n": self.regret_n,
                "sum": round(self.regret_sum, 3),
                "mean": round(self.regret_sum / self.regret_n, 3),
                "mean_abs": round(self.regret_abs / self.regret_n, 3),
            }
        else:
            doc["est_regret_ms"] = {"n": 0}
        if self.agree_measured_n:
            doc["agree_measured_pull_ms_mean"] = round(
                self.agree_measured_sum / self.agree_measured_n, 3)
            # The count the mean was taken over — judged agreements whose
            # live pull was actually measured (streamed responses with no
            # pair EWMA yet judge without one). merge_shadow MUST weight
            # by this, not by judged agreements.
            doc["agree_measured_n"] = self.agree_measured_n
        doc["recent_divergences"] = list(self.ring)
        return doc


class ShadowObservation:
    """Per-request shadow state riding ``request.shadow``: created
    synchronously at submit (so the completion hook knows the request was
    sampled), entries + the record block filled by the worker, ``done``
    guards the terminal enqueue to exactly once. ``entries == {}`` (empty,
    not None) marks an evaluation where no policy produced an entry — the
    terminal hook then skips its enqueue entirely."""

    __slots__ = ("entries", "block", "done")

    def __init__(self):
        self.entries: dict[str, dict[str, Any]] | None = None
        self.block: dict[str, Any] | None = None
        self.done = False


_SENTINEL = object()


class ShadowEvaluator:
    """The counterfactual ledger. Hot-path contract: ``submit`` /
    ``observe_response`` cost one attribute check when inert (no policies
    or kill-switch) and one ``SimpleQueue.put`` when active — evaluation,
    judging, and all rollup writes happen on the dedicated shadow worker
    thread (see module docstring for the single-writer rationale;
    ``bench.py --shadow`` measures the hook against the SCHED_HOTPATH
    cycle floor). Backlog is BOUNDED: a worker that falls behind the
    arrival rate (a stalled future policy) sheds new events instead of
    pinning request graphs until OOM — drops are counted and visible at
    /debug/shadow, never silent."""

    # Worker backlog bound: each queued event pins its request +
    # SchedulingResult graph, so the queue must not grow without limit
    # when a policy is slower than the arrival rate. Shadow evaluation is
    # advisory — shedding it is always safe.
    MAX_QUEUE = 4096

    def __init__(self, cfg: ShadowConfig | None = None, *,
                 datastore: Any = None):
        self.cfg = cfg or ShadowConfig()
        self.datastore = datastore
        self._policies: list[Any] = []
        self._by_name: dict[str, Any] = {}
        self._stats: dict[str, _PolicyStats] = {}
        for spec in self.cfg.policies:
            if isinstance(spec, str):
                spec = {"type": spec}
            ptype = spec.get("type") or spec.get("name")
            factory = SHADOW_POLICIES.get(ptype)
            if factory is None:
                raise ValueError(
                    f"unknown shadow policy {ptype!r} "
                    f"(registered: {sorted(SHADOW_POLICIES)})")
            policy = factory(spec.get("parameters") or {}, datastore)
            if policy.name in self._by_name:
                raise ValueError(f"duplicate shadow policy {policy.name!r}")
            self._policies.append(policy)
            self._by_name[policy.name] = policy
            self._stats[policy.name] = _PolicyStats(self.cfg.capacity)
        self._active = bool(self.cfg.enabled and self._policies)
        # Deterministic per-request sampling threshold (permille of 10k).
        self._sample_bound = int(self.cfg.sample_rate * 10_000)
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._worker: threading.Thread | None = None
        # Flat counters for the timeline sampler's per-tick deltas (worker
        # writes, loop reads — int/float loads are GIL-atomic).
        self._submitted = 0
        self._evaluated_total = 0
        self._diverged_total = 0
        self._regret_ms_sum = 0.0
        self._dropped = 0

    # ---- hot-path hooks (event loop / scheduler workers) ----------------

    @property
    def active(self) -> bool:
        return self._active

    @property
    def evaluated_total(self) -> int:
        return self._evaluated_total

    @property
    def diverged_total(self) -> int:
        return self._diverged_total

    @property
    def regret_ms_sum(self) -> float:
        return self._regret_ms_sum

    def submit(self, request: Any, result: Any, *,
               resubmit: bool = False) -> None:
        """Enqueue one live scheduling cycle for shadow evaluation. The
        result's profile totals/raw scores are frozen after the cycle, so
        the worker reads them race-free (the PR 5 snapshot contract).

        ``resubmit`` is the failover-reschedule path (the Director): the
        SAME request re-evaluates against the fresh result and the worker
        REPLACES the prior verdict in place — the judge must grade the
        pick that actually serves, not the pre-failover one (the PR 11
        classifier's re-classification precedent). A reschedule of an
        unsampled request stays unsampled."""
        if not self._active or result is None:
            return
        obs: ShadowObservation | None = getattr(request, "shadow", None)
        if obs is not None:
            # Re-evaluation of an already-sampled request (failover).
            if not obs.done and not self._shed():
                self._q.put(("sched", request, result))
            return
        if resubmit:
            return  # the original cycle was not sampled
        self._submitted += 1
        if self._sample_bound < 10_000 and (
                xxhash.xxh64_intdigest(request.request_id) % 10_000
                >= self._sample_bound):
            return
        if self._shed():
            return  # backlog full — sampled-but-shed, counted
        request.shadow = ShadowObservation()
        if self._worker is None:
            self._start_worker()
        self._q.put(("sched", request, result))

    def _shed(self) -> bool:
        """Backlog guard: True when the worker queue is over the bound
        (the event is dropped and counted — shadow work is advisory)."""
        if self._q.qsize() < self.MAX_QUEUE:
            return False
        self._dropped += 1
        return True

    def observe_response(self, request: Any, *,
                         transfer: dict[str, Any] | None = None,
                         status: int = 0) -> None:
        """Terminal hook (the gateway's proxy accounting): hand the
        measured outcome to the judge. One attribute check for unsampled
        requests."""
        obs: ShadowObservation | None = getattr(request, "shadow", None)
        if obs is None or obs.done:
            return
        obs.done = True
        if obs.entries is not None and not obs.entries:
            # Evaluated, but no policy produced an entry (ineligible
            # traffic — decode-only, classifier skip): nothing to judge,
            # skip the worker wakeup entirely.
            return
        if self._shed():
            return
        self._q.put(("done", request,
                     {"transfer": transfer, "status": status}))

    # ---- worker ---------------------------------------------------------

    def _start_worker(self) -> None:
        # submit() runs on the event loop only (the Director), so lazy
        # start needs no lock.
        t = threading.Thread(target=self._run, name="shadow-worker",
                             daemon=True)
        self._worker = t
        t.start()

    def stop(self) -> None:
        if self._worker is not None:
            self._q.put(_SENTINEL)
            self._worker.join(timeout=2.0)
            self._worker = None

    def flush(self, timeout: float = 5.0) -> bool:
        """Block until every event enqueued so far has been processed
        (tests and the bench use it; never called on the serving path)."""
        if self._worker is None:
            return True
        ev = threading.Event()
        self._q.put(("flush", ev))
        return ev.wait(timeout)

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is _SENTINEL:
                return
            try:
                kind = item[0]
                if kind == "sched":
                    self._evaluate(item[1], item[2])
                elif kind == "done":
                    self._judge(item[1], item[2])
                elif kind == "flush":
                    item[1].set()
            except Exception:
                log.exception("shadow worker event failed")

    def _count_verdict(self, stats: _PolicyStats, verdict: str,
                       sign: int) -> None:
        stats.evaluated += sign
        self._evaluated_total += sign
        if verdict == "diverge":
            stats.divergences += sign
            self._diverged_total += sign
        elif verdict == "agree":
            stats.agreements += sign
        else:
            stats.no_signal += sign

    def _evaluate(self, request: Any, result: Any) -> None:
        # Captured BEFORE evaluating: a re-submitted request object (or a
        # caller clearing request.shadow) must not crash the worker —
        # verdicts still count, only the per-request stamp is skipped.
        obs: ShadowObservation | None = getattr(request, "shadow", None)
        prior = (obs.entries if obs is not None else None) or {}
        entries: dict[str, dict[str, Any]] = {}
        for policy in self._policies:
            stats = self._stats[policy.name]
            try:
                entry = policy.evaluate(request, result)
            except Exception:
                log.exception("shadow policy %s evaluate failed",
                              policy.name)
                continue
            if entry is None:
                continue
            # Failover re-evaluation REPLACES the prior verdict (an
            # unjudged one — once the response landed the verdict is
            # history): the ledger must grade the pick that serves, so
            # back the superseded verdict out of the rollup. Prometheus
            # counters stay cumulative (every evaluation is an event).
            old = prior.get(policy.name)
            if old is not None and "judged" not in old:
                self._count_verdict(stats, old["verdict"], -1)
            self._count_verdict(stats, entry["verdict"], +1)
            SHADOW_DECISIONS_TOTAL.labels(policy.name,
                                          entry["verdict"]).inc()
            entries[policy.name] = entry
        if obs is None:
            return
        if obs.entries is None:
            obs.entries = entries
        else:
            # Failover re-evaluation: a policy that ABSTAINED this round
            # (e.g. the reschedule produced a decode-only result) must
            # not keep its stale pre-failover verdict — judging it
            # against the new pick's measured outcome would mint regret
            # for a pair that never served. Back it out and drop it.
            for name, old in list(obs.entries.items()):
                if name not in entries and "judged" not in old:
                    st = self._stats.get(name)
                    if st is not None:
                        self._count_verdict(st, old["verdict"], -1)
                    del obs.entries[name]
            obs.entries.update(entries)
        diverged = any(e["verdict"] == "diverge"
                       for e in obs.entries.values())
        if obs.block is not None:
            # The record references this dict (record_shadow is
            # first-wins): refresh it in place.
            obs.block["diverged"] = diverged
            obs.block["policies"] = obs.entries
        elif obs.entries:
            obs.block = {"diverged": diverged, "policies": obs.entries}
            rec = getattr(request, "decision", None)
            if rec is not None and hasattr(rec, "record_shadow"):
                rec.record_shadow(obs.block)

    def _judge(self, request: Any, outcome: dict[str, Any]) -> None:
        obs: ShadowObservation | None = getattr(request, "shadow", None)
        if obs is None or obs.entries is None:
            return
        for name, entry in obs.entries.items():
            policy = self._by_name.get(name)
            stats = self._stats.get(name)
            if policy is None or stats is None:
                continue
            try:
                res = policy.judge(entry, outcome)
            except Exception:
                log.exception("shadow policy %s judge failed", name)
                continue
            if res is None:
                continue
            kind, value = res
            if kind == "agree":
                stats.judged_agree += 1
                if value is not None:
                    stats.agree_measured_n += 1
                    stats.agree_measured_sum += value
            elif kind == "diverge":
                if value is None:
                    stats.estimate_missing += 1
                    continue
                stats.judged_diverge += 1
                stats.regret_n += 1
                stats.regret_sum += value
                stats.regret_abs += abs(value)
                self._regret_ms_sum += value
                SHADOW_REGRET_MS.labels(name).observe(value)
                stats.ring.append({
                    "request_id": request.request_id,
                    "live": entry.get("live"),
                    "shadow": entry.get("shadow"),
                    "margin": entry.get("margin"),
                    "est_regret_ms": round(value, 3),
                })

    # ---- render ---------------------------------------------------------

    def snapshot(self) -> dict[str, Any]:
        """The /debug/shadow payload. Read from the event loop while the
        worker writes — every field is a GIL-atomic load, and the recent
        ring is snapshotted via list() (the _live_items precedent)."""
        doc: dict[str, Any] = {
            "enabled": self.cfg.enabled,
            "active": self._active,
            "sample_rate": self.cfg.sample_rate,
            "submitted": self._submitted,
            "policies": {p.name: self._stats[p.name].render(self._submitted)
                         for p in self._policies},
        }
        if self._dropped:
            # Backlog sheds (worker slower than arrivals) — never silent.
            doc["dropped_events"] = self._dropped
        return doc


# ---------------------------------------------------------------------------
# Fleet fan-in: n-weighted merge of per-shard /debug/shadow payloads.
# ---------------------------------------------------------------------------

# Recent divergences kept per shard / total in the merged view (bounded;
# the full ring stays on each worker's own /debug/shadow).
MERGE_RECENT_PER_SHARD = 8
MERGE_RECENT_TOTAL = 32


def merge_shadow(docs: list[tuple[int, dict[str, Any]]]) -> dict[str, Any]:
    """Fleet /debug/shadow: counters summed across shards, agreement rate
    and coverage recomputed from the sums (never averaged), regret merged
    by summing (n, sum) — the n-weighted merge_kv precedent — and recent
    divergences concatenated shard-annotated, bounded."""
    out: dict[str, Any] = {
        "workers": len(docs),
        "enabled": any(d.get("enabled") for _, d in docs),
        "submitted": 0,
        "policies": {},
    }
    acc: dict[str, dict[str, Any]] = {}
    for shard, doc in docs:
        out["submitted"] += doc.get("submitted", 0)
        for name, row in (doc.get("policies") or {}).items():
            a = acc.setdefault(name, {
                "evaluated": 0, "agreements": 0, "divergences": 0,
                "no_signal": 0,
                "judged": {"agreements": 0, "divergences": 0,
                           "estimate_missing": 0},
                "regret_n": 0, "regret_sum": 0.0, "regret_abs": 0.0,
                "agree_n": 0, "agree_sum": 0.0,
                "recent": [],
            })
            for k in ("evaluated", "agreements", "divergences", "no_signal"):
                a[k] += row.get(k, 0)
            for k in ("agreements", "divergences", "estimate_missing"):
                a["judged"][k] += (row.get("judged") or {}).get(k, 0)
            reg = row.get("est_regret_ms") or {}
            n = reg.get("n", 0)
            if n:
                a["regret_n"] += n
                a["regret_sum"] += reg.get("sum", 0.0)
                a["regret_abs"] += abs(reg.get("mean_abs", 0.0)) * n
            am = row.get("agree_measured_pull_ms_mean")
            # Weight by the count the mean was taken over (judged
            # agreements without a measured pull are excluded from it).
            an = row.get("agree_measured_n", 0)
            if am is not None and an:
                a["agree_n"] += an
                a["agree_sum"] += am * an
            for div in (row.get("recent_divergences")
                        or [])[-MERGE_RECENT_PER_SHARD:]:
                a["recent"].append({**div, "shard": shard})
    for name, a in acc.items():
        decided = a["agreements"] + a["divergences"]
        row: dict[str, Any] = {
            "evaluated": a["evaluated"],
            "agreements": a["agreements"],
            "divergences": a["divergences"],
            "no_signal": a["no_signal"],
            "agreement_rate": (round(a["agreements"] / decided, 4)
                               if decided else None),
            "coverage": (round(decided / out["submitted"], 4)
                         if out["submitted"] else None),
            "judged": a["judged"],
        }
        if a["regret_n"]:
            row["est_regret_ms"] = {
                "n": a["regret_n"],
                "sum": round(a["regret_sum"], 3),
                "mean": round(a["regret_sum"] / a["regret_n"], 3),
                "mean_abs": round(a["regret_abs"] / a["regret_n"], 3),
            }
        else:
            row["est_regret_ms"] = {"n": 0}
        if a["agree_n"]:
            row["agree_measured_pull_ms_mean"] = round(
                a["agree_sum"] / a["agree_n"], 3)
        row["recent_divergences"] = a["recent"][-MERGE_RECENT_TOTAL:]
        out["policies"][name] = row
    return out
