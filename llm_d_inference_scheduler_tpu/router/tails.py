"""Tail-latency attribution observatory: per-request critical-path
waterfalls and the body-vs-tail cohort ledger behind ``GET /debug/tails``.

Every closed loop the router ships (SLO ledger, shadow regret, rebalance,
autoscale) judges *whether* a request was slow; nothing explains *where the
time went*. P/D-Serve (arXiv:2408.08147) shows the production tail is
dominated by a *changing* culprit stage — queueing vs prefill vs KV pull vs
decode — and NetKV (arXiv:2606.03910) shows transfer-pair skew specifically
hides inside aggregate TTFT. Both signals are already captured per request
here; this module is the read-side join that decomposes them.

One ``RequestWaterfall`` rides each InferenceRequest (``request.waterfall``),
mirroring the slo.py ``request.outcome`` discipline:

- opened by the gateway before orchestration (beside ``SloLedger.start``);
- stamped in place by the layer hooks, each a ``getattr(..., None)`` check
  when the kill-switch is off: flow-control admission (queue wait),
  the director's scheduling call (cycle + offload-dispatch time), the
  gateway's failover walk (time burned in failed attempts), and the
  response-header landing (``x-engine-queue-ms``, ``x-prefill-duration-ms``,
  ``x-kv-transfer-ms``/``-bytes`` + the ``x-kv-prefiller`` pair identity);
- closed exactly once on EVERY terminal path (first call wins), computing
  the decode-side residual TTFT — client TTFT minus every accounted stage,
  clamped at zero — and the streaming leg (first→last token).

The closed waterfall is stamped as a ``waterfall`` block on the
DecisionRecord (so ``/debug/decisions/<id>`` shows the stage split and
``?stage=<dominant>`` pages straight to a culprit cohort), summarized in the
``x-debug-decision`` echo, observed into ``router_stage_ms{stage}``, and fed
to the per-(model, band, shape) cohort rings that ``/debug/tails`` renders:
body-vs-tail split at ``tailQuantile``, per-stage p50/p95/p99, dominant-stage
attribution of the tail cohort's excess time with culprit drill-down
(endpoint, transfer pair, shed/degrade rung) and bounded exemplar request
ids. ``merge_tails`` fans shard payloads in for the fleet supervisor:
n-weighted stage quantiles via the bounded fixed-bin digests each cohort
exports, shard-annotated exemplars.

Config: ``tails: {enabled, capacity, tailQuantile, exemplars}`` — default-on
(the kvCache precedent); ``enabled: false`` is bit-identical (no waterfall
object is ever created, every hook degrades to one ``is None`` check).
"""

from __future__ import annotations

import dataclasses
import time
from bisect import bisect_left
from collections import OrderedDict, deque
from typing import Any

from .metrics import STAGE_MS, TAIL_DOMINANT_STAGE_TOTAL

# Critical-path stage names, in waterfall order. ``decode`` is the RESIDUAL
# stage (client TTFT minus every accounted stage, clamped >= 0 — clock skew
# between router and engine/sidecar stamps must never mint negative time);
# ``stream`` is the post-TTFT token relay (first→last token), outside the
# TTFT critical path.
STAGES = ("queue", "sched", "attempts", "engine_queue",
          "prefill", "kv_transfer", "decode")
STREAM_STAGE = "stream"

# Fixed log-spaced digest bounds (ms) shared by every per-stage digest: the
# bounded mergeable sketch merge_tails sums across shards. An upper bin
# catches everything past the last bound; per-digest max tightens its edge.
DIGEST_BOUNDS_MS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                    500.0, 1000.0, 2500.0, 5000.0, 10000.0)

# Cohort cache refresh cadence: the rolling tail threshold and body stage
# means used for complete()-time classification are recomputed every N
# closes (an O(capacity log capacity) sort amortized off the per-request
# path).
_REFRESH_EVERY = 32
# Minimum ring population before a cohort starts classifying tails — a
# 3-sample "p95" is noise, not a cohort.
_MIN_SAMPLES = 20


@dataclasses.dataclass
class TailsConfig:
    """The YAML ``tails:`` section (camelCase keys like the rest of the
    config surface). Default-on per the kvCache precedent; ``enabled:
    false`` is the bit-identical kill-switch the overhead contract
    (``bench.py --tails``) measures."""

    enabled: bool = True
    capacity: int = 512        # per-cohort sample ring
    tail_quantile: float = 0.95
    exemplars: int = 8         # bounded exemplar request-ids per cohort

    @classmethod
    def from_spec(cls, spec: dict[str, Any] | None) -> "TailsConfig":
        spec = spec or {}
        q = float(spec.get("tailQuantile", 0.95))
        return cls(enabled=bool(spec.get("enabled", True)),
                   capacity=max(16, int(spec.get("capacity", 512))),
                   tail_quantile=min(max(q, 0.5), 0.999),
                   exemplars=max(0, int(spec.get("exemplars", 8))))


class RequestWaterfall:
    """One request's critical-path stage accumulator. Mutated in place by
    the layer hooks; the observatory's ``complete()`` computes residual +
    verdict exactly once (first call wins — error paths overlap the proxy's
    finally, same as slo.py)."""

    __slots__ = ("request_id", "model", "band", "t_start",
                 "queue_ms", "sched_ms", "attempts_ms", "engine_queue_ms",
                 "prefill_ms", "kv_transfer_ms", "overlap_ms", "kv_bytes",
                 "pair", "endpoint", "shed_rung", "done")

    def __init__(self, request_id: str, model: str, band: int,
                 t_start: float):
        self.request_id = request_id
        self.model = model
        self.band = band
        self.t_start = t_start
        self.queue_ms = 0.0
        self.sched_ms = 0.0
        self.attempts_ms = 0.0
        self.engine_queue_ms = 0.0
        self.prefill_ms = 0.0
        self.kv_transfer_ms = 0.0
        # Pipelined-P/D pull time hidden behind prefill compute (raw pull −
        # exposed). Informational: kv_transfer_ms already holds only the
        # EXPOSED cost, so overlap is excluded from accounted_ms() — adding
        # it would double-count the hidden portion against TTFT.
        self.overlap_ms = 0.0
        self.kv_bytes = 0
        self.pair: str | None = None
        self.endpoint = ""
        self.shed_rung: str | None = None
        self.done = False

    def accounted_ms(self) -> float:
        """Sum of every directly-measured pre-first-token stage (everything
        but the decode residual)."""
        return (self.queue_ms + self.sched_ms + self.attempts_ms
                + self.engine_queue_ms + self.prefill_ms
                + self.kv_transfer_ms)


class _Digest:
    """Bounded fixed-bin histogram sketch — the mergeable per-stage quantile
    carrier for fleet fan-in. Bins share DIGEST_BOUNDS_MS; ``max`` tightens
    the overflow bin's upper edge at quantile time."""

    __slots__ = ("counts", "n", "sum_ms", "max_ms")

    def __init__(self):
        self.counts = [0] * (len(DIGEST_BOUNDS_MS) + 1)
        self.n = 0
        self.sum_ms = 0.0
        self.max_ms = 0.0

    def add(self, v: float) -> None:
        self.counts[bisect_left(DIGEST_BOUNDS_MS, v)] += 1
        self.n += 1
        self.sum_ms += v
        if v > self.max_ms:
            self.max_ms = v

    def to_doc(self) -> dict[str, Any]:
        return {"counts": list(self.counts), "n": self.n,
                "sum_ms": round(self.sum_ms, 3),
                "max_ms": round(self.max_ms, 3)}


def _digest_quantile(counts: list[int], n: int, max_ms: float,
                     q: float) -> float | None:
    """Linear-interpolated quantile from fixed-bin counts (the merged-shard
    read path; single-shard /debug/tails quantiles come from the exact ring
    instead)."""
    if n <= 0:
        return None
    target = q * n
    cum = 0
    for i, c in enumerate(counts):
        if c <= 0:
            continue
        lo = DIGEST_BOUNDS_MS[i - 1] if i > 0 else 0.0
        hi = (DIGEST_BOUNDS_MS[i] if i < len(DIGEST_BOUNDS_MS)
              else max(max_ms, lo))
        if cum + c >= target:
            frac = (target - cum) / c
            return round(lo + (hi - lo) * min(max(frac, 0.0), 1.0), 3)
        cum += c
    return round(max_ms, 3)


def _quantile(sorted_vals: list[float], q: float) -> float | None:
    """Exact linear-interpolated quantile over a pre-sorted list."""
    n = len(sorted_vals)
    if not n:
        return None
    if n == 1:
        return sorted_vals[0]
    pos = q * (n - 1)
    i = int(pos)
    frac = pos - i
    if i + 1 >= n:
        return sorted_vals[-1]
    return sorted_vals[i] + (sorted_vals[i + 1] - sorted_vals[i]) * frac


class _Sample:
    """One closed, served request in a cohort ring (compact slots — the
    ring holds capacity× of these per cohort)."""

    __slots__ = ("ttft_ms", "stages", "stream_ms", "request_id",
                 "endpoint", "pair", "rung")

    def __init__(self, ttft_ms: float, stages: tuple[float, ...],
                 stream_ms: float, request_id: str, endpoint: str,
                 pair: str | None, rung: str | None):
        self.ttft_ms = ttft_ms
        self.stages = stages          # aligned with STAGES
        self.stream_ms = stream_ms
        self.request_id = request_id
        self.endpoint = endpoint
        self.pair = pair
        self.rung = rung


class _Cohort:
    """Rolling per-(model, band, shape) ledger: sample ring + the cached
    classification state complete() reads. The per-stage digests are
    derived FROM the ring at render time, so the digest window and the
    quantile window are one and the same — and the close path stays out
    of the digest-maintenance business entirely."""

    __slots__ = ("ring", "exemplars",
                 "closed", "tail_closed", "dominant_counts",
                 "_since_refresh", "threshold_ms", "body_stage_means")

    def __init__(self, capacity: int, exemplars: int):
        self.ring: deque[_Sample] = deque(maxlen=capacity)
        self.exemplars: deque[dict[str, Any]] = deque(maxlen=max(1, exemplars))
        self.closed = 0
        self.tail_closed = 0
        self.dominant_counts: dict[str, int] = {}
        self._since_refresh = 0
        self.threshold_ms: float | None = None
        self.body_stage_means: tuple[float, ...] = (0.0,) * len(STAGES)

    def refresh(self, tail_q: float) -> None:
        """Recompute the rolling tail threshold and body per-stage means
        from the ring (amortized every _REFRESH_EVERY closes)."""
        self._since_refresh = 0
        n = len(self.ring)
        if n < _MIN_SAMPLES:
            self.threshold_ms = None
            return
        ttfts = sorted([s.ttft_ms for s in self.ring])
        self.threshold_ms = _quantile(ttfts, tail_q)
        thr = self.threshold_ms or 0.0
        # Column-sum via zip(*rows): the per-sample Python inner loop was
        # ~half the amortized close cost at capacity (bench.py --tails).
        body = [s.stages for s in self.ring if s.ttft_ms <= thr]
        if body:
            body_n = len(body)
            self.body_stage_means = tuple(
                col_sum / body_n for col_sum in map(sum, zip(*body)))


def _cohort_key(model: str, band: int, streamed: bool) -> str:
    return f"{model}|b{band}|{'stream' if streamed else 'unary'}"


def _fast_observer(child: Any):
    """Pre-bound histogram observe for a labeled child: one C bisect over
    the fixed bounds plus two value incs, skipping the public observe()'s
    per-call validation and Python bounds walk. Falls back to the public
    method if the client library's internals ever change shape."""
    try:
        sum_inc = child._sum.inc
        bucket_incs = tuple(b.inc for b in child._buckets)
        bounds = tuple(child._upper_bounds)
    except AttributeError:
        return child.observe
    if len(bucket_incs) != len(bounds) or list(bounds) != sorted(bounds):
        return child.observe

    def observe(v: float, _sum_inc=sum_inc, _cells=bucket_incs,
                _bounds=bounds) -> None:
        _sum_inc(v)
        _cells[bisect_left(_bounds, v)](1)

    return observe


class TailsObservatory:
    """Fleet-level tail-attribution rollup. All writers run on the
    gateway's event loop (the slo.py rule), so no locking; ``snapshot()``
    renders a point-in-time view for /debug/tails."""

    # Cohort cardinality is (models × bands × 2) — operationally bounded,
    # but model names arrive from clients, so the table is LRU-capped like
    # SloLedger.MAX_ENDPOINTS / TransferTable.MAX_PAIRS.
    MAX_COHORTS = 128

    def __init__(self, cfg: TailsConfig | None = None):
        self.cfg = cfg or TailsConfig()
        self._cohorts: OrderedDict[str, _Cohort] = OrderedDict()
        self._start_unix = time.time()
        # Cached metric children, pre-bound to their bucket cells: the
        # close path feeds up to 6 histogram stages per request under a 1%
        # cycle-floor budget (bench.py --tails), and the public observe()
        # re-validates observability and walks the bounds in Python on
        # every call — roughly half the whole hook's cost. (The timeline
        # _burn_fast_g precedent, taken one step further.)
        self._stage_hist = {s: _fast_observer(STAGE_MS.labels(s))
                            for s in STAGES}
        self._stage_hist[STREAM_STAGE] = _fast_observer(
            STAGE_MS.labels(STREAM_STAGE))
        self._dominant_children: dict[tuple[str, str], Any] = {}
        # Flat counters the timeline sampler reads every tick (delta
        # source — the SloLedger.totals precedent).
        self.closed_total = 0
        self.tail_total = 0
        self.dominant_total: dict[str, int] = {}

    @property
    def enabled(self) -> bool:
        return self.cfg.enabled

    # ---- open -----------------------------------------------------------

    def start(self, request: Any, t_start: float) -> RequestWaterfall | None:
        """Open a waterfall (None when the kill-switch is off — every layer
        hook then degrades to a single ``is None`` check and the request
        object never grows a ``waterfall`` attribute: bit-identical)."""
        if not self.cfg.enabled:
            return None
        wf = RequestWaterfall(request.request_id, request.target_model,
                              request.objectives.priority, t_start)
        request.waterfall = wf
        return wf

    # ---- close ----------------------------------------------------------

    def complete(self, request: Any, *, status: int,
                 endpoint: Any = None, usage: dict[str, int] | None = None,
                 reason: str | None = None, shed: bool = False) -> None:
        """Terminal accounting, exactly once per request (first call wins —
        mirrors SloLedger.complete's signature so the gateway closes both
        ledgers side by side on every terminal path)."""
        wf: RequestWaterfall | None = getattr(request, "waterfall", None)
        if wf is None or wf.done:
            return
        wf.done = True
        now = time.monotonic()
        # Band/model re-read at completion (the slo.py rationale: start()
        # runs before the director resolves objectives/rewrites).
        objectives = getattr(request, "objectives", None)
        if objectives is not None:
            wf.band = objectives.priority
        wf.model = getattr(request, "target_model", wf.model)
        if endpoint is not None:
            wf.endpoint = endpoint.metadata.address_port
        rec = getattr(request, "decision", None)
        if wf.shed_rung is None and rec is not None:
            # Shed/degrade rung culprit: the overload controller's ladder
            # action (router/overload.py record_shed block) — a degraded-
            # then-slow request's tail attribution names the rung.
            shed_block = getattr(rec, "shed", None)
            if isinstance(shed_block, dict) and shed_block.get("action"):
                wf.shed_rung = str(shed_block["action"])
        e2e_ms = (now - wf.t_start) * 1e3

        # TTFT and the streamed shape come from the SLO observation when it
        # exists (one clock discipline for both ledgers); fall back to
        # e2e-as-TTFT for non-streamed success when slo is disabled.
        obs = getattr(request, "outcome", None)
        streamed = bool(getattr(obs, "streamed", False))
        abort_reason = getattr(obs, "abort_reason", None)
        ttft_ms: float | None = None
        stream_ms = 0.0
        first = getattr(obs, "first_token_at", None)
        if first is not None:
            ttft_ms = (first - wf.t_start) * 1e3
            last = getattr(obs, "last_token_at", None)
            if last is not None:
                stream_ms = max(0.0, (last - first) * 1e3)
        elif status < 400 and reason is None and abort_reason is None \
                and not shed:
            ttft_ms = e2e_ms
        if obs is not None and obs.queue_ms and not wf.queue_ms:
            wf.queue_ms = obs.queue_ms

        # Verdict: the cohort rings hold SERVED requests only (a shed or
        # errored request has no meaningful stage split past its refusal
        # point), but the waterfall block stamps on every terminal shape.
        if shed:
            verdict = "shed"
        elif reason is not None or abort_reason is not None or status >= 400:
            verdict = "error"
        else:
            verdict = "ok"

        # Decode residual: client TTFT minus every accounted stage. Clamped
        # at zero — engine/sidecar stamps ride wall clocks on other hosts,
        # so skew must never mint negative decode time. Slot reads hoisted
        # once: this close path is the per-request hook the --tails bench
        # holds under 1% of the scheduling-cycle floor.
        q_ms, s_ms, a_ms = wf.queue_ms, wf.sched_ms, wf.attempts_ms
        eq_ms, p_ms, kv_ms = (wf.engine_queue_ms, wf.prefill_ms,
                              wf.kv_transfer_ms)
        decode_ms = 0.0
        if ttft_ms is not None:
            decode_ms = max(0.0, ttft_ms - (q_ms + s_ms + a_ms + eq_ms
                                            + p_ms + kv_ms))
        stage_vals = (q_ms, s_ms, a_ms, eq_ms, p_ms, kv_ms, decode_ms)

        self.closed_total += 1
        tail = False
        dominant: str | None = None
        stages_doc: dict[str, Any] = {}
        cohort_key = _cohort_key(wf.model, wf.band, streamed)
        if verdict == "ok" and ttft_ms is not None:
            cohort = self._cohort(cohort_key)
            cohort.closed += 1
            sample = _Sample(ttft_ms, stage_vals, stream_ms, wf.request_id,
                             wf.endpoint, wf.pair, wf.shed_rung)
            cohort.ring.append(sample)
            hist = self._stage_hist
            for name, v in zip(STAGES, stage_vals):
                if v > 0.0:
                    hist[name](v)
                    stages_doc[name] = round(v, 3)
            if stream_ms > 0.0:
                hist[STREAM_STAGE](stream_ms)
            cohort._since_refresh += 1
            if cohort._since_refresh >= _REFRESH_EVERY \
                    or cohort.threshold_ms is None:
                cohort.refresh(self.cfg.tail_quantile)
            thr = cohort.threshold_ms
            if thr is not None and ttft_ms > thr:
                # Complete()-time tail classification against the ROLLING
                # threshold: the counter family and the exemplar ring want
                # an online verdict; /debug/tails recomputes the split
                # exactly from the ring at read time.
                tail = True
                best = -1.0
                for name, v, m in zip(STAGES, stage_vals,
                                      cohort.body_stage_means):
                    excess = v - m
                    if excess > best:
                        best = excess
                        dominant = name
                cohort.tail_closed += 1
                self.tail_total += 1
                if dominant is not None:
                    cohort.dominant_counts[dominant] = \
                        cohort.dominant_counts.get(dominant, 0) + 1
                    self.dominant_total[dominant] = \
                        self.dominant_total.get(dominant, 0) + 1
                    child = self._dominant_children.get((cohort_key, dominant))
                    if child is None:
                        child = TAIL_DOMINANT_STAGE_TOTAL.labels(
                            cohort_key, dominant)
                        self._dominant_children[(cohort_key, dominant)] = child
                    child.inc()
                    ex: dict[str, Any] = {
                        "request_id": wf.request_id,
                        "ttft_ms": round(ttft_ms, 3),
                        "dominant": dominant,
                        "excess_ms": round(best, 3),
                    }
                    if wf.endpoint:
                        ex["endpoint"] = wf.endpoint
                    if wf.pair:
                        ex["pair"] = wf.pair
                    if wf.shed_rung:
                        ex["rung"] = wf.shed_rung
                    cohort.exemplars.append(ex)

        # Stamp the waterfall block into the decision record.
        if rec is not None and hasattr(rec, "record_waterfall"):
            if not stages_doc:  # non-ok verdicts skip the cohort loop
                stages_doc = {name: round(v, 3)
                              for name, v in zip(STAGES, stage_vals)
                              if v > 0.0}
            if stream_ms > 0.0:
                stages_doc[STREAM_STAGE] = round(stream_ms, 3)
            block: dict[str, Any] = {
                "stages": stages_doc,
                "e2e_ms": round(e2e_ms, 3),
                "verdict": verdict,
                "cohort": cohort_key,
            }
            if ttft_ms is not None:
                block["ttft_ms"] = round(ttft_ms, 3)
            if wf.pair:
                block["pair"] = wf.pair
            if wf.overlap_ms > 0.0:
                # Pull time hidden behind pipelined prefill: kept OUT of
                # the stage sums (kv_transfer above is exposed-only) so
                # stages still reconcile against ttft_ms.
                block["overlap_ms"] = round(wf.overlap_ms, 3)
            if wf.shed_rung:
                block["rung"] = wf.shed_rung
            if tail:
                block["tail"] = True
            if dominant is not None:
                block["dominant"] = dominant
            rec.record_waterfall(block)

    def _cohort(self, key: str) -> _Cohort:
        table = self._cohorts
        cohort = table.get(key)
        if cohort is not None:
            table.move_to_end(key)
            return cohort
        if len(table) >= self.MAX_COHORTS:
            table.popitem(last=False)
        cohort = table[key] = _Cohort(self.cfg.capacity, self.cfg.exemplars)
        return cohort

    # ---- render ---------------------------------------------------------

    def snapshot(self) -> dict[str, Any]:
        """The /debug/tails payload: per-cohort body-vs-tail split with
        per-stage quantiles, dominant-stage attribution of the tail
        cohort's excess time, culprit drill-down, exemplars, and the
        bounded digests merge_tails needs."""
        doc: dict[str, Any] = {
            "enabled": self.cfg.enabled,
            "since_unix": self._start_unix,
            "tail_quantile": self.cfg.tail_quantile,
            "closed": self.closed_total,
            "tail_closed": self.tail_total,
            "cohorts": {key: self._render_cohort(c)
                        for key, c in sorted(self._cohorts.items())},
        }
        return doc

    def _render_cohort(self, cohort: _Cohort) -> dict[str, Any]:
        samples = list(cohort.ring)
        n = len(samples)
        tail_q = self.cfg.tail_quantile
        # Digests are built here, from the same ring the quantiles read —
        # one rolling window for both, zero digest work on the close path.
        digests = {name: _Digest() for name in STAGES}
        ttft_digest = _Digest()
        for s in samples:
            ttft_digest.add(s.ttft_ms)
            for name, v in zip(STAGES, s.stages):
                if v > 0.0:
                    digests[name].add(v)
        out: dict[str, Any] = {
            "closed": cohort.closed,
            "tail_closed": cohort.tail_closed,
            "window_n": n,
            "digests": {name: d.to_doc() for name, d in digests.items()},
            "ttft_digest": ttft_digest.to_doc(),
            "exemplars": list(cohort.exemplars),
        }
        if not n:
            return out
        # Exact read-time split over the window (complete()-time counters
        # above track the rolling/online view).
        ttfts = sorted(s.ttft_ms for s in samples)
        thr = _quantile(ttfts, tail_q) or 0.0
        body = [s for s in samples if s.ttft_ms <= thr]
        tail = [s for s in samples if s.ttft_ms > thr]
        out["threshold_ttft_ms"] = round(thr, 3)
        out["body_n"] = len(body)
        out["tail_n"] = len(tail)
        out["ttft_ms"] = _stage_quantiles([s.ttft_ms for s in samples])
        stages_doc: dict[str, Any] = {}
        for i, name in enumerate(STAGES):
            vals = [s.stages[i] for s in samples]
            if not any(v > 0.0 for v in vals):
                continue
            row = _stage_quantiles(vals)
            if body:
                row["body_mean_ms"] = round(
                    sum(s.stages[i] for s in body) / len(body), 3)
            if tail:
                row["tail_mean_ms"] = round(
                    sum(s.stages[i] for s in tail) / len(tail), 3)
            stages_doc[name] = row
        stream_vals = [s.stream_ms for s in samples if s.stream_ms > 0.0]
        if stream_vals:
            stages_doc[STREAM_STAGE] = _stage_quantiles(stream_vals)
        out["stages"] = stages_doc
        if body and tail:
            out["attribution"] = _attribute(body, tail)
        return out


def _stage_quantiles(vals: list[float]) -> dict[str, Any]:
    vals = sorted(vals)
    return {"p50_ms": round(_quantile(vals, 0.50) or 0.0, 3),
            "p95_ms": round(_quantile(vals, 0.95) or 0.0, 3),
            "p99_ms": round(_quantile(vals, 0.99) or 0.0, 3)}


def _attribute(body: list[_Sample], tail: list[_Sample]) -> dict[str, Any]:
    """Dominant-stage attribution: how the tail cohort's excess TTFT (vs
    the body mean) splits across stages, plus culprit drill-down from the
    tail samples themselves. The shares answer "p99 TTFT is 71%
    kv_transfer"; the culprits answer "concentrated on pair
    prefill-X→decode-Y"."""
    nb, nt = len(body), len(tail)
    excess_by_stage: dict[str, float] = {}
    total_excess = 0.0
    for i, name in enumerate(STAGES):
        body_mean = sum(s.stages[i] for s in body) / nb
        tail_mean = sum(s.stages[i] for s in tail) / nt
        excess = max(0.0, tail_mean - body_mean)
        if excess > 0.0:
            excess_by_stage[name] = excess
            total_excess += excess
    doc: dict[str, Any] = {
        "tail_excess_ms_by_stage": {k: round(v, 3)
                                    for k, v in excess_by_stage.items()},
        "total_excess_ms": round(total_excess, 3),
    }
    if total_excess > 0.0:
        shares = {k: v / total_excess for k, v in excess_by_stage.items()}
        dominant = max(shares, key=shares.get)
        doc["shares"] = {k: round(v, 4) for k, v in shares.items()}
        doc["dominant"] = dominant
        doc["dominant_share"] = round(shares[dominant], 4)
        culprits: dict[str, Any] = {}
        ep = _top_count(s.endpoint for s in tail if s.endpoint)
        if ep is not None:
            culprits["endpoint"] = {"value": ep[0], "tail_n": ep[1]}
        pair = _top_count(s.pair for s in tail if s.pair)
        if pair is not None:
            culprits["pair"] = {"value": pair[0], "tail_n": pair[1]}
        rung = _top_count(s.rung for s in tail if s.rung)
        if rung is not None:
            culprits["rung"] = {"value": rung[0], "tail_n": rung[1]}
        if culprits:
            doc["culprits"] = culprits
        doc["statement"] = _statement(dominant, shares[dominant], culprits)
    return doc


def _top_count(values) -> tuple[str, int] | None:
    counts: dict[str, int] = {}
    for v in values:
        counts[v] = counts.get(v, 0) + 1
    if not counts:
        return None
    top = max(counts, key=counts.get)
    return top, counts[top]


def _statement(dominant: str, share: float,
               culprits: dict[str, Any]) -> str:
    s = f"tail TTFT excess is {share:.0%} {dominant}"
    where = culprits.get("pair") or culprits.get("endpoint")
    if where:
        s += f", concentrated on {where['value']}"
    return s


# ---- fleet fan-in -------------------------------------------------------


def merge_tails(shards: list[tuple[int, dict[str, Any]]]) -> dict[str, Any]:
    """Fleet supervisor fan-in for /debug/tails: n-weighted per-stage
    quantiles via the summed fixed-bin digests, n-weighted attribution from
    the per-shard tail-excess totals, shard-annotated exemplars. Input:
    (shard_index, worker /debug/tails payload) pairs."""
    merged: dict[str, Any] = {
        "shards": len(shards),
        "enabled": any(doc.get("enabled") for _, doc in shards),
        "closed": sum(int(doc.get("closed") or 0) for _, doc in shards),
        "tail_closed": sum(int(doc.get("tail_closed") or 0)
                           for _, doc in shards),
    }
    quantiles = (0.50, 0.95, 0.99)
    cohorts: dict[str, dict[str, Any]] = {}
    for key in sorted({k for _, doc in shards
                       for k in (doc.get("cohorts") or {})}):
        rows = [(shard, (doc.get("cohorts") or {}).get(key))
                for shard, doc in shards]
        rows = [(shard, c) for shard, c in rows if isinstance(c, dict)]
        if not rows:
            continue
        out: dict[str, Any] = {
            "closed": sum(int(c.get("closed") or 0) for _, c in rows),
            "tail_closed": sum(int(c.get("tail_closed") or 0)
                               for _, c in rows),
            "window_n": sum(int(c.get("window_n") or 0) for _, c in rows),
            "body_n": sum(int(c.get("body_n") or 0) for _, c in rows),
            "tail_n": sum(int(c.get("tail_n") or 0) for _, c in rows),
        }
        # n-weighted stage quantiles: sum each stage's fixed-bin digest
        # across shards, then read quantiles off the merged sketch.
        stages_doc: dict[str, Any] = {}
        for name in list(STAGES) + [STREAM_STAGE, "ttft"]:
            counts = [0] * (len(DIGEST_BOUNDS_MS) + 1)
            n = 0
            max_ms = 0.0
            for _, c in rows:
                d = (c.get("ttft_digest") if name == "ttft"
                     else (c.get("digests") or {}).get(name))
                if not isinstance(d, dict):
                    continue
                dc = d.get("counts") or []
                for i in range(min(len(counts), len(dc))):
                    counts[i] += int(dc[i])
                n += int(d.get("n") or 0)
                max_ms = max(max_ms, float(d.get("max_ms") or 0.0))
            if n <= 0:
                continue
            stages_doc[name] = {
                f"p{int(q * 100)}_ms": _digest_quantile(counts, n, max_ms, q)
                for q in quantiles}
            stages_doc[name]["n"] = n
        if stages_doc:
            ttft_row = stages_doc.pop("ttft", None)
            if ttft_row is not None:
                out["ttft_ms"] = ttft_row
            out["stages"] = stages_doc
        # n-weighted attribution: tail_n-weighted sum of each shard's
        # per-stage tail excess, shares recomputed over the merged totals.
        excess: dict[str, float] = {}
        for _, c in rows:
            attr = c.get("attribution") or {}
            tn = int(c.get("tail_n") or 0)
            for stage, ms in (attr.get("tail_excess_ms_by_stage")
                              or {}).items():
                try:
                    excess[stage] = excess.get(stage, 0.0) + float(ms) * tn
                except (TypeError, ValueError):
                    continue
        total = sum(excess.values())
        if total > 0.0:
            shares = {k: v / total for k, v in excess.items()}
            dominant = max(shares, key=shares.get)
            out["attribution"] = {
                "shares": {k: round(v, 4) for k, v in shares.items()},
                "dominant": dominant,
                "dominant_share": round(shares[dominant], 4),
            }
            # Culprit fan-in: the most tail-loaded shard's culprits speak
            # for the merged cohort (each shard already reduced its own
            # window; re-reducing value counts across shards would need the
            # raw samples the digests exist to avoid shipping).
            top_shard = max(rows, key=lambda r: int(r[1].get("tail_n") or 0))
            culprits = (top_shard[1].get("attribution") or {}).get("culprits")
            if culprits:
                out["attribution"]["culprits"] = culprits
                out["attribution"]["culprit_shard"] = top_shard[0]
            out["attribution"]["statement"] = _statement(
                dominant, shares[dominant], culprits or {})
        # Shard-annotated exemplars, bounded to one cohort's worth.
        exemplars: list[dict[str, Any]] = []
        for shard, c in rows:
            for ex in c.get("exemplars") or []:
                if isinstance(ex, dict):
                    exemplars.append({**ex, "shard": shard})
        exemplars.sort(key=lambda e: -(e.get("ttft_ms") or 0.0))
        if exemplars:
            out["exemplars"] = exemplars[:8]
        cohorts[key] = out
    merged["cohorts"] = cohorts
    return merged
