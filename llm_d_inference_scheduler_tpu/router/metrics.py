"""Router-side Prometheus metrics (reference: pkg/epp/metrics/metrics.go:88-460).

One process-global registry; families mirror the reference's names where the
concept carries over.
"""

from __future__ import annotations

from prometheus_client import CollectorRegistry, Counter, Gauge, Histogram

REGISTRY = CollectorRegistry()

REQUEST_TOTAL = Counter(
    "inference_extension_request_total", "Requests handled",
    ("model", "target_model"), registry=REGISTRY)
REQUEST_ERROR_TOTAL = Counter(
    "inference_extension_request_error_total", "Request errors",
    ("model", "error_code"), registry=REGISTRY)
REQUEST_DURATION = Histogram(
    "inference_extension_request_duration_seconds", "End-to-end request latency",
    ("model",), registry=REGISTRY)
TTFT_SECONDS = Histogram(
    "inference_extension_time_to_first_token_seconds", "TTFT observed at the router",
    ("model",), registry=REGISTRY,
    buckets=(.005, .01, .025, .05, .1, .25, .5, 1, 2.5, 5, 10, 30))
INPUT_TOKENS = Histogram(
    "inference_extension_input_tokens", "Prompt tokens per request",
    ("model",), registry=REGISTRY, buckets=(1, 8, 32, 128, 512, 2048, 8192, 32768))
OUTPUT_TOKENS = Histogram(
    "inference_extension_output_tokens", "Completion tokens per request",
    ("model",), registry=REGISTRY, buckets=(1, 8, 32, 128, 512, 2048, 8192))
RUNNING_REQUESTS = Gauge(
    "inference_extension_running_requests", "In-flight requests at the router",
    ("model",), registry=REGISTRY)
SCHEDULER_E2E_SECONDS = Histogram(
    "inference_extension_scheduler_e2e_duration_seconds", "Scheduling latency",
    registry=REGISTRY,
    buckets=(.0001, .0005, .001, .0025, .005, .01, .025, .05, .1))
PLUGIN_DURATION_SECONDS = Histogram(
    "inference_extension_plugin_duration_seconds", "Per-plugin latency",
    ("extension_point", "plugin"), registry=REGISTRY,
    buckets=(.0001, .0005, .001, .005, .01, .05, .1, .5))
DISAGG_DECISION_TOTAL = Counter(
    "disagg_decision_total", "Disaggregation decisions",
    ("decision_type",), registry=REGISTRY)
POOL_READY_ENDPOINTS = Gauge(
    "inference_pool_ready_pods", "Endpoints in the pool", registry=REGISTRY)
POOL_AVG_KV_CACHE = Gauge(
    "inference_pool_average_kv_cache_utilization", "Mean pool KV utilization",
    registry=REGISTRY)
POOL_AVG_QUEUE = Gauge(
    "inference_pool_average_queue_size", "Mean pool queue depth", registry=REGISTRY)
FLOW_CONTROL_QUEUE_SIZE = Gauge(
    "inference_extension_flow_control_queue_size", "Queued flow-control requests",
    registry=REGISTRY)
FLOW_CONTROL_QUEUE_SECONDS = Histogram(
    "inference_extension_flow_control_queue_duration_seconds",
    "Time spent queued in flow control", registry=REGISTRY,
    buckets=(.001, .005, .01, .05, .1, .5, 1, 5, 30))
PREFIX_HIT_RATIO = Histogram(
    "inference_extension_prefix_indexer_hit_ratio", "Prefix-cache hit ratio",
    registry=REGISTRY, buckets=(0, .1, .25, .5, .75, .9, 1))
# Predicted-latency subsystem (reference metrics.go: predicted ttft/tpot +
# slo-violation counters).
PREDICTED_TTFT_MS = Histogram(
    "inference_extension_predicted_time_to_first_token_ms",
    "Predicted TTFT at scheduling time", registry=REGISTRY,
    buckets=(1, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 10000))
PREDICTED_TPOT_MS = Histogram(
    "inference_extension_predicted_time_per_output_token_ms",
    "Predicted TPOT at scheduling time", registry=REGISTRY,
    buckets=(.1, .5, 1, 2.5, 5, 10, 25, 50, 100, 250))
LATENCY_TRAINING_SAMPLES = Counter(
    "inference_extension_latency_predictor_training_samples_total",
    "Online latency-model training samples ingested",
    ("kind",), registry=REGISTRY)  # kind: ttft | tpot
SLO_VIOLATION_TOTAL = Counter(
    "inference_extension_slo_violation_total",
    "Completed requests whose observed latency violated the request SLO",
    ("kind",), registry=REGISTRY)
# Metrics-data-source scrape health: per-endpoint failure counts and the
# scrape latency distribution (label cardinality bounded by pool size).
SCRAPE_ERRORS_TOTAL = Counter(
    "inference_extension_metrics_scrape_errors_total",
    "Failed engine /metrics scrapes", ("target",), registry=REGISTRY)
SCRAPE_DURATION_SECONDS = Histogram(
    "inference_extension_metrics_scrape_duration_seconds",
    "Engine /metrics scrape latency", registry=REGISTRY,
    buckets=(.001, .005, .01, .025, .05, .1, .25, .5, 1, 2))
# Resilient data plane (router/resilience.py): retry/failover, passive
# endpoint circuit breaking, end-to-end deadlines, stream-abort handling.
RETRIES_TOTAL = Counter(
    "router_retries_total",
    "Gateway retry/failover attempts after a pre-stream upstream failure",
    ("kind",), registry=REGISTRY)  # kind: connect | read | status
RETRY_BUDGET_EXHAUSTED_TOTAL = Counter(
    "router_retry_budget_exhausted_total",
    "Retries suppressed because the token-bucket retry budget was empty",
    registry=REGISTRY)
BREAKER_STATE = Gauge(
    "router_endpoint_circuit_breaker_state",
    "Per-endpoint breaker state: 0 closed, 1 half-open, 2 open",
    ("endpoint",), registry=REGISTRY)  # cardinality bounded by pool size
BREAKER_TRANSITIONS_TOTAL = Counter(
    "router_circuit_breaker_transitions_total",
    "Breaker state transitions per endpoint",
    ("endpoint", "to_state"), registry=REGISTRY)
DEADLINE_EXCEEDED_TOTAL = Counter(
    "router_request_deadline_exceeded_total",
    "Requests rejected at the gateway with the end-to-end deadline exhausted",
    registry=REGISTRY)
UPSTREAM_STREAM_ABORTED_TOTAL = Counter(
    "router_upstream_stream_aborted_total",
    "Response streams cut mid-relay by an upstream disconnect (closed "
    "cleanly toward the client instead of raising)", registry=REGISTRY)
# Decision flight recorder aggregates (router/decisions.py): the histogram/
# counter shadows of the per-request records, so score distributions, filter
# pressure, and pick decisiveness are graphable without reading records.
# Label cardinality is bounded by the configured plugin set.
SCORER_SCORE = Histogram(
    "router_scorer_score",
    "Per-endpoint raw scorer outputs observed at scheduling time",
    ("scorer",), registry=REGISTRY,
    buckets=(0.0, .1, .2, .3, .4, .5, .6, .7, .8, .9, 1.0))
FILTER_DROPPED_TOTAL = Counter(
    "router_filter_dropped_endpoints_total",
    "Candidate endpoints removed per scheduling filter",
    ("filter",), registry=REGISTRY)
PICKER_WIN_MARGIN = Histogram(
    "router_picker_win_margin",
    "Weighted-score margin between the picked endpoint and the runner-up "
    "(0 = coin flip; large = decisive pick)",
    ("picker",), registry=REGISTRY,
    buckets=(0.0, .01, .025, .05, .1, .25, .5, 1.0, 2.0, 4.0))
# Concurrent scheduling engine (router/schedpool.py + router/snapshot.py):
# off-loop scheduler workers over copy-on-write pool snapshots, batched
# flow-control dispatch.
SCHED_OFFLOAD_QUEUE_SECONDS = Histogram(
    "router_sched_offload_queue_seconds",
    "Time a scheduling cycle waited between submission to the worker pool "
    "and a worker picking it up",
    registry=REGISTRY,
    buckets=(.00001, .0001, .00025, .0005, .001, .0025, .005, .01, .05, .1))
SCHED_BATCH_SIZE = Histogram(
    "router_sched_batch_size",
    "Flow-control items dispatched per shard wake (co-dispatched batches "
    "share one pool-snapshot epoch)",
    registry=REGISTRY, buckets=(1, 2, 4, 8, 16, 32, 64))
LOOP_LAG_SECONDS = Histogram(
    "router_loop_lag_seconds",
    "Event-loop scheduling stall sampled by the gateway's heartbeat "
    "(sleep-overshoot of a 100ms timer; the stall token relays experience)",
    registry=REGISTRY,
    buckets=(.0001, .0005, .001, .0025, .005, .01, .025, .05, .1, .5))
# SLO & goodput ledger (router/slo.py): per-request serving outcomes,
# predictor calibration, goodput vs raw token rate. The per-request detail
# (predicted vs actual vs SLO, miss reason, transfer row) lives in the
# DecisionRecord outcome block; these are the graphable aggregates.
SLO_ATTAINMENT = Gauge(
    "router_slo_attainment",
    "Running SLO attainment ratio (slo_met terminal requests / all terminal "
    "requests) per endpoint", ("endpoint",),
    registry=REGISTRY)  # children evicted with SloLedger.MAX_ENDPOINTS LRU
SLO_REQUESTS_TOTAL = Counter(
    "router_slo_requests_total",
    "Terminal serving outcomes by verdict (met / missed / error)",
    ("verdict",), registry=REGISTRY)
GOODPUT_TOKENS_TOTAL = Counter(
    "router_goodput_tokens_total",
    "Completion tokens delivered inside the request SLO (goodput; "
    "P/D-Serve's fleet objective)", ("model",), registry=REGISTRY)
OUTPUT_TOKENS_TOTAL = Counter(
    "router_output_tokens_total",
    "All completion tokens delivered (raw token rate — divergence from "
    "router_goodput_tokens_total is wasted work)",
    ("model",), registry=REGISTRY)
PREDICTOR_ERROR_MS = Histogram(
    "router_predictor_error_ms",
    "Absolute error of the predicted-latency ridge vs the observed value "
    "(kind: ttft | tpot; role: served endpoint's pool role). Signed "
    "error/bias is in the /debug/slo rollup.",
    ("kind", "role"), registry=REGISTRY,
    buckets=(1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 2500))
KV_TRANSFER_MS = Histogram(
    "router_kv_transfer_ms",
    "Per-request KV pull duration measured by the decode engine and relayed "
    "through the sidecar (per-pair EWMA table at /debug/transfers)",
    registry=REGISTRY,
    buckets=(1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 2500))
KV_TRANSFER_EXPOSED_MS = Histogram(
    "router_kv_transfer_exposed_ms",
    "Per-request KV pull time NOT hidden behind prefill compute on pipelined "
    "P/D requests (raw pull minus overlap; the cost pair scorers/rebalancer "
    "read). Absent on serial 2-phase pulls, where exposed == raw.",
    registry=REGISTRY,
    buckets=(1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 2500))
# Goodput-max overload control (router/overload.py): predictive SLO
# admission, degrade ladder, Retry-After shedding, and predicted-unmeetable
# queue eviction. Reason/action label sets are fixed small enums.
ADMISSION_SHED_TOTAL = Counter(
    "router_admission_shed_total",
    "Requests shed by the overload controller before capacity was spent "
    "(reason: predicted_ttft_miss | predicted_tpot_miss | queue_unmeetable)",
    ("reason",), registry=REGISTRY)
DEGRADED_REQUESTS_TOTAL = Counter(
    "router_degraded_requests_total",
    "Requests admitted via the degrade ladder instead of being shed "
    "(action: clamp_max_tokens | model_rewrite)",
    ("action",), registry=REGISTRY)
RETRY_AFTER_SECONDS = Histogram(
    "router_retry_after_seconds",
    "Computed Retry-After handed to shed requests (derived from the queue "
    "drain rate; always finite)",
    registry=REGISTRY, buckets=(1, 2, 5, 10, 15, 30, 60))
QUEUE_DRAIN_RATE = Gauge(
    "router_queue_drain_rate",
    "Measured flow-control dispatch rate (requests/second, EWMA) feeding "
    "the overload controller's queue-wait and Retry-After estimates",
    registry=REGISTRY)
# KV-cache & prefix-reuse observability (router/kvobs.py): the
# predicted-vs-confirmed hit ledger behind /debug/kv. Per-request detail
# (per-candidate predictions, the engine-confirmed actual, signed error)
# lives in the DecisionRecord cache block; these are the graphable
# aggregates.
KV_PREDICTED_HIT_BLOCKS = Histogram(
    "router_kv_predicted_hit_blocks",
    "Schedule-time predicted prefix-hit depth (blocks) for the chosen "
    "endpoint (approx producer / precise scorer prediction)",
    registry=REGISTRY, buckets=(0, 1, 2, 4, 8, 16, 32, 64, 128))
KV_HIT_PREDICTION_ERROR = Histogram(
    "router_kv_hit_prediction_error",
    "Absolute error (blocks) between the predicted hit depth and the "
    "engine-confirmed actual (x-kv-hit-blocks); signed bias is in the "
    "/debug/kv rollup",
    registry=REGISTRY, buckets=(0, 1, 2, 4, 8, 16, 32, 64))
KV_ACTUAL_HIT_RATIO = Histogram(
    "router_kv_actual_hit_ratio",
    "Engine-confirmed prefix-hit ratio (hit tokens / prompt tokens) per "
    "completed request",
    registry=REGISTRY, buckets=(0.0, .1, .25, .5, .75, .9, 1.0))
# Session-aware prefill classifier (router/plugins/disagg.py): the
# ledger-driven placement stage that routes high-confidence cache-hit
# prefills straight to the decode pod (skip the P/D hop). Verdicts are a
# fixed small enum; the per-request detail (predicted depth, trust
# discount, threshold, post-hoc judgement) is the DecisionRecord
# classifier block, and per-pod precision/recall is on /debug/kv.
PD_CLASSIFIER_DECISIONS_TOTAL = Counter(
    "router_pd_classifier_decisions_total",
    "Prefill-classifier verdicts per evaluation (verdict: skip = route "
    "straight to the decode pod, keep = run the P/D decider as usual, "
    "low_confidence = not enough measured trust to act on the prediction)",
    ("verdict",), registry=REGISTRY)
PD_HOP_SKIPPED_TOTAL = Counter(
    "router_pd_hop_skipped_total",
    "Requests routed straight to the decode pod by the prefill classifier "
    "(no prefill leg, no KV pull — the P/D hop skipped)",
    registry=REGISTRY)
# Fleet flight recorder (router/timeline.py): the /debug/timeline sampler,
# the multi-window SLO burn-rate monitor, and the /debug/incidents ring.
# The per-tick detail lives in the timeline samples; these are the
# graphable aggregates (and the liveness signal that the sampler ticks).
TIMELINE_TICKS = Counter(
    "router_timeline_ticks_total",
    "Timeline sampler ticks recorded (liveness of the flight recorder; "
    "absent/frozen under the timeline kill-switch)", registry=REGISTRY)
SLO_BURN_RATE = Gauge(
    "router_slo_burn_rate",
    "Multi-window SLO error-budget burn rate ((1 - met/arrivals) / "
    "(1 - target); arrivals include sheds — the arrival-relative goodput "
    "view, deliberately stricter than /debug/slo's served-relative "
    "attainment)", ("window",), registry=REGISTRY)  # window: fast | slow
INCIDENTS_TOTAL = Counter(
    "router_incidents_total",
    "Triggered incident snapshots captured into the /debug/incidents ring "
    "(rule: burn_rate | shed_rate | drain_collapse | divergence); "
    "dedup/cooldown means a sustained episode counts once",
    ("rule",), registry=REGISTRY)
# Process self-telemetry feeding the timeline: before these the only
# process-health signal was router_loop_lag_seconds.
PROCESS_RSS_BYTES = Gauge(
    "router_process_rss_bytes",
    "Resident set size of the router process (/proc/self/statm, sampled "
    "per timeline tick)", registry=REGISTRY)
PROCESS_OPEN_FDS = Gauge(
    "router_process_open_fds",
    "Open file descriptors of the router process (sockets, pipes, files; "
    "sampled per timeline tick)", registry=REGISTRY)
GC_PAUSE_SECONDS = Counter(
    "router_gc_pause_seconds_total",
    "Cumulative stop-the-world garbage-collection pause time "
    "(gc.callbacks; every pause stalls the event loop and all scheduler "
    "workers)", registry=REGISTRY)
# Effective-config identity (/debug/config): the hash label changes only
# with the loaded config, so cardinality is one series per process — the
# fleet fan-in compares hashes across shards to catch config skew.
CONFIG_INFO = Gauge(
    "router_config_info",
    "Constant 1, labeled with the xxh64 hash of the effective loaded "
    "config — scrape-joinable config-skew detection (redacted snapshot at "
    "/debug/config)", ("hash",), registry=REGISTRY)
# Shadow policy evaluation (router/shadow.py): counterfactual scheduling
# verdicts and the signed estimated-regret distribution per registered
# policy. Policy/verdict label sets are bounded by the configured policy
# list and the fixed verdict enum; per-request detail is the DecisionRecord
# shadow block, the per-policy rollup is GET /debug/shadow.
SHADOW_DECISIONS_TOTAL = Counter(
    "router_shadow_decisions_total",
    "Shadow-policy counterfactual verdicts per evaluated scheduling cycle "
    "(verdict: agree = shadow pick matches the live pick, diverge = the "
    "policy would have picked differently, no_signal = the policy's "
    "measured feed has no data yet)",
    ("policy", "verdict"), registry=REGISTRY)
SHADOW_REGRET_MS = Histogram(
    "router_shadow_regret_ms",
    "Signed estimated regret of the LIVE policy per judged divergent pick "
    "(live measured cost minus the shadow arm's estimate from the measured "
    "feeds; positive = the shadow policy would have been cheaper). Only "
    "judged divergences observe — agreements credit both arms at "
    "/debug/shadow instead",
    ("policy",), registry=REGISTRY,
    buckets=(-250, -100, -50, -25, -10, -5, -1, 0,
             1, 5, 10, 25, 50, 100, 250))
# Self-balancing pool (router/rebalance.py): dynamic P/D role rebalancing
# with drain-cycle role flips and predictive scaling advice. Role/direction
# label sets are fixed small enums; the per-flip detail (full controller
# inputs) is served at /debug/rebalance.
REBALANCE_HEADROOM = Gauge(
    "router_rebalance_headroom",
    "Per-role goodput headroom computed by the rebalance controller each "
    "tick (0 = saturated, 1 = idle; 1 - max(engine queue pressure, "
    "workload SLO miss rate) — full inputs at /debug/rebalance)",
    ("role",), registry=REGISTRY)
ROLE_FLIPS_TOTAL = Counter(
    "router_role_flips",
    "Completed drain-cycle pod role flips (llm-d.ai/role republished "
    "after in-flight work cleared); every flip's full inputs are at "
    "/debug/rebalance",
    ("from", "to"), registry=REGISTRY)
POOL_ADVICE = Gauge(
    "router_pool_advice",
    "Predictive scaling advice per role (1 = advised): direction=up when "
    "a role starves and no role flip can help, direction=down when a role "
    "idles against a healthy peer (for prefill, a sustained hop-skip rate "
    "is extra evidence) — the autoscaler hook a k8s InferencePool "
    "reconciler would consume",
    ("role", "direction"), registry=REGISTRY)
POOL_ADVICE_CHANGES = Counter(
    "router_pool_advice_changes_total",
    "Scaling-advice state TRANSITIONS per role (incremented only when the "
    "advised direction changes, labeled with the direction entered: "
    "up | down | hold) — rate() this for advice churn; the point-in-time "
    "verdict stays on router_pool_advice",
    ("role", "direction"), registry=REGISTRY)
# Traffic forecaster & capacity observatory (router/forecast.py): judged
# multi-horizon prediction over the timeline grid. Series/horizon label
# sets are bounded (the engine caps tracked series; horizons come from
# config); the full ledger is GET /debug/forecast.
FORECAST_MAE = Gauge(
    "router_forecast_mae",
    "Windowed mean absolute forecast error per judged (series, horizon) "
    "cell, in the series' native unit (req/s, tokens/s, requests, "
    "headroom) — every elapsed forecast joins against the actual "
    "timeline sample, never assumed (/debug/forecast)",
    ("series", "horizon"), registry=REGISTRY)
FORECAST_SKILL = Gauge(
    "router_forecast_skill",
    "Forecast skill vs the naive last-value persistence baseline per "
    "(series, horizon): 1 - MAE/MAE_persistence over the judged window. "
    "<= 0 means the model cannot beat copying the current value forward "
    "— visibly worthless, by design", ("series", "horizon"),
    registry=REGISTRY)
FORECAST_COVERAGE = Gauge(
    "router_forecast_interval_coverage",
    "Fraction of judged forecasts whose actual landed inside the stamped "
    "prediction interval, per (series, horizon) — held against the "
    "configured forecast.intervals target", ("series", "horizon"),
    registry=REGISTRY)
FORECAST_STAMPS = Counter(
    "router_forecast_stamps_total",
    "Forecasts stamped (one per series per horizon per timeline tick "
    "after warmup; zero under the forecast kill-switch)",
    registry=REGISTRY)
FORECAST_JOINS = Counter(
    "router_forecast_joins_total",
    "Elapsed-horizon forecasts judged against their actual timeline "
    "sample (joins/(joins+gap_skips) is the join-coverage rate)",
    registry=REGISTRY)
FORECAST_GAP_SKIPS = Counter(
    "router_forecast_gap_skips_total",
    "Forecasts dropped unjudged because their target bucket was a gap "
    "(sampler stall/restart, or the series absent from the sample) — "
    "gaps are skipped, never interpolated", registry=REGISTRY)
TIME_TO_SATURATION = Gauge(
    "router_time_to_saturation_seconds",
    "Capacity observatory: projected seconds until the role's forecasted "
    "headroom crosses zero (level+trend zero-crossing of the rebalancer's "
    "per-role headroom series; +Inf when no saturation is projected) — "
    "the scale-ahead lead the pool advice carries as lead_s",
    ("role",), registry=REGISTRY)
# Confirmed-index replication (router/fleet.py): a follower that detects a
# sequence gap in the leader's KV delta stream stops applying deltas and
# waits for the next full-index checkpoint frame to resync. Worker-side —
# the fleet /metrics merge sums it across shards.
KV_INDEX_RESYNCS = Counter(
    "router_kv_index_resyncs_total",
    "Confirmed KV-index delta-stream resyncs in this worker: a sequence "
    "gap (dropped frame, leader change, reconnect) was detected and the "
    "replica waited for the next full-index checkpoint instead of "
    "applying deltas onto an uncertain base", registry=REGISTRY)
# Guarded elastic-fleet actuator (router/autoscale.py): every guarded
# action's terminal verdict, the rollback-freeze latch, and the live fleet
# census the actuator is steering.
AUTOSCALE_ACTIONS = Counter(
    "router_autoscale_actions",
    "Guarded actuator actions by terminal outcome (completed / refused / "
    "aborted / rolled_back) per kind (spawn_pod / retire_pod / "
    "spawn_worker / retire_worker) — refusals are deduplicated per "
    "sustained reason episode in the /debug/autoscale ledger but counted "
    "here per tick", ("kind", "outcome"), registry=REGISTRY)
AUTOSCALE_FROZEN = Gauge(
    "router_autoscale_frozen",
    "1 while the actuator is frozen by rollback-on-incident (a burn-rate "
    "trip or attainment collapse inside a post-action observation window "
    "reversed the last action and latched this until operator reset)",
    registry=REGISTRY)
FLEET_SIZE = Gauge(
    "router_fleet_size",
    "Live fleet census per role as the actuator sees it: engine pods per "
    "routing role (prefill / decode, draining included) plus the active "
    "gateway worker count under role=\"worker\" when worker scaling is "
    "wired", ("role",), registry=REGISTRY)
# Tail-latency attribution observatory (router/tails.py, ISSUE 18): the
# per-request critical-path waterfall decomposed into stage histograms, and
# the online dominant-stage verdict for requests classified into a cohort's
# tail at close time. Exemplar request-ids live in the /debug/tails JSON
# payload, never on labels (FORBIDDEN_LABELS).
STAGE_MS = Histogram(
    "router_stage_ms",
    "Per-request critical-path stage time (ms) from the closed waterfall: "
    "queue (flow-control admission wait), sched (scheduling cycle + "
    "offload dispatch), attempts (time burned in failed failover "
    "attempts), engine_queue (engine admission-to-first-step wait), "
    "prefill (x-prefill-duration-ms), kv_transfer (x-kv-transfer-ms), "
    "decode (residual TTFT), stream (first-to-last token relay)",
    ("stage",),
    buckets=(1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000,
             10000),
    registry=REGISTRY)
TAIL_DOMINANT_STAGE_TOTAL = Counter(
    "router_tail_dominant_stage",
    "Requests classified into their cohort's tail at close time (TTFT "
    "above the rolling tailQuantile threshold), by the stage with the "
    "largest excess over the cohort's body mean — the online twin of the "
    "/debug/tails attribution", ("cohort", "stage"), registry=REGISTRY)
# Multi-process sharded gateway (router/fleet.py): each worker exposes the
# pool-snapshot epoch it last built (leader) or applied from the IPC stream
# (follower) — the supervisor re-labels it per shard, making snapshot-IPC
# staleness graphable fleet-wide.
SNAPSHOT_EPOCH = Gauge(
    "router_snapshot_epoch",
    "Pool-snapshot epoch this process last built (datalayer leader / "
    "single-process router) or applied from the fleet leader's IPC stream "
    "(follower worker)", registry=REGISTRY)

# Binary snapshot frames (router/snapwire.py) that failed validation and
# were skipped by a follower. Skipped, not fatal: the outer length prefix
# keeps the stream aligned, so one bad frame costs one epoch of staleness.
# reason: truncated | checksum | version | malformed.
SNAPSHOT_FRAME_ERRORS = Counter(
    "router_snapshot_frame_errors",
    "Binary snapshot-IPC frames a follower rejected and skipped (bad "
    "magic/shape=malformed, payload digest mismatch=checksum, length "
    "short of the header's claim=truncated, unsupported format "
    "version=version)",
    ("reason",), registry=REGISTRY)

# Fleet-supervisor registry (router/fleet.py): families that exist only in
# the supervisor process — worker liveness, per-shard request/epoch views
# derived from the admin-plane scrapes, and the hash balancer's connection
# counts. A SEPARATE registry: the supervisor must not re-emit the router
# families above with zero values next to the workers' merged real ones.
FLEET_REGISTRY = CollectorRegistry()

FLEET_WORKERS = Gauge(
    "router_fleet_workers",
    "Configured gateway worker processes in the fleet",
    registry=FLEET_REGISTRY)
SHARD_UP = Gauge(
    "router_shard_up",
    "Per-shard worker liveness as seen by the fleet supervisor (1 = the "
    "worker process is alive and its admin plane answers)",
    ("shard",), registry=FLEET_REGISTRY)
SHARD_STATE = Gauge(
    "router_shard_state",
    "Per-shard lifecycle state companion to router_shard_up, so a worker "
    "retired ON PURPOSE by the scale-in path is distinguishable from a "
    "crashed one (0 = down/crashed, 1 = up, 2 = retiring — draining its "
    "flows before exit, 3 = retired — deliberately scaled in)",
    ("shard",), registry=FLEET_REGISTRY)
SHARD_SNAPSHOT_EPOCH = Gauge(
    "router_shard_snapshot_epoch",
    "router_snapshot_epoch per worker, re-labeled by shard at merge time — "
    "a follower lagging the leader's epoch is visible as a gap",
    ("shard",), registry=FLEET_REGISTRY)
SHARD_REQUESTS = Counter(
    "router_shard_requests",
    "Requests handled per shard (derived from each worker's "
    "inference_extension_request_total at merge time)",
    ("shard",), registry=FLEET_REGISTRY)
FLEET_BALANCER_CONNECTIONS = Counter(
    "router_fleet_balancer_connections",
    "Connections routed per shard by the hash-by-flow-id front balancer "
    "(fleet.balancer: hash; absent under SO_REUSEPORT kernel balancing)",
    ("shard",), registry=FLEET_REGISTRY)
FLEET_LEADER = Gauge(
    "router_fleet_leader",
    "Datalayer-leader role per shard (1 = this worker runs the scrape + "
    "kv-event pipeline and publishes snapshot/KV-delta frames; moves on "
    "leader re-election when the leader process dies)",
    ("shard",), registry=FLEET_REGISTRY)
LEADER_ELECTIONS = Counter(
    "router_leader_elections",
    "Datalayer-leader re-elections performed by the fleet supervisor (a "
    "dead leader was replaced by promoting the lowest-index live "
    "follower)", registry=FLEET_REGISTRY)
KV_INDEX_DIVERGENCE = Gauge(
    "router_kv_index_divergence",
    "Per-shard KV-index divergence derived at /debug/kv fan-in time: the "
    "fraction of the leader's engine-confirmed KvBlockIndex blocks a "
    "follower's (speculative-only) view cannot account for — 0 on the "
    "leader, 1 on a follower with no overlapping stamps. Measures the "
    "ROADMAP item-1 follower-fidelity caveat (run balancer: hash when it "
    "matters)", ("shard",), registry=FLEET_REGISTRY)
