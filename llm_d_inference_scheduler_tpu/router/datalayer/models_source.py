"""models-data-source / models-data-extractor: poll each endpoint's
/v1/models into an endpoint attribute for model-aware routing.

Reference: framework/plugins/datalayer/source/models (GET
<scheme>://<endpoint>/<path> per collector cycle, README.md:8-13) paired
with extractor/models (attribute key ``/v1/models`` holding
[{id, parent}] ModelData entries, extractor.go:15,106). The attribute is
the bus to model-aware consumers: the gateway's /v1/models union and the
model-serving-filter read it.
"""

from __future__ import annotations

import json
import logging
import time
from typing import Any

import httpx

from ..framework.datalayer import Endpoint
from ..framework.plugin import PluginBase, register_plugin

log = logging.getLogger("router.datalayer.models")

# Attribute key contract (reference extractor.go:15).
MODELS_ATTRIBUTE_KEY = "/v1/models"


@register_plugin("models-data-source")
class ModelsDataSource(PluginBase):
    TYPE = "models-data-source"

    def __init__(self, name: str | None = None):
        super().__init__(name)
        self._extractors: list[Any] = []
        self._scheme = "http"
        self._path = "/v1/models"
        self._timeout = 2.0
        # The model list changes on the order of deploys, not tokens:
        # refresh every few seconds instead of every 50 ms collector tick.
        self._refresh_s = 5.0
        # Reference default (source/models/README.md:22): in-cluster model
        # servers typically present pod-local certs.
        self._insecure_skip_verify = True
        self._last_poll: dict[str, float] = {}
        self._client: httpx.AsyncClient | None = None

    def configure(self, params: dict[str, Any], handle: Any) -> None:
        self._scheme = str(params.get("scheme", self._scheme))
        self._path = str(params.get("path", self._path))
        self._timeout = float(params.get("timeoutSeconds", self._timeout))
        self._refresh_s = float(params.get("refreshSeconds", self._refresh_s))
        self._insecure_skip_verify = bool(
            params.get("insecureSkipVerify", self._insecure_skip_verify))

    def add_extractor(self, ex: Any) -> None:
        self._extractors.append(ex)

    def extractors(self) -> list[Any]:
        if not self._extractors:
            # Default pairing (the reference wires this via data: sources:;
            # a bare source without extractors would collect into the void).
            self._extractors.append(ModelsDataExtractor("models-data-extractor"))
        return list(self._extractors)

    async def collect(self, endpoint: Endpoint) -> str | None:
        key = endpoint.metadata.address_port
        now = time.monotonic()
        if now - self._last_poll.get(key, -1e9) < self._refresh_s:
            return None  # fresh enough; extractor treats None as no-op
        self._last_poll[key] = now
        if self._client is None:
            self._client = httpx.AsyncClient(
                timeout=self._timeout,
                verify=not self._insecure_skip_verify)
        url = (f"{self._scheme}://{endpoint.metadata.address}:"
               f"{endpoint.metadata.port}{self._path}")
        try:
            r = await self._client.get(url)
            r.raise_for_status()
            return r.text
        except Exception as e:
            log.debug("models poll failed for %s: %s", key, e)
            return None

    async def close(self):
        if self._client is not None:
            await self._client.aclose()
            self._client = None


@register_plugin("models-data-extractor")
class ModelsDataExtractor(PluginBase):
    TYPE = "models-data-extractor"

    def extract(self, raw: str | None, endpoint: Endpoint) -> None:
        if raw is None:
            return
        try:
            doc = json.loads(raw)
            data = doc.get("data") or []
            models = [{"id": str(m.get("id", "")),
                       "parent": str(m.get("parent") or "")}
                      for m in data if isinstance(m, dict) and m.get("id")]
        except Exception as e:
            log.debug("unparseable /v1/models body for %s: %s",
                      endpoint.metadata.address_port, e)
            return
        endpoint.attributes.put(MODELS_ATTRIBUTE_KEY, models)


def endpoint_models(endpoint: Endpoint) -> list[dict[str, str]] | None:
    """The endpoint's served-model list, or None when not yet polled."""
    return endpoint.attributes.get(MODELS_ATTRIBUTE_KEY)
