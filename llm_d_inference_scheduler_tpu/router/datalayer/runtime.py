"""Data-layer runtime: per-endpoint collectors on a poll ticker.

Mirrors /root/reference/pkg/epp/datalayer/{runtime.go:36-466,
collector.go:52-154}: the runtime owns registered data sources; each endpoint
gets a Collector task that, every tick (default 50ms like the reference),
runs source.collect() and feeds the raw result through the source's
extractors, updating the endpoint's Metrics/Attributes in place. Endpoint
lifecycle events fan out to registered EndpointLifecycle plugins.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any

from ..framework.datalayer import Endpoint
from .datastore import Datastore

log = logging.getLogger("router.datalayer.runtime")

DEFAULT_POLL_INTERVAL_S = 0.05  # reference: datalayer/collector.go:52


class _Collector:
    def __init__(self, endpoint: Endpoint, sources: list[Any], interval: float):
        self.endpoint = endpoint
        self.sources = sources
        self.interval = interval
        self._task: asyncio.Task | None = None

    def start(self):
        self._task = asyncio.get_running_loop().create_task(self._run())

    def stop(self):
        if self._task:
            self._task.cancel()

    async def _run(self):
        try:
            while True:
                for src in self.sources:
                    try:
                        raw = await src.collect(self.endpoint)
                        for ex in src.extractors():
                            ex.extract(raw, self.endpoint)
                    except Exception:
                        log.exception("collector error for %s",
                                      self.endpoint.metadata.address_port)
                await asyncio.sleep(self.interval)
        except asyncio.CancelledError:
            pass


class DataLayerRuntime:
    def __init__(self, datastore: Datastore, poll_interval: float = DEFAULT_POLL_INTERVAL_S):
        self.datastore = datastore
        self.poll_interval = poll_interval
        self.sources: list[Any] = []
        self.lifecycle_plugins: list[Any] = []
        self._collectors: dict[str, _Collector] = {}
        self._started = False
        datastore.on_endpoint_event(self._on_endpoint_event)

    def register_source(self, source: Any) -> None:
        self.sources.append(source)

    def register_lifecycle(self, plugin: Any) -> None:
        self.lifecycle_plugins.append(plugin)

    async def start(self):
        self._started = True
        for ep in self.datastore.endpoint_list():
            self._start_collector(ep)

    async def stop(self):
        self._started = False
        for c in self._collectors.values():
            c.stop()
        self._collectors.clear()
        for src in self.sources:
            close = getattr(src, "close", None)
            if close:
                await close()

    def _on_endpoint_event(self, event: str, ep: Endpoint) -> None:
        if event == "added":
            if self._started:
                self._start_collector(ep)
            for p in self.lifecycle_plugins:
                try:
                    getattr(p, "endpoint_added", lambda _ep: None)(ep)
                except Exception:
                    log.exception("lifecycle plugin failure (add)")
        elif event == "removed":
            c = self._collectors.pop(ep.metadata.address_port, None)
            if c:
                c.stop()
            for p in self.lifecycle_plugins:
                try:
                    getattr(p, "endpoint_removed", lambda _ep: None)(ep)
                except Exception:
                    log.exception("lifecycle plugin failure (remove)")

    def _start_collector(self, ep: Endpoint) -> None:
        key = ep.metadata.address_port
        if key in self._collectors:
            return
        c = _Collector(ep, self.sources, self.poll_interval)
        self._collectors[key] = c
        c.start()
