"""Data-layer runtime: per-endpoint collectors on a poll ticker.

Mirrors /root/reference/pkg/epp/datalayer/{runtime.go:36-466,
collector.go:52-154}: the runtime owns registered data sources; each endpoint
gets a Collector task that, every tick (default 50ms like the reference),
runs source.collect() and feeds the raw result through the source's
extractors, updating the endpoint's Metrics/Attributes in place. Endpoint
lifecycle events fan out to registered EndpointLifecycle plugins.

Two scale behaviors (ISSUE 5):

- **Extractor offload**: the Prometheus text parse inside each extractor is
  pure-Python CPU (at 128 pods × 1 s it rides the event loop between every
  SSE token write). With an ``offload`` executor attached (the scheduler
  pool's workers, router/schedpool.py), extraction runs off-loop; the
  collector awaits completion, so per-endpoint write ordering is unchanged.
- **Start-time jitter**: collectors used to start in phase, so every
  interval tick scraped the whole fleet in one burst. The first collect
  stays immediate (readiness), then each collector sleeps a random fraction
  of one interval once, de-phasing the fleet permanently.

Each completed scrape marks the datastore's scheduling snapshot dirty —
the copy-on-write publication point of router/snapshot.py.
"""

from __future__ import annotations

import asyncio
import logging
import random
from typing import Any, Callable

from ..framework.datalayer import Endpoint
from .datastore import Datastore

log = logging.getLogger("router.datalayer.runtime")

DEFAULT_POLL_INTERVAL_S = 0.05  # reference: datalayer/collector.go:52


class _Collector:
    def __init__(self, endpoint: Endpoint, sources: list[Any], interval: float,
                 *, offload: Any = None, jitter_s: float = 0.0,
                 on_scrape: Callable[[], None] | None = None):
        self.endpoint = endpoint
        self.sources = sources
        self.interval = interval
        self.offload = offload            # executor for off-loop extraction
        self.jitter_s = jitter_s          # one-shot phase offset (anti-herd)
        self.on_scrape = on_scrape        # snapshot dirty notification
        self._task: asyncio.Task | None = None

    def start(self):
        self._task = asyncio.get_running_loop().create_task(self._run())

    def stop(self):
        if self._task:
            self._task.cancel()

    def _extract(self, src: Any, raw: Any) -> None:
        """One source's extractor chain (runs on a worker when offloaded:
        the Prometheus text parse is the CPU; extractors write scalar
        metric fields + whole attribute values, both GIL-atomic, and the
        collector awaits completion so ordering per endpoint holds)."""
        for ex in src.extractors():
            ex.extract(raw, self.endpoint)

    async def _run(self):
        try:
            first = True
            while True:
                landed = False
                for src in self.sources:
                    try:
                        raw = await src.collect(self.endpoint)
                        if self.offload is not None:
                            await asyncio.get_running_loop().run_in_executor(
                                self.offload, self._extract, src, raw)
                        else:
                            self._extract(src, raw)
                        landed = True
                    except Exception:
                        log.exception("collector error for %s",
                                      self.endpoint.metadata.address_port)
                if landed and self.on_scrape is not None:
                    self.on_scrape()
                if first:
                    # De-phase after the immediate first collect: without
                    # this every collector started by start() ticks in
                    # lockstep and each interval scrapes the fleet in one
                    # thundering-herd burst.
                    first = False
                    if self.jitter_s > 0:
                        await asyncio.sleep(self.jitter_s)
                await asyncio.sleep(self.interval)
        except asyncio.CancelledError:
            pass


class DataLayerRuntime:
    def __init__(self, datastore: Datastore, poll_interval: float = DEFAULT_POLL_INTERVAL_S):
        self.datastore = datastore
        self.poll_interval = poll_interval
        self.sources: list[Any] = []
        self.lifecycle_plugins: list[Any] = []
        # CPU-offload executor for extractor parsing (the gateway attaches
        # the scheduler pool's workers when `scheduling.workers > 0`).
        self.offload: Any = None
        self._collectors: dict[str, _Collector] = {}
        self._started = False
        self._jitter_rng = random.Random()
        datastore.on_endpoint_event(self._on_endpoint_event)

    def register_source(self, source: Any) -> None:
        self.sources.append(source)

    def register_lifecycle(self, plugin: Any) -> None:
        self.lifecycle_plugins.append(plugin)

    async def start(self):
        self._started = True
        for ep in self.datastore.endpoint_list():
            self._start_collector(ep)

    async def stop(self):
        self._started = False
        for c in self._collectors.values():
            c.stop()
        self._collectors.clear()
        for src in self.sources:
            close = getattr(src, "close", None)
            if close:
                await close()

    def _on_endpoint_event(self, event: str, ep: Endpoint) -> None:
        if event == "added":
            if self._started:
                self._start_collector(ep)
            for p in self.lifecycle_plugins:
                try:
                    getattr(p, "endpoint_added", lambda _ep: None)(ep)
                except Exception:
                    log.exception("lifecycle plugin failure (add)")
        elif event == "removed":
            c = self._collectors.pop(ep.metadata.address_port, None)
            if c:
                c.stop()
            for p in self.lifecycle_plugins:
                try:
                    getattr(p, "endpoint_removed", lambda _ep: None)(ep)
                except Exception:
                    log.exception("lifecycle plugin failure (remove)")

    def _start_collector(self, ep: Endpoint) -> None:
        key = ep.metadata.address_port
        if key in self._collectors:
            return
        c = _Collector(ep, self.sources, self.poll_interval,
                       offload=self.offload,
                       jitter_s=self._jitter_rng.uniform(0, self.poll_interval),
                       on_scrape=self.datastore.mark_snapshot_dirty)
        self._collectors[key] = c
        c.start()
