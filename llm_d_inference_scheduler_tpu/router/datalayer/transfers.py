"""Per-(prefill, decode)-pair KV-transfer telemetry: the measured-cost table
transfer-aware P/D pairing (NetKV, arXiv:2606.03910 — ROADMAP item 3) will
score against.

The decode engine times its own KV pull (engine/core.py ``_fetch_inner``:
device wire vs host-staged HTTP, exact bytes moved) and stamps
``x-kv-pull-ms`` / ``x-kv-pull-bytes`` on its non-streaming response; the
sidecar relays them — beside its existing ``x-prefill-duration-ms`` — as
``x-kv-transfer-ms`` / ``x-kv-transfer-bytes`` plus ``x-kv-prefiller`` (the
prefill candidate that actually served, post-failover). The gateway lands
each observation here, keyed by the (prefill, decode) endpoint pair, as
exponentially-weighted moving averages; ``GET /debug/transfers`` serves the
table. Writers run on the gateway event loop — no locking needed.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Any


class PairTransferStats:
    """EWMA transfer cost for one (prefill → decode) pair."""

    __slots__ = ("pulls", "ewma_pull_ms", "ewma_exposed_ms", "ewma_bytes",
                 "ewma_prefill_ms", "bytes_total", "last_unix")

    def __init__(self):
        self.pulls = 0
        self.ewma_pull_ms: float | None = None
        # EXPOSED (non-overlapped) pull cost on pipelined P/D requests —
        # raw pull minus the portion hidden behind the prefill engine's
        # remaining compute. None until the pair serves a pipelined pull.
        self.ewma_exposed_ms: float | None = None
        self.ewma_bytes: float | None = None
        self.ewma_prefill_ms: float | None = None
        self.bytes_total = 0
        self.last_unix = 0.0

    def cost_ms(self) -> float | None:
        """The pair cost consumers (pair scorer, shadow judge, rebalancer,
        prefill classifier) should score against: the EXPOSED pull EWMA
        when the pair has pipelined observations — what a request actually
        waits on — falling back to the raw pull EWMA for serial-only
        pairs, where exposed == raw by definition."""
        if self.ewma_exposed_ms is not None:
            return self.ewma_exposed_ms
        return self.ewma_pull_ms

    def render(self) -> dict[str, Any]:
        doc: dict[str, Any] = {"pulls": self.pulls,
                               "bytes_total": self.bytes_total,
                               "last_unix": self.last_unix}
        if self.ewma_pull_ms is not None:
            doc["ewma_pull_ms"] = round(self.ewma_pull_ms, 3)
        if self.ewma_exposed_ms is not None:
            doc["exposed_ms"] = round(self.ewma_exposed_ms, 3)
        if self.ewma_bytes is not None:
            doc["ewma_bytes"] = round(self.ewma_bytes, 1)
            if self.ewma_pull_ms:
                # MB/s = bytes/ms / 1e3 — the wire-speed signal that
                # separates same-host from cross-host pairs.
                doc["ewma_mb_per_s"] = round(
                    self.ewma_bytes / self.ewma_pull_ms / 1e3, 3)
        if self.ewma_prefill_ms is not None:
            doc["ewma_prefill_ms"] = round(self.ewma_prefill_ms, 3)
        return doc


class TransferTable:
    """Bounded LRU of per-pair EWMA transfer stats (lives on the Datastore,
    like the breaker registry, so future scheduling plugins can read it)."""

    ALPHA = 0.2        # EWMA weight of the newest observation
    MAX_PAIRS = 512    # pool_size² bound for pathological pools

    def __init__(self):
        self._pairs: OrderedDict[tuple[str, str], PairTransferStats] = \
            OrderedDict()

    def record(self, prefill: str, decode: str, *,
               pull_ms: float | None = None, nbytes: int | None = None,
               prefill_ms: float | None = None,
               exposed_ms: float | None = None) -> None:
        key = (prefill, decode)
        stats = self._pairs.get(key)
        if stats is None:
            if len(self._pairs) >= self.MAX_PAIRS:
                self._pairs.popitem(last=False)
            stats = self._pairs[key] = PairTransferStats()
        else:
            self._pairs.move_to_end(key)
        stats.last_unix = time.time()
        a = self.ALPHA
        if pull_ms is not None:
            # `pulls` counts MEASURED pulls only: prefill-only rows (streamed
            # responses carry no engine pull stats) must not inflate the
            # sample count a transfer-cost scorer will weigh evidence by.
            stats.pulls += 1
            stats.ewma_pull_ms = (pull_ms if stats.ewma_pull_ms is None
                                  else (1 - a) * stats.ewma_pull_ms
                                  + a * pull_ms)
        if exposed_ms is not None:
            stats.ewma_exposed_ms = (
                exposed_ms if stats.ewma_exposed_ms is None
                else (1 - a) * stats.ewma_exposed_ms + a * exposed_ms)
        if nbytes is not None:
            stats.bytes_total += nbytes
            stats.ewma_bytes = (float(nbytes) if stats.ewma_bytes is None
                                else (1 - a) * stats.ewma_bytes + a * nbytes)
        if prefill_ms is not None:
            stats.ewma_prefill_ms = (
                prefill_ms if stats.ewma_prefill_ms is None
                else (1 - a) * stats.ewma_prefill_ms + a * prefill_ms)

    def pair(self, prefill: str, decode: str) -> PairTransferStats | None:
        """Lookup for future transfer-cost scorers (no LRU touch: reading a
        pair's cost must not pin it against eviction)."""
        return self._pairs.get((prefill, decode))

    def cheapest_pull_ms(self, decode: str) -> float | None:
        """Cheapest measured pull cost INTO one decode pod over every
        measured (prefill, decode) pair — the prefill classifier's
        pair-cost margin input (a cheap available pull weakens the case
        for skipping the P/D hop). Reads the EXPOSED cost when a pair has
        pipelined observations (``cost_ms``): a pull fully hidden behind
        prefill compute is ~free from the request's perspective. None when
        no pair into the pod has a measured pull yet. Bounded
        O(MAX_PAIRS) scan, paid only while the classifier's pairCostRefMs
        coupling is configured on."""
        best: float | None = None
        for (_p, d), stats in self._pairs.items():
            if d != decode:
                continue
            cost = stats.cost_ms()
            if cost is not None and (best is None or cost < best):
                best = cost
        return best

    def snapshot(self) -> dict[str, Any]:
        return {"pairs": [{"prefill": p, "decode": d, **stats.render()}
                          for (p, d), stats in self._pairs.items()]}

    def __len__(self) -> int:
        return len(self._pairs)
