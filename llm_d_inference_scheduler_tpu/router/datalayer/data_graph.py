"""Producer/consumer data-DAG ordering.

Mirrors /root/reference/pkg/epp/datalayer/data_graph.go:
ValidateAndOrderDataDependencies — topologically sorts DataProducer plugins by
their Produces()/Consumes() keys and rejects cycles, so producers always run
after the producers of their inputs.
"""

from __future__ import annotations

from typing import Any


class DataDependencyError(Exception):
    pass


def validate_and_order_producers(producers: list[Any]) -> list[Any]:
    """Topo-sort producers so consumed keys are produced first; raise on cycles."""
    produced_by: dict[str, Any] = {}
    for p in producers:
        for key in p.produces():
            if key in produced_by:
                raise DataDependencyError(
                    f"attribute {key!r} produced by both "
                    f"{produced_by[key].typed_name()} and {p.typed_name()}")
            produced_by[key] = p

    # edges: producer-of-consumed-key -> consumer
    indeg = {id(p): 0 for p in producers}
    edges: dict[int, list[Any]] = {id(p): [] for p in producers}
    for p in producers:
        for key in p.consumes():
            dep = produced_by.get(key)
            if dep is not None and dep is not p:
                edges[id(dep)].append(p)
                indeg[id(p)] += 1

    ready = [p for p in producers if indeg[id(p)] == 0]
    out: list[Any] = []
    while ready:
        p = ready.pop(0)
        out.append(p)
        for q in edges[id(p)]:
            indeg[id(q)] -= 1
            if indeg[id(q)] == 0:
                ready.append(q)
    if len(out) != len(producers):
        stuck = [str(p.typed_name()) for p in producers if p not in out]
        raise DataDependencyError(f"data-dependency cycle among producers: {stuck}")
    return out


def unsatisfied_keys(producers: list[Any], consumers: list[Any]) -> set[str]:
    """Attribute keys consumed by scorers/producers that nothing produces
    (reference: CreateMissingDataProducers feeds on this)."""
    produced = {k for p in producers for k in p.produces()}
    wanted: set[str] = set()
    for c in consumers:
        get = getattr(c, "consumes", None)
        if get:
            wanted.update(get())
    return wanted - produced
