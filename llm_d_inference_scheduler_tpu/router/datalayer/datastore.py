"""Datastore: the router's view of the endpoint pool + inference objectives +
model rewrites.

Mirrors /root/reference/pkg/epp/datastore/datastore.go:62-475. In standalone
mode (no k8s) the pool is seeded from config; a k8s reconciler layer can drive
the same mutation API.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Any, Callable, Iterable

from ..framework.datalayer import (
    DRAINING_LABEL,
    ROLE_LABEL,
    Endpoint,
    EndpointMetadata,
)
from ..metrics import SNAPSHOT_EPOCH
from ..resilience import BreakerRegistry
from ..snapshot import (
    NUMERIC_FIELDS,
    ColumnMetrics,
    ColumnsRef,
    PoolColumns,
    PoolSnapshot,
)
from .transfers import TransferTable


@dataclasses.dataclass
class EndpointPool:
    name: str = "default-pool"
    namespace: str = "default"
    target_ports: list[int] = dataclasses.field(default_factory=list)
    selector: dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class InferenceObjective:
    name: str
    priority: int = 0


@dataclasses.dataclass
class ModelRewriteTarget:
    model: str
    weight: int = 1


@dataclasses.dataclass
class InferenceModelRewrite:
    """Weighted model-name rewrite (A/B, canary) — reference
    apix/v1alpha2/inferencemodelrewrite_types.go:29-176."""

    name: str
    source_model: str
    targets: list[ModelRewriteTarget] = dataclasses.field(default_factory=list)

    def pick_target(self, rng: random.Random | None = None) -> str:
        rng = rng or random
        total = sum(t.weight for t in self.targets) or 1
        r = rng.uniform(0, total)
        acc = 0.0
        for t in self.targets:
            acc += t.weight
            if r <= acc:
                return t.model
        return self.targets[-1].model if self.targets else self.source_model


class Datastore:
    def __init__(self):
        self._pool: EndpointPool | None = None
        self._endpoints: dict[str, Endpoint] = {}  # key: address_port
        self._objectives: dict[str, InferenceObjective] = {}
        self._rewrites: dict[str, InferenceModelRewrite] = {}
        self._listeners: list[Callable[[str, Endpoint], None]] = []
        # Passive per-endpoint circuit breakers (router/resilience.py):
        # shared by the gateway's retry path and the circuit-breaker-filter
        # scheduling plugin so ejections apply fleet-wide.
        self.breakers = BreakerRegistry()
        # Per-(prefill, decode)-pair KV-transfer EWMA table
        # (datalayer/transfers.py): fed by the gateway from sidecar-relayed
        # pull stats, served at /debug/transfers, readable by future
        # transfer-cost scorers (ROADMAP item 3).
        self.transfers = TransferTable()
        # Per-pod measured prefix-reuse table (router/kvobs.py): actual
        # hit-rate + signed prediction-error EWMAs fed by the gateway's
        # CacheLedger, served at /debug/kv, readable by future scheduling
        # plugins (ROADMAP item 2's prefill classifier). Imported lazily to
        # keep the datalayer package import-light.
        from ..kvobs import KvHitTable

        self.kv_obs = KvHitTable()
        # Copy-on-write scheduling snapshot (router/snapshot.py). Two dirty
        # levels: membership changes (add/delete/resync) force a rebuild on
        # the next snapshot() call — a deleted endpoint must leave the
        # scheduling view promptly; scrape landings mark the snapshot STALE,
        # rebuilt only once the current epoch is older than
        # SNAPSHOT_MIN_REFRESH_S. Under steady scraping (128 collectors ×
        # 50 ms poll ≈ 2.5k landings/s) an unconditional rebuild would copy
        # the whole pool on the event loop for nearly every request — and
        # co-dispatched batch members could each see a different epoch if a
        # scrape landed between their director steps. The refresh floor
        # bounds rebuild CPU and keeps one epoch per dispatch batch; scraped
        # metrics are inherently ≥ one poll interval stale anyway.
        self._snapshot: PoolSnapshot | None = None
        self._snapshot_dirty = True   # hard: membership changed
        self._snapshot_stale = False  # soft: scrape data landed
        self._snapshot_epoch = 0
        # Rebalancer-owned label overlays (router/rebalance.py), keyed by
        # address_port: a role flip / draining mark must survive an
        # external resync (kube pod event, config-file reconcile) that
        # rebuilds metadata from the pre-flip source of truth — otherwise
        # any watch event silently reverts the flip (or un-drains a pod
        # mid-drain-cycle) while the controller still reports it active.
        # The overlay wins until the pod leaves the pool or the
        # controller republishes. Fleet followers never write overlays
        # (their controllers are view-only), so leader frames applied via
        # apply_remote_snapshot pass through untouched.
        self._label_overrides: dict[str, dict[str, str]] = {}
        # Fleet follower mode (router/fleet.py): once a leader-published
        # snapshot has been applied, this datastore stops building its own
        # epochs — membership and scrape state both arrive via IPC frames,
        # and a locally-built epoch would race the leader's numbering.
        self._remote_snapshots = False
        # Binary-wire follower state (router/snapwire.py): the one mutable
        # cell every live ColumnMetrics proxy reads through, so a
        # metrics-delta apply is ONE pointer swap — not a rebind of every
        # endpoint's metrics object.
        self._columns_ref: ColumnsRef | None = None

    # ---- scheduling snapshot ------------------------------------------

    SNAPSHOT_MIN_REFRESH_S = 0.01

    @property
    def snapshot_epoch(self) -> int:
        """The epoch last built (or applied from the fleet leader) —
        WITHOUT forcing a rebuild the way snapshot() can; the timeline
        sampler reads this every tick."""
        return self._snapshot_epoch

    def mark_snapshot_dirty(self) -> None:
        """A scrape landed: refresh the snapshot once the rate-limit floor
        passes (soft staleness — pool membership is unchanged)."""
        self._snapshot_stale = True

    def snapshot(self) -> PoolSnapshot:
        """Current copy-on-write pool snapshot (rebuilt lazily when dirty)."""
        snap = self._snapshot
        if self._remote_snapshots and snap is not None:
            return snap
        rebuild = snap is None or self._snapshot_dirty or (
            self._snapshot_stale
            and time.monotonic() - snap.built_at >= self.SNAPSHOT_MIN_REFRESH_S)
        if rebuild:
            self._snapshot_epoch += 1
            self._snapshot = PoolSnapshot(self._snapshot_epoch,
                                          self._endpoints.values())
            self._snapshot_dirty = False
            self._snapshot_stale = False
            SNAPSHOT_EPOCH.set(self._snapshot_epoch)
        return self._snapshot

    def apply_remote_snapshot(self, epoch: int, entries: list) -> None:
        """Install a leader-published PoolSnapshot epoch (fleet snapshot
        IPC, router/fleet.py). The frame is authoritative for BOTH pool
        membership and per-endpoint scrape state: the live Endpoint objects
        are resynced and updated in place (the saturation detector, pool
        gauges, and proxy legs read those), then the frame is installed as
        THE scheduling snapshot under the leader's epoch number — a batch
        dispatched in this worker schedules against exactly the epoch a
        single-process router would have seen."""
        self.resync([meta for meta, _metrics, _attrs in entries])
        for meta, metrics, attrs in entries:
            ep = self._endpoints.get(meta.address_port)
            if ep is not None:
                ep.metrics = metrics
                ep.attributes._data = dict(attrs)
        self._snapshot = PoolSnapshot.from_entries(epoch, entries)
        self._snapshot_epoch = epoch
        self._snapshot_dirty = False
        self._snapshot_stale = False
        self._remote_snapshots = True
        self._columns_ref = None  # pickle frames retire any binary-wire view
        SNAPSHOT_EPOCH.set(epoch)

    def apply_remote_columns(self, epoch: int, cols: PoolColumns) -> None:
        """Install a decoded binary full frame (router/snapwire.py) as THE
        scheduling snapshot — the received columns ARE the scheduling view,
        no per-endpoint re-marshal. Live Endpoint objects are resynced to
        the frame's membership and handed ColumnMetrics proxies that read
        through ``self._columns_ref``, so subsequent metrics-delta frames
        reach the saturation detector / pool gauges / proxy legs via one
        pointer swap."""
        self.resync(list(cols.metas))
        ref = self._columns_ref
        if ref is None:
            ref = self._columns_ref = ColumnsRef(cols)
        else:
            ref.cols = cols
        for i, key in enumerate(cols.keys):
            ep = self._endpoints.get(key)
            if ep is not None:
                ep.metrics = ColumnMetrics(ref, i)
                ep.attributes._data = dict(cols.attrs[i])
        self._snapshot = PoolSnapshot.from_columns(epoch, cols)
        self._snapshot_epoch = epoch
        self._snapshot_dirty = False
        self._snapshot_stale = False
        self._remote_snapshots = True
        SNAPSHOT_EPOCH.set(epoch)

    def apply_remote_delta(self, epoch: int, base_id: int,
                           num: dict) -> bool:
        """Apply a metrics-only binary delta frame on top of the installed
        full frame. Returns False (caller drops the frame; the next full
        re-anchors) when no binary full is installed or the delta was cut
        against a different full than the one installed here — its row
        order would be meaningless."""
        ref = self._columns_ref
        if ref is None:
            return False
        cols = ref.cols
        if cols.base_id != base_id or cols.n != len(num[NUMERIC_FIELDS[0]]):
            return False
        new_cols = cols.with_arrays(num)
        ref.cols = new_cols  # every live ColumnMetrics proxy now reads this
        self._snapshot = PoolSnapshot.from_columns(epoch, new_cols)
        self._snapshot_epoch = epoch
        self._snapshot_dirty = False
        self._snapshot_stale = False
        SNAPSHOT_EPOCH.set(epoch)
        return True

    def resume_local_snapshots(self) -> None:
        """Fleet leader promotion (router/fleet.py): this follower now owns
        the datalayer, so snapshot epochs are minted locally again. Epoch
        numbering CONTINUES from the last applied remote epoch — follower
        epoch gauges must never run backwards across an election. Any
        binary-wire ColumnMetrics proxies are materialized into mutable
        Metrics first: the promoted worker's own collectors write scrape
        fields in place, which a read-only column proxy can't absorb."""
        for ep in self._endpoints.values():
            if isinstance(ep.metrics, ColumnMetrics):
                ep.metrics = ep.metrics.materialize()
        self._columns_ref = None
        self._remote_snapshots = False
        self._snapshot_dirty = True

    # ---- pool ----------------------------------------------------------

    def pool_set(self, pool: EndpointPool | None) -> None:
        self._pool = pool

    def pool_get(self) -> EndpointPool | None:
        return self._pool

    @property
    def pool_ready(self) -> bool:
        return self._pool is not None

    # ---- endpoints -----------------------------------------------------

    def on_endpoint_event(self, fn: Callable[[str, Endpoint], None]) -> None:
        """fn(event, endpoint) with event in {'added','removed'}."""
        self._listeners.append(fn)

    def endpoint_add_or_update(self, meta: EndpointMetadata) -> Endpoint:
        key = meta.address_port
        self._snapshot_dirty = True
        overrides = self._label_overrides.get(key)
        if overrides:
            meta = dataclasses.replace(
                meta, labels={**meta.labels, **overrides})
        ep = self._endpoints.get(key)
        if ep is None:
            ep = Endpoint(meta)
            self._endpoints[key] = ep
            for fn in self._listeners:
                fn("added", ep)
        else:
            ep.metadata = meta
        return ep

    def endpoint_delete(self, address_port: str) -> None:
        ep = self._endpoints.pop(address_port, None)
        self._label_overrides.pop(address_port, None)
        if ep is not None:
            self._snapshot_dirty = True
            self.breakers.remove(address_port)
            for fn in self._listeners:
                fn("removed", ep)

    def endpoint_list(self, predicate: Callable[[Endpoint], bool] | None = None) -> list[Endpoint]:
        eps = list(self._endpoints.values())
        return [e for e in eps if predicate(e)] if predicate else eps

    def endpoint_get(self, address_port: str) -> Endpoint | None:
        return self._endpoints.get(address_port)

    def _republish_labels(self, address_port: str,
                          labels: dict[str, str]) -> bool:
        """Replace one endpoint's metadata with new labels (metrics,
        attributes, and the live Endpoint object are preserved) and dirty
        the snapshot — the routing-attribute republish half of the
        rebalancer's drain cycle. A whole new metadata object is installed
        (never an in-place label mutation): published PoolSnapshots share
        metadata by reference, so an in-flight scheduling cycle must keep
        seeing the epoch it started with."""
        ep = self._endpoints.get(address_port)
        if ep is None:
            return False
        ep.metadata = dataclasses.replace(ep.metadata, labels=labels)
        self._snapshot_dirty = True
        return True

    def set_endpoint_draining(self, address_port: str,
                              draining: bool) -> bool:
        """Mark/clear the drain-cycle label (router/rebalance.py): the
        role filters exclude a draining pod from every new pick while its
        in-flight work clears. Returns False when the pod is unknown."""
        ep = self._endpoints.get(address_port)
        if ep is None:
            return False
        labels = dict(ep.metadata.labels)
        overrides = self._label_overrides.setdefault(address_port, {})
        if draining:
            labels[DRAINING_LABEL] = "true"
            overrides[DRAINING_LABEL] = "true"
        else:
            labels.pop(DRAINING_LABEL, None)
            overrides.pop(DRAINING_LABEL, None)
        return self._republish_labels(address_port, labels)

    def set_endpoint_role(self, address_port: str, role: str) -> bool:
        """Republish one endpoint's ``llm-d.ai/role`` routing attribute
        (the final step of a drain-cycle role flip), clearing any draining
        mark in the same republish so the pod rejoins scheduling under its
        new role atomically."""
        ep = self._endpoints.get(address_port)
        if ep is None:
            return False
        labels = dict(ep.metadata.labels)
        labels[ROLE_LABEL] = role
        labels.pop(DRAINING_LABEL, None)
        overrides = self._label_overrides.setdefault(address_port, {})
        overrides[ROLE_LABEL] = role
        overrides.pop(DRAINING_LABEL, None)
        return self._republish_labels(address_port, labels)

    def role_census(self) -> dict[str, dict[str, Any]]:
        """Per-role pod census for the elastic-fleet actuator
        (router/autoscale.py): pod counts and compact per-pod rows
        (address, draining mark, current load) grouped by the
        ``llm-d.ai/role`` routing label. Pods without a role label group
        under ``""``."""
        out: dict[str, dict[str, Any]] = {}
        for ep in self._endpoints.values():
            role = ep.metadata.labels.get(ROLE_LABEL, "")
            row = out.setdefault(role, {"total": 0, "ready": 0,
                                        "pods": []})
            draining = ep.metadata.labels.get(DRAINING_LABEL) == "true"
            row["total"] += 1
            if not draining:
                row["ready"] += 1
            row["pods"].append({
                "address_port": ep.metadata.address_port,
                "draining": draining,
                "load": (ep.metrics.running_requests_size
                         + ep.metrics.waiting_queue_size),
            })
        return out

    def resync(self, metas: Iterable[EndpointMetadata]) -> None:
        """Replace the endpoint set (pool change / reconciler resync)."""
        new_keys = set()
        for m in metas:
            new_keys.add(m.address_port)
            self.endpoint_add_or_update(m)
        for key in [k for k in self._endpoints if k not in new_keys]:
            self.endpoint_delete(key)

    # ---- objectives & rewrites ----------------------------------------

    def objective_set(self, obj: InferenceObjective) -> None:
        self._objectives[obj.name] = obj

    def objective_delete(self, name: str) -> None:
        self._objectives.pop(name, None)

    def objective_get(self, name: str) -> InferenceObjective | None:
        return self._objectives.get(name)

    def objective_names(self) -> list[str]:
        return list(self._objectives)

    def rewrite_set(self, rw: InferenceModelRewrite) -> None:
        self._rewrites[rw.source_model] = rw

    def rewrite_delete(self, source_model: str) -> None:
        self._rewrites.pop(source_model, None)

    def rewrite_for(self, source_model: str) -> InferenceModelRewrite | None:
        return self._rewrites.get(source_model)

    def rewrite_sources(self) -> list[str]:
        return list(self._rewrites)
