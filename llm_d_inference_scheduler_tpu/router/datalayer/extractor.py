"""Core metrics extractor: Prometheus text → Metrics snapshot.

Mirrors the reference's core-metrics-extractor with its per-engine-type
MappingRegistry (/root/reference/pkg/epp/framework/plugins/datalayer/extractor/
metrics/mapping_registry.go:24-40): heterogeneous fleets map different metric
names per pod via the `llm-d.ai/engine-type` label; `default` is the fallback.
The default mapping speaks the TPU engines' jetstream:* contract; a vllm
mapping ships for mixed fleets.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any

from prometheus_client.parser import text_string_to_metric_families

from ..framework.datalayer import ENGINE_TYPE_LABEL, Endpoint
from ..framework.plugin import PluginBase


@dataclasses.dataclass(frozen=True)
class MetricSpec:
    """Metric name + optional label matches (reference backend/metrics/
    metrics_spec.go:25-119)."""

    name: str
    labels: dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class MetricMapping:
    waiting: MetricSpec
    running: MetricSpec
    kv_usage: MetricSpec
    lora_info: MetricSpec | None = None
    cache_config: MetricSpec | None = None
    # Free-block depth (engine telemetry beyond the five-signal contract);
    # engines without the family simply leave Metrics.free_kv_blocks at -1.
    free_blocks: MetricSpec | None = None
    # Prefix-reuse counter pair (incremented together at prefill admission;
    # hit/total = the pod's actual hit ratio, surfaced at /debug/kv).
    prefill_tokens: MetricSpec | None = None
    prefix_hit_tokens: MetricSpec | None = None


JETSTREAM_MAPPING = MetricMapping(
    waiting=MetricSpec("jetstream:num_requests_waiting"),
    running=MetricSpec("jetstream:num_requests_running"),
    kv_usage=MetricSpec("jetstream:kv_cache_usage_perc"),
    lora_info=MetricSpec("jetstream:lora_requests_info"),
    cache_config=MetricSpec("jetstream:cache_config_info"),
    free_blocks=MetricSpec("jetstream:num_free_kv_blocks"),
    prefill_tokens=MetricSpec("jetstream:prefill_tokens_total"),
    prefix_hit_tokens=MetricSpec("jetstream:prefix_hit_tokens_total"),
)

VLLM_MAPPING = MetricMapping(
    waiting=MetricSpec("vllm:num_requests_waiting"),
    running=MetricSpec("vllm:num_requests_running"),
    kv_usage=MetricSpec("vllm:kv_cache_usage_perc"),
    lora_info=MetricSpec("vllm:lora_requests_info"),
    cache_config=MetricSpec("vllm:cache_config_info"),
)


class MappingRegistry:
    def __init__(self):
        self._by_engine: dict[str, MetricMapping] = {
            "default": JETSTREAM_MAPPING,
            "jetstream": JETSTREAM_MAPPING,
            "tpu": JETSTREAM_MAPPING,
            "vllm": VLLM_MAPPING,
        }

    def register(self, engine_type: str, mapping: MetricMapping) -> None:
        self._by_engine[engine_type] = mapping

    def for_endpoint(self, ep: Endpoint) -> MetricMapping:
        et = ep.metadata.labels.get(ENGINE_TYPE_LABEL, "default")
        return self._by_engine.get(et, self._by_engine["default"])


def _sample_value(families: dict, spec: MetricSpec):
    fam = families.get(spec.name)
    if fam is None:
        return None, None
    best = None
    for s in fam.samples:
        if s.name != spec.name:
            continue
        if all(s.labels.get(k) == v for k, v in spec.labels.items()):
            best = s
    return (best.value, best.labels) if best is not None else (None, None)


class CoreMetricsExtractor(PluginBase):
    TYPE = "core-metrics-extractor"

    def __init__(self, name: str | None = None, registry: MappingRegistry | None = None):
        super().__init__(name)
        self.registry = registry or MappingRegistry()

    def configure(self, params: dict[str, Any], handle: Any) -> None:
        # engineConfigs: {engineType: {waiting: name, running: name, kvUsage: name}}
        for et, cfg in (params.get("engineConfigs") or {}).items():
            self.registry.register(et, MetricMapping(
                waiting=MetricSpec(cfg["waiting"]),
                running=MetricSpec(cfg["running"]),
                kv_usage=MetricSpec(cfg["kvUsage"]),
                lora_info=MetricSpec(cfg["loraInfo"]) if "loraInfo" in cfg else None,
                cache_config=MetricSpec(cfg["cacheConfig"]) if "cacheConfig" in cfg else None,
            ))

    def extract(self, raw: Any, endpoint: Endpoint) -> None:
        if not raw:
            return
        mapping = self.registry.for_endpoint(endpoint)
        families = {f.name: f for f in text_string_to_metric_families(raw)}
        # prometheus_client strips the _total/_info suffixes into family names;
        # index under both the family name and the sample names.
        for f in list(families.values()):
            for s in f.samples:
                families.setdefault(s.name, f)

        m = endpoint.metrics
        v, _ = _sample_value(families, mapping.waiting)
        if v is not None:
            m.waiting_queue_size = int(v)
        v, _ = _sample_value(families, mapping.running)
        if v is not None:
            m.running_requests_size = int(v)
        v, _ = _sample_value(families, mapping.kv_usage)
        if v is not None:
            m.kv_cache_usage_percent = float(v)
        if mapping.lora_info:
            v, labels = _sample_value(families, mapping.lora_info)
            if v is not None and labels:
                running = [x for x in labels.get("running_lora_adapters", "").split(",") if x]
                waiting = [x for x in labels.get("waiting_lora_adapters", "").split(",") if x]
                m.active_models = {name: 1 for name in running}
                m.waiting_models = {name: 1 for name in waiting}
                try:
                    m.max_active_models = int(labels.get("max_lora", "0"))
                except ValueError:
                    pass
        if mapping.free_blocks:
            v, _ = _sample_value(families, mapping.free_blocks)
            if v is not None:
                m.free_kv_blocks = int(v)
        if mapping.prefill_tokens:
            v, _ = _sample_value(families, mapping.prefill_tokens)
            if v is not None:
                m.prefill_tokens = float(v)
        if mapping.prefix_hit_tokens:
            v, _ = _sample_value(families, mapping.prefix_hit_tokens)
            if v is not None:
                m.prefix_hit_tokens = float(v)
        if mapping.cache_config:
            v, labels = _sample_value(families, mapping.cache_config)
            if v is not None and labels:
                try:
                    m.cache_block_size = int(labels.get("block_size", "0"))
                    m.cache_num_blocks = int(labels.get("num_gpu_blocks", "0") or 0)
                    m.kv_cache_max_token_capacity = m.cache_block_size * m.cache_num_blocks
                except ValueError:
                    pass
        m.update_time = time.monotonic()
