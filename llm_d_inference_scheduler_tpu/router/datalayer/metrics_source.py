"""Metrics data source: scrapes each endpoint's Prometheus /metrics.

Reference: framework/plugins/datalayer/source/metrics (HTTP scrape) feeding
core-metrics-extractor — SURVEY §2.5.
"""

from __future__ import annotations

import logging
import time
from typing import Any

import httpx

from ..framework.datalayer import Endpoint
from ..framework.plugin import PluginBase
from ..metrics import SCRAPE_DURATION_SECONDS, SCRAPE_ERRORS_TOTAL

log = logging.getLogger("router.datalayer.metrics")


class MetricsDataSource(PluginBase):
    TYPE = "metrics-data-source"

    def __init__(self, name: str | None = None, timeout_s: float = 2.0):
        super().__init__(name)
        self._extractors: list[Any] = []
        self._timeout = timeout_s
        self._client: httpx.AsyncClient | None = None
        # TLS verification for https scrape targets: default skip-verify
        # (pod-local certs, the reference scrape client's default), or a CA
        # bundle for real verification (tlsutil.client_verify; ADVICE r5).
        self._insecure_skip_verify = True
        self._ca_cert_path: str | None = None

    def configure(self, params: dict[str, Any], handle: Any) -> None:
        self._timeout = float(params.get("timeoutSeconds", self._timeout))
        self._insecure_skip_verify = bool(
            params.get("insecureSkipVerify", self._insecure_skip_verify))
        self._ca_cert_path = params.get("caCertPath") or None

    def add_extractor(self, ex: Any) -> None:
        self._extractors.append(ex)

    def extractors(self) -> list[Any]:
        return list(self._extractors)

    async def collect(self, endpoint: Endpoint) -> str | None:
        if self._client is None:
            from ..tlsutil import client_verify

            self._client = httpx.AsyncClient(
                timeout=self._timeout,
                verify=client_verify(self._insecure_skip_verify,
                                     self._ca_cert_path))
        t0 = time.monotonic()
        try:
            r = await self._client.get(endpoint.metadata.metrics_url)
            r.raise_for_status()
            SCRAPE_DURATION_SECONDS.observe(time.monotonic() - t0)
            return r.text
        except Exception as e:
            SCRAPE_ERRORS_TOTAL.labels(endpoint.metadata.address_port).inc()
            log.debug("scrape failed for %s: %s", endpoint.metadata.address_port, e)
            return None

    async def close(self):
        if self._client is not None:
            await self._client.aclose()
            self._client = None
