from .datastore import Datastore, EndpointPool
from .runtime import DataLayerRuntime
from .metrics_source import MetricsDataSource
from .extractor import CoreMetricsExtractor, MappingRegistry
from .data_graph import validate_and_order_producers

__all__ = ["Datastore", "EndpointPool", "DataLayerRuntime", "MetricsDataSource",
           "CoreMetricsExtractor", "MappingRegistry", "validate_and_order_producers"]
