from .datastore import Datastore, EndpointPool
from .runtime import DataLayerRuntime
from .metrics_source import MetricsDataSource
from .models_source import (
    MODELS_ATTRIBUTE_KEY,
    ModelsDataExtractor,
    ModelsDataSource,
)
from .extractor import CoreMetricsExtractor, MappingRegistry
from .data_graph import validate_and_order_producers
from .http_source import HttpDataExtractor, HttpDataSource

__all__ = ["Datastore", "EndpointPool", "DataLayerRuntime", "MetricsDataSource",
           "ModelsDataSource", "ModelsDataExtractor", "MODELS_ATTRIBUTE_KEY",
           "CoreMetricsExtractor", "MappingRegistry", "validate_and_order_producers",
           "HttpDataSource", "HttpDataExtractor"]
