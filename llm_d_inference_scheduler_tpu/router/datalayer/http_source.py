"""Generic HTTP data source: poll an arbitrary path on every endpoint into
an endpoint attribute.

Reference: framework/plugins/datalayer/source/http/{datasource.go,client.go}
— a reusable HTTP/HTTPS poller (scheme + path + skip-verify + pluggable
parser) that specific sources build on; the metrics source is its main
embedder, but it is also registrable standalone so deployments can scrape
any engine endpoint (e.g. /server_info) into the datastore without writing
a plugin. The parser here is the paired http-data-extractor: JSON when the
body parses, raw text otherwise, stored under a configurable attribute key.
"""

from __future__ import annotations

import json
import logging
import time
from typing import Any

import httpx

from ..framework.datalayer import Endpoint
from ..framework.plugin import PluginBase, register_plugin

log = logging.getLogger("router.datalayer.http")


@register_plugin("http-data-source")
class HttpDataSource(PluginBase):
    TYPE = "http-data-source"

    def __init__(self, name: str | None = None):
        super().__init__(name)
        self._extractors: list[Any] = []
        self._scheme = "http"
        self._path = "/"
        self._timeout = 10.0  # reference client.go timeout
        self._insecure_skip_verify = False
        # 0 = poll every collector cycle (the reference polls each cycle);
        # raise for slow-moving data to keep scrape load down.
        self._refresh_s = 0.0
        self._last_poll: dict[str, float] = {}
        self._client: httpx.AsyncClient | None = None

    def configure(self, params: dict[str, Any], handle: Any) -> None:
        scheme = str(params.get("scheme", self._scheme))
        if scheme not in ("http", "https"):
            # Reference datasource.go:46 rejects anything else.
            raise ValueError(f"unsupported scheme: {scheme}")
        self._scheme = scheme
        self._path = str(params.get("path", self._path))
        if not self._path.startswith("/"):
            self._path = "/" + self._path
        self._timeout = float(params.get("timeoutSeconds", self._timeout))
        self._refresh_s = float(params.get("refreshSeconds", self._refresh_s))
        self._insecure_skip_verify = bool(
            params.get("insecureSkipVerify", self._insecure_skip_verify))

    def add_extractor(self, ex: Any) -> None:
        self._extractors.append(ex)

    def extractors(self) -> list[Any]:
        if not self._extractors:
            ex = HttpDataExtractor("http-data-extractor")
            ex.configure({"attributeKey": self._path}, None)
            self._extractors.append(ex)
        return list(self._extractors)

    async def collect(self, endpoint: Endpoint) -> str | None:
        key = endpoint.metadata.address_port
        now = time.monotonic()
        if self._refresh_s > 0 and now - self._last_poll.get(key, -1e9) < self._refresh_s:
            return None
        self._last_poll[key] = now
        if self._client is None:
            self._client = httpx.AsyncClient(
                timeout=self._timeout,
                verify=not self._insecure_skip_verify)
        # Reference polls the metrics host (client.go GetMetricsHost).
        port = endpoint.metadata.metrics_port or endpoint.metadata.port
        url = f"{self._scheme}://{endpoint.metadata.address}:{port}{self._path}"
        try:
            r = await self._client.get(url)
            r.raise_for_status()
            return r.text
        except Exception as e:
            log.debug("http poll failed for %s%s: %s", key, self._path, e)
            return None

    async def close(self):
        if self._client is not None:
            await self._client.aclose()
            self._client = None


@register_plugin("http-data-extractor")
class HttpDataExtractor(PluginBase):
    TYPE = "http-data-extractor"

    def __init__(self, name: str | None = None):
        super().__init__(name)
        self._attribute_key = "http-data"
        self._format = "auto"  # auto | json | text

    def configure(self, params: dict[str, Any], handle: Any) -> None:
        self._attribute_key = str(params.get("attributeKey",
                                             self._attribute_key))
        fmt = str(params.get("format", self._format))
        if fmt not in ("auto", "json", "text"):
            raise ValueError(f"unsupported format: {fmt}")
        self._format = fmt

    def extract(self, raw: str | None, endpoint: Endpoint) -> None:
        if raw is None:
            return
        value: Any = raw
        if self._format in ("auto", "json"):
            try:
                value = json.loads(raw)
            except Exception:
                if self._format == "json":
                    log.debug("unparseable JSON body for %s (key %s)",
                              endpoint.metadata.address_port,
                              self._attribute_key)
                    return
        endpoint.attributes.put(self._attribute_key, value)
