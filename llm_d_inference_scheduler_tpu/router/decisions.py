"""Scheduling decision flight recorder: per-request explainability.

PR 1's traces show *where time went* and PR 2's counters show *aggregate
outcomes*; this module answers "why did request X land on pod Y?" — the gap
P/D-Serve (arXiv:2408.08147) blames for undebuggable fleet-scale P/D
regressions, and NetKV (arXiv:2606.03910) closes by recording *per-candidate*
scores, not just the winner.

One ``DecisionRecord`` accumulates as the request crosses the layers:

- admission: controller verdict, flow-control queue time, priority band,
  flow id, shed/evict retries (requestcontrol/admission.py,
  flowcontrol/admission.py);
- model rewrite and producer budget spend (requestcontrol/director.py);
- per profile, per scheduling round: candidate count in, per-filter drops
  (filter name → endpoints removed), per-scorer per-endpoint raw and
  weighted scores (top-K, configurable), the picker's choice and win margin
  (scheduling/scheduler.py, carried through the cycle via CycleState);
- post-schedule: the gateway's retry/failover attempt trail — which ranked
  candidate each attempt used and why it moved on (gateway.py);
- post-serve: the SLO ledger's outcome block (router/slo.py) — predicted vs
  actual TTFT/TPOT vs the request SLO, the slo_met verdict with its miss
  reason, and the per-pair KV-transfer row on the disagg path.

Storage is a bounded ring (default ~1k records) with an id index, zero-egress
like the trace buffer: inspect via ``GET /debug/decisions`` /
``/debug/decisions/<request-id>``, opt into a compact per-request verdict
with the ``x-debug-decision: summary`` request header, or read the phase
summaries as span events on the orchestration span
(``/debug/traces?merge=1``). A config kill-switch (``decisions.enabled:
false``) reduces every hook to one ``is None`` check — the overhead contract
``bench.py --sched-microbench`` measures.
"""

from __future__ import annotations

import dataclasses
import sys
import time
from collections import deque
from typing import Any

SCHEMA_VERSION = 1

# CycleState key under which the scheduler publishes the active record so
# plugins (and the profile loop itself) can annotate the cycle they run in.
DECISION_STATE_KEY = "decision_record"


class DecisionRecord:
    """One request's decision trail. Mutated in place by the layer hooks;
    ``to_dict()`` is the schema-versioned wire form."""

    __slots__ = ("request_id", "model", "target_model", "priority",
                 "_start", "_admission", "_producers",
                 "_rounds", "_attempts", "_final", "_outcome", "_shed",
                 "_cache", "_classifier", "_shadow", "_waterfall", "top_k")

    # Container fields are lazily created (None until first write): a record
    # is opened on EVERY request, and five eager container allocations per
    # request are measurable GC pressure on the flow-control dispatch path.
    _EMPTY_DICT: dict[str, Any] = {}
    _EMPTY_LIST: list[Any] = []

    @staticmethod
    def _live_items(d: dict[str, Any]) -> list[tuple[str, Any]]:
        """Snapshot a dict's items for render-side iteration. Scheduling
        cycles may run on scheduler-pool worker threads
        (router/schedpool.py), so a record rendered by GET /debug/decisions
        on the event loop can be mid-mutation: a key insert during a plain
        ``.items()`` walk raises RuntimeError (and a bounded retry does NOT
        converge against a busy writer — the walk loses the race every
        time). ``dict(d)`` of a plain dict is a single C-level copy under
        the GIL, atomic w.r.t. concurrent inserts; iterating the private
        copy can never see a resize. A half-written round then renders as
        a point-in-time view — fine for a debug surface."""
        return list(dict(d).items())

    def __init__(self, request_id: str, model: str, *, top_k: int = 8):
        self.top_k = top_k
        self._reset(request_id, model)

    def _reset(self, request_id: str, model: str) -> None:
        """(Re)initialize for a fresh request — the recorder pools evicted
        records to keep the per-request cost on the flow-control dispatch
        path to a handful of attribute stores (no allocation)."""
        self.request_id = request_id
        self.model = model
        self.target_model = model
        self.priority = 0
        self._start = time.monotonic()
        self._admission = None
        self._producers = None
        self._rounds = None
        self._attempts = None
        self._final = None
        self._outcome = None
        self._shed = None
        self._cache = None
        self._classifier = None
        self._shadow = None
        self._waterfall = None

    @property
    def start_unix(self) -> float:
        """Wall-clock request start, derived from the monotonic anchor at
        read time (one fewer clock read on the record-open hot path)."""
        return time.time() - (time.monotonic() - self._start)

    @property
    def admission(self) -> dict[str, Any]:
        return self._admission if self._admission is not None else self._EMPTY_DICT

    @property
    def producers(self) -> dict[str, Any]:
        return self._producers if self._producers is not None else self._EMPTY_DICT

    @property
    def rounds(self) -> list[dict[str, Any]]:
        return self._rounds if self._rounds is not None else self._EMPTY_LIST

    @property
    def attempts(self) -> list[dict[str, Any]]:
        return self._attempts if self._attempts is not None else self._EMPTY_LIST

    @property
    def final(self) -> dict[str, Any]:
        return self._final if self._final is not None else self._EMPTY_DICT

    @property
    def outcome(self) -> dict[str, Any]:
        return self._outcome if self._outcome is not None else self._EMPTY_DICT

    @property
    def shed(self) -> dict[str, Any]:
        return self._shed if self._shed is not None else self._EMPTY_DICT

    @property
    def cache(self) -> dict[str, Any]:
        return self._cache if self._cache is not None else self._EMPTY_DICT

    @property
    def classifier(self) -> dict[str, Any]:
        return (self._classifier if self._classifier is not None
                else self._EMPTY_DICT)

    @property
    def shadow(self) -> dict[str, Any]:
        return self._shadow if self._shadow is not None else self._EMPTY_DICT

    @property
    def waterfall(self) -> dict[str, Any]:
        return (self._waterfall if self._waterfall is not None
                else self._EMPTY_DICT)

    # ---- layer hooks ----------------------------------------------------

    def record_rewrite(self, target_model: str) -> None:
        self.target_model = target_model

    def record_admission(self, mechanism: str, outcome: str, *,
                         flow_id: str | None = None,
                         priority_band: int | None = None,
                         queue_ms: float | None = None,
                         retried_after_shed: bool = False,
                         reason: str | None = None,
                         shed_victims: list[str] | None = None,
                         shard: int | None = None) -> None:
        # Hot path (flow-control dispatch): one dict literal on the common
        # shape; rounding happens at render time (to_dict).
        if (flow_id is not None and priority_band is not None
                and queue_ms is not None and not retried_after_shed
                and not reason and not shed_victims):
            self._admission = {"mechanism": mechanism, "outcome": outcome,
                               "flow_id": flow_id,
                               "priority_band": priority_band,
                               "queue_ms": queue_ms}
            if shard is not None:
                # Fleet worker identity (router/fleet.py): which shard's
                # flow-control queues admitted this request.
                self._admission["shard"] = shard
            return
        a: dict[str, Any] = {"mechanism": mechanism, "outcome": outcome}
        if flow_id is not None:
            a["flow_id"] = flow_id
        if priority_band is not None:
            a["priority_band"] = priority_band
        if queue_ms is not None:
            a["queue_ms"] = queue_ms
        if shard is not None:
            a["shard"] = shard
        if retried_after_shed:
            a["retried_after_shed"] = True
        if shed_victims:
            # The queued/in-flight requests sacrificed so THIS request's
            # capacity-shed retry could be admitted (flowcontrol/
            # admission.py) — /debug/decisions explains who was evicted.
            a["shed_victims"] = list(shed_victims)
        if reason:
            a["reason"] = reason
        self._admission = a

    def record_admit_plugin_reject(self, plugin: str, reason: str) -> None:
        """AdmitRequest-plugin rejection: lands beside (not over) a
        flow-control admission section when one exists."""
        if self._admission is None:
            self._admission = {}
        self._admission.setdefault("admit_plugin", plugin)
        self._admission["outcome"] = "rejected"
        self._admission.setdefault("reason", reason)

    def record_producers(self, spent_ms: float, budget_ms: float,
                         names: list[str]) -> None:
        self._producers = {"spent_ms": round(spent_ms, 3),
                          "budget_ms": round(budget_ms, 3),
                          "producers": names}

    def begin_round(self, reason: str, candidates_in: int) -> dict[str, Any]:
        rnd = {"reason": reason, "candidates_in": candidates_in,
               "profiles": {}}
        if self._rounds is None:
            self._rounds = []
        self._rounds.append(rnd)
        return rnd

    def begin_profile(self, profile: str, candidates_in: int) -> dict[str, Any]:
        """Profile section within the CURRENT round (the scheduler opens the
        round before running profiles)."""
        if not self._rounds:
            self.begin_round("schedule", candidates_in)
        sec = {"candidates_in": candidates_in, "filters": [],
               "scorers": {}, "picker": None, "outcome": "pending"}
        self._rounds[-1]["profiles"][profile] = sec
        return sec

    @staticmethod
    def profile_filter(sec: dict[str, Any], name: str,
                       n_in: int, kept: list[str],
                       dropped: list[str]) -> None:
        sec["filters"].append({"plugin": name, "in": n_in, "out": len(kept),
                               "dropped": dropped})

    @staticmethod
    def profile_scorer(sec: dict[str, Any], name: str, weight: float,
                       raw: dict[str, float]) -> None:
        """Per-endpoint raw scores. Zero-copy on the scheduling hot path:
        the scorer's freshly-built result dict is referenced (never mutated
        after score() returns); top-K trimming, weighting, and rounding all
        happen at render time (to_dict)."""
        sec["scorers"][name] = {"weight": weight, "_raw": raw}

    @staticmethod
    def profile_picker(sec: dict[str, Any], name: str, picked: list[str],
                       totals: dict[str, float]) -> None:
        ranked = sorted(totals.items(), key=lambda kv: kv[1], reverse=True)
        winner_total = totals.get(picked[0], 0.0) if picked else None
        runner_up = next(((ep, t) for ep, t in ranked
                          if not picked or ep != picked[0]), None)
        sec["picker"] = {
            "plugin": name,
            "picked": picked,
            "winner_total": (round(winner_total, 6)
                             if winner_total is not None else None),
            "runner_up": runner_up[0] if runner_up else None,
            "margin": (round(winner_total - runner_up[1], 6)
                       if winner_total is not None and runner_up else None),
        }
        sec["outcome"] = "picked" if picked else "no_pick"

    def record_attempt(self, endpoint: str, outcome: str, *,
                       status: int | None = None,
                       reason: str | None = None) -> None:
        """One dispatch attempt in the gateway's retry/failover walk.
        ``outcome``: "ok" or the UpstreamFailure kind
        ("connect"/"read"/"status"/"deadline")."""
        if self._attempts is None:
            self._attempts = []
        a: dict[str, Any] = {"rank": len(self._attempts),
                             "endpoint": endpoint, "outcome": outcome}
        if status is not None:
            a["status"] = status
        if reason:
            a["reason"] = reason
        self._attempts.append(a)

    def record_event(self, kind: str, **detail: Any) -> None:
        """Out-of-band failover events (breaker denial, reschedule, retry
        budget exhaustion) interleaved into the attempt trail."""
        if self._attempts is None:
            self._attempts = []
        self._attempts.append({"rank": len(self._attempts),
                               "event": kind, **detail})

    def record_shed(self, block: dict[str, Any], *,
                    escalate: bool = False) -> None:
        """Overload-control verdict (router/overload.py): predicted TTFT vs
        SLO vs the queue-drain estimate, the ladder rung taken (degrade
        actions or shed + Retry-After) — every shed/degrade decision is
        explainable at /debug/decisions/<id>. ``escalate`` replaces an
        earlier block (a degraded-then-admitted request later evicted from
        the queue as unmeetable must explain the eviction, not the rung it
        was admitted on), keeping the superseded block under ``prior``."""
        if self._shed is None:
            self._shed = block
        elif escalate:
            block["prior"] = self._shed
            self._shed = block

    def record_cache(self, block: dict[str, Any]) -> None:
        """KV-cache observability block (router/kvobs.py CacheLedger): the
        per-candidate schedule-time predicted hit depth, joined in place
        with the engine-confirmed actual on completion (the ledger mutates
        the SAME dict, so no second stamp is needed). First stamp wins."""
        if self._cache is None:
            self._cache = block

    def record_classifier(self, block: dict[str, Any]) -> None:
        """Prefill-classifier verdict block (router/plugins/disagg.py):
        predicted hit depth, trust discount, threshold, and the skip/keep
        verdict. The handler mutates the SAME dict on a failover
        re-classification and the CacheLedger's post-hoc judge adds the
        ``judged`` sub-block in place, so one stamp suffices. First stamp
        wins (same contract as record_cache)."""
        if self._classifier is None:
            self._classifier = block

    def record_shadow(self, block: dict[str, Any]) -> None:
        """Shadow-policy counterfactual block (router/shadow.py
        ShadowEvaluator): per-policy shadow pick, verdict, and win margin,
        with the ``judged`` sub-blocks landing in place at terminal
        accounting through the shared per-policy dicts (the record_cache
        contract). Written from the shadow worker thread — a single slot
        store, GIL-atomic like the scheduler's off-loop round writes.
        First stamp wins."""
        if self._shadow is None:
            self._shadow = block

    def record_outcome(self, outcome: dict[str, Any]) -> None:
        """SLO-ledger serving outcome (router/slo.py): predicted vs actual
        TTFT/TPOT vs SLO targets, slo_met verdict, miss reason, and (on the
        disagg path) the per-pair KV-transfer row. Stamped exactly once on
        every terminal path — success, shed, retry-exhausted, deadline,
        abort — so /debug/decisions/<id> closes the predict→observe loop."""
        if self._outcome is None:
            self._outcome = outcome

    def record_waterfall(self, block: dict[str, Any]) -> None:
        """Critical-path stage waterfall (router/tails.py): per-stage time
        split, decode residual, the cohort key, and — when the request
        landed in its cohort's tail — the dominant-stage verdict the
        ``?stage=`` list filter pages on. Stamped exactly once on every
        terminal path (the record_outcome contract). First stamp wins."""
        if self._waterfall is None:
            self._waterfall = block

    def finalize(self, status: int, *, destination: str | None = None,
                 reason: str | None = None) -> None:
        if self._final:
            return  # first terminal outcome wins (error paths may overlap)
        self._final = {"status": status,
                       "duration_ms": round(
                           (time.monotonic() - self._start) * 1e3, 3)}
        if destination:
            self._final["destination"] = destination
        if reason:
            self._final["reason"] = reason

    # ---- render ---------------------------------------------------------

    def to_dict(self, *, compact: bool = False) -> dict[str, Any]:
        doc: dict[str, Any] = {
            "schema_version": SCHEMA_VERSION,
            "request_id": self.request_id,
            "model": self.model,
            "target_model": self.target_model,
            "priority": self.priority,
            "start_unix": self.start_unix,
            "admission": self._render_admission(),
            "final": self.final,
            "outcome": self.outcome,
        }
        if self._shed is not None:
            doc["shed"] = self._shed
        if self._cache is not None:
            doc["cache"] = self._cache
        if self._classifier is not None:
            doc["classifier"] = self._classifier
        if self._shadow is not None:
            doc["shadow"] = self._render_shadow()
        if self._waterfall is not None:
            doc["waterfall"] = self._waterfall
        if compact:
            doc["summary"] = self.summary_line()
            return doc
        doc["producers"] = self.producers
        doc["rounds"] = [self._render_round(r) for r in list(self.rounds)]
        doc["attempts"] = list(self.attempts)
        return doc

    def _render_shadow(self) -> dict[str, Any]:
        """Point-in-time copy of the shadow block: the shadow WORKER
        thread mutates these dicts in place (judged inserts, failover
        re-evaluation) — same off-loop-writer rule as the scheduler's
        round dicts, so the render must snapshot via _live_items instead
        of handing the live dicts to the serializer."""
        doc = dict(self._shadow)
        pols = doc.get("policies")
        if isinstance(pols, dict):
            doc["policies"] = {name: dict(entry)
                               for name, entry in self._live_items(pols)}
        return doc

    def _render_admission(self) -> dict[str, Any]:
        a = dict(self.admission)
        if "queue_ms" in a:
            a["queue_ms"] = round(a["queue_ms"], 3)
        return a

    def _render_round(self, rnd: dict[str, Any]) -> dict[str, Any]:
        return {"reason": rnd["reason"],
                "candidates_in": rnd["candidates_in"],
                "profiles": {p: self._render_profile(sec)
                             for p, sec in self._live_items(rnd["profiles"])}}

    def _render_profile(self, sec: dict[str, Any]) -> dict[str, Any]:
        scorers = {}
        for name, s in self._live_items(sec["scorers"]):
            raw = s["_raw"]
            w = s["weight"]
            top = sorted(raw.items(), key=lambda kv: kv[1],
                         reverse=True)[: self.top_k]
            scorers[name] = {
                "weight": w,
                "scores": {ep: {"raw": round(v, 6),
                                "weighted": round(
                                    w * min(max(v, 0.0), 1.0), 6)}
                           for ep, v in top},
                "candidates": len(raw),
            }
        return {"candidates_in": sec["candidates_in"],
                "filters": sec["filters"],
                "scorers": scorers,
                "picker": sec["picker"],
                "outcome": sec["outcome"]}

    def _primary_picker(self) -> dict[str, Any] | None:
        """Picker section of the last round's first picked profile (the
        primary is scheduled first by every profile handler here)."""
        for rnd in reversed(list(self.rounds)):
            for _, sec in self._live_items(rnd["profiles"]):
                if sec.get("picker") and sec["picker"].get("picked"):
                    return sec["picker"]
        return None

    def summary_line(self) -> str:
        """Compact one-line verdict for the x-debug-decision response header:
        winner, runner-up, margin, per-filter drop counts, attempt count."""
        parts: list[str] = []
        pk = self._primary_picker()
        if pk:
            parts.append(f"winner={pk['picked'][0]}")
            if pk.get("runner_up"):
                parts.append(f"runner_up={pk['runner_up']}")
            if pk.get("margin") is not None:
                parts.append(f"margin={pk['margin']:.4f}")
        if self.admission:
            parts.append(f"admission={self.admission.get('outcome')}")
            if "queue_ms" in self.admission:
                parts.append(f"queue_ms={self.admission['queue_ms']:.3f}")
        if self._shed is not None:
            parts.append(f"overload={self._shed.get('action')}")
        if self._classifier is not None:
            parts.append(f"pd={self._classifier.get('verdict')}")
        shadow = self._shadow
        if shadow is not None:
            # Counterfactual verdict beside the pick: which registered
            # shadow policies would have picked differently. A block whose
            # every policy abstained (no measured signal yet) must not
            # read as an endorsement. ONE snapshot for both reads — two
            # could straddle a worker-side re-evaluation and disagree.
            items = self._live_items(shadow.get("policies") or {})
            verdicts = [e.get("verdict") for _, e in items]
            diverged = [name for name, e in items
                        if e.get("verdict") == "diverge"]
            if diverged:
                parts.append("shadow=diverge:" + ",".join(diverged))
            elif "agree" in verdicts:
                parts.append("shadow=agree")
            else:
                parts.append("shadow=no_signal")
        cache = self._cache
        if cache is not None:
            # Cache verdict beside the pick: predicted vs engine-confirmed
            # hit blocks (actual absent until the join lands — streamed
            # responses confirm only at the terminal usage record).
            pred = (cache.get("predicted") or {}).get(
                cache.get("chosen") or "", {})
            verdict = f"cache=pred:{pred.get('blocks', '?')}"
            actual = cache.get("actual")
            if actual is not None:
                verdict += f"/act:{actual.get('blocks', '?')}"
            parts.append(verdict)
        wf = self._waterfall
        if wf is not None:
            # Waterfall verdict beside the pick: the dominant stage when
            # this request landed in its cohort's tail, else the decode
            # residual that closed the split.
            dom = wf.get("dominant")
            if dom is not None:
                ms = (wf.get("stages") or {}).get(dom)
                parts.append(f"tail={dom}" + (f":{ms:.1f}ms"
                                              if ms is not None else ""))
            elif wf.get("ttft_ms") is not None:
                parts.append(f"ttft={wf['ttft_ms']:.1f}ms")
        drops = []
        for rnd in list(self.rounds):
            for pname, sec in self._live_items(rnd["profiles"]):
                for f in list(sec["filters"]):
                    if f["dropped"]:
                        drops.append(f"{pname}/{f['plugin']}:{len(f['dropped'])}")
        if drops:
            parts.append("drops=" + ",".join(drops))
        if len(self.attempts) > 1:
            parts.append(f"attempts={len(self.attempts)}")
        return " ".join(parts) or "no-decision"

    def span_events(self) -> list[tuple[str, dict[str, Any]]]:
        """Phase summaries to attach to the orchestration span so
        /debug/traces?merge=1 correlates decision and latency in one tree."""
        events: list[tuple[str, dict[str, Any]]] = []
        if self.admission:
            events.append(("decision.admission", dict(self.admission)))
        for i, rnd in enumerate(self.rounds):
            for pname, sec in rnd["profiles"].items():
                attrs: dict[str, Any] = {
                    "round": i, "reason": rnd["reason"],
                    "candidates_in": sec["candidates_in"],
                    "outcome": sec["outcome"],
                }
                dropped = sum(len(f["dropped"]) for f in sec["filters"])
                if dropped:
                    attrs["filter_dropped"] = dropped
                pk = sec.get("picker")
                if pk and pk.get("picked"):
                    attrs["picked"] = pk["picked"][0]
                    if pk.get("margin") is not None:
                        attrs["margin"] = pk["margin"]
                events.append((f"decision.profile.{pname}", attrs))
        if len(self.attempts) > 1:
            events.append(("decision.failover", {
                "attempts": [a.get("endpoint") or a.get("event")
                             for a in self.attempts],
            }))
        return events


def _profile_picked(doc: dict[str, Any], name: str) -> bool:
    """Did any scheduling round's ``name`` profile produce a pick? Works on
    both rendered and raw round dicts (the gateway grafts the raw rounds
    onto compact list-view probes, the endpoint-filter precedent)."""
    for rnd in doc.get("rounds") or []:
        sec = (rnd.get("profiles") or {}).get(name)
        if sec is not None and sec.get("outcome") == "picked":
            return True
    return False


def record_matches(doc: dict[str, Any], *, verdict: str | None = None,
                   endpoint: str | None = None,
                   outcome: str | None = None,
                   profile: str | None = None,
                   divergent: Any = None,
                   stage: str | None = None) -> bool:
    """Operator-side list-view filters over a rendered record dict (the
    gateway's ``/debug/decisions?verdict=&endpoint=&outcome=&profile=`` —
    and the fleet fan-in forwards the same params to every worker):

    - ``verdict``: the SLO ledger's serving verdict (met | missed | error |
      shed), read from the outcome block;
    - ``endpoint``: the destination that served (``final.destination``) or
      any endpoint in the attempt trail — find every record that TOUCHED a
      pod, not just the ones it ultimately served;
    - ``outcome``: convenience aliases — ``miss`` (SLO missed or error: any
      served-but-failed row) and ``shed`` (refused at admission);
    - ``profile``: the disaggregation shape the request took — ``prefill``
      (a prefill profile produced a pick: the P/D hop ran), ``decode``
      (decode-only: the decider kept it local or the classifier skipped),
      ``skip-hop`` (decode-only specifically because the prefill
      classifier's verdict was ``skip``);
    - ``divergent``: shadow-policy counterfactual filter (``?divergent=1``)
      — records where at least one registered shadow policy would have
      picked differently (the ``shadow`` block's ``diverged`` flag,
      router/shadow.py);
    - ``stage``: tail-attribution filter (``?stage=kv_transfer``) — records
      whose waterfall landed in the cohort tail with that dominant stage
      (router/tails.py), so an operator can page straight from a
      /debug/tails attribution to the requests behind it.

    All given filters must match (AND)."""
    out = doc.get("outcome") or {}
    v = out.get("verdict")
    if v is None and out:
        # Records written before the verdict field existed: derive it.
        if out.get("shed"):
            v = "shed"
        elif out.get("slo_met"):
            v = "met"
        elif out.get("reason"):
            v = "error"
        else:
            v = "missed"
    if doc.get("shed") and v is None:
        v = "shed"
    if verdict is not None and v != verdict:
        return False
    if outcome is not None:
        if outcome == "shed":
            if v != "shed" and not doc.get("shed"):
                return False
        elif outcome == "miss":
            if v not in ("missed", "error"):
                return False
        else:
            return False  # unknown alias matches nothing, loudly-by-empty
    if endpoint is not None:
        final = doc.get("final") or {}
        if final.get("destination") != endpoint and not any(
                a.get("endpoint") == endpoint
                for a in doc.get("attempts") or []):
            return False
    if profile is not None:
        cls_verdict = (doc.get("classifier") or {}).get("verdict")
        if profile == "prefill":
            if not _profile_picked(doc, "prefill"):
                return False
        elif profile == "decode":
            if (not _profile_picked(doc, "decode")
                    or _profile_picked(doc, "prefill")):
                return False
        elif profile in ("skip-hop", "skip"):
            if cls_verdict != "skip" or _profile_picked(doc, "prefill"):
                return False
        else:
            return False  # unknown value matches nothing, loudly-by-empty
    if divergent is not None:
        if not isinstance(divergent, bool):
            return False  # unknown value matches nothing, loudly-by-empty
        if bool((doc.get("shadow") or {}).get("diverged")) != divergent:
            return False
    if stage is not None:
        # Unknown stage names match nothing, loudly-by-empty (the profile
        # filter convention) — and only TAIL-classified records carry a
        # dominant stage, so ?stage pages exactly the attributed cohort.
        if (doc.get("waterfall") or {}).get("dominant") != stage:
            return False
    return True


@dataclasses.dataclass
class DecisionConfig:
    """The YAML ``decisions:`` section (camelCase keys like the rest of the
    config surface). ``enabled: false`` is the kill-switch the overhead
    contract requires."""

    enabled: bool = True
    capacity: int = 1024
    top_k: int = 8

    @classmethod
    def from_spec(cls, spec: dict[str, Any] | None) -> "DecisionConfig":
        spec = spec or {}
        return cls(enabled=bool(spec.get("enabled", True)),
                   capacity=max(1, int(spec.get("capacity", 1024))),
                   top_k=max(1, int(spec.get("topK", 8))))


class DecisionRecorder:
    """Bounded, lock-free ring of DecisionRecords with an id index.

    Ring and index mutation (start/evict/lookup) stays on the gateway's
    event loop. Record CONTENT writers are loop-bound too (director,
    flow-control admission, proxy failover) with one exception: the
    scheduler's round/profile hooks run on scheduler-pool worker threads
    when `scheduling.workers > 0` (router/schedpool.py). Every such write
    is an individually GIL-atomic list append or dict insert, so the path
    stays lock-free; the render side (GET /debug/decisions, header
    summaries) snapshots live dicts via ``DecisionRecord._live_items``
    instead of iterating them raw — an in-flight record renders as a
    point-in-time view rather than raising mid-mutation. The ring bounds
    memory: evicting the oldest record also drops its index entry (unless
    a newer record reused the id)."""

    def __init__(self, cfg: DecisionConfig | None = None):
        self.cfg = cfg or DecisionConfig()
        self._ring: deque[DecisionRecord] = deque()
        self._by_id: dict[str, DecisionRecord] = {}

    @property
    def enabled(self) -> bool:
        return self.cfg.enabled

    def start(self, request_id: str, model: str) -> DecisionRecord | None:
        """Open a record (None when the kill-switch is off — every layer
        hook then degrades to a single ``is None`` check)."""
        cfg = self.cfg
        if not cfg.enabled:
            return None
        ring, by_id = self._ring, self._by_id
        rec = None
        if len(ring) >= cfg.capacity:
            old = ring.popleft()
            if by_id.get(old.request_id) is old:
                del by_id[old.request_id]
            # Pool the evicted record IF nothing else still references it
            # (refcount = the local + getrefcount's argument): a record
            # evicted out from under a still-in-flight request or a debug
            # reader must not be recycled into another request's trail.
            if sys.getrefcount(old) == 2:
                old._reset(request_id, model)
                old.top_k = cfg.top_k
                rec = old
        if rec is None:
            rec = DecisionRecord(request_id, model, top_k=cfg.top_k)
        ring.append(rec)
        by_id[request_id] = rec
        return rec

    def get(self, request_id: str) -> DecisionRecord | None:
        return self._by_id.get(request_id)

    def snapshot(self, n: int | None = None) -> list[DecisionRecord]:
        """Most-recent-first."""
        out = list(self._ring)
        out.reverse()
        return out[:n] if n else out

    def __len__(self) -> int:
        return len(self._ring)
