"""Request tracing: span instrumentation across the router hot path.

Mirrors the reference's OTel span topology (SURVEY §5): `gateway.request`
(handlers/server.go:172), `gateway.request_orchestration` (director.go:183),
scorer spans, disagg decision spans, sidecar P/D spans with true_ttft_ms /
prefill_duration_ms attributes (connector_nixlv2.go:276-299).

Zero-egress environment: instead of OTLP export, spans go to a ring buffer
(inspectable via the gateway's /debug/traces endpoint) and, at TRACE log
level, to the logger. The Span API is OTel-shaped so an OTLP exporter can
replace the sink without touching instrumentation. Env-configured like the
reference: TRACING_ENABLED=1, TRACING_SAMPLE_RATIO (default 0.1 — the
reference's default sampler ratio, telemetry/tracing.go:48-51).
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
import os
import random
import time
import uuid
from collections import deque
from typing import Any

log = logging.getLogger("router.tracing")

_current_span: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "current_span", default=None)

# W3C Trace Context wire headers (https://www.w3.org/TR/trace-context/):
# traceparent = version "-" trace-id "-" parent-id "-" trace-flags.
TRACEPARENT = "traceparent"
TRACESTATE = "tracestate"


def format_traceparent(span: "Span") -> str:
    """W3C traceparent for ``span`` as the parent of the next hop."""
    return (f"00-{span.trace_id[:32].rjust(32, '0')}"
            f"-{span.span_id[:16].rjust(16, '0')}-01")


_HEX = set("0123456789abcdef")


def _is_hex(s: str) -> bool:
    # Strict per-char check: int(x, 16) also accepts '+', '-', and '_'
    # separators, which are invalid on the wire.
    return bool(s) and all(c in _HEX for c in s)


def parse_traceparent(value: str) -> tuple[str, str, bool] | None:
    """Validate a traceparent header → (trace_id, parent_span_id, sampled),
    or None for anything malformed (bad field widths, non-hex, all-zero ids,
    extra fields under version 00, the forbidden version ff)."""
    parts = value.strip().lower().split("-")
    if len(parts) < 4:
        return None
    version, trace_id, span_id, flags = parts[0], parts[1], parts[2], parts[3]
    if (len(version) != 2 or version == "ff"
            or len(trace_id) != 32 or len(span_id) != 16 or len(flags) < 2):
        return None
    if not (_is_hex(version) and _is_hex(trace_id) and _is_hex(span_id)
            and _is_hex(flags[:2])):
        return None
    if version == "00" and len(parts) != 4:
        return None  # version 00 defines exactly four fields
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id, bool(int(flags[:2], 16) & 0x01)


class Span:
    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start", "end",
                 "start_unix_ns", "attributes", "status", "tracestate",
                 "events")

    # Per-span event cap: events carry decision-record phase summaries and
    # similar annotations, never unbounded streams.
    MAX_EVENTS = 64

    def __init__(self, name: str, trace_id: str, parent_id: str | None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = uuid.uuid4().hex[:16]
        self.parent_id = parent_id
        self.start = time.monotonic()       # duration measurement
        self.start_unix_ns = time.time_ns()  # exporter wall-clock anchor
        self.end: float | None = None
        self.attributes: dict[str, Any] = {}
        self.status = "ok"
        self.tracestate: str | None = None   # W3C tracestate, passed through
        self.events: list[dict[str, Any]] = []

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def add_event(self, name: str, **attributes: Any) -> None:
        """OTel-shaped span event: a named, timestamped annotation inside
        the span (decision-record phase summaries ride these)."""
        if len(self.events) >= self.MAX_EVENTS:
            return
        self.events.append({"name": name, "time_unix_ns": time.time_ns(),
                            "attributes": attributes})

    def to_dict(self) -> dict[str, Any]:
        doc = {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "duration_ms": round(((self.end or time.monotonic()) - self.start) * 1e3, 3),
            "start_unix_ns": self.start_unix_ns,
            "attributes": self.attributes,
            "status": self.status,
        }
        if self.events:
            doc["events"] = self.events
        return doc


class Tracer:
    def __init__(self, *, enabled: bool | None = None,
                 sample_ratio: float | None = None, capacity: int = 512):
        self.enabled = (enabled if enabled is not None
                        else os.environ.get("TRACING_ENABLED", "") == "1")
        self.sample_ratio = (sample_ratio if sample_ratio is not None
                             else float(os.environ.get("TRACING_SAMPLE_RATIO", "0.1")))
        self.finished: deque[dict[str, Any]] = deque(maxlen=capacity)
        self._rng = random.Random()
        # Exporter slot (the OTLP analogue): callbacks receive each finished
        # span dict. TRACING_EXPORT_PATH wires the built-in raw-JSONL file
        # exporter; the OTLP-shaped sinks (OTEL_EXPORTER_OTLP_ENDPOINT →
        # HTTP, OTEL_EXPORTER_OTLP_TRACES_FILE → OTLP/JSON file) come from
        # otlp.env_exporters() so router and engine share one encoder
        # (reference: telemetry/tracing.go:52-129 env-configured exporter).
        self._exporters: list[Any] = []
        export_path = os.environ.get("TRACING_EXPORT_PATH", "")
        if export_path:
            self.add_exporter(FileSpanExporter(export_path))
        from .otlp import env_exporters

        for exp in env_exporters():
            self.add_exporter(exp)

    def add_exporter(self, exporter: Any) -> None:
        """exporter(span_dict) or an object with .export(span_dict)."""
        self._exporters.append(getattr(exporter, "export", exporter))

    @contextlib.contextmanager
    def span(self, name: str, *, remote_parent: tuple[str, str, bool] | None = None,
             tracestate: str | None = None, **attributes):
        """Open a span. ``remote_parent`` is an upstream W3C context
        ``(trace_id, parent_span_id, sampled)`` extracted from headers: the
        caller's sampling decision is honored (sampled=False drops the whole
        local subtree; sampled=True records without re-rolling the dice)."""
        parent = _current_span.get()
        if not self.enabled or parent is _DROPPED:
            yield _NoopSpan()
            return
        if parent is None and remote_parent is not None and not remote_parent[2]:
            # Upstream sampled this trace out: propagate the drop.
            token = _current_span.set(_DROPPED)
            try:
                yield _NoopSpan()
            finally:
                _current_span.reset(token)
            return
        if (parent is None and remote_parent is None
                and self._rng.random() > self.sample_ratio):
            # Propagate the drop decision so children don't re-roll into
            # orphan spans with no assemblable root.
            token = _current_span.set(_DROPPED)
            try:
                yield _NoopSpan()
            finally:
                _current_span.reset(token)
            return
        if parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        elif remote_parent is not None:
            trace_id, parent_id = remote_parent[0], remote_parent[1]
        else:
            trace_id, parent_id = uuid.uuid4().hex, None
        s = Span(name, trace_id, parent_id)
        s.tracestate = (parent.tracestate if parent is not None else tracestate)
        s.attributes.update(attributes)
        token = _current_span.set(s)
        try:
            yield s
        except BaseException as e:
            s.status = f"error: {type(e).__name__}"
            raise
        finally:
            s.end = time.monotonic()
            _current_span.reset(token)
            doc = s.to_dict()
            self.finished.append(doc)
            for export in self._exporters:
                try:
                    export(doc)
                except Exception:
                    log.exception("span exporter failure")
            log.debug("span %s %.2fms %s", s.name,
                      (s.end - s.start) * 1e3, s.attributes)

    def span_from_headers(self, name: str, headers: Any, **attributes):
        """Open a span whose parent context comes from inbound W3C
        ``traceparent``/``tracestate`` headers (any Mapping with .get).
        Malformed or absent headers start a fresh root (local sampling
        applies); a valid header joins the caller's trace with its sampling
        decision intact — the cross-process half of span() nesting."""
        remote = None
        state = None
        raw = headers.get(TRACEPARENT) if headers is not None else None
        if raw:
            remote = parse_traceparent(raw)
            if remote is not None:
                state = headers.get(TRACESTATE) or None
        return self.span(name, remote_parent=remote, tracestate=state,
                         **attributes)

    def inject_headers(self, headers: dict[str, str]) -> None:
        """Stamp the current span's W3C context onto an outbound header
        mapping. A sampled-out trace propagates as flags 00 (fresh ids —
        the receiver only reads the drop bit), so downstream components
        don't re-roll their own sample and emit rootless partial traces.
        No-op when tracing is off or no span context exists at all."""
        s = _current_span.get()
        if isinstance(s, Span):
            headers[TRACEPARENT] = format_traceparent(s)
            if s.tracestate:
                headers[TRACESTATE] = s.tracestate
        elif s is _DROPPED:
            headers[TRACEPARENT] = (f"00-{uuid.uuid4().hex}"
                                    f"-{uuid.uuid4().hex[:16]}-00")

    def current_span(self) -> "Span | None":
        s = _current_span.get()
        return s if isinstance(s, Span) else None

    def record(self, name: str, start_monotonic: float, end_monotonic: float,
               *, parent: "Span | None" = None, **attributes) -> None:
        """Emit an already-timed phase span (post-hoc instrumentation for
        windows only known after the fact, e.g. engine prefill vs decode).
        Parents under ``parent`` or the current context span; silently
        drops when neither exists or tracing is off."""
        if not self.enabled:
            return
        p = parent if parent is not None else self.current_span()
        if not isinstance(p, Span):
            return
        s = Span(name, p.trace_id, p.span_id)
        s.start = start_monotonic
        s.end = end_monotonic
        # Re-anchor wall clock: now minus how long ago the phase started.
        s.start_unix_ns = time.time_ns() - int(
            (time.monotonic() - start_monotonic) * 1e9)
        s.attributes.update(attributes)
        doc = s.to_dict()
        self.finished.append(doc)
        for export in self._exporters:
            try:
                export(doc)
            except Exception:
                log.exception("span exporter failure")

    def snapshot(self) -> list[dict[str, Any]]:
        return list(self.finished)


class FileSpanExporter:
    """JSONL span sink: one OTLP-shaped record per finished span."""

    def __init__(self, path: str):
        self.path = path

    def export(self, span: dict[str, Any]) -> None:
        import json

        with open(self.path, "a") as f:
            f.write(json.dumps(span) + "\n")


class _NoopSpan:
    def set_attribute(self, key: str, value: Any) -> None:
        pass

    def add_event(self, name: str, **attributes: Any) -> None:
        pass


_DROPPED = object()  # contextvar sentinel: this trace was sampled out


# Process-global tracer (the reference similarly holds a global tracer
# initialised from env at process start, telemetry/tracing.go:129).
tracer = Tracer()
