"""Request tracing: span instrumentation across the router hot path.

Mirrors the reference's OTel span topology (SURVEY §5): `gateway.request`
(handlers/server.go:172), `gateway.request_orchestration` (director.go:183),
scorer spans, disagg decision spans, sidecar P/D spans with true_ttft_ms /
prefill_duration_ms attributes (connector_nixlv2.go:276-299).

Zero-egress environment: instead of OTLP export, spans go to a ring buffer
(inspectable via the gateway's /debug/traces endpoint) and, at TRACE log
level, to the logger. The Span API is OTel-shaped so an OTLP exporter can
replace the sink without touching instrumentation. Env-configured like the
reference: TRACING_ENABLED=1, TRACING_SAMPLE_RATIO (default 0.1 — the
reference's default sampler ratio, telemetry/tracing.go:48-51).
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
import os
import random
import time
import uuid
from collections import deque
from typing import Any

log = logging.getLogger("router.tracing")

_current_span: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "current_span", default=None)


class Span:
    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start", "end",
                 "start_unix_ns", "attributes", "status")

    def __init__(self, name: str, trace_id: str, parent_id: str | None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = uuid.uuid4().hex[:16]
        self.parent_id = parent_id
        self.start = time.monotonic()       # duration measurement
        self.start_unix_ns = time.time_ns()  # exporter wall-clock anchor
        self.end: float | None = None
        self.attributes: dict[str, Any] = {}
        self.status = "ok"

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def to_dict(self) -> dict[str, Any]:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "duration_ms": round(((self.end or time.monotonic()) - self.start) * 1e3, 3),
            "start_unix_ns": self.start_unix_ns,
            "attributes": self.attributes,
            "status": self.status,
        }


class Tracer:
    def __init__(self, *, enabled: bool | None = None,
                 sample_ratio: float | None = None, capacity: int = 512):
        self.enabled = (enabled if enabled is not None
                        else os.environ.get("TRACING_ENABLED", "") == "1")
        self.sample_ratio = (sample_ratio if sample_ratio is not None
                             else float(os.environ.get("TRACING_SAMPLE_RATIO", "0.1")))
        self.finished: deque[dict[str, Any]] = deque(maxlen=capacity)
        self._rng = random.Random()
        # Exporter slot (the OTLP analogue): callbacks receive each finished
        # span dict. TRACING_EXPORT_PATH wires the built-in JSONL file
        # exporter (OTLP-shaped records, collectable by any log shipper —
        # genuine export in a zero-egress environment).
        self._exporters: list[Any] = []
        export_path = os.environ.get("TRACING_EXPORT_PATH", "")
        if export_path:
            self.add_exporter(FileSpanExporter(export_path))
        # OTLP/HTTP export via OTEL_EXPORTER_OTLP_ENDPOINT (reference:
        # telemetry/tracing.go:52-129 env-configured OTLP exporter).
        from .otlp import maybe_start_otlp_exporter

        otlp = maybe_start_otlp_exporter()
        if otlp is not None:
            self.add_exporter(otlp)

    def add_exporter(self, exporter: Any) -> None:
        """exporter(span_dict) or an object with .export(span_dict)."""
        self._exporters.append(getattr(exporter, "export", exporter))

    @contextlib.contextmanager
    def span(self, name: str, **attributes):
        parent = _current_span.get()
        if not self.enabled or parent is _DROPPED:
            yield _NoopSpan()
            return
        if parent is None and self._rng.random() > self.sample_ratio:
            # Propagate the drop decision so children don't re-roll into
            # orphan spans with no assemblable root.
            token = _current_span.set(_DROPPED)
            try:
                yield _NoopSpan()
            finally:
                _current_span.reset(token)
            return
        trace_id = parent.trace_id if parent else uuid.uuid4().hex
        s = Span(name, trace_id, parent.span_id if parent else None)
        s.attributes.update(attributes)
        token = _current_span.set(s)
        try:
            yield s
        except BaseException as e:
            s.status = f"error: {type(e).__name__}"
            raise
        finally:
            s.end = time.monotonic()
            _current_span.reset(token)
            doc = s.to_dict()
            self.finished.append(doc)
            for export in self._exporters:
                try:
                    export(doc)
                except Exception:
                    log.exception("span exporter failure")
            log.debug("span %s %.2fms %s", s.name,
                      (s.end - s.start) * 1e3, s.attributes)

    def snapshot(self) -> list[dict[str, Any]]:
        return list(self.finished)


class FileSpanExporter:
    """JSONL span sink: one OTLP-shaped record per finished span."""

    def __init__(self, path: str):
        self.path = path

    def export(self, span: dict[str, Any]) -> None:
        import json

        with open(self.path, "a") as f:
            f.write(json.dumps(span) + "\n")


class _NoopSpan:
    def set_attribute(self, key: str, value: Any) -> None:
        pass


_DROPPED = object()  # contextvar sentinel: this trace was sampled out


# Process-global tracer (the reference similarly holds a global tracer
# initialised from env at process start, telemetry/tracing.go:129).
tracer = Tracer()
