"""Plugin framework: typed names, registry, factories.

Mirrors the reference's plugin registry
(/root/reference/pkg/epp/framework/interface/plugin/registry.go:25-36): every
plugin has a (type, name) TypedName; factories instantiate plugins from config
parameters; a process-global registry maps type names to factories.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Protocol, runtime_checkable


@dataclasses.dataclass(frozen=True)
class TypedName:
    type: str
    name: str

    def __str__(self) -> str:
        return f"{self.type}/{self.name}"


@runtime_checkable
class Plugin(Protocol):
    def typed_name(self) -> TypedName: ...


class PluginBase:
    """Convenience base: plugins get .name and .typed_name() for free."""

    TYPE: str = "plugin"

    def __init__(self, name: str | None = None):
        self.name = name or self.TYPE

    def typed_name(self) -> TypedName:
        return TypedName(self.TYPE, self.name)


# A factory builds a plugin from (name, parameters, handle). The handle exposes
# shared services (datastore, pool info, event loop) like the reference's
# plugin Handle.
Factory = Callable[[str, dict[str, Any], Any], Any]


class PluginRegistry:
    def __init__(self):
        self._factories: dict[str, Factory] = {}

    def register(self, type_name: str, factory: Factory, *aliases: str) -> None:
        for t in (type_name, *aliases):
            if t in self._factories:
                raise ValueError(f"plugin type {t!r} already registered")
            self._factories[t] = factory

    def known_types(self) -> list[str]:
        return sorted(self._factories)

    def instantiate(self, type_name: str, name: str, params: dict[str, Any], handle: Any):
        try:
            factory = self._factories[type_name]
        except KeyError:
            raise ValueError(
                f"unknown plugin type {type_name!r}; known: {self.known_types()}") from None
        plugin = factory(name, params or {}, handle)
        if hasattr(plugin, "name"):
            plugin.name = name
        return plugin


global_registry = PluginRegistry()


def register_plugin(type_name: str, *aliases: str):
    """Decorator: register a PluginBase subclass whose factory is cls(name) +
    optional cls.configure(params, handle)."""

    def deco(cls):
        def factory(name: str, params: dict[str, Any], handle: Any):
            obj = cls(name)
            if hasattr(obj, "configure"):
                obj.configure(params or {}, handle)
            return obj

        cls.TYPE = type_name
        global_registry.register(type_name, factory, *aliases)
        return cls

    return deco
