from .plugin import Plugin, PluginRegistry, TypedName, global_registry
from . import datalayer, scheduling, requestcontrol

__all__ = ["Plugin", "PluginRegistry", "TypedName", "global_registry",
           "datalayer", "scheduling", "requestcontrol"]
