"""Scheduling contracts: request/result types and the plugin extension points.

Mirrors /root/reference/pkg/epp/framework/interface/scheduling/
{plugins.go:43-76, types.go:39-168, cycle_state.go:43}.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Protocol, runtime_checkable

from .datalayer import Endpoint


@dataclasses.dataclass
class Objectives:
    priority: int = 0


@dataclasses.dataclass
class InferenceRequestBody:
    """Parsed request body; exactly one of the payload fields is set
    (reference InferenceRequestBody, interface/requesthandling/types.go:
    64-88 — Completions | ChatCompletions | Responses | Conversations |
    Embeddings)."""

    completions: dict[str, Any] | None = None
    chat_completions: dict[str, Any] | None = None
    responses: dict[str, Any] | None = None
    conversations: dict[str, Any] | None = None
    embeddings: dict[str, Any] | None = None
    raw: bytes | None = None
    tokenized_prompt: list[int] | None = None

    @property
    def payload(self) -> dict[str, Any] | None:
        for p in (self.completions, self.chat_completions, self.responses,
                  self.conversations, self.embeddings):
            if p is not None:
                return p
        return None

    def prompt_text(self) -> str:
        """Plain-text prompt for scoring (reference PromptText(),
        types.go:117-147)."""
        if self.completions is not None:
            p = self.completions.get("prompt", "")
            if isinstance(p, list):
                return " ".join(str(x) for x in p)
            return str(p)
        if self.chat_completions is not None:
            parts = []
            for m in self.chat_completions.get("messages", []):
                c = m.get("content") or ""
                if isinstance(c, list):
                    c = " ".join(x.get("text", "") for x in c if isinstance(x, dict))
                parts.append(f"{m.get('role', 'user')}: {c}")
            return "\n".join(parts)
        if self.responses is not None:
            inp = self.responses.get("input", "")
            if isinstance(inp, str):
                return inp
            import json as _json

            return _json.dumps(inp)
        if self.conversations is not None:
            import json as _json

            return _json.dumps(self.conversations.get("items", []))
        if self.embeddings is not None:
            # Reference PlainText() of EmbeddingsRequest.Input
            # (types.go:139-140): string, list of strings, or token ids —
            # the size estimate and prefix hash must see the real input,
            # not an empty prompt.
            inp = self.embeddings.get("input", "")
            if isinstance(inp, str):
                return inp
            if isinstance(inp, list):
                return " ".join(
                    x if isinstance(x, str) else str(x) for x in inp)
            return str(inp)
        return ""

    def cache_salt(self) -> str:
        """Prefix-cache isolation salt (reference CacheSalt(),
        types.go:166-184)."""
        for p in (self.conversations, self.responses, self.chat_completions,
                  self.completions, self.embeddings):
            if p is not None:
                return str(p.get("cache_salt") or "")
        return ""

    def stream(self) -> bool:
        p = self.payload
        return bool(p and p.get("stream"))


@dataclasses.dataclass
class InferenceRequest:
    request_id: str
    target_model: str
    body: InferenceRequestBody
    headers: dict[str, str] = dataclasses.field(default_factory=dict)
    objectives: Objectives = dataclasses.field(default_factory=Objectives)
    request_size_bytes: int = 0
    # filled by the director after scheduling:
    scheduling_result: "SchedulingResult | None" = None
    # Decision flight-recorder record (router/decisions.py DecisionRecord),
    # opened by the director when the recorder is enabled; every layer hook
    # degrades to one `is None` check when it is off. The scheduler republishes
    # it into CycleState (DECISION_STATE_KEY) so plugins can annotate the
    # cycle they run in.
    decision: Any = None
    # SLO-ledger observation (router/slo.py RequestObservation), opened by
    # the gateway before orchestration when the ledger is enabled; the
    # flow-control admission and predicted-latency PreRequest hooks write
    # queue time and per-request predictions into it, and the gateway closes
    # it exactly once on every terminal path. None = ledger kill-switch.
    outcome: Any = None
    # KV-cache observation (router/kvobs.py CacheObservation), opened by the
    # gateway after scheduling when the cache ledger is enabled: carries the
    # per-candidate predicted hit depth until the engine-confirmed actual
    # (x-kv-hit-* headers / usage.prompt_tokens_details) joins it exactly
    # once on completion. None = kvCache kill-switch or no prefix signal.
    cache: Any = None
    # Shadow-policy observation (router/shadow.py ShadowObservation),
    # attached by the ShadowEvaluator when the request is sampled for
    # counterfactual evaluation; the gateway's terminal accounting hands
    # the measured outcome to the judge through it. None = shadow inert
    # (no policies configured / kill-switch) or not sampled.
    shadow: Any = None
    # Chosen decode pod's address_port, stamped by the disagg profile
    # handler BEFORE the prefill profile runs — what lets prefill-profile
    # scorers (transfer-aware-pair-scorer) and shadow policies score the
    # (prefill, decode) PAIR instead of the legs independently.
    decode_pick: str | None = None
    # Prefix-hash memo (router/hashmemo.py PrefixHashMemo), lazily attached
    # by the first producer/scorer that needs a hash chain and reused by
    # every later consumer of the cycle — including failover reschedules of
    # the same request object.
    prefix_hashes: Any = None
    # Prefill-classifier verdict block (router/plugins/disagg.py): stamped
    # by the DisaggProfileHandler's classifier stage when `disagg:
    # {classifier: {enabled: true}}` — the same dict the DecisionRecord
    # references, so the CacheLedger's post-hoc judgement (predicted vs
    # engine-confirmed cold tokens) lands in /debug/decisions/<id> in
    # place. None = classifier kill-switch (the default) or no decode pick.
    classifier: Any = None


class CycleState:
    """Per-scheduling-cycle scratch shared between plugins of one cycle."""

    def __init__(self):
        self._data: dict[str, Any] = {}

    def write(self, key: str, value: Any) -> None:
        self._data[key] = value

    def read(self, key: str, default: Any = None) -> Any:
        return self._data.get(key, default)


@dataclasses.dataclass
class ScoredEndpoint:
    endpoint: Endpoint
    score: float


@dataclasses.dataclass
class ProfileRunResult:
    """Outcome of running one SchedulerProfile."""

    target_endpoints: list[Endpoint]
    raw_scores: dict[str, dict[str, float]] = dataclasses.field(default_factory=dict)
    # raw_scores: scorer type -> endpoint address_port -> [0,1] score
    # Weighted per-candidate totals the picker ranked (address_port ->
    # sum of weight × clamped score). Zero-copy reference to the cycle's
    # totals dict, frozen after the cycle — shadow policies
    # (router/shadow.py) re-score counterfactuals from it without
    # re-running the profile.
    totals: dict[str, float] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class SchedulingResult:
    profile_results: dict[str, ProfileRunResult]
    primary_profile_name: str

    def primary(self) -> ProfileRunResult:
        return self.profile_results[self.primary_profile_name]

    def all_endpoints(self) -> list[Endpoint]:
        seen, out = set(), []
        for r in self.profile_results.values():
            for ep in r.target_endpoints:
                if ep.metadata.address_port not in seen:
                    seen.add(ep.metadata.address_port)
                    out.append(ep)
        return out


# ---- extension points --------------------------------------------------
#
# Thread-safety contract (scheduler-pool offload, router/schedpool.py):
# every registered Filter/Scorer/Picker class must declare a ``THREAD_SAFE``
# class attribute — ``True`` after auditing that concurrent off-loop
# ``filter``/``score``/``pick`` calls cannot corrupt its state (pure reads,
# lock-protected shared structures, or individually GIL-atomic mutations),
# ``False`` otherwise. Plugins that declare ``False`` (or nothing — but
# ``scripts/verify_threadsafe.py`` lints that in-tree plugins always
# declare) are transparently trampolined back onto the event loop when the
# pool is offloaded: correct, just not concurrent.
#
# Vectorized-kernel contract (columnar scheduling, router/snapshot.py
# PoolColumns + scheduling/scheduler.py SchedulerProfile._run_batch): a
# plugin MAY additionally expose a batch kernel —
#
#   filter_batch(ctx, state, request, batch, rows) -> bool mask | None
#   score_batch(ctx, state, request, batch, rows)  -> float64 vector | None
#   pick_batch(ctx, state, request, totals)        -> list[int] | None
#
# where ``batch`` is a router.snapshot.EndpointBatch, ``rows`` the int64
# row-index array of surviving candidates (kernel outputs align with it),
# and a picker's ``totals`` the weighted score vector (returned ints are
# positions into it). Returning None DECLINES the batch — the scheduler
# falls back to the scalar method through its auto-adapter, which is also
# what happens when no kernel exists at all, so scalar-only out-of-tree
# plugins schedule unchanged inside vectorized cycles. A kernel MUST be
# bit-identical to its scalar method (same IEEE ops, same RNG draw
# sequence); when that cannot hold for some input (e.g. NaN metrics under
# Python's order-dependent min/max), decline instead of approximating.
# ``scripts/verify_vectorized.py`` lints that every registered in-tree
# filter/scorer/picker either ships a kernel or is explicitly listed as
# scalar-fallback.


@runtime_checkable
class Filter(Protocol):
    """Prunes the candidate set. The returned list MUST be a (possibly
    reordered) subset of ``endpoints`` — a filter drops candidates, it never
    substitutes or invents them. The scheduler relies on this: an unchanged
    length means nothing was dropped (drop bookkeeping and the decision
    record's filter trail are keyed on it)."""

    def typed_name(self): ...
    def filter(self, ctx: Any, state: CycleState, request: InferenceRequest,
               endpoints: list[Endpoint]) -> list[Endpoint]: ...


@runtime_checkable
class Scorer(Protocol):
    def typed_name(self): ...
    def score(self, ctx: Any, state: CycleState, request: InferenceRequest,
              endpoints: list[Endpoint]) -> dict[str, float]: ...
    # returns address_port -> [0,1]


@runtime_checkable
class Picker(Protocol):
    def typed_name(self): ...
    def pick(self, ctx: Any, state: CycleState, request: InferenceRequest,
             scored: list[ScoredEndpoint]) -> list[Endpoint]: ...


class ProfileHandler(Protocol):
    """Decides which profiles run next and folds their results together
    (reference: ProfileHandler{Pick,ProcessResults}, plugins.go:43-76)."""

    def typed_name(self): ...

    def pick_profiles(self, ctx: Any, request: InferenceRequest,
                      profiles: dict[str, Any],
                      results: dict[str, ProfileRunResult]) -> dict[str, Any]: ...

    def process_results(self, ctx: Any, request: InferenceRequest,
                        results: dict[str, ProfileRunResult]) -> SchedulingResult: ...
