"""Data-layer contracts: endpoint model, metrics snapshot, attributes.

Mirrors the reference's framework/interface/datalayer
(/root/reference/pkg/epp/framework/interface/datalayer/{metrics.go:26-42,
endpoint_metadata.go:27-35, attributemap.go:24-95}): an Endpoint is
Metadata + Metrics + AttributeMap; scorers/filters read this view and never
touch the datastore directly.
"""

from __future__ import annotations

import copy
import dataclasses
import functools
import time
from typing import Any, Iterable, Protocol, runtime_checkable

ROLE_LABEL = "llm-d.ai/role"
ENGINE_TYPE_LABEL = "llm-d.ai/engine-type"
# Drain-cycle mark (router/rebalance.py): a pod mid-role-flip carries this
# label so the role filters exclude it from every new pick while its
# in-flight work runs to completion. Set/cleared only through the
# Datastore's set_endpoint_draining / set_endpoint_role republish helpers.
DRAINING_LABEL = "llm-d.ai/draining"


@dataclasses.dataclass
class EndpointMetadata:
    name: str
    address: str
    port: int
    namespace: str = "default"
    metrics_port: int | None = None
    labels: dict[str, str] = dataclasses.field(default_factory=dict)
    # "https" for TLS model servers (engines started with --secure-serving);
    # router clients skip verification against in-cluster pod-local certs —
    # the reference scrape client's insecureSkipVerify default.
    scheme: str = "http"

    @property
    def url(self) -> str:
        return f"{self.scheme}://{self.address}:{self.port}"

    @property
    def metrics_url(self) -> str:
        return (f"{self.scheme}://{self.address}:"
                f"{self.metrics_port or self.port}/metrics")

    @functools.cached_property
    def address_port(self) -> str:
        # Cached: address/port never change after construction (the datastore
        # replaces the whole metadata object on endpoint churn), and this key
        # is read dozens of times per scheduling cycle.
        return f"{self.address}:{self.port}"

    @property
    def role(self) -> str:
        return self.labels.get(ROLE_LABEL, "")


@dataclasses.dataclass
class Metrics:
    """Per-endpoint engine telemetry snapshot (the five-signal contract of
    SURVEY §2.5, plus derived cache geometry)."""

    active_models: dict[str, int] = dataclasses.field(default_factory=dict)
    waiting_models: dict[str, int] = dataclasses.field(default_factory=dict)
    max_active_models: int = 0
    running_requests_size: int = 0
    waiting_queue_size: int = 0
    kv_cache_usage_percent: float = 0.0
    kv_cache_max_token_capacity: int = 0
    cache_block_size: int = 0
    cache_num_blocks: int = 0
    # Engine free-list depth (jetstream:num_free_kv_blocks); -1 = unknown
    # (engine doesn't publish the family / not yet scraped).
    free_kv_blocks: int = -1
    # Prefix-reuse counter pair (jetstream:prefill_tokens /
    # jetstream:prefix_hit_tokens, incremented together at prefill
    # admission): hit/total is the pod's ACTUAL cumulative hit ratio,
    # served per pod at /debug/kv. -1 = engine doesn't publish them.
    prefill_tokens: float = -1.0
    prefix_hit_tokens: float = -1.0
    update_time: float = 0.0

    def clone(self) -> "Metrics":
        return copy.deepcopy(self)

    @property
    def fresh(self) -> bool:
        return (time.monotonic() - self.update_time) < 5.0 if self.update_time else False


class AttributeMap:
    """Typed k/v bus between DataProducers and scorers/filters.

    Values exposing .clone() are cloned on read (the reference's
    clone-on-read Cloneable contract); plain values are returned as-is and
    must be treated as immutable.
    """

    def __init__(self):
        self._data: dict[str, Any] = {}

    def put(self, key: str, value: Any) -> None:
        self._data[key] = value

    def get(self, key: str, default: Any = None) -> Any:
        v = self._data.get(key, default)
        if v is not default and hasattr(v, "clone"):
            return v.clone()
        return v

    def keys(self) -> Iterable[str]:
        return self._data.keys()

    def __contains__(self, key: str) -> bool:
        return key in self._data


class Endpoint:
    """The scorer-visible endpoint view: metadata + metrics + attributes."""

    def __init__(self, metadata: EndpointMetadata):
        self.metadata = metadata
        self.metrics = Metrics()
        self.attributes = AttributeMap()

    def __repr__(self) -> str:
        return f"Endpoint({self.metadata.address_port}, role={self.metadata.role!r})"


@runtime_checkable
class DataSource(Protocol):
    """Polling data source: fetches raw data from an endpoint each tick."""

    def typed_name(self): ...
    async def collect(self, endpoint: Endpoint) -> Any: ...
    def extractors(self) -> list["Extractor"]: ...
    def add_extractor(self, ex: "Extractor") -> None: ...


@runtime_checkable
class Extractor(Protocol):
    """Turns a source's raw output into endpoint metrics/attributes."""

    def typed_name(self): ...
    def extract(self, raw: Any, endpoint: Endpoint) -> None: ...


class EndpointLifecycle(Protocol):
    """Receives endpoint add/delete events (e.g. to manage per-pod
    subscriptions, like the reference's EndpointExtractors)."""

    def endpoint_added(self, endpoint: Endpoint) -> None: ...
    def endpoint_removed(self, endpoint: Endpoint) -> None: ...
