"""Request-control extension points (reference: framework/interface
requestcontrol plugins — DataProducer, AdmitRequest, PreRequest, Response*).
"""

from __future__ import annotations

from typing import Any, Protocol, runtime_checkable

from .datalayer import Endpoint
from .scheduling import InferenceRequest, SchedulingResult


@runtime_checkable
class DataProducer(Protocol):
    """Produces per-endpoint attributes before scheduling (runs under the
    director's producer budget). Declares produced/consumed keys for the
    data-DAG ordering (reference: datalayer/data_graph.go)."""

    def typed_name(self): ...
    def produces(self) -> list[str]: ...
    def consumes(self) -> list[str]: ...
    async def produce(self, ctx: Any, request: InferenceRequest,
                      endpoints: list[Endpoint]) -> None: ...


@runtime_checkable
class AdmitRequest(Protocol):
    def typed_name(self): ...
    async def admit(self, ctx: Any, request: InferenceRequest,
                    endpoints: list[Endpoint]) -> tuple[bool, str]: ...
    # (admitted, reason-if-denied)


@runtime_checkable
class PreRequest(Protocol):
    """Runs after scheduling, before the response is sent to the proxy; may
    mutate request headers (e.g. disagg routing headers)."""

    def typed_name(self): ...
    def pre_request(self, ctx: Any, request: InferenceRequest,
                    result: SchedulingResult) -> None: ...


@runtime_checkable
class ResponseReceived(Protocol):
    def typed_name(self): ...
    def response_received(self, ctx: Any, request: InferenceRequest,
                          endpoint: Endpoint | None, status: int) -> None: ...


@runtime_checkable
class ResponseStreaming(Protocol):
    def typed_name(self): ...
    def response_streaming(self, ctx: Any, request: InferenceRequest,
                           endpoint: Endpoint | None, chunk: bytes) -> None: ...


@runtime_checkable
class ResponseComplete(Protocol):
    def typed_name(self): ...
    def response_complete(self, ctx: Any, request: InferenceRequest,
                          endpoint: Endpoint | None, usage: dict[str, int]) -> None: ...
