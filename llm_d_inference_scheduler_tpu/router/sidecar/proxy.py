"""P/D disaggregation sidecar: the decode-pod data plane.

Mirrors /root/reference/pkg/sidecar/proxy (SURVEY §2.10): an HTTP reverse
proxy colocated with each decode engine that executes the multi-stage
Prefill→Decode lifecycle. It reads and strips the router's
x-prefiller-host-port header, runs the configured KV connector protocol
against the remote prefill worker, then dispatches decode locally. No sidecar
runs on prefill nodes (docs/disaggregation.md:168-177).

Connectors:
- tpu-dcn (default; the NIXL-v2 analogue, connector_nixlv2.go:35-300):
  2-phase — (1) prefill request with kv_transfer_params{do_remote_decode},
  stream=false, max_tokens=1; (2) decode request carrying the prefiller's
  returned kv_transfer_params so the decode engine pulls KV over the
  host-staged DCN path (engine /kv fetch). Falls back to plain decode when
  prefill fails.
- passthrough: ignore disagg headers, always decode locally.

SSRF protection: with an allowlist configured, only listed prefill targets
are honored (reference allowlist.go).
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
import time
from typing import Any

import httpx
from aiohttp import web

from ..requestcontrol.director import H_DATA_PARALLEL, H_ENCODERS, H_PREFILLER
from ..resilience import DEADLINE_EXCEEDED_REASON, Deadline, H_REQUEST_TIMEOUT
from ..slo import finite_float_or_none

log = logging.getLogger("router.sidecar")

GEN_PATHS = ("/v1/completions", "/v1/chat/completions", "/v1/responses")


@dataclasses.dataclass
class SidecarConfig:
    port: int = 8000
    host: str = "127.0.0.1"
    decoder_url: str = "http://127.0.0.1:8200"
    # "tpu-dcn" | "shared-storage" | "sglang" | "passthrough"
    connector: str = "tpu-dcn"
    cache_hit_threshold: float = 0.8   # shared-storage decode-first probe
    # sglang connector: engine-side KV bootstrap rendezvous port
    # (reference connector_sglang.go init: SGLANG_BOOTSTRAP_PORT, default 8998).
    bootstrap_port: int = 8998
    ssrf_allowlist: list[str] | None = None  # None disables SSRF protection
    prefill_timeout_s: float = 120.0
    decode_timeout_s: float = 300.0
    # Chunked decode (reference decode.go:62-444): split decode into
    # max_tokens=N slices, re-appending generated text. 0 disables.
    decode_chunk_size: int = 0
    # Data parallelism (reference data_parallel.go:19-88): one extra listener
    # per DP rank; rank i listens on port+i and dispatches to decoderPort+i.
    data_parallel_size: int = 1
    # Prefiller sampling (reference chat_completions.go:79-95): when the
    # router supplies MULTIPLE prefill candidates (repeated header values or
    # one comma-separated value), pick one uniformly at random instead of
    # always the first — spreads prefill load when the scheduler returns a
    # candidate set rather than a single pick.
    enable_prefiller_sampling: bool = False
    # Secure serving + per-leg TLS (reference proxy.go:153-170): the sidecar
    # itself can serve HTTPS (cert dir or self-signed fallback), and each
    # outbound leg independently chooses TLS + verification — in-cluster
    # engines usually present pod-local certs, so skip-verify is per-leg.
    secure_serving: bool = False
    cert_path: str | None = None
    enable_cert_reload: bool = False
    use_tls_for_prefiller: bool = False
    use_tls_for_decoder: bool = False
    use_tls_for_encoder: bool = False
    insecure_skip_verify_prefiller: bool = False
    insecure_skip_verify_decoder: bool = False
    insecure_skip_verify_encoder: bool = False
    # Pipelined P/D (the ``pipeline: {enabled: ...}`` mode): pre-assign the
    # prefill request id, fire the prefill leg concurrently, and dispatch
    # the decode leg — with a chunk-streaming KV pull — as soon as the
    # prefill engine acks first-chunk staging, so the transfer overlaps the
    # remainder of prefill (docs/disaggregation.md §Pipelined KV streaming).
    # Default OFF: the serial 2-phase path stays bit-identical (the
    # vectorized/rebalance kill-switch precedent). Any pre-dispatch failure
    # falls back to the serial candidate walk.
    pipeline_enabled: bool = False


class Sidecar:
    def __init__(self, cfg: SidecarConfig, *, dp_rank: int = 0):
        import random

        from prometheus_client import (
            CollectorRegistry,
            Counter,
            Gauge,
            Histogram,
        )

        self.cfg = cfg
        self.dp_rank = dp_rank
        # Injectable for tests (reference prefillSamplerFn).
        self._prefill_sampler = random.randrange
        self.app = web.Application()
        self.app.add_routes([web.post(p, self.handle_generate) for p in GEN_PATHS])
        self.app.add_routes([
            # Embeddings carry no KV state → no disagg protocol; straight
            # passthrough to the local engine (the reference proxies
            # non-generate OpenAI surfaces the same way).
            web.post("/v1/embeddings", self._proxy_post),
            web.get("/metrics", self._metrics),
            web.get("/health", self._health),
            web.get("/debug/traces", self._traces),
            web.get("/v1/models", self._proxy_get),
            # Streaming: the precise-prefix scorer's SSE subscriber must work
            # against sidecar-fronted decode endpoints too (ADVICE r1).
            web.get("/kv_events", self._proxy_get_stream),
        ])
        self._runner: web.AppRunner | None = None
        self._client: httpx.AsyncClient | None = None       # decode leg
        self._prefill_client: httpx.AsyncClient | None = None
        self._encode_client: httpx.AsyncClient | None = None
        self._tls = None          # TlsServing; rank 0 owns, children borrow
        self._tls_owned = False
        self._inflight = 0        # live generate requests (SIGTERM drain)
        self.draining = False     # SIGTERM: health 503s, new work refused
        self._dp_children: list["Sidecar"] = []
        self._bg_tasks: set = set()  # strong refs for fire-and-forget legs
        # Sidecar-local metric families, appended to the proxied engine
        # scrape so the drain (and relay load) is observable per pod.
        self.metrics_registry = CollectorRegistry()
        self._g_draining = Gauge(
            "sidecar_draining",
            "1 while this sidecar is draining after SIGTERM",
            registry=self.metrics_registry)
        self._g_inflight = Gauge(
            "sidecar_inflight_requests",
            "Generate requests currently relayed by this sidecar",
            registry=self.metrics_registry)
        self._c_prefill_failover = Counter(
            "sidecar_prefill_failovers_total",
            "Prefill attempts that failed over to the next header candidate",
            registry=self.metrics_registry)
        self._c_stream_aborted = Counter(
            "sidecar_upstream_stream_aborted_total",
            "Decode streams cut mid-relay by an upstream disconnect "
            "(closed cleanly toward the client)",
            registry=self.metrics_registry)
        self._c_deadline = Counter(
            "sidecar_deadline_exceeded_total",
            "Requests rejected because the end-to-end deadline was exhausted",
            registry=self.metrics_registry)
        self._h_kv_transfer = Histogram(
            "sidecar_kv_transfer_ms",
            "KV pull duration measured by the decode engine and relayed "
            "through this sidecar (x-kv-pull-ms -> x-kv-transfer-ms)",
            registry=self.metrics_registry,
            buckets=(1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 2500))
        self._h_kv_overlap = Histogram(
            "sidecar_kv_overlap_ms",
            "Per-request KV pull time hidden behind the prefill engine's "
            "remaining compute on pipelined P/D requests (pull wall-time "
            "minus exposed time; 0 on serial requests)",
            registry=self.metrics_registry,
            buckets=(1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 2500))
        self._c_pipeline_fallback = Counter(
            "sidecar_pipeline_fallbacks_total",
            "Pipelined P/D attempts that fell back to the serial 2-phase "
            "candidate walk (prefill leg failed or never acked a chunk)",
            registry=self.metrics_registry)

    # ---- per-leg TLS (reference proxy.go:153-166) -----------------------

    def _prefill_base(self, prefiller: str) -> str:
        scheme = "https" if self.cfg.use_tls_for_prefiller else "http"
        return f"{scheme}://{prefiller}"

    def _encode_base(self, host: str) -> str:
        scheme = "https" if self.cfg.use_tls_for_encoder else "http"
        return f"{scheme}://{host}"

    def _dp_header_url(self, request: web.Request) -> str | None:
        """Legacy x-data-parallel-host-port dispatch (data_parallel.go:19-88):
        honored only when it names one of THIS decoder's rank ports."""
        hp = request.headers.get(H_DATA_PARALLEL)
        if not hp:
            return None
        from urllib.parse import urlsplit

        parts = urlsplit(self.cfg.decoder_url)
        try:
            host, _, port = hp.rpartition(":")
            port = int(port)
        except ValueError:
            return None
        if (host == parts.hostname and parts.port is not None
                and parts.port <= port < parts.port + max(self.cfg.data_parallel_size, 1)):
            scheme = ("https" if self.cfg.use_tls_for_decoder
                      else parts.scheme)
            return f"{scheme}://{host}:{port}{parts.path.rstrip('/')}"
        log.warning("ignoring out-of-range %s: %s", H_DATA_PARALLEL, hp)
        return None

    def _rank_url(self) -> str:
        """decoder URL shifted by this listener's DP rank (data_parallel.go:39-88);
        use_tls_for_decoder upgrades the scheme (proxy.go:155). Any path
        prefix on the decoder URL is preserved."""
        from urllib.parse import urlsplit

        parts = urlsplit(self.cfg.decoder_url)
        scheme = "https" if self.cfg.use_tls_for_decoder else parts.scheme
        path = parts.path.rstrip("/")
        if self.dp_rank == 0:
            return f"{scheme}://{parts.netloc}{path}"
        if parts.port is None:
            raise ValueError(
                f"decoder URL {self.cfg.decoder_url!r} needs an explicit port "
                f"for data-parallel rank dispatch")
        return (f"{scheme}://{parts.hostname}:{parts.port + self.dp_rank}"
                f"{path}")

    async def start(self):
        from ..tlsutil import client_verify

        self._client = httpx.AsyncClient(
            timeout=httpx.Timeout(self.cfg.decode_timeout_s, connect=5.0),
            verify=client_verify(self.cfg.insecure_skip_verify_decoder))
        self._prefill_client = httpx.AsyncClient(
            timeout=httpx.Timeout(self.cfg.prefill_timeout_s, connect=5.0),
            verify=client_verify(self.cfg.insecure_skip_verify_prefiller))
        self._encode_client = httpx.AsyncClient(
            timeout=httpx.Timeout(self.cfg.prefill_timeout_s, connect=5.0),
            verify=client_verify(self.cfg.insecure_skip_verify_encoder))
        if self.cfg.secure_serving and self._tls is None:
            from ..tlsutil import TlsServing

            self._tls = TlsServing(self.cfg.cert_path,
                                   self.cfg.enable_cert_reload)
            self._tls_owned = True
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.cfg.host,
                           self.cfg.port + self.dp_rank,
                           ssl_context=self._tls.ssl_context
                           if self._tls else None)
        await site.start()
        log.info("sidecar on %s:%s -> decoder %s (connector=%s, dp_rank=%d)",
                 self.cfg.host, self.cfg.port + self.dp_rank, self._rank_url(),
                 self.cfg.connector, self.dp_rank)
        if self.dp_rank == 0 and self.cfg.data_parallel_size > 1:
            for rank in range(1, self.cfg.data_parallel_size):
                child = Sidecar(self.cfg, dp_rank=rank)
                child._tls = self._tls  # one serving identity per pod
                child._rank_url()  # fail fast on port-less decoder URLs
                await child.start()
                self._dp_children.append(child)

    async def begin_drain(self):
        """SIGTERM step 1: stop ACCEPTING WORK before waiting out in-flight
        requests — readiness flips 503 (the LB/router pulls this replica)
        and new generate arrivals get an immediate retryable 503 instead of
        being reset at the end of the grace window. The listener itself
        stays up through the window so /health and /metrics (including the
        sidecar_draining gauge) stay observable from fresh connections;
        stop() closes it after the drain."""
        self.draining = True
        self._g_draining.set(1)
        for child in self._dp_children:
            await child.begin_drain()

    async def stop(self):
        for child in self._dp_children:
            await child.stop()
        self._dp_children.clear()
        if self._runner:
            await self._runner.cleanup()
        for c in (self._client, self._prefill_client, self._encode_client):
            if c is not None:
                await c.aclose()
        if self._tls is not None and self._tls_owned:
            self._tls.close()

    @staticmethod
    def _trace_headers(extra: dict[str, str] | None = None) -> dict[str, str]:
        """Outbound headers carrying the current span's W3C trace context
        (empty when no span is live — tracing off or sampled out)."""
        from ..tracing import tracer

        h = dict(extra or {})
        tracer.inject_headers(h)
        return h

    # ---- request handling ------------------------------------------------

    async def handle_generate(self, request: web.Request) -> web.StreamResponse:
        from ..tracing import tracer

        if self.draining:
            # Clean retryable rejection: the router resubmits elsewhere; a
            # request accepted now could be cut off mid-stream at teardown.
            return web.json_response(
                {"error": "sidecar draining"}, status=503,
                headers={"x-removal-reason": "sidecar-draining"})
        self._inflight += 1
        self._g_inflight.set(self._inflight)
        try:
            # Joins the gateway's trace via the propagated traceparent; the
            # connector-protocol spans nest under this server span, and the
            # decode/prefill legs re-propagate the context to the engines.
            with tracer.span_from_headers("sidecar.request", request.headers,
                                          path=request.path,
                                          connector=self.cfg.connector,
                                          dp_rank=self.dp_rank) as span:
                resp = await self._handle_generate(request)
                span.set_attribute("status", resp.status)
                return resp
        finally:
            self._inflight -= 1
            self._g_inflight.set(self._inflight)

    async def _handle_generate(self, request: web.Request) -> web.StreamResponse:
        raw = await request.read()
        try:
            body = json.loads(raw)
        except Exception:
            return web.json_response({"error": "invalid JSON"}, status=400)

        # End-to-end deadline: the gateway stamps the REMAINING budget on
        # x-request-timeout; every leg below inherits what's left.
        deadline = Deadline.from_headers(request.headers)
        if deadline is not None and deadline.expired:
            self._c_deadline.inc()
            return web.json_response(
                {"error": "deadline exceeded"}, status=504,
                headers={"x-removal-reason": DEADLINE_EXCEEDED_REASON})

        # Disagg headers are consumed here and never forwarded downstream
        # (upstream dispatch builds its own header set).
        prefillers = self._prefiller_candidates(request)
        encoders = request.headers.get(H_ENCODERS)

        if encoders and self.cfg.connector != "passthrough":
            hosts = [h.strip() for h in encoders.split(",") if h.strip()]
            if self.cfg.ssrf_allowlist is not None:
                bad = [h for h in hosts if h not in self.cfg.ssrf_allowlist]
                if bad:
                    return web.json_response(
                        {"error": f"encoders {bad} not in allowlist"}, status=403)
            err = await self._run_encode_primers(request, body, hosts)
            if err is not None:
                log.warning("encode primer failed (%s); continuing without", err)

        if prefillers and self.cfg.connector != "passthrough":
            if self.cfg.ssrf_allowlist is not None:
                allowed = [h for h in prefillers
                           if h in self.cfg.ssrf_allowlist]
                if not allowed:
                    return web.json_response(
                        {"error": f"prefillers {prefillers} not in allowlist"},
                        status=403)
                if len(allowed) < len(prefillers):
                    log.warning("dropping non-allowlisted prefill candidates "
                                "%s", [h for h in prefillers
                                       if h not in allowed])
                prefillers = allowed
            if self.cfg.connector == "shared-storage":
                return await self._run_shared_storage_protocol(
                    request, body, prefillers, deadline)
            if self.cfg.connector == "sglang":
                return await self._run_sglang_protocol(request, body,
                                                       prefillers, deadline)
            return await self._run_pd_protocol(request, body, prefillers,
                                               deadline)
        return await self._dispatch_decode(request, body, deadline=deadline)

    def _prefiller_candidates(self, request: web.Request) -> list[str]:
        """Resolve the FULL ordered prefill candidate list from the routing
        header (chat_completions.go:79-95): the router may send repeated
        header values or one comma-separated value. The P/D and SGLang
        protocols walk this list on prefiller failure before falling back to
        local decode. With sampling enabled, the list is rotated to a
        uniformly random starting candidate (the sampling knob became a
        shuffle of the failover order, spreading prefill load while keeping
        every candidate reachable)."""
        values = request.headers.getall(H_PREFILLER, [])
        if len(values) == 1:
            values = values[0].split(",")
        hosts = [v.strip() for v in values if v.strip()]
        if len(hosts) > 1 and self.cfg.enable_prefiller_sampling:
            start = self._prefill_sampler(len(hosts))
            hosts = hosts[start:] + hosts[:start]
        return hosts

    def _pick_prefiller(self, request: web.Request) -> str | None:
        """First candidate of the ordered list (kept for callers that need
        exactly one target)."""
        hosts = self._prefiller_candidates(request)
        return hosts[0] if hosts else None

    async def _run_sglang_protocol(self, request: web.Request,
                                   body: dict[str, Any],
                                   prefillers: list[str],
                                   deadline: Deadline | None = None
                                   ) -> web.StreamResponse:
        """SGLang-style connector (reference connector_sglang.go:43-231):
        inject bootstrap {host, port, room-id} into BOTH legs, fire the
        prefill request asynchronously, and dispatch decode CONCURRENTLY —
        the engines rendezvous on the bootstrap channel for the KV transfer
        (no kv_transfer_params relay, no prefill-completion wait). The
        async prefill leg walks the candidate list on failure; the decode
        leg keeps the first candidate's bootstrap fields because the
        rendezvous target is fixed the moment decode is dispatched. With
        real sglang engines a failed-over prefill therefore warms the new
        candidate's cache but cannot complete THIS request's KV transfer —
        the decode engine times out its bootstrap wait and computes
        locally, exactly as it would with no failover at all (no-worse);
        deferring decode until a prefiller answers would forfeit the
        connector's defining concurrency."""
        import asyncio
        import random
        import time as _time

        from ..tracing import tracer

        boot = dict(body)
        boot["bootstrap_host"] = (prefillers[0].rpartition(":")[0]
                                  or prefillers[0])
        boot["bootstrap_port"] = self.cfg.bootstrap_port
        boot["bootstrap_room"] = _time.time_ns() + random.randint(0, 999)

        with tracer.span("sidecar.sglang_protocol", prefiller=prefillers[0],
                         room=boot["bootstrap_room"]) as span:
            # Snapshot the trace context NOW: the leg may outlive this span.
            leg_headers = self._trace_headers()

            async def prefill_leg():
                # Fire-and-forget with its own lifetime: the decode response
                # finishing first must not cancel the prefill leg
                # (connector_sglang.go uses context.WithoutCancel).
                for i, prefiller in enumerate(prefillers):
                    if deadline is not None and deadline.expired:
                        return
                    if i:
                        self._c_prefill_failover.inc()
                    leg_boot = dict(boot)
                    leg_boot["bootstrap_host"] = (
                        prefiller.rpartition(":")[0] or prefiller)
                    hdrs = dict(leg_headers)
                    timeout = self.cfg.prefill_timeout_s
                    if deadline is not None:
                        # Re-stamped per attempt: a later candidate must see
                        # what is left NOW, not the walk-start snapshot.
                        timeout = max(min(timeout, deadline.remaining_s), 0.001)
                        hdrs[H_REQUEST_TIMEOUT] = deadline.header_value()
                    try:
                        r = await self._prefill_client.post(
                            self._prefill_base(prefiller) + request.path,
                            json=leg_boot, headers=hdrs,
                            timeout=timeout)
                        if r.status_code < 300:
                            return
                        log.warning("sglang prefill at %s returned %d",
                                    prefiller, r.status_code)
                    except Exception as e:
                        log.warning("sglang prefill at %s failed: %s",
                                    prefiller, e)

            task = asyncio.get_running_loop().create_task(prefill_leg())
            self._bg_tasks.add(task)
            task.add_done_callback(self._bg_tasks.discard)
            t0 = time.monotonic()
            try:
                return await self._dispatch_decode(request, boot,
                                                   deadline=deadline)
            finally:
                span.set_attribute("decode_duration_ms",
                                   round((time.monotonic() - t0) * 1e3, 1))

    async def _run_shared_storage_protocol(self, request: web.Request,
                                           body: dict[str, Any],
                                           prefillers: list[str],
                                           deadline: Deadline | None = None
                                           ) -> web.StreamResponse:
        """Shared-storage connector (reference connector_shared_storage.go:
        30-271): try decode FIRST with a cache_hit_threshold probe; only if the
        decode engine reports finish_reason=cache_threshold (cache too cold),
        run the remote prefill leg, then retry decode. Here the 'shared
        storage' is the prefill engine's staged KV export pulled over DCN."""
        from ..tracing import tracer

        with tracer.span("sidecar.shared_storage_protocol",
                         prefiller=prefillers[0]) as span:
            # Cheap probe: max_tokens=1 so a warm hit never generates the
            # completion twice; the real generation always goes through
            # _dispatch_decode (which also honors decode_chunk_size/stream).
            probe_body = dict(body)
            probe_body["cache_hit_threshold"] = self.cfg.cache_hit_threshold
            probe_body["stream"] = False
            probe_body[self._max_tokens_field(request.path)] = 1
            warm = False
            try:
                r = await self._client.post(self._rank_url() + request.path,
                                            json=probe_body,
                                            headers=self._trace_headers())
                if r.status_code == 200:
                    doc = r.json()
                    if doc.get("object") == "response":
                        # Responses bodies carry truncation cause in
                        # incomplete_details, not choices[].finish_reason.
                        finish = (doc.get("incomplete_details")
                                  or {}).get("reason")
                    else:
                        finish = (doc.get("choices")
                                  or [{}])[0].get("finish_reason")
                    warm = finish != "cache_threshold"
            except Exception as e:
                log.warning("shared-storage probe failed (%s); running P/D", e)
            span.set_attribute("cache_hit", warm)
            if warm:
                return await self._dispatch_decode(request, body,
                                                   deadline=deadline)
            return await self._run_pd_protocol(request, body, prefillers,
                                               deadline)

    @staticmethod
    def _multimodal_items(body: dict[str, Any]) -> list[dict[str, Any]]:
        """Extract image/video/audio content blocks from a chat body
        (reference multimodal_helpers.go)."""
        items = []
        for m in body.get("messages") or []:
            content = m.get("content")
            if isinstance(content, list):
                for block in content:
                    if isinstance(block, dict) and block.get("type") in (
                            "image_url", "video_url", "input_audio"):
                        items.append(block)
        return items

    async def _run_encode_primers(self, request: web.Request,
                                  body: dict[str, Any],
                                  hosts: list[str]) -> str | None:
        """E/PD stage: fan multimodal items out across the encode workers
        (reference connector_epd_shared_storage.go:38-211). Items are split
        round-robin; every worker is primed with its share before P/D runs."""
        items = self._multimodal_items(body)
        if not items or not hosts:
            return None
        rid = (body.get("request_id")
               or request.headers.get("x-request-id")
               or f"epd-{id(body):x}")
        shares: list[list[dict[str, Any]]] = [[] for _ in hosts]
        share_indices: list[list[int]] = [[] for _ in hosts]
        for i, item in enumerate(items):
            shares[i % len(hosts)].append(item)
            share_indices[i % len(hosts)].append(i)
        try:
            import asyncio as _aio

            primed = [(h, share, idxs) for h, share, idxs
                      in zip(hosts, shares, share_indices) if share]
            trace_headers = self._trace_headers()
            results = await _aio.gather(*[
                self._encode_client.post(self._encode_base(h) + "/v1/encode",
                                         json={"request_id": rid,
                                               "items": share,
                                               "item_indices": idxs},
                                         headers=trace_headers)
                for h, share, idxs in primed])
            for r in results:
                if r.status_code != 200:
                    return f"encoder returned {r.status_code}"
        except Exception as e:
            return str(e)
        # Tell the downstream engines where to pull the staged embeddings
        # (the EC-connector config of reference engines, here per-request).
        # Scheme-qualified when the encoder leg is TLS so the decode
        # engine's /ec pull dials the right protocol.
        body["request_id"] = rid
        body["ec_sources"] = [self._encode_base(h)
                              if self.cfg.use_tls_for_encoder else h
                              for h, _, _ in primed]
        return None

    async def _run_pd_protocol(self, request: web.Request, body: dict[str, Any],
                               prefillers: list[str],
                               deadline: Deadline | None = None
                               ) -> web.StreamResponse:
        """2-phase tpu-dcn protocol (NIXL-v2 analogue). Span attributes mirror
        the reference's sidecar spans (true_ttft_ms/prefill_duration_ms,
        connector_nixlv2.go:276-299)."""
        from ..tracing import tracer

        with tracer.span("sidecar.pd_protocol",
                         prefiller=prefillers[0]) as span:
            return await self._run_pd_protocol_inner(request, body, prefillers,
                                                     span, deadline)

    @staticmethod
    def _max_tokens_field(path: str) -> str:
        """The Responses API bounds output with ``max_output_tokens``
        (reference proxy.go:48); the other OpenAI surfaces use
        ``max_tokens``."""
        return ("max_output_tokens" if path.endswith("/responses")
                else "max_tokens")

    async def _run_pd_protocol_inner(self, request, body, prefillers, span,
                                     deadline=None):
        if self.cfg.pipeline_enabled:
            resp = await self._run_pd_pipelined(request, body, prefillers,
                                                span, deadline)
            if resp is not None:
                return resp
            # Pipelined attempt failed BEFORE the decode leg was dispatched
            # (prefill error / no ack): fall through to the serial
            # candidate walk below — the client sees no error, and the
            # fallback is visible via sidecar_pipeline_fallbacks_total and
            # the span's pipeline_fallback attribute.
        t0 = time.monotonic()
        prefill_body = dict(body)
        prefill_body["kv_transfer_params"] = {"do_remote_decode": True}
        prefill_body["stream"] = False
        # connector_nixlv2.go:109-131: prefill generates exactly one token;
        # the decode leg keeps the caller's original limit (or absence).
        prefill_body[self._max_tokens_field(request.path)] = 1

        # Failover across the router's ranked candidates (P/D-Serve's fast
        # inter-instance failover): each attempt inherits the REMAINING
        # deadline budget; when every candidate fails (or the budget runs
        # out) the request falls back to aggregated local decode.
        ktp = None
        served_prefiller = None
        hit_headers: dict[str, str] = {}
        attempts = 0
        for i, prefiller in enumerate(prefillers):
            if deadline is not None and deadline.expired:
                log.warning("prefill deadline exhausted after %d attempt(s); "
                            "falling back to decode", attempts)
                break
            if i:
                self._c_prefill_failover.inc()
            attempts += 1
            timeout = self.cfg.prefill_timeout_s
            headers = self._trace_headers()
            if deadline is not None:
                timeout = max(min(timeout, deadline.remaining_s), 0.001)
                headers[H_REQUEST_TIMEOUT] = deadline.header_value()
            try:
                r = await self._prefill_client.post(
                    self._prefill_base(prefiller) + request.path,
                    json=prefill_body, headers=headers, timeout=timeout)
                if r.status_code == 200:
                    ktp = r.json().get("kv_transfer_params")
                    served_prefiller = prefiller
                    # The PREFILL leg is where the prefix-cache hit actually
                    # happened on a P/D split — relay its engine-confirmed
                    # depth (engine server _kv_hit_headers) so the router's
                    # cache ledger joins it against the prediction. The
                    # decode leg's own headers (absent for KV imports) must
                    # not shadow these.
                    for h in ("x-kv-hit-blocks", "x-kv-hit-tokens"):
                        v = r.headers.get(h)
                        if v is not None:
                            hit_headers[h] = v
                    span.set_attribute("prefill_endpoint", prefiller)
                    break
                log.warning("prefill at %s returned %d; %s", prefiller,
                            r.status_code,
                            "trying next candidate"
                            if i + 1 < len(prefillers)
                            else "falling back to decode")
            except Exception as e:
                log.warning("prefill at %s failed (%s); %s", prefiller, e,
                            "trying next candidate"
                            if i + 1 < len(prefillers)
                            else "falling back to decode")

        decode_body = dict(body)
        if ktp is not None:
            decode_body["kv_transfer_params"] = ktp
        prefill_ms = (time.monotonic() - t0) * 1e3
        span.set_attribute("prefill_duration_ms", round(prefill_ms, 1))
        span.set_attribute("prefill_attempts", attempts)
        span.set_attribute("fallback_to_decode", ktp is None)
        extra = {"x-prefill-duration-ms": f"{prefill_ms:.1f}", **hit_headers}
        if served_prefiller is not None:
            # Pair identity for the router's /debug/transfers table: the
            # prefill candidate that actually served (post-failover), not
            # whatever the routing header listed first.
            extra["x-kv-prefiller"] = served_prefiller
        return await self._dispatch_decode(request, decode_body,
                                           extra_headers=extra,
                                           deadline=deadline)

    async def _run_pd_pipelined(self, request, body, prefillers, span,
                                deadline=None):
        """Pipelined P/D handoff (``pipeline_enabled``): pre-assign the
        prefill request id so the export record is addressable before the
        prefill response exists, fire the prefill leg concurrently, long-poll
        the prefill engine's ``/kv/{rid}?ack=1`` surface for first-chunk
        staging, and dispatch the decode leg — whose engine pulls KV chunk k
        while the prefill engine computes chunk k+1 — the moment the ack
        lands. Returns the client response, or None to fall back to the
        serial candidate walk (nothing was dispatched decode-side yet, so
        the fallback is invisible to the client). A prefill engine that dies
        AFTER decode dispatch is the decode engine's problem: its chunk poll
        404s and it degrades to local prefill (zero client-visible errors —
        the chaos drill's contract)."""
        import uuid as _uuid

        t0 = time.monotonic()
        prefiller = prefillers[0]
        rid = str(body.get("request_id")
                  or f"pd-{_uuid.uuid4().hex[:12]}")
        prefill_body = dict(body)
        prefill_body["request_id"] = rid
        prefill_body["kv_transfer_params"] = {"do_remote_decode": True,
                                              "stream_chunks": True}
        prefill_body["stream"] = False
        prefill_body[self._max_tokens_field(request.path)] = 1
        timeout = self.cfg.prefill_timeout_s
        headers = self._trace_headers()
        if deadline is not None:
            timeout = max(min(timeout, deadline.remaining_s), 0.001)
            headers[H_REQUEST_TIMEOUT] = deadline.header_value()

        async def _prefill_leg():
            r = await self._prefill_client.post(
                self._prefill_base(prefiller) + request.path,
                json=prefill_body, headers=headers, timeout=timeout)
            return r, (time.monotonic() - t0) * 1e3

        task = asyncio.get_running_loop().create_task(_prefill_leg())
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)

        if not await self._await_first_chunk(prefiller, rid, task, deadline):
            self._c_pipeline_fallback.inc()
            span.set_attribute("pipeline_fallback", True)
            self._reap_pipelined_prefill(prefiller, rid, task)
            return None

        span.set_attribute("prefill_endpoint", prefiller)
        span.set_attribute("pipelined", True)
        host, _, port = prefiller.rpartition(":")
        decode_body = dict(body)
        decode_body["kv_transfer_params"] = {
            "remote_host": host, "remote_port": int(port),
            "remote_request_id": rid, "stream_chunks": True,
            "remote_scheme": ("https" if self.cfg.use_tls_for_prefiller
                              else "http"),
        }
        resp = await self._dispatch_decode(
            request, decode_body,
            extra_headers={"x-kv-prefiller": prefiller}, deadline=deadline)

        # The prefill leg necessarily finished before the decode engine's
        # final chunk pull, so stamping its timing/hit headers here adds no
        # wall-clock — but a prepared stream's headers are already on the
        # wire (same loss as the serial path's pull headers on streams).
        try:
            r, prefill_ms = await asyncio.wait_for(asyncio.shield(task),
                                                   timeout=10.0)
        except Exception:
            return resp
        if not resp.prepared:
            resp.headers["x-prefill-duration-ms"] = f"{prefill_ms:.1f}"
            if r.status_code == 200:
                for h in ("x-kv-hit-blocks", "x-kv-hit-tokens"):
                    v = r.headers.get(h)
                    if v is not None:
                        resp.headers[h] = v
        span.set_attribute("prefill_duration_ms", round(prefill_ms, 1))
        return resp

    async def _await_first_chunk(self, prefiller: str, rid: str, task,
                                 deadline=None) -> bool:
        """Bounded long-poll for first-chunk staging on the prefill engine.
        True once any chunk is staged (or the whole prefill completed —
        engines that never chunk still ack at completion); False when the
        prefill leg failed or the budget ran out (caller falls back)."""
        bound = self.cfg.prefill_timeout_s
        if deadline is not None:
            bound = max(min(bound, deadline.remaining_s), 0.001)
        t_end = time.monotonic() + bound
        url = self._prefill_base(prefiller) + f"/kv/{rid}"
        while time.monotonic() < t_end:
            if task.done():
                try:
                    r, _ = task.result()
                except Exception:
                    return False
                return r.status_code == 200
            try:
                r = await self._prefill_client.get(
                    url, params={"ack": "1", "wait_ms": 500}, timeout=5.0)
                if r.status_code == 200:
                    return True
            except Exception:
                pass  # engine booting / mid-restart: keep polling in budget
            await asyncio.sleep(0.01)
        return False

    def _reap_pipelined_prefill(self, prefiller: str, rid: str, task) -> None:
        """Fallback cleanup: let the stray prefill leg drain in the
        background, then release whatever export it staged (best-effort —
        the engine's TTL sweep is the backstop)."""

        async def _reap():
            try:
                await task
            except Exception:
                pass
            try:
                await self._prefill_client.delete(
                    self._prefill_base(prefiller) + f"/kv/{rid}",
                    timeout=5.0)
            except Exception:
                pass

        t = asyncio.get_running_loop().create_task(_reap())
        self._bg_tasks.add(t)
        t.add_done_callback(self._bg_tasks.discard)

    async def _dispatch_decode(self, request: web.Request, body: dict[str, Any],
                               extra_headers: dict[str, str] | None = None,
                               deadline: Deadline | None = None
                               ) -> web.StreamResponse:
        if deadline is not None and deadline.expired:
            # The prefill walk (or queueing) consumed the whole budget:
            # honor the deadline contract instead of dispatching a decode
            # doomed to a 1 ms timeout and surfacing as a retryable 502.
            self._c_deadline.inc()
            return web.json_response(
                {"error": "deadline exceeded"}, status=504,
                headers={**(extra_headers or {}),
                         "x-removal-reason": DEADLINE_EXCEEDED_REASON})
        chunkable = (self.cfg.decode_chunk_size > 0 and not body.get("stream")
                     and "kv_transfer_params" not in body
                     and int(body.get("max_tokens") or 16) > 0
                     and ("messages" in body or isinstance(body.get("prompt"), str)))
        base_url = self._dp_header_url(request) or self._rank_url()
        if chunkable:
            return await self._chunked_decode(request, body, extra_headers,
                                              base_url, deadline)
        url = base_url + request.path
        leg_headers = self._trace_headers({"content-type": "application/json"})
        timeout = self.cfg.decode_timeout_s
        if deadline is not None:
            # The decode leg inherits the remaining end-to-end budget.
            timeout = max(min(timeout, deadline.remaining_s), 0.001)
            leg_headers[H_REQUEST_TIMEOUT] = deadline.header_value()
        try:
            upstream = self._client.build_request(
                "POST", url, json=body, headers=leg_headers, timeout=timeout)
            resp = await self._client.send(upstream, stream=True)
        except Exception as e:
            return web.json_response({"error": f"decode dispatch failed: {e}"},
                                     status=502,
                                     headers=dict(extra_headers or {}))
        out_headers = {"content-type": resp.headers.get("content-type",
                                                        "application/json")}
        out_headers.update(extra_headers or {})
        # Relay the decode engine's measured KV pull cost (non-streaming
        # responses only — streamed headers leave before the pull resolves)
        # so the router can land the (prefill, decode) pair observation.
        pull_ms = resp.headers.get("x-kv-pull-ms")
        if pull_ms:
            out_headers["x-kv-transfer-ms"] = pull_ms
            pull_bytes = resp.headers.get("x-kv-pull-bytes")
            if pull_bytes:
                out_headers["x-kv-transfer-bytes"] = pull_bytes
            v = finite_float_or_none(pull_ms)
            if v is not None:
                self._h_kv_transfer.observe(v)
            # Pipelined pulls also report the NON-overlapped tail: relay it
            # (x-kv-transfer-exposed-ms → the router's exposed pair EWMAs)
            # and observe how much transfer time the overlap hid.
            exposed_ms = resp.headers.get("x-kv-pull-exposed-ms")
            if exposed_ms:
                out_headers["x-kv-transfer-exposed-ms"] = exposed_ms
                ve = finite_float_or_none(exposed_ms)
                if v is not None and ve is not None:
                    self._h_kv_overlap.observe(max(v - ve, 0.0))
        # Relay the decode engine's measured admission wait (same
        # non-streaming caveat) so the router's tail waterfall can split
        # engine queueing out of the decode residual (router/tails.py).
        queue_ms = resp.headers.get("x-engine-queue-ms")
        if queue_ms:
            out_headers["x-engine-queue-ms"] = queue_ms
        # Local-decode fallback (and passthrough/monolithic fronting): the
        # decode engine's own prefix-hit headers relay unless a prefill
        # leg already supplied the authoritative pair (extra_headers).
        if "x-kv-hit-tokens" not in out_headers:
            for h in ("x-kv-hit-blocks", "x-kv-hit-tokens"):
                v = resp.headers.get(h)
                if v is not None:
                    out_headers[h] = v
        try:
            if "text/event-stream" in out_headers["content-type"]:
                ws = web.StreamResponse(status=resp.status_code, headers=out_headers)
                await ws.prepare(request)
                # Engine reads vs client writes fail differently: an engine
                # disconnect mid-stream is counted and the relay closed
                # cleanly (the status line is on the wire — the router's
                # stream-abort guard mirrors this on its own hop); a client
                # hangup is routine and must not count as an engine abort.
                engine_iter = resp.aiter_bytes()
                while True:
                    try:
                        chunk = await engine_iter.__anext__()
                    except StopAsyncIteration:
                        break
                    except (httpx.HTTPError, ConnectionResetError,
                            ConnectionError) as e:
                        self._c_stream_aborted.inc()
                        log.warning("decode stream aborted mid-relay: %s", e)
                        break
                    try:
                        await ws.write(chunk)
                    except (ConnectionResetError, ConnectionError) as e:
                        log.debug("client closed stream mid-relay: %s", e)
                        break
                try:
                    await ws.write_eof()
                except (ConnectionResetError, ConnectionError):
                    pass  # client already gone
                return ws
            try:
                data = await resp.aread()
            except (httpx.HTTPError, ConnectionResetError,
                    ConnectionError) as e:
                # Body read died before anything was relayed: still a clean
                # 502 toward the client, with the prefill timing headers
                # preserved for observability.
                self._c_stream_aborted.inc()
                return web.json_response(
                    {"error": f"decode read failed: {e}"}, status=502,
                    headers=dict(extra_headers or {}))
            return web.Response(body=data, status=resp.status_code,
                                headers=out_headers)
        finally:
            await resp.aclose()

    async def _chunked_decode(self, request: web.Request, body: dict[str, Any],
                              extra_headers: dict[str, str] | None,
                              base_url: str | None = None,
                              deadline: Deadline | None = None) -> web.StreamResponse:
        """Bounded decode slices (reference decode.go:62-444): issue decode in
        max_tokens=chunk steps, re-appending the generated text between steps
        (chat uses the continue-final-message pattern)."""
        chunk = self.cfg.decode_chunk_size
        total = int(body.get("max_tokens", 16))
        chat = "messages" in body
        acc_text = ""
        completion_tokens = 0
        doc: dict[str, Any] = {}
        remaining = total
        while remaining > 0:
            step_body = dict(body)
            step_body["max_tokens"] = min(chunk, remaining)
            if chat:
                msgs = list(body["messages"])
                if acc_text:
                    msgs.append({"role": "assistant", "content": acc_text})
                    step_body["continue_final_message"] = True
                step_body["messages"] = msgs
            else:
                step_body["prompt"] = body["prompt"] + acc_text
            step_headers = self._trace_headers()
            step_timeout = self.cfg.decode_timeout_s
            if deadline is not None:
                if deadline.expired:
                    # Mid-sequence deadline: return what was decoded so far
                    # rather than burning budget on further slices.
                    break
                step_timeout = max(min(step_timeout, deadline.remaining_s),
                                   0.001)
                step_headers[H_REQUEST_TIMEOUT] = deadline.header_value()
            r = await self._client.post(
                (base_url or self._rank_url()) + request.path, json=step_body,
                headers=step_headers, timeout=step_timeout)
            if r.status_code != 200:
                return web.Response(body=r.content, status=r.status_code,
                                    content_type="application/json")
            doc = r.json()
            choice = doc["choices"][0]
            piece = (choice.get("message", {}).get("content")
                     if chat else choice.get("text")) or ""
            acc_text += piece
            completion_tokens += doc.get("usage", {}).get("completion_tokens", 0)
            remaining -= step_body["max_tokens"]
            if choice.get("finish_reason") != "length":
                break

        if not doc:
            # Deadline expired before the first slice completed.
            self._c_deadline.inc()
            return web.json_response(
                {"error": "deadline exceeded"}, status=504,
                headers={**(extra_headers or {}),
                         "x-removal-reason": DEADLINE_EXCEEDED_REASON})
        if chat:
            doc["choices"][0]["message"]["content"] = acc_text
        else:
            doc["choices"][0]["text"] = acc_text
        if "usage" in doc:
            doc["usage"]["completion_tokens"] = completion_tokens
            doc["usage"]["total_tokens"] = (doc["usage"].get("prompt_tokens", 0)
                                            + completion_tokens)
        headers = {"content-type": "application/json"}
        headers.update(extra_headers or {})
        return web.Response(body=json.dumps(doc).encode(), headers=headers)

    async def _proxy_post(self, request: web.Request) -> web.Response:
        try:
            r = await self._client.post(
                self._rank_url() + request.path, content=await request.read(),
                headers={"content-type": "application/json"})
            return web.Response(body=r.content, status=r.status_code,
                                content_type=r.headers.get(
                                    "content-type",
                                    "application/json").split(";")[0])
        except Exception as e:
            return web.json_response({"error": str(e)}, status=502)

    async def _proxy_get(self, request: web.Request) -> web.Response:
        try:
            r = await self._client.get(self._rank_url() + request.path)
            return web.Response(body=r.content, status=r.status_code,
                                content_type=r.headers.get("content-type",
                                                           "text/plain").split(";")[0])
        except Exception as e:
            return web.json_response({"error": str(e)}, status=502)

    async def _health(self, request: web.Request) -> web.Response:
        """Readiness couples to the drain state: a draining sidecar reports
        503 immediately (the LB/router stops routing here) instead of
        relaying the engine's still-green health."""
        if self.draining:
            return web.json_response({"status": "draining"}, status=503)
        return await self._proxy_get(request)

    async def _traces(self, request: web.Request) -> web.Response:
        """Sidecar span ring buffer + the decode engine's, merged (dedup by
        span_id). The gateway's /debug/traces?merge=1 only sees POOL
        endpoints — in a P/D topology that's this sidecar, so it must relay
        the engine's spans or the engine leg of every trace is invisible."""
        from ..tracing import tracer

        spans = list(tracer.snapshot())
        seen = {s["span_id"] for s in spans}
        try:
            r = await self._client.get(self._rank_url() + "/debug/traces",
                                       timeout=2.0)
            remote = (r.json().get("spans") or []) if r.status_code == 200 else []
        except Exception:
            remote = []
        for s in remote:
            if isinstance(s, dict) and s.get("span_id") not in seen:
                seen.add(s.get("span_id"))
                spans.append(s)
        return web.json_response({"service": "sidecar", "spans": spans})

    async def _metrics(self, request: web.Request) -> web.Response:
        """Engine scrape relay + sidecar-local families (sidecar_draining,
        sidecar_inflight_requests) appended, so one scrape covers both. An
        unreachable engine still yields the sidecar families — the drain
        gauge must stay observable through teardown."""
        from prometheus_client import generate_latest

        own = generate_latest(self.metrics_registry)
        try:
            r = await self._client.get(self._rank_url() + "/metrics")
            if r.status_code == 200:
                body = r.content + own
            else:
                # A non-2xx relay would make Prometheus discard the whole
                # body, losing the sidecar families too — degrade to a
                # comment + own families instead.
                body = (f"# engine /metrics returned {r.status_code}\n"
                        .encode()) + own
        except Exception as e:
            body = (f"# engine scrape failed: {e}\n".encode()) + own
        return web.Response(body=body, status=200,
                            content_type="text/plain", charset="utf-8")

    async def _proxy_get_stream(self, request: web.Request) -> web.StreamResponse:
        """Long-lived streaming GET proxy (SSE /kv_events): bytes are relayed
        as they arrive, no buffering — the KV index must see events live."""
        url = self._rank_url() + request.path
        try:
            upstream = self._client.build_request(
                "GET", url, headers={"accept": "text/event-stream"})
            resp = await self._client.send(upstream, stream=True)
        except Exception as e:
            return web.json_response({"error": str(e)}, status=502)
        ws = web.StreamResponse(status=resp.status_code, headers={
            "content-type": resp.headers.get("content-type",
                                             "text/event-stream")})
        try:
            await ws.prepare(request)
            async for chunk in resp.aiter_bytes():
                await ws.write(chunk)
            await ws.write_eof()
        except (ConnectionResetError, ConnectionError, httpx.HTTPError) as e:
            # Routine subscriber teardown / engine restart mid-stream: not an
            # error worth a traceback; the subscriber reconnects.
            log.debug("kv_events relay ended: %s", e)
        finally:
            await resp.aclose()
        return ws


def main(argv: list[str] | None = None):
    import argparse
    import asyncio

    p = argparse.ArgumentParser(description="P/D disaggregation sidecar")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--decoder", default="http://127.0.0.1:8200")
    p.add_argument("--connector", default="tpu-dcn",
                   choices=["tpu-dcn", "shared-storage", "sglang", "passthrough"])
    p.add_argument("--cache-hit-threshold", type=float, default=0.8)
    p.add_argument("--bootstrap-port", type=int, default=8998,
                   help="sglang connector: engine KV bootstrap rendezvous port")
    p.add_argument("--allowlist", default=None,
                   help="comma-separated allowed prefill host:ports "
                        "(enables SSRF protection)")
    p.add_argument("--decode-chunk-size", type=int, default=0)
    p.add_argument("--data-parallel-size", type=int, default=1)
    p.add_argument("--enable-prefiller-sampling", action="store_true",
                   help="sample a random prefiller from the candidate list "
                        "instead of the first (chat_completions.go:89)")
    p.add_argument("--pipeline", action="store_true",
                   help="pipelined P/D: dispatch the decode leg on first-"
                        "chunk staging so the KV pull overlaps prefill "
                        "(docs/disaggregation.md); default serial 2-phase")
    p.add_argument("--secure-serving", action="store_true",
                   help="serve HTTPS; without --cert-path a self-signed "
                        "certificate is minted (proxy_helpers.go:55-100)")
    p.add_argument("--cert-path", default=None,
                   help="directory holding tls.crt + tls.key")
    p.add_argument("--enable-cert-reload", action="store_true",
                   help="re-read --cert-path when it changes")
    for leg in ("prefiller", "decoder", "encoder"):
        p.add_argument(f"--use-tls-for-{leg}", action="store_true",
                       help=f"send {leg} requests over https (proxy.go:155)")
        p.add_argument(f"--insecure-skip-verify-{leg}", action="store_true",
                       help=f"skip TLS verification on the {leg} leg")
    args = p.parse_args(argv)
    cfg = SidecarConfig(
        port=args.port, host=args.host, decoder_url=args.decoder,
        connector=args.connector,
        ssrf_allowlist=[s.strip() for s in args.allowlist.split(",") if s.strip()]
        if args.allowlist else None,
        decode_chunk_size=args.decode_chunk_size,
        data_parallel_size=args.data_parallel_size,
        cache_hit_threshold=args.cache_hit_threshold,
        bootstrap_port=args.bootstrap_port,
        enable_prefiller_sampling=args.enable_prefiller_sampling,
        pipeline_enabled=args.pipeline,
        secure_serving=args.secure_serving,
        cert_path=args.cert_path,
        enable_cert_reload=args.enable_cert_reload,
        use_tls_for_prefiller=args.use_tls_for_prefiller,
        use_tls_for_decoder=args.use_tls_for_decoder,
        use_tls_for_encoder=args.use_tls_for_encoder,
        insecure_skip_verify_prefiller=args.insecure_skip_verify_prefiller,
        insecure_skip_verify_decoder=args.insecure_skip_verify_decoder,
        insecure_skip_verify_encoder=args.insecure_skip_verify_encoder)
    logging.basicConfig(level=logging.INFO)

    async def run():
        import signal

        sc = Sidecar(cfg)
        await sc.start()
        stop_ev = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                loop.add_signal_handler(sig, stop_ev.set)
            except (NotImplementedError, RuntimeError):
                pass
        try:
            await stop_ev.wait()
            # Drain: flip readiness + reject new generate work FIRST (clean
            # retryable 503s instead of resets at teardown), then let
            # in-flight P/D protocols finish (each leg has its own timeout),
            # bounded. The sidecar_draining gauge marks the window; /health
            # and /metrics stay reachable until stop().
            await sc.begin_drain()
            deadline = loop.time() + 30.0
            inflight = lambda: sc._inflight + sum(  # noqa: E731
                ch._inflight for ch in sc._dp_children)
            log.info("SIGTERM: draining %d in-flight requests", inflight())
            while loop.time() < deadline and inflight() > 0:
                await asyncio.sleep(0.25)
        except asyncio.CancelledError:
            pass
        await sc.stop()

    asyncio.run(run())


if __name__ == "__main__":
    main()
