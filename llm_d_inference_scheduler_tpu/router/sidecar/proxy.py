"""P/D disaggregation sidecar: the decode-pod data plane.

Mirrors /root/reference/pkg/sidecar/proxy (SURVEY §2.10): an HTTP reverse
proxy colocated with each decode engine that executes the multi-stage
Prefill→Decode lifecycle. It reads and strips the router's
x-prefiller-host-port header, runs the configured KV connector protocol
against the remote prefill worker, then dispatches decode locally. No sidecar
runs on prefill nodes (docs/disaggregation.md:168-177).

Connectors:
- tpu-dcn (default; the NIXL-v2 analogue, connector_nixlv2.go:35-300):
  2-phase — (1) prefill request with kv_transfer_params{do_remote_decode},
  stream=false, max_tokens=1; (2) decode request carrying the prefiller's
  returned kv_transfer_params so the decode engine pulls KV over the
  host-staged DCN path (engine /kv fetch). Falls back to plain decode when
  prefill fails.
- passthrough: ignore disagg headers, always decode locally.

SSRF protection: with an allowlist configured, only listed prefill targets
are honored (reference allowlist.go).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import time
from typing import Any

import httpx
from aiohttp import web

from ..requestcontrol.director import H_ENCODERS, H_PREFILLER

log = logging.getLogger("router.sidecar")

GEN_PATHS = ("/v1/completions", "/v1/chat/completions", "/v1/responses")


@dataclasses.dataclass
class SidecarConfig:
    port: int = 8000
    host: str = "127.0.0.1"
    decoder_url: str = "http://127.0.0.1:8200"
    connector: str = "tpu-dcn"         # "tpu-dcn" | "passthrough"
    ssrf_allowlist: list[str] | None = None  # None disables SSRF protection
    prefill_timeout_s: float = 120.0
    decode_timeout_s: float = 300.0


class Sidecar:
    def __init__(self, cfg: SidecarConfig):
        self.cfg = cfg
        self.app = web.Application()
        self.app.add_routes([web.post(p, self.handle_generate) for p in GEN_PATHS])
        self.app.add_routes([
            web.get("/metrics", self._proxy_get),
            web.get("/health", self._proxy_get),
            web.get("/v1/models", self._proxy_get),
        ])
        self._runner: web.AppRunner | None = None
        self._client: httpx.AsyncClient | None = None

    async def start(self):
        self._client = httpx.AsyncClient(
            timeout=httpx.Timeout(self.cfg.decode_timeout_s, connect=5.0))
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.cfg.host, self.cfg.port)
        await site.start()
        log.info("sidecar on %s:%s -> decoder %s (connector=%s)",
                 self.cfg.host, self.cfg.port, self.cfg.decoder_url,
                 self.cfg.connector)

    async def stop(self):
        if self._runner:
            await self._runner.cleanup()
        if self._client:
            await self._client.aclose()

    # ---- request handling ------------------------------------------------

    async def handle_generate(self, request: web.Request) -> web.StreamResponse:
        raw = await request.read()
        try:
            body = json.loads(raw)
        except Exception:
            return web.json_response({"error": "invalid JSON"}, status=400)

        # Disagg headers are consumed here and never forwarded downstream
        # (upstream dispatch builds its own header set).
        prefiller = request.headers.get(H_PREFILLER)
        encoders = request.headers.get(H_ENCODERS)  # E/PD protocol: phase 2
        del encoders

        if prefiller and self.cfg.connector != "passthrough":
            if (self.cfg.ssrf_allowlist is not None
                    and prefiller not in self.cfg.ssrf_allowlist):
                return web.json_response(
                    {"error": f"prefiller {prefiller} not in allowlist"}, status=403)
            return await self._run_pd_protocol(request, body, prefiller)
        return await self._dispatch_decode(request, body)

    async def _run_pd_protocol(self, request: web.Request, body: dict[str, Any],
                               prefiller: str) -> web.StreamResponse:
        """2-phase tpu-dcn protocol (NIXL-v2 analogue)."""
        t0 = time.monotonic()
        prefill_body = dict(body)
        prefill_body["kv_transfer_params"] = {"do_remote_decode": True}
        prefill_body["stream"] = False
        prefill_body["max_tokens"] = 1  # connector_nixlv2.go:109-131

        ktp = None
        try:
            r = await self._client.post(
                f"http://{prefiller}{request.path}", json=prefill_body,
                timeout=self.cfg.prefill_timeout_s)
            if r.status_code == 200:
                ktp = r.json().get("kv_transfer_params")
            else:
                log.warning("prefill at %s returned %d; falling back to decode",
                            prefiller, r.status_code)
        except Exception as e:
            log.warning("prefill at %s failed (%s); falling back to decode",
                        prefiller, e)

        decode_body = dict(body)
        if ktp is not None:
            decode_body["kv_transfer_params"] = ktp
        prefill_ms = (time.monotonic() - t0) * 1e3
        return await self._dispatch_decode(request, decode_body,
                                           extra_headers={
                                               "x-prefill-duration-ms": f"{prefill_ms:.1f}"})

    async def _dispatch_decode(self, request: web.Request, body: dict[str, Any],
                               extra_headers: dict[str, str] | None = None
                               ) -> web.StreamResponse:
        url = self.cfg.decoder_url + request.path
        try:
            upstream = self._client.build_request(
                "POST", url, json=body, headers={"content-type": "application/json"})
            resp = await self._client.send(upstream, stream=True)
        except Exception as e:
            return web.json_response({"error": f"decode dispatch failed: {e}"},
                                     status=502)
        out_headers = {"content-type": resp.headers.get("content-type",
                                                        "application/json")}
        out_headers.update(extra_headers or {})
        try:
            if "text/event-stream" in out_headers["content-type"]:
                ws = web.StreamResponse(status=resp.status_code, headers=out_headers)
                await ws.prepare(request)
                async for chunk in resp.aiter_bytes():
                    await ws.write(chunk)
                await ws.write_eof()
                return ws
            data = await resp.aread()
            return web.Response(body=data, status=resp.status_code,
                                headers=out_headers)
        finally:
            await resp.aclose()

    async def _proxy_get(self, request: web.Request) -> web.Response:
        try:
            r = await self._client.get(self.cfg.decoder_url + request.path)
            return web.Response(body=r.content, status=r.status_code,
                                content_type=r.headers.get("content-type",
                                                           "text/plain").split(";")[0])
        except Exception as e:
            return web.json_response({"error": str(e)}, status=502)


def main(argv: list[str] | None = None):
    import argparse
    import asyncio

    p = argparse.ArgumentParser(description="P/D disaggregation sidecar")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--decoder", default="http://127.0.0.1:8200")
    p.add_argument("--connector", default="tpu-dcn",
                   choices=["tpu-dcn", "passthrough"])
    p.add_argument("--allowlist", default=None,
                   help="comma-separated allowed prefill host:ports "
                        "(enables SSRF protection)")
    args = p.parse_args(argv)
    cfg = SidecarConfig(
        port=args.port, host=args.host, decoder_url=args.decoder,
        connector=args.connector,
        ssrf_allowlist=[s.strip() for s in args.allowlist.split(",") if s.strip()]
        if args.allowlist else None)
    logging.basicConfig(level=logging.INFO)

    async def run():
        sc = Sidecar(cfg)
        await sc.start()
        try:
            while True:
                await asyncio.sleep(3600)
        except asyncio.CancelledError:
            await sc.stop()

    asyncio.run(run())


if __name__ == "__main__":
    main()
